"""Raft-replicated uniqueness provider — the distributed notary commit log.

Capability match for the reference's Raft tier (reference:
node/src/main/kotlin/net/corda/node/services/transactions/
RaftUniquenessProvider.kt:44-115 and DistributedImmutableMap.kt:24-106, built
on Copycat/Atomix): a cluster of notary nodes replicates a first-committer-
wins input map through leader-based consensus, so notarisation survives the
loss of a minority of cluster members.

Design (idiomatic to this framework, not a Copycat port):
  * consensus state machine implements the Raft paper's core: randomized
    election timeouts, RequestVote/AppendEntries over the node's existing
    MessagingService (TCP in production, the in-memory fake in tests — the
    reference runs its own Netty transport; ours rides the one transport);
  * the replicated command is PutAll{refs -> ConsumingTx}; apply = the same
    first-committer-wins check/insert as PersistentUniquenessProvider, so
    conflict detection is byte-identical to the single-node path;
  * log + term/votedFor persist in the NodeDatabase (raft_log/raft_meta
    tables) — a restarted member rejoins with its log intact;
  * RaftUniquenessProvider.commit() submits to the local member; a follower
    forwards to the leader. While waiting it pumps the node's messaging so
    consensus traffic flows — SMM flow dispatch is re-entrancy-guarded, so
    session messages queue up and run after the flow step completes.

Commit pipeline (ARCHITECTURE.md "Commit pipeline"): the leader merges a
round's submissions into ONE PutAllBatch log entry (group commit, per-
request conflict isolation at apply), replication streams pre-encoded entry
blobs through per-peer in-flight windows (pipelined nextIndex — a tail goes
out once, in bounded chunks), and decisions coalesce into multi-outcome
ClientReplyBatch frames. RaftConfig(group_commit=False) restores the
one-command-per-entry path.

Timing is injected (clock callable) so tests can drive elections
deterministically fast.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import random
import sqlite3
import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from ...crypto.hashes import SecureHash
from ...crypto.party import Party
from ...obs import telemetry as _tm
from ...obs import trace as _obs
from ...qos import context as _qos
from ...serialization.codec import deserialize, register, serialize
from ...testing import faults as _faults
from . import integrity as _integrity
from ..messaging.api import MessagingService, TopicSession
from .api import (
    ConsumingTx,
    UniquenessConflict,
    UniquenessException,
    UniquenessProvider,
    UniquenessUnavailableException,
)

RAFT_TOPIC = "platform.raft"

_RAFT_SCHEMA = """
CREATE TABLE IF NOT EXISTS raft_log (
    idx  INTEGER PRIMARY KEY,
    term INTEGER NOT NULL,
    blob BLOB NOT NULL,
    crc  INTEGER
);
CREATE TABLE IF NOT EXISTS raft_meta (
    singleton INTEGER PRIMARY KEY CHECK (singleton = 1),
    term      INTEGER NOT NULL,
    voted_for TEXT
);
CREATE TABLE IF NOT EXISTS reserved_states (
    state_ref  BLOB PRIMARY KEY,
    tx_id      BLOB NOT NULL,
    expires_at REAL NOT NULL,
    crc        INTEGER
);
"""


# -- wire messages ----------------------------------------------------------


@register
@dataclass(frozen=True)
class PutAllCommand:
    """The replicated command: claim `refs` for tx_id (DistributedImmutableMap
    putAll capability)."""

    refs: tuple
    tx_id: SecureHash
    caller: Party
    request_id: bytes  # correlates the client's reply
    # Coordinator wall-clock stamp (epoch seconds), carried IN the command so
    # every replica evaluates reservation expiry against the same value — the
    # state machine never reads a local clock (replicas would diverge). A
    # reservation with expires_at=E blocks a different-tx command iff its
    # issued_at < E; issued_at >= E is a deterministic steal. Re-stamped on
    # every resubmission (same request_id), so a command parked behind a
    # crashed coordinator's reservation gets through once the TTL passes.
    issued_at: float = 0.0


@register
@dataclass(frozen=True)
class ReserveCommand:
    """Phase 1 of the cross-shard two-phase commit (services/sharding.py):
    claim a REVOCABLE hold on `refs` for tx_id. Applies atomically — every
    ref free (or held/committed by the same tx) or none. Outcomes: ok
    (reserved), conflict (some ref committed by another tx — final), or BUSY
    (some ref reserved by another unexpired tx — retryable bounce). The hold
    expires at issued_at + ttl_s, so a coordinator that dies between phases
    never wedges inputs: expiry is decided from command-carried stamps, not
    replica clocks (see PutAllCommand.issued_at)."""

    refs: tuple
    tx_id: SecureHash
    caller: Party
    request_id: bytes
    issued_at: float
    ttl_s: float


@register
@dataclass(frozen=True)
class CommitReservedCommand:
    """Phase 2 commit: promote tx_id's reservations on `refs` to durable
    committed_states rows. Idempotent (already-committed-by-this-tx is ok);
    conflicts only if another tx committed a ref first — a reservation lost
    to TTL expiry does NOT block the commit, which is what guarantees phase
    2 terminates (the steal window is documented in ARCHITECTURE.md)."""

    refs: tuple
    tx_id: SecureHash
    caller: Party
    request_id: bytes


@register
@dataclass(frozen=True)
class AbortReservedCommand:
    """Phase 2 abort: release tx_id's own reservations on `refs`. Always
    succeeds (releasing nothing is fine) — abort must never add a failure
    mode to a 2PC already unwinding."""

    refs: tuple
    tx_id: SecureHash
    request_id: bytes


@register
@dataclass(frozen=True)
class ShardFenceCommand:
    """Elastic-reshard fence (services/sharding.py): a replicated marker
    that moves THIS group's shard-ownership state machine through the
    split/merge handoff. ``mode="seal"`` freezes the moving keyspace — from
    this log position on, any command touching a ref that epoch `epoch`
    assigns elsewhere bounces with the retryable WRONG_EPOCH outcome, while
    refs the group keeps commit normally (no outage for the unmoved
    keyspace). The seal's log position IS the linearization point of the
    handoff snapshot: everything applied before it is in the streamed
    ranges, everything after it bounces. ``mode="activate"`` installs the
    new epoch as current (count = to_count); a group whose index falls
    outside to_count becomes "retired" and bounces everything forever.
    Idempotent and never-downgrading within an epoch, so coordinator
    retries and full log replays converge. Deterministic: ownership is
    decided from the command's own fields + the ref hash — never a clock."""

    group: int  # this group's index in the shard map
    from_count: int  # group count of the epoch being left
    to_count: int  # group count of the epoch being entered
    epoch: int  # the shard-map epoch this fence installs
    mode: str  # "seal" | "activate"
    request_id: bytes
    issued_at: float = 0.0


@register
@dataclass(frozen=True)
class InstallShardStateCommand:
    """Elastic-reshard state handoff frame: one chunk of the source group's
    sealed `committed_states` / `reserved_states` ranges, replicated into
    the TARGET group's log. Rows are the exact source blobs (the same
    (state_ref, consuming) / (state_ref, tx_id, expires_at) shapes
    InstallSnapshot already ships), applied INSERT OR IGNORE so coordinator
    retries and log replays are idempotent. The first frame fences the
    target as "importing" (all traffic bounces WRONG_EPOCH until the
    coordinator activates it) — a new-epoch client that races ahead of the
    cutover retries instead of committing against a half-installed ledger.
    Reservation rows carry their original coordinator-stamped expires_at,
    so a 2PC hold orphaned by a crashed handoff coordinator still releases
    by TTL on the new owner (replicas never read clocks)."""

    committed_rows: tuple  # ((state_ref_blob, consuming_blob), ...)
    reserved_rows: tuple  # ((state_ref_blob, tx_id_bytes, expires_at), ...)
    group: int  # the TARGET group's index at to_count
    from_count: int
    to_count: int
    epoch: int
    request_id: bytes
    issued_at: float = 0.0


@register
@dataclass(frozen=True)
class PutAllBatch:
    """Group commit: every PutAllCommand a leader's scheduling round
    coalesced, replicated as ONE log entry — one log append/fsync, one
    AppendEntries slot, one apply pass for the burst. Conflict isolation is
    per inner command: apply runs each PutAllCommand through the same
    first-committer-wins check independently, so one double-spend yields
    its own ClientReply(ok=False, conflict=...) without poisoning batch
    siblings."""

    commands: tuple  # (PutAllCommand, ...)


@register
@dataclass(frozen=True)
class RequestVote:
    term: int
    candidate: str
    last_log_index: int
    last_log_term: int


@register
@dataclass(frozen=True)
class VoteReply:
    term: int
    granted: bool
    voter: str


@register
@dataclass(frozen=True)
class PreVote:
    """Pre-vote canvass (partition plane, round 20; Raft §9.6 / Ongaro's
    thesis §9.6): a would-be candidate asks "would you vote for me at
    ``term``?" WITHOUT incrementing or persisting anything on either side.
    ``term`` is the term the canvasser WOULD campaign at (current + 1).
    A rejoining minority member therefore cannot inflate the cluster term
    and depose a healthy leader just by having sat behind a cut: it first
    has to win a canvass, which a quorum with a live leader refuses. Only
    sent when ``[raft] prevote = true`` — a cluster with the flag off
    never puts this frame on the wire."""

    term: int
    candidate: str
    last_log_index: int
    last_log_term: int


@register
@dataclass(frozen=True)
class PreVoteReply:
    """Canvass answer. ``term`` is the REPLIER's current term (so a
    canvasser behind on terms catches up without a disruptive election);
    ``granted`` means "your log is current AND I have not heard from a
    live leader within the minimum election window". Never persisted."""

    term: int
    granted: bool
    voter: str


@register
@dataclass(frozen=True)
class AppendEntries:
    term: int
    leader: str
    prev_index: int
    prev_term: int
    # ((term, blob), ...): blob is the PRE-ENCODED command (the exact bytes
    # stored in raft_log). The leader serializes each entry once ever — at
    # append — and every peer × every rebroadcast reuses the cached blob;
    # the follower inserts the blob verbatim and deserializes lazily at
    # apply time. (Pre-pipeline, entries carried live dataclasses that were
    # re-serialized per peer per broadcast — O(tail) codec work per tick.)
    entries: tuple
    leader_commit: int


@register
@dataclass(frozen=True)
class AppendReply:
    term: int
    success: bool
    match_index: int
    follower: str
    # On failure: the follower's last log/snapshot index (-1 = no hint), so
    # the leader can jump next_index instead of decrementing one entry per
    # round trip (and instead of livelocking against a follower whose
    # snapshot is AHEAD of the leader's own compaction point). 0 is a REAL
    # hint: an empty-log follower wants next_index = 1 immediately.
    hint_index: int = -1


@register
@dataclass(frozen=True)
class ClientCommit:
    """Follower->leader forwarding of a client commit."""

    command: PutAllCommand
    reply_to: str


@register
@dataclass(frozen=True)
class ClientCommitBatch:
    """Follower->leader forwarding, coalesced: every commit a follower's
    round buffered rides one frame (one outbox insert/ACK) instead of one
    ClientCommit frame per command."""

    commands: tuple  # (PutAllCommand, ...)
    reply_to: str


@register
@dataclass(frozen=True)
class ClientCommitBatchQos:
    """Follower->leader forwarding with QoS context riding along: one
    wire-encoded QosContext per command (b"" = unlabelled), positionally
    parallel to `commands`, so the leader's deadline-aware batch seal can
    see forwarded interactive deadlines too. Sent only when the QoS plane
    is armed AND at least one buffered command carries a context — a
    qos-disabled deployment never sees this type on the wire, keeping the
    classic frame set byte-identical."""

    commands: tuple  # (PutAllCommand, ...)
    reply_to: str
    qos: tuple  # (bytes, ...) parallel to commands; b"" = no context


@register
@dataclass(frozen=True)
class ClientReply:
    request_id: bytes
    ok: bool
    conflict: UniquenessConflict | None
    leader_hint: str | None
    # True when the command bounced off a shard-reshard fence (WRONG_EPOCH
    # outcome): the ref now belongs to another group/epoch, so resubmitting
    # HERE can never succeed — the submitter must re-derive the shard
    # directory first. Wire-only (ClientReply is never persisted) and every
    # process in a deployment runs the same code, so extending the frame is
    # safe; pre-reshard traffic always sends the default False.
    wrong_epoch: bool = False


@register
@dataclass(frozen=True)
class ClientReplyBatch:
    """Leader->member decisions, coalesced: one multi-outcome frame per
    destination per apply pass. Redelivery-safe — recording a decision is
    idempotent and each waiting request polls its id at most once."""

    replies: tuple  # (ClientReply, ...)


@register
@dataclass(frozen=True)
class InstallSnapshot:
    """Leader -> lagging follower: the state-machine content replaces the
    follower's, when the leader's log was compacted past the follower's
    position (DistributedImmutableMap.kt snapshot/install capability).
    CHUNKED: large maps ship as an ordered series of frames (each well under
    the transport's frame cap); `offset` is the entry index of the first
    entry in this chunk, `done` marks the last chunk. Live reservations
    (cross-shard 2PC holds) ride the final chunk only — the table is small
    (in-flight 2PCs, not history), and a follower restored without them
    could commit a PutAll straight through a hold the rest of the group is
    honouring."""

    term: int
    leader: str
    last_included_index: int
    last_included_term: int
    entries: tuple  # ((state_ref_blob, consuming_blob), ...)
    offset: int = 0
    done: bool = True
    reservations: tuple = ()  # ((state_ref_blob, tx_id_bytes, expires_at),)
    # CRC32C over this chunk's entry blobs (durability plane): a follower
    # discards a damaged chunk instead of installing it; 0 = unverified
    # (frames from pre-durability senders decode with the default).
    crc: int = 0


@register
@dataclass(frozen=True)
class InstallSnapshotReply:
    term: int
    follower: str
    last_included_index: int


def _snapshot_chunk_crc(entries) -> int:
    """Running CRC32C over an InstallSnapshot chunk's entry blobs, in
    order — binds both content and sequence of the (ref, consuming) pairs."""
    c = 0
    for ref, consuming in entries:
        c = _integrity.crc32c(bytes(consuming), _integrity.crc32c(bytes(ref), c))
    return c


class _Busy:
    """Third apply outcome beside None (ok) and UniquenessConflict (final):
    the command lost to another transaction's UNEXPIRED reservation. Mapped
    by _apply_committed to the retryable bounce reply form (ok=False,
    conflict=None) that commit pollers already answer by resubmitting — the
    resubmission carries a fresh issued_at, so it wins deterministically
    once the hold expires, or resolves against the holder's commit/abort."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "BUSY"


BUSY = _Busy()


class _WrongEpoch:
    """Fourth apply outcome: the command touches refs this group no longer
    (or does not yet) own under the shard-map epoch its fence installed.
    Unlike BUSY, resubmitting to the SAME group can never succeed — the
    submitter must re-derive the shard directory (flows/notary.py watches
    the network map) and route to the new owner. Mapped by _apply_committed
    to ClientReply(ok=False, conflict=None, wrong_epoch=True)."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "WRONG_EPOCH"


WRONG_EPOCH = _WrongEpoch()


class RaftMember:
    """One member of the notary cluster's consensus group."""

    ELECTION_TIMEOUT = (0.15, 0.30)  # seconds, randomized per election
    HEARTBEAT = 0.05

    def __init__(
        self,
        name: str,
        peers: dict[str, Any],  # name -> transport address (excluding self)
        messaging: MessagingService,
        db,  # NodeDatabase
        apply_command: Callable[[PutAllCommand], UniquenessConflict | None],
        clock: Callable[[], float] = _time.monotonic,
        rng: random.Random | None = None,
        timeout_scale: float = 1.0,
        config=None,  # RaftConfig; None = defaults (group commit ON)
    ):
        from ..config import RaftConfig

        self.config = config or RaftConfig()
        self.name = name
        self.peers = dict(peers)
        # Cross-group reply routing (sharded notary): a 2PC coordinator in
        # ANOTHER Raft group sends ClientCommit frames here with a reply_to
        # that is not one of this group's peers. The node injects a netmap-
        # backed name->address resolver so decisions find their way back;
        # None keeps the single-group behaviour exactly (peers-only).
        self.resolve_addr: Callable[[str], Any] | None = None
        self.messaging = messaging
        self.db = db
        self.apply_command = apply_command
        self.clock = clock
        self.rng = rng or random.Random(hash(name) & 0xFFFF)
        self.scale = timeout_scale

        with db.lock:
            db.conn.executescript(_RAFT_SCHEMA)
            # Legacy databases created before the durability plane get the
            # nullable crc column added in place (IF NOT EXISTS above only
            # covers fresh files).
            _integrity.ensure_integrity_schema(db.conn)
            row = db.conn.execute(
                "SELECT term, voted_for FROM raft_meta WHERE singleton=1"
            ).fetchone()
            if row is None:
                db.conn.execute(
                    "INSERT INTO raft_meta (singleton, term, voted_for) "
                    "VALUES (1, 0, NULL)")
                db.conn.commit()
                self.term, self.voted_for = 0, None
            else:
                self.term, self.voted_for = row[0], row[1]
        self.role = "follower"
        self.leader_name: str | None = None
        self.commit_index = int(db.get_setting("raft_commit_index") or 0)
        self.last_applied = int(db.get_setting("raft_last_applied") or 0)
        # Log compaction marker: entries <= snapshot_index live only in the
        # applied state machine (committed_states), not the log.
        self.snapshot_index = int(db.get_setting("raft_snapshot_index") or 0)
        self.snapshot_term = int(db.get_setting("raft_snapshot_term") or 0)
        self._votes: set[str] = set()
        self._election_attempts = 0  # consecutive failed elections (backoff)
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._last_heartbeat = self.clock()
        self._snapshot_sent_at: dict[str, float] = {}
        self._election_deadline = self._next_election_deadline()
        # Partition hardening (round 20, [raft] prevote): pre-vote canvass
        # state (who granted the current canvass; advisory only — a real
        # election still collects real votes), last contact from a live
        # leader (follower side: the §9.6 leader-stickiness check), last
        # reply from each peer (leader side: check-quorum). The contact
        # stamps are written unconditionally — plain attribute writes with
        # no observable effect — but every BEHAVIOR (canvassing, granting
        # semantics, step-down) is gated on config.prevote so the flag-off
        # path is bit-identical to pre-round-20.
        self._prevote_votes: set[str] = set()
        self._prevoting = False
        self._last_leader_contact = self.clock()
        self._peer_contact: dict[str, float] = {}
        # Candidacy start (self.clock() timeline) for the `election` marker
        # span recorded at the win; spans canvass start when prevote is on.
        self._candidacy_t0: float | None = None
        # request_id -> ClientReply for commits decided at this member.
        # Bounded: late/duplicate replies for abandoned requests must not
        # accumulate on a long-running cluster.
        self.decided: dict[bytes, ClientReply] = {}
        self._decided_cap = 4096
        # Leader-side dedupe: request_ids appended to the log but not yet
        # applied — a client's periodic resubmission (liveness across leader
        # changes) must not append duplicate log entries on a slow quorum.
        self._appending: set[bytes] = set()
        # In-memory mirror of recent log entries (idx -> (term, command)):
        # replication re-reads the same entries once per broadcast per peer,
        # and re-deserializing sqlite blobs each time made a 256-commit
        # burst O(n^2) in codec work. Evicted on truncate/compaction.
        self._entry_cache: dict[int, tuple[int, Any]] = {}
        # Coalesced replication: submit() marks the log dirty and
        # flush_appends()/tick() broadcasts ONCE per scheduling round — a
        # burst of submissions previously triggered one full broadcast EACH.
        self._append_dirty = False
        # Group commit (config.group_commit): leader-side buffer of commands
        # submitted this round, sealed into ONE PutAllBatch log entry by
        # flush_appends(). Drained with bounce replies if deposed mid-round.
        self._pending_batch: list[PutAllCommand] = []
        # Follower-side forwarding buffer: commands bound for the leader,
        # coalesced into one ClientCommitBatch frame per round.
        self._pending_forward: list[PutAllCommand] = []
        # Encoded-entry mirror (idx -> (term, blob)): the serialized form of
        # recent log entries, so replication never re-serializes an entry per
        # peer per broadcast. Evicted with _entry_cache on truncate/compact.
        self._blob_cache: dict[int, tuple[int, bytes]] = {}
        # Pipelined replication: highest index already streamed to each peer
        # on the current leadership (>= next_index-1). Broadcasts send only
        # (sent, sent+chunk] instead of re-sending the whole un-acked tail
        # every tick; heartbeats probe at prev=sent so a lost frame surfaces
        # as a failure reply that rewinds the stream.
        self._sent_index: dict[str, int] = {}
        # Per-peer exponential next_index backoff for hint-less failures
        # (doubles per consecutive failure, resets on success): a diverged
        # follower converges in O(log tail) round trips, not O(tail).
        self._backoff: dict[str, int] = {}
        # Replication RTT: first-broadcast clock per entry index, popped when
        # quorum commit passes it.
        self._bcast_at: dict[int, float] = {}
        # Tracing (obs/trace.py), all leader-local and empty when disarmed:
        # the flow trace ids riding each sealed log entry (idx -> hex list,
        # popped at quorum commit for the replication span) and the members
        # of the entry currently being appended (read by _log_append).
        self._trace_members: dict[int, list] = {}
        self._obs_members: list | None = None
        # Replication stamps (exported via node_metrics / loadtest / bench):
        # entries-per-batch, reply coalescing, RTT — the self-describing
        # numbers the commit-pipeline work is judged on.
        self.metrics = {
            "group_commits": 0,     # batched log entries sealed
            "group_commands": 0,    # commands coalesced into them
            "solo_commits": 0,      # single-command log entries
            "append_frames": 0,     # AppendEntries frames sent (incl. probes)
            "append_entries_sent": 0,  # log entries streamed inside them
            "reply_frames": 0,      # leader->member decision frames
            "reply_commands": 0,    # decisions inside them
            "forward_frames": 0,    # follower->leader commit frames
            "forward_commands": 0,  # commands inside them
            "replication_rtt_s": 0.0,  # broadcast -> quorum commit, summed
            "replication_rtt_n": 0,
            "qos_early_seals": 0,   # rounds sealed early for a deadline
            # Pipelined commit plane (round 18): mid-round seals (round N+1
            # sealed while N replicates), executor batches applied, and NEW
            # submissions shed off a full commit queue.
            "midround_seals": 0,
            "apply_batches": 0,
            "apply_shed": 0,
            # Durability plane (integrity.py): corrupt rows detected on the
            # log read paths, repairs taken, and disk-exhaustion degrades.
            "integrity_errors": 0,  # crc mismatches detected
            "log_truncations": 0,   # corrupt-suffix heals (truncate/compact)
            "leader_stepdowns": 0,  # leaderships ceded to corruption/disk
            "disk_degraded": 0,     # disk-full write failures absorbed
            # Partition plane (round 20): pre-vote canvasses started here,
            # canvass grants withheld here (live leader / stale log), and
            # leaderships ceded because a quorum of peers went silent
            # (check-quorum; these ALSO count in leader_stepdowns). All 0
            # with [raft] prevote off. elections_won counts every
            # leadership this member assumed — with prevote on, term and
            # elections_won stay bounded across a partition/heal cycle;
            # with it off, term inflates once per futile minority timeout.
            "prevotes": 0,
            "prevote_rejections": 0,
            "checkquorum_stepdowns": 0,
            "elections_won": 0,
        }
        # Leader seal-path phase accumulators (seconds), read as per-round
        # deltas by node.run_once to split its raft segment into the
        # round_breakdown's seal / replicate / apply phases. Unconditional
        # and unlocked: three perf_counter reads per flush, single
        # (node-loop) writer.
        self.phase_s = {"seal": 0.0, "replicate": 0.0, "apply": 0.0}
        # Pipelined commit plane (round 18): committed entries hand off to a
        # dedicated apply-executor thread through a bounded queue, so state
        # apply + reply construction overlap the consensus thread's next
        # seal/replicate pass. overlap_s accumulates executor wall time
        # (single writer: the executor thread) — kept OUT of phase_s so the
        # round_breakdown's coverage never double-counts overlapped time.
        self.overlap_s = {"apply": 0.0}
        # Lazily created with the executor thread (None = serial apply).
        self._apply_queue: _queue.Queue | None = None
        self._apply_thread: threading.Thread | None = None
        # Completed (idx, commands, replies, error) items, drained on the
        # consensus thread (deque appends/pops are thread-safe).
        self._apply_results: deque = deque()
        # Enqueue cursor: highest index handed to the executor. last_applied
        # only advances when results drain, so a crash mid-overlap replays
        # the queued suffix idempotently from the durable log.
        self._applied_enqueued = self.last_applied
        # Columnar fast path: make_apply_command exposes the batch variant
        # as an attribute on the apply closure (apply.many).
        self._commit_many = (getattr(apply_command, "many", None)
                             if self.config.commit_many else None)
        messaging.add_message_handler(RAFT_TOPIC, 0, self._on_message)

    # -- persistence -------------------------------------------------------

    def _save_meta(self) -> None:
        with self.db.lock:
            self.db.conn.execute(
                "UPDATE raft_meta SET term=?, voted_for=? WHERE singleton=1",
                (self.term, self.voted_for))
            self.db.commit()

    def _log_last(self) -> tuple[int, int]:
        row = self.db.conn.execute(
            "SELECT idx, term FROM raft_log ORDER BY idx DESC LIMIT 1"
        ).fetchone()
        return (row[0], row[1]) if row else (self.snapshot_index,
                                             self.snapshot_term)

    def _log_term_at(self, idx: int) -> int | None:
        if idx == 0:
            return 0
        if idx == self.snapshot_index:
            return self.snapshot_term
        cached = self._entry_cache.get(idx)
        if cached is not None:
            return cached[0]
        row = self.db.conn.execute(
            "SELECT term FROM raft_log WHERE idx=?", (idx,)).fetchone()
        return None if row is None else row[0]

    def _log_append(self, idx: int, term: int, command) -> None:
        if _faults.ACTIVE is not None:
            _faults.fire_fsync("raft.fsync")
            _faults.fire_disk_full()
        # Traced only on the leader's seal path (_obs_members set): the
        # serialize+insert is the raft_append span, the db.commit (sqlite's
        # fsync point outside batched rounds) is the fsync span.
        traced = _obs.ACTIVE is not None and self._obs_members is not None
        t0 = _obs.now() if traced else 0.0
        blob = serialize(command).bytes
        with self.db.lock:
            self.db.conn.execute(
                "INSERT OR REPLACE INTO raft_log (idx, term, blob, crc) "
                "VALUES (?, ?, ?, ?)",
                (idx, term, blob, _integrity.log_crc(idx, term, blob)))
            t1 = _obs.now() if traced else 0.0
            self.db.commit()
        if traced:
            attrs = {"member_traces": self._obs_members, "idx": idx}
            _obs.record("raft_append", t0, t1, attrs=attrs)
            _obs.record("fsync", t1, _obs.now(), attrs=attrs)
        self._entry_cache[idx] = (term, command)
        self._blob_cache[idx] = (term, blob)

    def _log_append_blob(self, idx: int, term: int, blob: bytes) -> None:
        """Follower-side append of a pre-encoded entry: the wire blob goes
        into raft_log verbatim (no decode on the replication hot path);
        deserialization happens lazily at apply time. The crc rides a
        separate column, so the stored blob stays byte-identical to the
        leader's."""
        if _faults.ACTIVE is not None:
            _faults.fire_fsync("raft.fsync")
            _faults.fire_disk_full()
        blob = bytes(blob)
        with self.db.lock:
            self.db.conn.execute(
                "INSERT OR REPLACE INTO raft_log (idx, term, blob, crc) "
                "VALUES (?, ?, ?, ?)",
                (idx, term, blob, _integrity.log_crc(idx, term, blob)))
            self.db.commit()
        self._entry_cache.pop(idx, None)
        self._blob_cache[idx] = (term, blob)

    def _log_truncate_from(self, idx: int) -> None:
        with self.db.lock:
            self.db.conn.execute("DELETE FROM raft_log WHERE idx >= ?", (idx,))
            self.db.commit()
        for cache in (self._entry_cache, self._blob_cache):
            for i in [i for i in cache if i >= idx]:
                del cache[i]

    def _verified_log_rows(self, idx: int, limit: int):
        """sqlite read path shared by _log_entries_from/_log_blobs_from:
        fetch rows, apply the seeded ``disk.corrupt`` fault (bit-flips on
        READ bytes — stored bytes stay intact, so repair genuinely
        recovers), and verify each row's crc frame. The first corrupt row
        triggers :meth:`_heal_corrupt_entry` and ends the batch — callers
        get the verified prefix, the healed member re-fetches the rest
        through normal replication."""
        rows = self.db.conn.execute(
            "SELECT idx, term, blob, crc FROM raft_log WHERE idx >= ? "
            "ORDER BY idx LIMIT ?", (idx, limit)).fetchall()
        out = []
        for r in rows:
            row_idx, row_term, blob = r[0], r[1], bytes(r[2])
            if _faults.ACTIVE is not None:
                blob = _faults.fire_disk_corrupt(blob)
            if r[3] is not None and \
                    _integrity.log_crc(row_idx, row_term, blob) != int(r[3]):
                self._heal_corrupt_entry(row_idx)
                break
            out.append((row_idx, row_term, blob))
        return out

    def _log_entries_from(self, idx: int, limit: int = 256):
        # Serve from the in-memory mirror when it covers the whole span.
        last_idx, _ = self._log_last()
        if idx > last_idx:
            return []
        span = range(idx, min(last_idx, idx + limit - 1) + 1)
        if all(i in self._entry_cache for i in span):
            return [(i, *self._entry_cache[i]) for i in span]
        out = []
        for row_idx, row_term, blob in self._verified_log_rows(idx, limit):
            entry = (row_idx, row_term, deserialize(blob))
            self._entry_cache[row_idx] = (entry[1], entry[2])
            out.append(entry)
        return out

    def _log_blobs_from(self, idx: int, limit: int = 256):
        """[(idx, term, blob)] — the replication read path. Serves encoded
        entries straight from the blob mirror (or sqlite bytes) with ZERO
        codec work: what the wire carries is exactly what the log stores."""
        last_idx, _ = self._log_last()
        if idx > last_idx or limit <= 0:
            return []
        span = range(idx, min(last_idx, idx + limit - 1) + 1)
        if all(i in self._blob_cache for i in span):
            return [(i, *self._blob_cache[i]) for i in span]
        out = []
        for entry in self._verified_log_rows(idx, limit):
            self._blob_cache[entry[0]] = (entry[1], entry[2])
            out.append(entry)
        return out

    def _heal_corrupt_entry(self, idx: int) -> None:
        """Self-healing for a corrupt log row detected at *idx*: corruption
        becomes a LAGGING member, never a diverged one.

        * ``idx > last_applied`` — the damaged entry's effects are not yet
          in the state machine: truncate the log from idx (the last
          verified prefix survives), clamp commit_index to what remains,
          and let next_index backoff / InstallSnapshot re-replicate.
        * ``idx <= last_applied`` — the effects are durable in
          committed_states: compact the applied prefix behind a snapshot
          marker (same ONE-transaction invariant as maybe_compact), which
          drops the damaged row legitimately.

        A leader additionally steps down: its log can no longer vouch for
        the range it was replicating (the corrupt-unreplicated-suffix
        case), and a healthy majority elects around it."""
        self.metrics["integrity_errors"] += 1
        self.metrics["log_truncations"] += 1
        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        was_leader = self.role == "leader"
        with self.db.lock:
            try:
                if idx <= self.last_applied:
                    upto = self.last_applied
                    term = self._log_term_at(upto)
                    if term is None:
                        term = self.snapshot_term
                    self.db.conn.execute(
                        "DELETE FROM raft_log WHERE idx <= ?", (upto,))
                    for key, value in (("raft_snapshot_index", str(upto)),
                                       ("raft_snapshot_term", str(term))):
                        self.db.conn.execute(
                            "INSERT OR REPLACE INTO settings (key, value) "
                            "VALUES (?, ?)", (key, value))
                    self.db.commit()
                    self.snapshot_index, self.snapshot_term = upto, int(term)
                    evict = lambda i: i <= upto  # noqa: E731
                else:
                    self.db.conn.execute(
                        "DELETE FROM raft_log WHERE idx >= ?", (idx,))
                    self.commit_index = min(self.commit_index, idx - 1)
                    self.db.conn.execute(
                        "INSERT OR REPLACE INTO settings (key, value) "
                        "VALUES (?, ?)",
                        ("raft_commit_index", str(self.commit_index)))
                    self.db.commit()
                    evict = lambda i: i >= idx  # noqa: E731
            except BaseException:
                if not self.db.in_batch:
                    self.db.conn.rollback()
                raise
        for cache in (self._entry_cache, self._blob_cache):
            for i in [i for i in cache if evict(i)]:
                del cache[i]
        if _obs.ACTIVE is not None:
            _obs.record("repair", t0, _obs.now(),
                        attrs={"kind": "raft_log", "idx": idx,
                               "node": self.name})
        if was_leader:
            self.metrics["leader_stepdowns"] += 1
            self._become_follower(self.term)

    # -- timers (driven from the node's run loop) --------------------------

    def _next_election_deadline(self) -> float:
        lo, hi = self.ELECTION_TIMEOUT
        # Randomized-timeout backoff: under a coarse scheduler (nodes pumped
        # round-robin, each round gated on fsync) the base window quantizes
        # to pump-cycle granularity and two candidates can split votes
        # REPEATEDLY. Each consecutive failed election widens the window, so
        # collisions decay geometrically instead of recurring for seconds.
        spread = 1.0 + 0.5 * min(self._election_attempts, 6)
        return self.clock() + self.rng.uniform(lo, hi * spread) * self.scale

    def tick(self) -> None:
        now = self.clock()
        if self.config.pipeline and self.config.apply_queue_depth > 0:
            # Pipelined plane: drain finished executor results (decision
            # bookkeeping + reply frames run on this thread) and top the
            # bounded queue back up from the committed-but-unapplied tail.
            # The enqueue check runs even with no live queue: after an
            # executor crash-reset the backlog must re-enqueue through a
            # FRESH executor without waiting for new commit traffic.
            if self._apply_queue is not None:
                self._drain_apply_results()
            if self._applied_enqueued < self.commit_index:
                self._enqueue_committed()
        if self.role == "leader":
            if self.config.prevote and not self._quorum_alive(now):
                # Check-quorum: a leader that cannot hear a quorum (e.g. it
                # landed on the minority side of a cut) steps down instead
                # of silently accepting submissions it can never commit —
                # clients get bounced to re-route promptly rather than
                # timing out against a zombie leader.
                self._checkquorum_stepdown()
            elif (self._append_dirty
                    or now - self._last_heartbeat
                    >= self.HEARTBEAT * self.scale):
                self.flush_appends()
        else:
            self._flush_forwards()
            if now >= self._election_deadline:
                if self.config.prevote:
                    self._start_prevote()
                else:
                    self._start_election()

    def flush_appends(self) -> None:
        """The commit pipeline's per-round flush: seal the round's buffered
        submissions into one group-commit log entry, replicate (single
        pipelined AppendEntries per peer per round, however many submissions
        the round coalesced) and advance local commit bookkeeping. On a
        follower, flushes the coalesced leader-forwarding buffer instead."""
        if self.role != "leader":
            self._flush_forwards()
            return
        t = _time.perf_counter
        t0 = t()
        self._seal_batch()
        self._append_dirty = False
        t1 = t()
        self._broadcast_append()
        t2 = t()
        self._advance_commit()
        ph = self.phase_s
        ph["seal"] += t1 - t0
        ph["replicate"] += t2 - t1
        ph["apply"] += t() - t2

    def _seal_batch(self) -> None:
        """Merge the round's buffered commands into ONE log entry (one
        sqlite insert, one fsync outside batched rounds, one AppendEntries
        slot). A single command appends bare — the wire/apply path for
        un-batched traffic is byte-identical to the pre-group-commit one."""
        if not self._pending_batch:
            return
        cmds = tuple(self._pending_batch)
        self._pending_batch.clear()
        last_idx, _ = self._log_last()
        if _obs.ACTIVE is not None:
            # The flow traces riding this entry (link map filled by
            # commit_async on THIS process — the hot path, where the flow
            # node is the leader; forwarded commands have no link and are
            # an honest attribution gap).
            members = []
            for cmd in cmds:
                link = _obs.ACTIVE.peek_link(cmd.request_id)
                if link is not None:
                    members.append(link[0].hex())
            self._obs_members = members or None
            if members:
                self._trace_members[last_idx + 1] = members
        try:
            if len(cmds) == 1:
                self.metrics["solo_commits"] += 1
                self._log_append(last_idx + 1, self.term, cmds[0])
            else:
                self.metrics["group_commits"] += 1
                self.metrics["group_commands"] += len(cmds)
                self._log_append(last_idx + 1, self.term, PutAllBatch(cmds))
            if _tm.ACTIVE is not None:
                _tm.inc("raft_seals_total")
                _tm.inc("raft_seal_entries_total", len(cmds))
                _tm.observe("raft_seal_entries", len(cmds))
        except sqlite3.OperationalError as e:
            if not _integrity.is_disk_full(e):
                raise
            # Graceful disk exhaustion: a leader that cannot extend its log
            # must stop leading, not crash the process. The round's commands
            # were never sealed — restore them so _depose bounces each with
            # a retryable reply, and cede leadership to a member that can
            # still write.
            self.metrics["disk_degraded"] += 1
            self._trace_members.pop(last_idx + 1, None)
            self._pending_batch = list(cmds)
            self._become_follower(self.term)
        finally:
            self._obs_members = None

    def _flush_forwards(self) -> None:
        """Coalesced follower->leader forwarding: the round's buffered
        commands ride one ClientCommitBatch frame. No known leader by flush
        time: bounce each so the waiting flows re-route/resubmit."""
        if not self._pending_forward:
            return
        cmds, self._pending_forward = tuple(self._pending_forward), []
        if self.role == "leader":
            for cmd in cmds:  # elected between buffer and flush
                self.submit(cmd)
            return
        addr = (self.peers.get(self.leader_name)
                if self.leader_name is not None else None)
        if addr is None:
            for cmd in cmds:
                self._record_decision(cmd.request_id, ClientReply(
                    cmd.request_id, False, None, self.leader_name))
            return
        self.metrics["forward_frames"] += 1
        self.metrics["forward_commands"] += len(cmds)
        qos_wire = None
        plane = _qos.ACTIVE
        if plane is not None:
            encoded = tuple(
                ctx.to_wire() if ctx is not None else b""
                for ctx in (plane.peek_link(cmd.request_id) for cmd in cmds))
            if any(encoded):
                qos_wire = encoded
        if qos_wire is not None:
            self._send(addr, ClientCommitBatchQos(cmds, self.name, qos_wire))
        elif len(cmds) == 1:
            self._send(addr, ClientCommit(cmds[0], self.name))
        else:
            self._send(addr, ClientCommitBatch(cmds, self.name))

    # -- roles -------------------------------------------------------------

    def _become_follower(self, term: int, leader: str | None = None) -> None:
        if term > self.term:
            self.term, self.voted_for = term, None
            self._save_meta()
        was_leader = self.role == "leader"
        self.role = "follower"
        # Any follower transition invalidates an in-flight canvass (a live
        # leader or higher term appeared) and the candidacy span anchor;
        # harmless no-ops when prevote off.
        self._prevoting = False
        self._candidacy_t0 = None
        if leader is not None:
            self.leader_name = leader
            self._election_attempts = 0  # a live leader resets the backoff
        self._election_deadline = self._next_election_deadline()
        if was_leader:
            self._depose()

    def _depose(self) -> None:
        """Leader change mid-batch: commands buffered but never sealed into
        the log bounce back (ok=False + leader hint) so their clients
        re-route to the new leader — order is preserved by the resubmit
        protocol, and apply idempotency absorbs any entry that DID make the
        old log and survives. Leader-only bookkeeping resets with them:
        stale _appending ids must not swallow a resubmission if this member
        is re-elected later (the log they referenced may have been
        truncated), and the pipeline/RTT state is meaningless without
        leadership."""
        pending, self._pending_batch = list(self._pending_batch), []
        for cmd in pending:
            fwd = getattr(self, "_forward_replies", {}).pop(
                cmd.request_id, None)
            reply = ClientReply(cmd.request_id, False, None, self.leader_name)
            addr = self._peer_addr(fwd)
            if addr is not None:
                self._send(addr, reply)
            else:
                self._record_decision(cmd.request_id, reply)
        self._appending.clear()
        self._sent_index.clear()
        self._backoff.clear()
        self._bcast_at.clear()
        self._trace_members.clear()

    def _start_election(self) -> None:
        if self.role == "candidate":
            self._election_attempts += 1  # previous election went nowhere
        if self._candidacy_t0 is None:
            # Canvass-initiated elections already stamped candidacy start;
            # a direct (prevote-off) election starts its span here.
            self._candidacy_t0 = self.clock()
        self.term += 1
        self.voted_for = self.name
        self._save_meta()
        self.role = "candidate"
        self.leader_name = None
        self._votes = {self.name}
        self._election_deadline = self._next_election_deadline()
        last_idx, last_term = self._log_last()
        msg = RequestVote(self.term, self.name, last_idx, last_term)
        for peer in self.peers.values():
            self._send(peer, msg)
        self._maybe_win()

    def _maybe_win(self) -> None:
        if self.role != "candidate":
            return
        if len(self._votes) * 2 > len(self.peers) + 1:
            self.role = "leader"
            self.leader_name = self.name
            self._election_attempts = 0
            self.metrics["elections_won"] += 1
            now = self.clock()
            if self.config.prevote:
                # Check-quorum baseline: every peer counts as heard-from at
                # the moment of the win, so a fresh leadership gets a full
                # window to establish contact before step-down can trigger.
                self._peer_contact = {p: now for p in self.peers}
            if _obs.ACTIVE is not None and self._candidacy_t0 is not None:
                # Re-anchor the candidacy (monotonic clock) onto the epoch
                # timeline ending now — same convention as the replication
                # span in _advance_commit.
                epoch = _obs.now()
                _obs.record(
                    "election",
                    epoch - (now - self._candidacy_t0), epoch,
                    attrs={"term": self.term,
                           "prevote": bool(self.config.prevote)})
            self._candidacy_t0 = None
            last_idx, _ = self._log_last()
            self._next_index = {p: last_idx + 1 for p in self.peers}
            self._match_index = {p: 0 for p in self.peers}
            # Pipeline state is per-leadership: nothing streamed yet.
            self._sent_index = {p: last_idx for p in self.peers}
            self._backoff.clear()
            self._bcast_at.clear()
            self._broadcast_append()  # assert leadership immediately

    # -- client interface --------------------------------------------------

    def submit(self, command: PutAllCommand) -> None:
        """Start replication of a command; the outcome appears in
        self.decided[request_id] once committed (possibly ok=False with a
        leader hint if this member cannot get it committed)."""
        if self.role == "leader":
            if command.request_id in self._appending:
                return  # already replicating; resubmission is a no-op
            if self.apply_overloaded():
                # Bounded-queue backpressure: the apply executor is full, so
                # NEW submissions shed with a retryable bounce instead of
                # growing an unbounded committed-but-unapplied backlog.
                # In-flight commands (already in _appending) are never shed
                # — committed work always drains.
                self.metrics["apply_shed"] += 1
                if _tm.ACTIVE is not None:
                    _tm.inc("raft_apply_shed_total")
                fwd = getattr(self, "_forward_replies", {}).pop(
                    command.request_id, None)
                reply = ClientReply(command.request_id, False, None,
                                    self.leader_name)
                addr = self._peer_addr(fwd)
                if addr is not None:
                    self._send(addr, reply)
                else:
                    self._record_decision(command.request_id, reply)
                return
            self._appending.add(command.request_id)
            if self.config.group_commit:
                # Group commit: buffer; flush_appends() seals the round's
                # burst into ONE PutAllBatch log entry (one insert/fsync/
                # AppendEntries slot for every command in the burst).
                self._pending_batch.append(command)
            else:
                last_idx, _ = self._log_last()
                self._log_append(last_idx + 1, self.term, command)
            # Coalesced: flush_appends()/tick() broadcasts once per round,
            # covering every command submitted in the burst.
            self._append_dirty = True
            if _qos.ACTIVE is not None and self._qos_should_seal():
                # Deadline-aware group commit (queueing point 3 of the QoS
                # plane): an interactive entry in the round's buffer is
                # about to breach its SLO deadline — seal and replicate NOW
                # instead of waiting for the scheduling round to close.
                self.metrics["qos_early_seals"] += 1
                if _obs.ACTIVE is not None:
                    mark = _obs.now()
                    _obs.record("qos_flush", mark, mark,
                                attrs={"point": "raft_seal"})
                self.flush_appends()
            if (self.config.pipeline
                    and len(self._pending_batch) >= self.config.append_chunk
                    and (self._log_last()[0] - self.commit_index
                         < self.config.pipeline_window)):
                # Pipelined rounds: a full append_chunk of buffered commands
                # seals and broadcasts MID-ROUND (round N+1 starts
                # replicating while round N's entries are still in flight in
                # the per-peer pipeline window), instead of waiting for the
                # scheduling round to close. The window bound keeps a stalled
                # quorum from piling unacked entries without limit.
                self.metrics["midround_seals"] += 1
                self.flush_appends()
        elif self.leader_name is not None and self.leader_name in self.peers:
            # Buffered: tick()/flush_appends() forwards the round's commands
            # in one ClientCommitBatch frame.
            self._pending_forward.append(command)
        else:
            self.decided[command.request_id] = ClientReply(
                command.request_id, False, None, self.leader_name)

    def _qos_should_seal(self) -> bool:
        """True when some buffered command's QoS context (link map filled
        by commit_async locally or by ClientCommitBatchQos for forwarded
        commands) is an interactive entry near its deadline. The deadline
        evaluation — the only clock read — lives in the QoS plane, never
        here: consensus code stays wall-clock-free (the no-wallclock-in-
        apply invariant)."""
        plane = _qos.ACTIVE
        if plane is None or not self._pending_batch:
            return False
        for cmd in self._pending_batch:
            qctx = plane.peek_link(cmd.request_id)
            if qctx is not None and plane.near_deadline(qctx):
                return True
        return False

    # -- message handling --------------------------------------------------

    def _peer_addr(self, name: str | None):
        """Transport address for a member name: this group's peers first,
        then the injected netmap resolver (cross-group 2PC reply routing)."""
        if name is None:
            return None
        addr = self.peers.get(name)
        if addr is None and self.resolve_addr is not None:
            addr = self.resolve_addr(name)
        return addr

    def _send(self, to, payload) -> None:
        if _faults.ACTIVE is not None and isinstance(
                payload, (AppendEntries, AppendReply)):
            # raft.append: only the replication stream — votes stay intact
            # so an armed plan cannot make leader election itself impossible.
            act = _faults.ACTIVE.fire("raft.append")
            if act is not None:
                action, delay_s = act
                if action == "drop":
                    return
                if action in ("delay", "reorder") and delay_s > 0:
                    _time.sleep(delay_s)
                elif action == "duplicate":
                    self.messaging.send(TopicSession(RAFT_TOPIC, 0),
                                        serialize(payload).bytes, to)
        self.messaging.send(TopicSession(RAFT_TOPIC, 0),
                            serialize(payload).bytes, to)

    def _on_message(self, message) -> None:
        try:
            payload = deserialize(message.data)
        except Exception:
            return
        if isinstance(payload, RequestVote):
            self._on_request_vote(payload, message.sender)
        elif isinstance(payload, VoteReply):
            self._on_vote_reply(payload)
        elif isinstance(payload, PreVote):
            self._on_prevote(payload, message.sender)
        elif isinstance(payload, PreVoteReply):
            self._on_prevote_reply(payload)
        elif isinstance(payload, AppendEntries):
            self._on_append(payload, message.sender)
        elif isinstance(payload, AppendReply):
            self._on_append_reply(payload)
        elif isinstance(payload, ClientCommit):
            self._on_client_commit(payload)
        elif isinstance(payload, ClientCommitBatch):
            for cmd in payload.commands:
                self._on_client_commit(ClientCommit(cmd, payload.reply_to))
        elif isinstance(payload, ClientCommitBatchQos):
            plane = _qos.ACTIVE
            for cmd, qw in zip(payload.commands, payload.qos):
                if plane is not None and qw:
                    qctx = _qos.QosContext.from_wire(qw)
                    if qctx is not None:
                        plane.register_link(cmd.request_id, qctx)
                self._on_client_commit(ClientCommit(cmd, payload.reply_to))
        elif isinstance(payload, ClientReply):
            self._record_decision(payload.request_id, payload)
        elif isinstance(payload, ClientReplyBatch):
            # Idempotent per reply: a redelivered batch re-records decisions
            # already recorded (each waiting request polls its id at most
            # once, so duplicates are absorbed, never re-applied).
            for reply in payload.replies:
                self._record_decision(reply.request_id, reply)
        elif isinstance(payload, InstallSnapshot):
            self._on_install_snapshot(payload, message.sender)
        elif isinstance(payload, InstallSnapshotReply):
            if payload.term > self.term:
                self._become_follower(payload.term)
            elif self.role == "leader":
                self._peer_contact[payload.follower] = self.clock()
                match = max(self._match_index.get(payload.follower, 0),
                            payload.last_included_index)
                self._match_index[payload.follower] = match
                # Never move next_index BACKWARDS past what the follower
                # already matched (a stale snapshot reply must not restart
                # replication behind a fresher position).
                self._next_index[payload.follower] = max(
                    self._next_index.get(payload.follower, 1), match + 1)

    def _on_request_vote(self, rv: RequestVote, sender) -> None:
        if rv.term > self.term:
            self._become_follower(rv.term)
        granted = False
        if rv.term == self.term and self.voted_for in (None, rv.candidate):
            last_idx, last_term = self._log_last()
            up_to_date = (rv.last_log_term, rv.last_log_index) >= (
                last_term, last_idx)
            if up_to_date:
                granted = True
                self.voted_for = rv.candidate
                self._save_meta()
                self._election_deadline = self._next_election_deadline()
        if (not granted and self.role == "candidate"
                and rv.term == self.term):
            # Symmetric-candidacy livelock breaker (observed under a coarse
            # round-robin scheduler whose pump cycle exceeded the election
            # timeout: both members' timers expired EVERY cycle, each voted
            # for itself each term, forever). Safety-neutral tiebreak — the
            # vote stays rejected (no double voting); the LOWER-priority
            # candidate merely stops racing: it steps down and sits out a
            # full election window, so the rival runs the next term alone.
            last_idx, last_term = self._log_last()
            rival_priority = ((rv.last_log_term, rv.last_log_index,
                               rv.candidate)
                              >= (last_term, last_idx, self.name))
            if rival_priority:
                self.role = "follower"
                # Long enough for the rival's next election AND its
                # RequestVote to traverse a slow pump cycle before our
                # timer can fire again.
                lo, hi = self.ELECTION_TIMEOUT
                self._election_deadline = self.clock() + 4 * hi * self.scale
        self._send(sender, VoteReply(self.term, granted, self.name))

    def _on_vote_reply(self, vr: VoteReply) -> None:
        if vr.term > self.term:
            self._become_follower(vr.term)
            return
        if self.role == "candidate" and vr.term == self.term and vr.granted:
            self._votes.add(vr.voter)
            self._maybe_win()

    # -- pre-vote / check-quorum (partition plane, round 20) ---------------

    def _start_prevote(self) -> None:
        """Canvass at term+1 WITHOUT touching persisted state: role stays
        follower, term/voted_for untouched, nothing fsynced. Only a
        majority of would-grant replies converts into a real election —
        so a member that spent the cut on the minority side times out
        forever without inflating the cluster term, and rejoins at heal
        as a follower instead of deposing the healthy leader."""
        self._prevoting = True
        self._prevote_votes = {self.name}
        self._candidacy_t0 = self.clock()
        self.metrics["prevotes"] += 1
        if _tm.ACTIVE is not None:
            _tm.inc("raft_prevotes_total")
        self._election_deadline = self._next_election_deadline()
        last_idx, last_term = self._log_last()
        msg = PreVote(self.term + 1, self.name, last_idx, last_term)
        for peer in self.peers.values():
            self._send(peer, msg)
        self._maybe_canvass_win()

    def _maybe_canvass_win(self) -> None:
        if not self._prevoting:
            return
        if len(self._prevote_votes) * 2 > len(self.peers) + 1:
            self._prevoting = False
            self._start_election()

    def _on_prevote(self, pv: PreVote, sender) -> None:
        """Answer a canvass. NEVER mutates term/voted_for/role — granting
        here is a promise-free opinion ("I would vote for you"), so
        concurrent canvassers are harmless. Withheld when this member is
        the leader or heard from one within the MINIMUM election window
        (§9.6 leader stickiness: a live leader's cluster refuses to be
        disrupted), or when the canvasser's log is behind."""
        granted = False
        if pv.term >= self.term:
            last_idx, last_term = self._log_last()
            up_to_date = (pv.last_log_term, pv.last_log_index) >= (
                last_term, last_idx)
            lo, _hi = self.ELECTION_TIMEOUT
            leader_live = (
                self.role == "leader"
                or (self.leader_name is not None
                    and self.clock() - self._last_leader_contact
                    < lo * self.scale))
            granted = up_to_date and not leader_live
        if not granted:
            self.metrics["prevote_rejections"] += 1
            if _tm.ACTIVE is not None:
                _tm.inc("raft_prevote_rejections_total")
        self._send(sender, PreVoteReply(self.term, granted, self.name))

    def _on_prevote_reply(self, pvr: PreVoteReply) -> None:
        if pvr.term > self.term:
            # A peer is ahead: adopt its term quietly (no election) — the
            # exact rejoin path the canvass exists for.
            self._become_follower(pvr.term)
            return
        if self._prevoting and pvr.granted:
            self._prevote_votes.add(pvr.voter)
            self._maybe_canvass_win()

    def _quorum_alive(self, now: float) -> bool:
        """Leader-side check-quorum: does a majority (self included) have
        a reply newer than the check window? The window is twice the max
        election timeout — wide enough that one slow pump cycle cannot
        fake a partition, narrow enough that a minority-side leader cedes
        within a couple of election windows of the cut."""
        _lo, hi = self.ELECTION_TIMEOUT
        window = 2 * hi * self.scale
        alive = 1 + sum(
            1 for p in self.peers
            if now - self._peer_contact.get(p, 0.0) <= window)
        return alive * 2 > len(self.peers) + 1

    def _checkquorum_stepdown(self) -> None:
        self.metrics["leader_stepdowns"] += 1
        self.metrics["checkquorum_stepdowns"] += 1
        if _tm.ACTIVE is not None:
            _tm.inc("raft_checkquorum_stepdowns_total")
        # No known successor: clients bounce with leader hint None and
        # re-derive the leader after the (majority-side) election.
        self.leader_name = None
        self._become_follower(self.term)

    COMPACT_THRESHOLD = 256  # log entries kept before compacting applied ones
    SNAPSHOT_CHUNK = 10_000  # map entries per InstallSnapshot frame

    def _broadcast_append(self) -> None:
        if self.role != "leader":
            # A disk-full degrade or corruption heal inside this round's
            # seal/read path stepped us down: nothing to broadcast.
            return
        self._last_heartbeat = now = self.clock()
        for peer_name, addr in self.peers.items():
            nxt = self._next_index.get(peer_name, 1)
            if nxt <= self.snapshot_index:
                # The entries this peer needs were compacted away: ship the
                # applied state instead (DistributedImmutableMap
                # snapshot/install capability). Throttled — a snapshot is
                # O(map) to read+serialize, so don't re-send every heartbeat
                # while one is in flight — and CHUNKED so a large map never
                # exceeds the transport frame cap.
                sent_at = self._snapshot_sent_at.get(peer_name, 0.0)
                backlog_fn = getattr(self.messaging, "outbox_backlog", None)
                backlog = backlog_fn(addr) if backlog_fn is not None else 0
                if backlog > 64:
                    # Peer unreachable: even keepalives must stop piling into
                    # its durable outbox (they redeliver on reconnect anyway).
                    continue
                if (now - sent_at >= 10 * self.HEARTBEAT * self.scale
                        and backlog <= 8):
                    # Backlog gate: a live peer ACKs frames and stays near
                    # zero; an unreachable one accumulates them, and its
                    # durable outbox must NOT gain a superseded snapshot
                    # series every throttle window.
                    self._snapshot_sent_at[peer_name] = now
                    content = self._state_machine_content()
                    reservations = self._reservation_content()
                    chunks = []
                    for off in range(0, max(len(content), 1),
                                     self.SNAPSHOT_CHUNK):
                        chunk = content[off:off + self.SNAPSHOT_CHUNK]
                        done = off + self.SNAPSHOT_CHUNK >= len(content)
                        chunks.append(serialize(InstallSnapshot(
                            self.term, self.name, self.snapshot_index,
                            self.snapshot_term, chunk, off, done,
                            reservations if done else (),
                            crc=_snapshot_chunk_crc(chunk))).bytes)
                    # The whole ordered series hits the durable outbox as
                    # one burst (one executemany/fsync, one bridge wakeup).
                    self._send_burst(addr, chunks)
                # Keep the follower's election timer fed between snapshot
                # rounds with a prev=0 keepalive: index 0 exists on every
                # member, so this ALWAYS succeeds (reply match=0, absorbed by
                # the monotone success path) and never generates the failure
                # churn an un-appendable heartbeat would.
                self._send(addr, AppendEntries(
                    self.term, self.name, 0, 0, (), self.commit_index))
                self.metrics["append_frames"] += 1
                continue
            # Pipelined streaming: send only entries this peer has not been
            # sent on this leadership (a long tail goes out ONCE in bounded
            # chunks, not re-sent wholesale every tick), capped so at most
            # pipeline_window entries ride un-acked beyond next_index.
            sent = max(self._sent_index.get(peer_name, nxt - 1), nxt - 1)
            room = min(self.config.append_chunk,
                       self.config.pipeline_window - (sent - (nxt - 1)))
            blobs = self._log_blobs_from(sent + 1, limit=room)
            if self.role != "leader":
                return  # a corrupt row in the read span healed + stepped down
            if blobs:
                prev_idx = sent
                entries = tuple((term, blob) for _i, term, blob in blobs)
                sent = blobs[-1][0]
            else:
                # Caught up (or window full): probe at prev=sent — success
                # advances match past everything streamed; failure rewinds
                # the stream to wherever the follower actually diverged.
                prev_idx, entries = sent, ()
            prev_term = self._log_term_at(prev_idx) or 0
            self._send(addr, AppendEntries(
                self.term, self.name, prev_idx, prev_term, entries,
                self.commit_index))
            self.metrics["append_frames"] += 1
            self.metrics["append_entries_sent"] += len(entries)
            for i, _t, _b in blobs:
                self._bcast_at.setdefault(i, now)  # replication RTT start
            self._sent_index[peer_name] = sent

    def _send_burst(self, to, payloads) -> None:
        """Multi-frame burst to one peer: one outbox executemany + one
        bridge wakeup when the transport supports it (TcpMessaging
        send_many); falls back to per-frame sends on fakes."""
        send_many = getattr(self.messaging, "send_many", None)
        if send_many is not None:
            send_many(TopicSession(RAFT_TOPIC, 0), payloads, to)
        else:
            for payload in payloads:
                self.messaging.send(TopicSession(RAFT_TOPIC, 0), payload, to)

    def _state_machine_content(self) -> tuple:
        rows = self.db.conn.execute(
            "SELECT state_ref, consuming FROM committed_states").fetchall()
        return tuple((bytes(r[0]), bytes(r[1])) for r in rows)

    def _reservation_content(self) -> tuple:
        """Live 2PC holds — part of the replicated state (a snapshot-
        installed follower without them would let a PutAll through a hold
        the rest of the group honours). Small by construction: in-flight
        reservations, not history."""
        rows = self.db.conn.execute(
            "SELECT state_ref, tx_id, expires_at FROM reserved_states"
        ).fetchall()
        return tuple((bytes(r[0]), bytes(r[1]), float(r[2])) for r in rows)

    def maybe_compact(self) -> None:
        """Drop applied log entries once the log outgrows the threshold —
        their effects live durably in committed_states; lagging peers get an
        InstallSnapshot instead of replay."""
        (log_len,) = self.db.conn.execute(
            "SELECT COUNT(*) FROM raft_log").fetchone()
        if log_len <= self.COMPACT_THRESHOLD:
            return
        # Retain a tail so slightly-behind followers get AppendEntries, and
        # respect follower match positions — but only down to a FLOOR: a
        # dead peer must not pin the log forever (it will get a snapshot).
        upto = self.last_applied - self.COMPACT_THRESHOLD // 2
        if self.role == "leader" and self._match_index:
            floor = self.last_applied - 4 * self.COMPACT_THRESHOLD
            upto = min(upto, max(min(self._match_index.values()), floor))
        if upto <= self.snapshot_index:
            return
        term = self._log_term_at(upto)
        if term is None:
            return
        with self.db.lock:
            # Log prefix deletion and the snapshot marker must be ONE
            # transaction: a crash between them would leave a log whose
            # indices silently rebase to 1 — replicated-log corruption.
            try:
                self.db.conn.execute(
                    "DELETE FROM raft_log WHERE idx <= ?", (upto,))
                for key, value in (("raft_snapshot_index", str(upto)),
                                   ("raft_snapshot_term", str(term))):
                    self.db.conn.execute(
                        "INSERT OR REPLACE INTO settings (key, value) "
                        "VALUES (?, ?)", (key, value))
                self.db.commit()
            except BaseException:
                # A failure between the DELETE and the marker write must not
                # leave the half-compacted prefix in the open transaction —
                # a later unrelated commit would persist it WITHOUT the
                # marker, silently rebasing log indices.
                if not self.db.in_batch:
                    self.db.conn.rollback()
                raise
        for cache in (self._entry_cache, self._blob_cache):
            for i in [i for i in cache if i <= upto]:
                del cache[i]
        self.snapshot_index, self.snapshot_term = upto, term

    def _on_install_snapshot(self, snap: InstallSnapshot, sender) -> None:
        if snap.term < self.term:
            # Reply with our term so a deposed leader steps down instead of
            # re-sending the snapshot every heartbeat forever.
            self._send(sender, InstallSnapshotReply(self.term, self.name, 0))
            return
        self._become_follower(snap.term, leader=snap.leader)
        if snap.crc and _snapshot_chunk_crc(snap.entries) != snap.crc:
            # Damaged chunk: drop the whole staged series rather than
            # install bad ledger rows — the leader re-sends on its throttle.
            self.metrics["integrity_errors"] += 1
            self._snapshot_staging = None
            return
        # Chunk assembly: chunks of one snapshot series arrive in order on
        # the same bridge; offset 0 restarts staging, mismatched continuation
        # discards (the leader re-sends the series on its throttle).
        series_key = (snap.term, snap.last_included_index)
        if snap.offset == 0:
            self._snapshot_staging = (series_key, list(snap.entries))
        else:
            staged = getattr(self, "_snapshot_staging", None)
            if staged is None or staged[0] != series_key \
                    or len(staged[1]) != snap.offset:
                return  # out-of-sequence chunk: wait for a fresh series
            staged[1].extend(snap.entries)
        if not snap.done:
            return
        entries = tuple(self._snapshot_staging[1])
        self._snapshot_staging = None
        if snap.last_included_index > self.last_applied:
            new_commit = max(self.commit_index, snap.last_included_index)
            with self.db.lock:
                # State replacement + markers in ONE transaction (crash
                # between them would desync applied state from the log view).
                self.db.conn.execute("DELETE FROM committed_states")
                self.db.conn.executemany(
                    "INSERT OR REPLACE INTO committed_states "
                    "(state_ref, consuming, crc) VALUES (?, ?, ?)",
                    [(ref, con, _integrity.committed_crc(
                        bytes(ref), bytes(con)))
                     for ref, con in entries])
                self.db.conn.execute("DELETE FROM reserved_states")
                self.db.conn.executemany(
                    "INSERT OR REPLACE INTO reserved_states "
                    "(state_ref, tx_id, expires_at, crc) "
                    "VALUES (?, ?, ?, ?)",
                    [(bytes(ref), bytes(tx), float(exp),
                      _integrity.reserved_crc(
                          bytes(ref), bytes(tx), float(exp)))
                     for ref, tx, exp in snap.reservations])
                self._entry_cache.clear()
                self._blob_cache.clear()
                self.db.conn.execute("DELETE FROM raft_log")
                for key, value in (
                        ("raft_snapshot_index",
                         str(snap.last_included_index)),
                        ("raft_snapshot_term",
                         str(snap.last_included_term)),
                        ("raft_commit_index", str(new_commit)),
                        ("raft_last_applied",
                         str(snap.last_included_index))):
                    self.db.conn.execute(
                        "INSERT OR REPLACE INTO settings (key, value) "
                        "VALUES (?, ?)", (key, value))
                self.db.commit()
            self.last_applied = snap.last_included_index
            self.commit_index = new_commit
            self.snapshot_index = snap.last_included_index
            self.snapshot_term = snap.last_included_term
            # Pipelined plane: the enqueue cursor must never trail a
            # snapshot-installed last_applied (the log prefix it pointed
            # into was just replaced). Stale queued items drain harmlessly:
            # their rows are part of the installed snapshot state and the
            # drain never moves last_applied backwards.
            self._applied_enqueued = max(self._applied_enqueued,
                                         self.last_applied)
        self._send(sender, InstallSnapshotReply(
            self.term, self.name, snap.last_included_index))

    def _on_append(self, ae: AppendEntries, sender) -> None:
        if ae.term < self.term:
            self._send(sender, AppendReply(self.term, False, 0, self.name))
            return
        self._become_follower(ae.term, leader=ae.leader)
        # Leader-stickiness stamp (round 20): any valid append — heartbeat
        # or entries — counts as live-leader contact for _on_prevote.
        self._last_leader_contact = self.clock()
        local_prev = self._log_term_at(ae.prev_index)
        if local_prev is None or local_prev != ae.prev_term:
            self._send(sender, AppendReply(
                self.term, False, 0, self.name,
                hint_index=self._log_last()[0]))
            return
        idx = ae.prev_index
        try:
            for term, blob in ae.entries:
                idx += 1
                existing = self._log_term_at(idx)
                if existing is not None and existing != term:
                    self._log_truncate_from(idx)
                    existing = None
                if existing is None:
                    # The wire carries the leader's encoded blob: insert it
                    # verbatim (no decode on the replication hot path).
                    self._log_append_blob(idx, term, blob)
        except sqlite3.OperationalError as e:
            if not _integrity.is_disk_full(e):
                raise
            # Graceful disk exhaustion on the follower append path: the
            # entries landed up to a verified prefix; reply failure with an
            # honest hint so the leader rewinds and retries later, instead
            # of crashing the member out of the quorum.
            self.metrics["disk_degraded"] += 1
            self._send(sender, AppendReply(
                self.term, False, 0, self.name,
                hint_index=self._log_last()[0]))
            return
        if ae.leader_commit > self.commit_index:
            # Raft §5.3: commit only up to the VERIFIED prefix — the index of
            # the last entry THIS append confirmed (prev + entries) — never
            # the whole local log, which may hold stale divergent entries a
            # prev=0 keepalive did not vouch for.
            self.commit_index = max(self.commit_index,
                                    min(ae.leader_commit, idx))
            self._apply_committed()
        self._send(sender, AppendReply(self.term, True, idx, self.name))

    def _on_append_reply(self, ar: AppendReply) -> None:
        if ar.term > self.term:
            self._become_follower(ar.term)
            return
        if self.role != "leader":
            return
        # Check-quorum stamp (round 20): ANY append reply — success or
        # divergence backoff — proves the peer is reachable.
        self._peer_contact[ar.follower] = self.clock()
        if ar.success:
            # Monotone: a success for an EARLIER position (e.g. the prev=0
            # keepalive heartbeat used during snapshot transfer) must not
            # move match/next backwards.
            match = max(self._match_index.get(ar.follower, 0), ar.match_index)
            self._match_index[ar.follower] = match
            self._next_index[ar.follower] = max(
                self._next_index.get(ar.follower, 1), match + 1)
            # The pipeline stream stays ahead of (or at) the acked position.
            self._sent_index[ar.follower] = max(
                self._sent_index.get(ar.follower, 0), match)
            self._backoff.pop(ar.follower, None)
            self._advance_commit()
        else:
            nxt = self._next_index.get(ar.follower, 1)
            if ar.hint_index >= 0 and ar.hint_index < nxt - 1:
                # Jump straight past what the follower actually has (covers
                # an empty-log follower — hint 0 — one freshly snapshot-
                # installed beyond our compaction point, AND one that lost
                # its disk: no clamping against match_index here, because a
                # wiped follower's truth supersedes our stale bookkeeping).
                nxt = ar.hint_index + 1
                self._backoff.pop(ar.follower, None)
            else:
                # Hint-less (or useless-hint) divergence: back off by a
                # per-peer window that DOUBLES each consecutive failure —
                # O(log tail) round trips to converge instead of the old
                # decrement-by-one's O(tail).
                step = self._backoff.get(ar.follower, 1)
                self._backoff[ar.follower] = min(
                    step * 2, self.config.append_chunk)
                nxt = max(1, nxt - step)
            self._next_index[ar.follower] = nxt
            # Rewind the stream: everything past the new next_index must be
            # re-sent once the divergence point is found.
            self._sent_index[ar.follower] = nxt - 1

    _forward_replies: dict

    def _on_client_commit(self, cc: ClientCommit) -> None:
        if self.role == "leader":
            if not hasattr(self, "_forward_replies"):
                self._forward_replies = {}
            # Remember where to send the decision, then replicate.
            self._forward_replies[cc.command.request_id] = cc.reply_to
            self.submit(cc.command)
        else:
            # Not the leader anymore: bounce with a hint so the origin
            # re-routes after its next ticks. The origin may live in ANOTHER
            # Raft group (a cross-shard 2PC coordinator) — resolve beyond
            # this group's peers.
            addr = self._peer_addr(cc.reply_to)
            if addr is not None:
                self._send(addr, ClientReply(
                    cc.command.request_id, False, None, self.leader_name))

    def _advance_commit(self) -> None:
        if self.role != "leader":
            return
        prev_commit = self.commit_index
        last_idx, _ = self._log_last()
        for n in range(self.commit_index + 1, last_idx + 1):
            votes = 1 + sum(
                1 for m in self._match_index.values() if m >= n)
            if votes * 2 > len(self.peers) + 1 and \
                    self._log_term_at(n) == self.term:
                self.commit_index = n
        if self.commit_index > prev_commit:
            # Replication RTT: first broadcast of an entry -> quorum commit.
            now = self.clock()
            for n in range(prev_commit + 1, self.commit_index + 1):
                t0 = self._bcast_at.pop(n, None)
                if t0 is not None:
                    self.metrics["replication_rtt_s"] += now - t0
                    self.metrics["replication_rtt_n"] += 1
                    if _obs.ACTIVE is not None:
                        members = self._trace_members.pop(n, None)
                        if members:
                            # The RTT clock is monotonic; re-anchor the span
                            # onto the epoch timeline ending now.
                            epoch = _obs.now()
                            _obs.record(
                                "replication", epoch - (now - t0), epoch,
                                attrs={"member_traces": members, "idx": n})
                    else:
                        self._trace_members.pop(n, None)
        self._apply_committed()

    def _record_decision(self, request_id: bytes, reply: ClientReply) -> None:
        self.decided[request_id] = reply
        while len(self.decided) > self._decided_cap:
            self.decided.pop(next(iter(self.decided)))

    def _build_reply(self, cmd, outcome) -> ClientReply:
        """Map an apply outcome to the client's decision frame."""
        if outcome is BUSY:
            # Reserved by another unexpired 2PC: the retryable bounce
            # form (ok=False, conflict=None) — the submitting poller
            # resubmits with a fresh issued_at until the hold
            # resolves or expires.
            return ClientReply(cmd.request_id, False, None,
                               self.leader_name)
        if outcome is WRONG_EPOCH:
            # Reshard fence: this group no longer/not yet owns the
            # refs. Retryable, but ONLY after the submitter
            # re-derives the shard directory — the flag tells its
            # poller to stop resubmitting here.
            return ClientReply(cmd.request_id, False, None,
                               self.leader_name, wrong_epoch=True)
        return ClientReply(cmd.request_id, outcome is None,
                           outcome, self.leader_name)

    def _entry_commands(self, idx: int):
        """The next committed entry's command tuple, or None if the entry
        at *idx* is unavailable (raced compaction / corruption heal)."""
        entries = self._log_entries_from(idx, limit=1)
        if not entries or entries[0][0] != idx:
            return None
        entry = entries[0][2]
        return (entry.commands if isinstance(entry, PutAllBatch)
                else (entry,) if entry is not None else ())

    def _apply_committed(self) -> None:
        if self.config.pipeline and self.config.apply_queue_depth > 0:
            # Pipelined commit plane: hand the committed tail to the apply
            # executor and fold in whatever it has already finished. The
            # consensus thread returns to sealing/replicating the next
            # round while per-tx sqlite work runs on the executor.
            self._enqueue_committed()
            self._drain_apply_results()
            return
        applied_any = False
        # Replies for commands whose origin is another member coalesce into
        # ONE multi-outcome frame per destination for the whole apply pass.
        outbound: dict[str, list[ClientReply]] = {}
        while self.last_applied < self.commit_index:
            # Read FIRST, advance after: if the next entry is missing (raced
            # compaction) or corrupt (heal truncated it out from under us),
            # last_applied must still name the last entry whose effects are
            # durably in committed_states — the heal path's "idx <=
            # last_applied" compact-vs-truncate decision depends on it.
            commands = self._entry_commands(self.last_applied + 1)
            if commands is None:
                break
            self.last_applied += 1
            self._applied_enqueued = max(self._applied_enqueued,
                                         self.last_applied)
            applied_any = True
            for cmd in commands:
                # Per-request conflict isolation: each command in a group-
                # commit batch runs the first-committer-wins check on its
                # own — one double-spend rejects alone, its batch siblings
                # commit normally.
                reply = self._build_reply(cmd, self.apply_command(cmd))
                self._settle_decision(cmd, reply, outbound)
        self._flush_outbound_replies(outbound)
        if applied_any:  # no idle-heartbeat sqlite churn
            with self.db.lock:  # foreign-thread writers share one conn
                self.db.set_setting("raft_commit_index",
                                    str(self.commit_index))
                self.db.set_setting("raft_last_applied",
                                    str(self.last_applied))
            self.maybe_compact()

    def _settle_decision(self, cmd, reply: ClientReply,
                         outbound: dict) -> None:
        """Consensus-thread decision bookkeeping for one applied command:
        record, un-dedupe, unlink QoS, route a forwarded origin's reply
        into the per-destination coalescing buffer."""
        self._record_decision(cmd.request_id, reply)
        self._appending.discard(cmd.request_id)
        if _qos.ACTIVE is not None:
            _qos.ACTIVE.pop_link(cmd.request_id)
        fwd = getattr(self, "_forward_replies", {}).pop(
            cmd.request_id, None)
        if fwd is not None and self._peer_addr(fwd) is not None:
            outbound.setdefault(fwd, []).append(reply)

    def _flush_outbound_replies(self, outbound: dict) -> None:
        for fwd, replies in outbound.items():
            self.metrics["reply_frames"] += 1
            self.metrics["reply_commands"] += len(replies)
            if len(replies) == 1:
                self._send(self._peer_addr(fwd), replies[0])
            else:
                self._send(self._peer_addr(fwd),
                           ClientReplyBatch(tuple(replies)))

    # -- pipelined apply executor (round 18) -------------------------------

    def _ensure_executor(self) -> _queue.Queue:
        if self._apply_thread is None or not self._apply_thread.is_alive():
            self._apply_queue = _queue.Queue(
                maxsize=self.config.apply_queue_depth)
            self._apply_thread = threading.Thread(
                target=self._executor_loop, args=(self._apply_queue,),
                name=f"raft-apply-{self.name}", daemon=True)
            self._apply_thread.start()
        return self._apply_queue

    def _executor_loop(self, q: _queue.Queue) -> None:
        """Apply-executor thread body: state apply (sqlite work under
        db.lock — the I/O serialization lock, by design) and client-reply
        construction, off the consensus thread. Items complete strictly in
        queue order; an apply exception parks the error for the consensus
        thread and exits (the entry re-applies idempotently after the
        executor is rebuilt). perf_counter here is telemetry only (the
        overlap accumulator) — apply determinism never reads a clock."""
        while True:
            item = q.get()
            if item is None:  # shutdown sentinel (tests)
                q.task_done()
                return
            idx, commands = item
            t0 = _time.perf_counter()
            replies, err = None, None
            try:
                if len(commands) > 1 and self._commit_many is not None:
                    outcomes = self._commit_many(commands)
                else:
                    outcomes = [self.apply_command(c) for c in commands]
                replies = tuple(self._build_reply(c, o)
                                for c, o in zip(commands, outcomes))
            except BaseException as e:  # surfaces on the consensus thread
                err = e
            self.overlap_s["apply"] += _time.perf_counter() - t0
            self.metrics["apply_batches"] += 1
            if _tm.ACTIVE is not None:
                _tm.inc("raft_apply_batches_total")
                _tm.observe("raft_apply_batch_commands", len(commands))
            self._apply_results.append((idx, commands, replies, err))
            q.task_done()
            if err is not None:
                return  # stop in order; successors re-enqueue after reset

    def _enqueue_committed(self) -> None:
        """Feed the bounded commit queue from the committed-but-unapplied
        log tail. A full queue just stops the feed — committed entries are
        durable in the log and the next tick resumes where this left off."""
        if self._applied_enqueued >= self.commit_index:
            return
        q = self._ensure_executor()
        while self._applied_enqueued < self.commit_index:
            if q.full():
                break
            commands = self._entry_commands(self._applied_enqueued + 1)
            if commands is None:
                break
            self._applied_enqueued += 1
            q.put((self._applied_enqueued, tuple(commands)))

    def _drain_apply_results(self) -> None:
        """Fold finished executor items back into consensus state, in
        order: advance last_applied, record decisions, coalesce forwarded
        replies — all single-threaded bookkeeping stays on this thread."""
        results = self._apply_results
        if not results:
            return
        applied_any = False
        err = None
        outbound: dict[str, list[ClientReply]] = {}
        while results:
            idx, commands, replies, item_err = results.popleft()
            if item_err is not None:
                err = item_err
                break
            # Never regress past a snapshot install that superseded queued
            # items (their rows are part of the installed state).
            if idx > self.last_applied:
                self.last_applied = idx
                applied_any = True
            for cmd, reply in zip(commands, replies):
                self._settle_decision(cmd, reply, outbound)
        self._flush_outbound_replies(outbound)
        if applied_any:
            # The executor thread may be mid-transaction applying the NEXT
            # entry on the same sqlite connection — settings writes must
            # serialize through db.lock like every other foreign-thread
            # write, or the two implicit BEGINs collide.
            with self.db.lock:
                self.db.set_setting("raft_commit_index",
                                    str(self.commit_index))
                self.db.set_setting("raft_last_applied",
                                    str(self.last_applied))
            self.maybe_compact()
        if err is not None:
            # The failed entry (and any queued successors) re-apply
            # idempotently from the durable log through a fresh executor;
            # the error itself surfaces exactly like the serial path's.
            self._apply_thread = None
            self._apply_queue = None
            self._apply_results.clear()
            self._applied_enqueued = self.last_applied
            raise err

    def apply_backlog(self) -> int:
        """Committed-but-unapplied entries (durable in the log; drains as
        the executor catches up)."""
        return max(0, self.commit_index - self.last_applied)

    def apply_overloaded(self) -> bool:
        """True when the bounded commit queue is full — the admission
        signal that sheds NEW submissions with a retryable overload bounce
        (in-flight and committed work is never shed)."""
        q = self._apply_queue
        return q is not None and q.full()

    def quiesce_apply(self, timeout: float = 5.0) -> None:
        """Drain the pipelined plane to a fixpoint: every enqueued entry
        applied AND its results folded back on the calling (consensus)
        thread. Tests and deterministic harnesses call this where the
        serial path was synchronous by construction."""
        if self._apply_queue is None:
            return
        deadline = _time.monotonic() + timeout
        while True:
            self._drain_apply_results()
            if self.last_applied >= self._applied_enqueued:
                return
            if _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"apply executor stalled: enqueued="
                    f"{self._applied_enqueued} applied={self.last_applied}")
            _time.sleep(0.0005)

    def stamp(self) -> dict:
        """Self-describing replication stamp (plain JSON types only):
        exported via node_metrics -> loadtest node_stamps -> the bench raft
        open-loop section, so every trend line records how the commit
        pipeline actually behaved (round-4 verdict: un-stamped numbers made
        cross-round comparison a trap)."""
        m = self.metrics
        sealed = m["group_commits"] + m["solo_commits"]
        commands = m["group_commands"] + m["solo_commits"]
        frames = m["reply_frames"]
        rtt_n = m["replication_rtt_n"]
        (reserved,) = self.db.conn.execute(
            "SELECT COUNT(*) FROM reserved_states").fetchone()
        (committed,) = self.db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        return {
            "role": self.role,
            "term": self.term,
            "commit_index": self.commit_index,
            # Durable spent-input rows on THIS member — the ledger side of
            # the cross-process exactly-once audit (each consumed ref is
            # one row; loadtest sums max-over-members per shard group).
            "committed_states": committed,
            # Live 2PC holds — a drained workload must show 0 here (leaked
            # reservations would mean wedged inputs; TTL abort is the
            # backstop, this stamp is how audits see it worked).
            "reserved_states": reserved,
            "group_commit": self.config.group_commit,
            "group_commits": m["group_commits"],
            "group_commands": m["group_commands"],
            # Commands committed per sealed log entry (solo entries count 1)
            # — > 1 means group commit actually amortized the burst.
            "entries_per_batch": (round(commands / sealed, 3)
                                  if sealed else None),
            "append_frames": m["append_frames"],
            "append_entries_sent": m["append_entries_sent"],
            "reply_frames": frames,
            "reply_commands": m["reply_commands"],
            "reply_coalesce_ratio": (round(m["reply_commands"] / frames, 3)
                                     if frames else None),
            "forward_frames": m["forward_frames"],
            "forward_commands": m["forward_commands"],
            # QoS plane: scheduling rounds sealed early because a buffered
            # interactive entry neared its SLO deadline (0 when disarmed).
            "qos_early_seals": m["qos_early_seals"],
            # Durability plane: corruption detections, the self-healing
            # actions they triggered, and disk-full degrades — all 0 on a
            # healthy store; the bitrot chaos audit asserts the first is > 0.
            "integrity_errors": m["integrity_errors"],
            "log_truncations": m["log_truncations"],
            "leader_stepdowns": m["leader_stepdowns"],
            "disk_degraded": m["disk_degraded"],
            # Partition plane (round 20): prevote canvass traffic and
            # check-quorum cessions (0 with the flag off); elections_won +
            # term are the A/B observables the partition_chaos bench reads
            # for term inflation across a cut/heal cycle.
            "prevote": bool(self.config.prevote),
            "prevotes": m["prevotes"],
            "prevote_rejections": m["prevote_rejections"],
            "checkquorum_stepdowns": m["checkquorum_stepdowns"],
            "elections_won": m["elections_won"],
            "replication_rtt_ms_avg": (
                round(1e3 * m["replication_rtt_s"] / rtt_n, 3)
                if rtt_n else None),
            # Leader seal-path wall time by phase (the round profiler's
            # seal/replicate/apply split, summed over every flush).
            "phase_s": {k: round(v, 6) for k, v in self.phase_s.items()},
            # Pipelined commit plane (round 18): whether rounds overlap and
            # how the detached apply executor behaved — the doctor's rule
            # table branches on `pipeline` so a "rounds" verdict suggests
            # executor-side experiments instead of re-suggesting round-loop
            # amortization.
            "pipeline": bool(self.config.pipeline),
            "apply_queue_depth": self.config.apply_queue_depth,
            "commit_many": self._commit_many is not None,
            "midround_seals": m["midround_seals"],
            "apply_batches": m["apply_batches"],
            "apply_shed": m["apply_shed"],
            "apply_backlog": self.apply_backlog(),
            # Executor wall time overlapped under the consensus thread's
            # seal/replicate (NOT part of phase_s — coverage stays honest).
            "overlap_s": {k: round(v, 6) for k, v in self.overlap_s.items()},
        }


from ...utils.excheckpoint import register_flow_exception


@register_flow_exception
class CommitTimeoutException(UniquenessUnavailableException):
    """The cluster could not commit within the deadline (no quorum/leader).
    Distinct from UniquenessException: a timeout is retriable, a conflict is
    final — surfacing one as the other would tell a client its transaction
    double-spent when the cluster was merely degraded. Whitelisted for typed
    checkpoint replay so flows can branch on it live and post-restore."""


@register_flow_exception
class CommitQueueFullException(UniquenessUnavailableException):
    """The leader's bounded commit queue (the pipelined apply executor's
    admission point, [raft] apply_queue_depth) is full: NEW submissions
    shed instead of growing an unbounded committed-but-unapplied backlog.
    Retryable after a short backoff — says nothing about the transaction
    itself. The notary flow surfaces it as OverloadedError("commit") so
    clients reuse the QoS plane's shed-retry handling."""

    RETRY_AFTER_MS = 50.0


@register_flow_exception
class WrongShardEpochException(UniquenessUnavailableException):
    """The group bounced the command off a reshard fence: under the shard
    map the group currently enforces, it does not own (some of) the touched
    StateRefs. Retryable — but unlike a leaderless bounce, resubmitting to
    the SAME group can never succeed. The caller must re-derive the shard
    directory (network map) and route to the owning group at the new epoch.
    Subclasses UniquenessUnavailableException so catch sites that predate
    resharding still treat it as a retriable non-conflict."""


class RaftUniquenessProvider(UniquenessProvider):
    """UniquenessProvider facade over a RaftMember (reference:
    RaftUniquenessProvider.kt:44-115 — commit() submits PutAll and waits for
    the replicated state machine's verdict).

    The flow-facing path is commit_async(): it returns a poll callable the
    node's run loop drives (ServiceRequest suspension), so a notary flow
    never blocks the message pump that consensus itself rides on. The
    synchronous commit() exists for direct/production use where the caller
    may block while a pump callable runs the node."""

    RESUBMIT_EVERY = 0.5  # sec; re-offer after leader changes (idempotent)

    def __init__(self, member: RaftMember, pump: Callable[[], None],
                 timeout: float = 25.0):
        # 25 s, not 10: the commit poll RESUBMITS through leader changes
        # (idempotent request ids), so the window only bounds how long a
        # caller waits out cluster unavailability. Measured leaderless
        # blips under a coarse scheduler (an election churn episode plus
        # redelivery backoff) recover in 10-20 s — a 10 s window turned
        # exactly those transients into spurious tx rejections.
        self.member = member
        self._pump = pump  # drives messaging + raft ticks while waiting
        self.timeout = timeout

    def commit_async(self, states: Sequence, tx_id: SecureHash,
                     caller_identity: Party) -> Callable[[], bool | None]:
        # Hot path: `os` is imported at module top (an import inside here
        # paid a sys.modules lookup per notarisation). The refs tuple is
        # built ONCE; each RESUBMIT_EVERY re-offer re-stamps issued_at on
        # the same request_id (idempotent across leader changes) — a frozen
        # stamp would stay parked behind an expired reservation forever,
        # because expiry is judged against the command's own stamp, never a
        # replica clock (see PutAllCommand.issued_at).
        request_id = os.urandom(16)
        refs = tuple(states)
        state = {"deadline": _time.monotonic() + self.timeout,
                 "submitted_at": 0.0}
        ctx = _obs.get_context() if _obs.ACTIVE is not None else None
        if ctx is not None:
            # Link map: lets the leader's batch seal attribute this entry
            # back to the submitting flow's trace without widening the
            # consensus API. t0 anchors the per-tx raft_commit span.
            _obs.register_link(request_id, ctx[0], ctx[1])
            state["trace_t0"] = _obs.now()
        qctx = _qos.get_context() if _qos.ACTIVE is not None else None
        if qctx is not None:
            # QoS link map, same shape as the trace link: lets the leader's
            # deadline-aware seal (and a forwarding follower) see this
            # request's lane/deadline without widening the consensus API.
            _qos.ACTIVE.register_link(request_id, qctx)

        def poll():
            now = _time.monotonic()
            reply = self.member.decided.pop(request_id, None)
            if reply is not None:
                decided = (reply.ok or reply.conflict is not None
                           or reply.wrong_epoch)
                if decided and ctx is not None and _obs.ACTIVE is not None:
                    # submit -> decision, stitched under the notary flow.
                    # (A leaderless bounce is not a decision: the command
                    # resubmits below and the span stays open.)
                    _obs.record(
                        "raft_commit", state.get("trace_t0", _obs.now()),
                        _obs.now(), trace_id=ctx[0], parent=ctx[1],
                        attrs={"ok": bool(reply.ok)})
                    _obs.pop_link(request_id)
                if decided and _qos.ACTIVE is not None:
                    _qos.ACTIVE.pop_link(request_id)
                if reply.ok:
                    return True
                if reply.conflict is not None:
                    raise UniquenessException(reply.conflict)
                if reply.wrong_epoch:
                    # Reshard fence bounce: this group no longer (or does
                    # not yet) own the refs. Resubmitting here is futile —
                    # surface so the client re-derives the directory.
                    raise WrongShardEpochException(
                        f"group fenced off {tx_id} (reshard in progress; "
                        f"re-derive the shard directory)")
                state["submitted_at"] = 0.0  # no leader yet: resubmit below
            if now >= state["deadline"]:
                raise CommitTimeoutException(
                    f"raft commit of {tx_id} not decided within "
                    f"{self.timeout}s (leader: {self.member.leader_name})")
            if (state["submitted_at"] == 0.0
                    or now - state["submitted_at"] >= self.RESUBMIT_EVERY):
                if (state["submitted_at"] == 0.0
                        and self.member.apply_overloaded()):
                    # Admission-point backpressure: only a NOT-in-flight
                    # (re)submission sheds — a command already replicating
                    # keeps polling for its decision.
                    raise CommitQueueFullException(
                        f"commit queue full shedding {tx_id} "
                        f"(leader: {self.member.leader_name})")
                self.member.submit(PutAllCommand(
                    refs, tx_id, caller_identity, request_id,
                    # lint: allow(no-wallclock-in-apply) coordinator stamping site: resubmission re-stamps on the submitting node; replicas only ever see the carried value
                    issued_at=_time.time()))
                state["submitted_at"] = now
            return None

        return poll

    def commit(self, states: Sequence, tx_id: SecureHash,
               caller_identity: Party) -> None:
        poll = self.commit_async(states, tx_id, caller_identity)
        while True:
            outcome = poll()
            if outcome is not None:
                return
            self._pump()

    @property
    def committed_count(self) -> int:
        (n,) = self.member.db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        return n

    def leader_hint(self) -> str | None:
        """Legal name of the member this replica believes leads the cluster
        (None during elections) — attached to NotaryUnavailable replies so
        retrying clients can skip a redirect round trip."""
        return self.member.leader_name


def make_apply_command(db) -> Callable[[Any], Any]:
    """The replicated state machine's apply step: first-committer-wins over
    the same committed_states table as the single-node provider, extended
    with the cross-shard 2PC commands (Reserve / CommitReserved /
    AbortReserved — services/sharding.py). Idempotent for re-applied entries
    (same tx claims same refs -> no conflict).

    Outcomes: None (ok), UniquenessConflict (final), BUSY (reserved by
    another unexpired tx — retryable). DETERMINISM INVARIANT: every branch
    below depends only on the command's own fields and replicated table
    state — never on a local clock — so replicas applying the same log
    prefix always agree (reservation expiry compares the command's
    issued_at stamp against the stored expires_at)."""
    # Lazy import: sharding imports raft at module level (commands,
    # RaftMember), so the shard hash comes in at closure-build time instead
    # of creating an import cycle. One definition, two layers.
    from .sharding import shard_of

    with db.lock:
        # The member normally creates this table, but apply closures are
        # built before RaftMember.__init__ runs its schema script.
        db.conn.executescript(_RAFT_SCHEMA)
        _integrity.ensure_integrity_schema(db.conn)
        db.conn.commit()
        raw = db.get_setting("shard_fence")
    # Reshard fence, cached across applies and persisted in settings so a
    # restarted member rebuilds it BEFORE replaying the log (replay then
    # re-installs the same fences idempotently — never-downgrade below).
    fence: dict[str, Any] = {"state": json.loads(raw) if raw else None}
    # Fence modes outrank each other within an epoch (a retried "seal" must
    # not regress an already-activated cutover); a higher epoch always wins.
    _RANK = {"sealed": 1, "importing": 1, "active": 2, "retired": 2}

    def _set_fence(state: dict) -> None:
        fence["state"] = state
        db.set_setting("shard_fence", json.dumps(state))

    def _fence_bounce(refs):
        """WRONG_EPOCH iff the installed fence says some ref is not (or is
        no longer) this group's to serve; None = proceed. Pure function of
        the fence record + ref hashes — no clocks, no local state."""
        f = fence["state"]
        if not f:
            return None
        mode = f["mode"]
        if mode == "sealed":
            # Handoff in progress: the keyspace MOVING to another group is
            # frozen at the seal's log position; refs this group keeps
            # under the new epoch commit straight through (no outage for
            # the unmoved majority).
            cnt, g = f["to_count"], f["group"]
            if g >= cnt:  # retiring group (merge): everything is moving
                return WRONG_EPOCH
            for ref in refs:
                if shard_of(ref, cnt) != g:
                    return WRONG_EPOCH
            return None
        if mode in ("importing", "retired"):
            # importing: half-installed ledger — a racing new-epoch client
            # must retry until the coordinator activates us. retired: a
            # merged-away group never serves again.
            return WRONG_EPOCH
        # mode == "active": epoch installed. Bounce refs we don't own so a
        # stale-directory client re-derives instead of committing against
        # the wrong group's ledger (the split sibling has its history).
        cnt, g = f["count"], f["group"]
        for ref in refs:
            if shard_of(ref, cnt) != g:
                return WRONG_EPOCH
        return None

    def _committed_conflicts(conn, refs, tx_id) -> dict:
        conflicts = {}
        for ref in refs:
            row = conn.execute(
                "SELECT consuming FROM committed_states WHERE state_ref = ?",
                (serialize(ref).bytes,)).fetchone()
            if row is not None:
                consuming = deserialize(bytes(row[0]))
                if consuming.id != tx_id:
                    conflicts[ref] = consuming
        return conflicts

    def _blocked_by_reservation(conn, refs, tx_id, issued_at) -> bool:
        """True iff some ref is held by a DIFFERENT tx whose hold has not
        expired relative to this command's stamp (issued_at < expires_at;
        issued_at >= expires_at is the deterministic steal)."""
        for ref in refs:
            row = conn.execute(
                "SELECT tx_id, expires_at FROM reserved_states "
                "WHERE state_ref = ?", (serialize(ref).bytes,)).fetchone()
            if row is not None and bytes(row[0]) != tx_id.bytes \
                    and issued_at < float(row[1]):
                return True
        return False

    def _apply_put_all(cmd: PutAllCommand):
        with db.lock:
            conn = db.conn
            bounced = _fence_bounce(cmd.refs)
            if bounced is not None:
                return bounced
            conflicts = _committed_conflicts(conn, cmd.refs, cmd.tx_id)
            if conflicts:
                return UniquenessConflict(conflicts)
            if _blocked_by_reservation(conn, cmd.refs, cmd.tx_id,
                                       cmd.issued_at):
                return BUSY
            for i, ref in enumerate(cmd.refs):
                blob = serialize(ref).bytes
                consuming = serialize(
                    ConsumingTx(cmd.tx_id, i, cmd.caller)).bytes
                conn.execute(
                    "INSERT OR IGNORE INTO committed_states "
                    "(state_ref, consuming, crc) VALUES (?, ?, ?)",
                    (blob, consuming,
                     _integrity.committed_crc(blob, consuming)))
                # Clear any hold the commit supersedes (our own retried
                # reserve, or an expired one we just stole past).
                conn.execute(
                    "DELETE FROM reserved_states WHERE state_ref = ?",
                    (blob,))
            db.commit()
            return None

    def _apply_reserve(cmd: ReserveCommand):
        with db.lock:
            conn = db.conn
            bounced = _fence_bounce(cmd.refs)
            if bounced is not None:
                return bounced
            conflicts = _committed_conflicts(conn, cmd.refs, cmd.tx_id)
            if conflicts:
                return UniquenessConflict(conflicts)
            if _blocked_by_reservation(conn, cmd.refs, cmd.tx_id,
                                       cmd.issued_at):
                return BUSY
            expires = cmd.issued_at + cmd.ttl_s
            for ref in cmd.refs:
                # REPLACE: refreshes our own hold on a retried reserve and
                # deterministically steals an expired foreign one.
                blob = serialize(ref).bytes
                conn.execute(
                    "INSERT OR REPLACE INTO reserved_states "
                    "(state_ref, tx_id, expires_at, crc) VALUES (?, ?, ?, ?)",
                    (blob, cmd.tx_id.bytes, expires,
                     _integrity.reserved_crc(blob, cmd.tx_id.bytes, expires)))
            db.commit()
            return None

    def _apply_commit_reserved(cmd: CommitReservedCommand):
        with db.lock:
            conn = db.conn
            bounced = _fence_bounce(cmd.refs)
            if bounced is not None:
                return bounced
            conflicts = _committed_conflicts(conn, cmd.refs, cmd.tx_id)
            if conflicts:
                return UniquenessConflict(conflicts)
            for i, ref in enumerate(cmd.refs):
                blob = serialize(ref).bytes
                consuming = serialize(
                    ConsumingTx(cmd.tx_id, i, cmd.caller)).bytes
                conn.execute(
                    "INSERT OR IGNORE INTO committed_states "
                    "(state_ref, consuming, crc) VALUES (?, ?, ?)",
                    (blob, consuming,
                     _integrity.committed_crc(blob, consuming)))
                conn.execute(
                    "DELETE FROM reserved_states WHERE state_ref = ?",
                    (blob,))
            db.commit()
            return None

    def _apply_abort(cmd: AbortReservedCommand):
        with db.lock:
            for ref in cmd.refs:
                db.conn.execute(
                    "DELETE FROM reserved_states "
                    "WHERE state_ref = ? AND tx_id = ?",
                    (serialize(ref).bytes, cmd.tx_id.bytes))
            db.commit()
            return None

    def _apply_fence(cmd: ShardFenceCommand):
        new_mode = ("sealed" if cmd.mode == "seal"
                    else "retired" if cmd.group >= cmd.to_count
                    else "active")
        with db.lock:
            f = fence["state"]
            if f and ((f["epoch"], _RANK.get(f["mode"], 0))
                      >= (cmd.epoch, _RANK[new_mode])):
                return None  # coordinator retry / replay: never downgrade
            _set_fence({"epoch": cmd.epoch, "group": cmd.group,
                        "from_count": cmd.from_count,
                        "to_count": cmd.to_count, "count": cmd.to_count,
                        "mode": new_mode})
            # Activation purges rows the group no longer owns. Safe: the
            # coordinator activates the TARGET before the source, so by the
            # time a source applies "active"/"retired" the moved rows are
            # durable on the target's quorum. Keeping them instead would
            # double-count the ledger audit (sum of per-group rows).
            if new_mode == "retired":
                db.conn.execute("DELETE FROM committed_states")
                db.conn.execute("DELETE FROM reserved_states")
            elif new_mode == "active":
                for table in ("committed_states", "reserved_states"):
                    gone = [
                        (bytes(row[0]),)
                        for row in db.conn.execute(
                            f"SELECT state_ref FROM {table}").fetchall()
                        if shard_of(deserialize(bytes(row[0])),
                                    cmd.to_count) != cmd.group]
                    if gone:
                        db.conn.executemany(
                            f"DELETE FROM {table} WHERE state_ref = ?",
                            gone)
            db.commit()
            return None

    def _apply_install(cmd: InstallShardStateCommand):
        with db.lock:
            conn = db.conn
            f = fence["state"]
            if not f or ((f["epoch"], _RANK.get(f["mode"], 0))
                         < (cmd.epoch, 1)):
                # First handoff frame fences the target as importing —
                # WRONG_EPOCH to everyone until the coordinator activates.
                _set_fence({"epoch": cmd.epoch, "group": cmd.group,
                            "from_count": cmd.from_count,
                            "to_count": cmd.to_count, "count": cmd.to_count,
                            "mode": "importing"})
            for blob, consuming in cmd.committed_rows:
                conn.execute(
                    "INSERT OR IGNORE INTO committed_states "
                    "(state_ref, consuming, crc) VALUES (?, ?, ?)",
                    (bytes(blob), bytes(consuming),
                     _integrity.committed_crc(bytes(blob), bytes(consuming))))
            for blob, tx_id, expires in cmd.reserved_rows:
                # OR IGNORE: a retried frame never clobbers, and the hold
                # keeps its original coordinator-stamped expires_at so the
                # TTL backstop carries across the handoff unchanged.
                conn.execute(
                    "INSERT OR IGNORE INTO reserved_states "
                    "(state_ref, tx_id, expires_at, crc) "
                    "VALUES (?, ?, ?, ?)",
                    (bytes(blob), bytes(tx_id), float(expires),
                     _integrity.reserved_crc(
                         bytes(blob), bytes(tx_id), float(expires))))
            db.commit()
            return None

    def _select_map(conn, table: str, cols: str, blobs):
        """state_ref -> row tuple over a set of refs, chunked under
        sqlite's bound-parameter limit. One (or a few) set-wide SELECTs
        replace the serial path's per-ref probe."""
        out = {}
        blobs = list(blobs)
        for i in range(0, len(blobs), 500):
            chunk = blobs[i:i + 500]
            marks = ",".join("?" * len(chunk))
            for row in conn.execute(
                    f"SELECT state_ref, {cols} FROM {table} "
                    f"WHERE state_ref IN ({marks})", chunk):
                out[bytes(row[0])] = row[1:]
        return out

    def _put_all_many(cmds):
        """Columnar PutAll batch: outcomes and ledger rows byte-identical
        to applying each command in order, with the per-tx fixed costs
        amortized — serialization + CRC32C precomputed OUTSIDE db.lock
        (native _ccommit releases the GIL across the CRC batch), conflict/
        reservation probes collapsed to set-wide SELECT ... IN, and the
        inserts/deletes flushed through executemany. In-batch claims are
        tracked in the lookup maps so first-committer-wins ordering within
        the batch matches the serial replay exactly."""
        pre = []
        crc_pairs = []
        for cmd in cmds:
            ref_blobs = tuple(serialize(ref).bytes for ref in cmd.refs)
            cons_blobs = tuple(
                serialize(ConsumingTx(cmd.tx_id, i, cmd.caller)).bytes
                for i in range(len(cmd.refs)))
            pre.append((cmd, ref_blobs, cons_blobs))
            crc_pairs.extend(zip(ref_blobs, cons_blobs))
        crcs = _integrity.committed_crc_many(crc_pairs)
        crc_at = 0
        outcomes = []
        with db.lock:
            conn = db.conn
            all_refs = {rb for _c, rbs, _cb in pre for rb in rbs}
            committed = _select_map(
                conn, "committed_states", "consuming", all_refs)
            reserved = _select_map(
                conn, "reserved_states", "tx_id, expires_at", all_refs)
            ins_rows, del_rows = [], []
            for cmd, ref_blobs, cons_blobs in pre:
                cmd_crcs = crcs[crc_at:crc_at + len(ref_blobs)]
                crc_at += len(ref_blobs)
                bounced = _fence_bounce(cmd.refs)
                if bounced is not None:
                    outcomes.append(bounced)
                    continue
                conflicts = {}
                for ref, rb in zip(cmd.refs, ref_blobs):
                    got = committed.get(rb)
                    if got is None:
                        continue
                    if not isinstance(got, ConsumingTx):
                        got = deserialize(bytes(got[0]))
                        committed[rb] = got  # decode once per ref
                    if got.id != cmd.tx_id:
                        conflicts[ref] = got
                if conflicts:
                    outcomes.append(UniquenessConflict(conflicts))
                    continue
                busy = False
                for rb in ref_blobs:
                    held = reserved.get(rb)
                    if held is not None \
                            and bytes(held[0]) != cmd.tx_id.bytes \
                            and cmd.issued_at < float(held[1]):
                        busy = True
                        break
                if busy:
                    outcomes.append(BUSY)
                    continue
                for i, (rb, cb, crc) in enumerate(
                        zip(ref_blobs, cons_blobs, cmd_crcs)):
                    ins_rows.append((rb, cb, crc))
                    del_rows.append((rb,))
                    committed[rb] = ConsumingTx(cmd.tx_id, i, cmd.caller)
                    reserved.pop(rb, None)
                outcomes.append(None)
            if ins_rows:
                conn.executemany(
                    "INSERT OR IGNORE INTO committed_states "
                    "(state_ref, consuming, crc) VALUES (?, ?, ?)", ins_rows)
                conn.executemany(
                    "DELETE FROM reserved_states WHERE state_ref = ?",
                    del_rows)
            db.commit()
        return outcomes

    def apply(cmd):
        if isinstance(cmd, ReserveCommand):
            return _apply_reserve(cmd)
        if isinstance(cmd, CommitReservedCommand):
            return _apply_commit_reserved(cmd)
        if isinstance(cmd, AbortReservedCommand):
            return _apply_abort(cmd)
        if isinstance(cmd, ShardFenceCommand):
            return _apply_fence(cmd)
        if isinstance(cmd, InstallShardStateCommand):
            return _apply_install(cmd)
        return _apply_put_all(cmd)

    def apply_many(cmds):
        """Batch dispatcher (RaftMember._commit_many): consecutive runs of
        plain PutAllCommands take the columnar fast path; anything else
        (2PC / fence / install commands) flushes the run and applies
        one-at-a-time, preserving exact serial order."""
        outcomes = []
        run = []

        def _flush():
            if len(run) > 1:
                outcomes.extend(_put_all_many(tuple(run)))
            elif run:
                outcomes.append(_apply_put_all(run[0]))
            run.clear()

        for cmd in cmds:
            if type(cmd) is PutAllCommand:
                run.append(cmd)
            else:
                _flush()
                outcomes.append(apply(cmd))
        _flush()
        return outcomes

    apply.many = apply_many
    return apply
