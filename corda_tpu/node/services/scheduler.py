"""Time-triggered flow scheduling from SchedulableStates.

Capability match for the reference's NodeSchedulerService +
ScheduledActivityObserver (reference: node/src/main/kotlin/net/corda/node/
services/events/NodeSchedulerService.kt:45-70, ScheduledActivityObserver.kt):
states on the ledger can request a flow run at a future time (e.g. an
interest-rate fixing); the scheduler watches vault updates, tracks the
earliest activity per state, and launches the whitelisted flow when due.

Differences by design: the reference persists ScheduledStateRefs and runs a
dedicated timer thread; here the schedule rebuilds from the vault on startup
(the vault itself rebuilds from durable transaction storage) and `tick()` is
driven by the node's single-threaded run loop — same capability, no timer
thread, no duplicate persistence.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...contracts.structures import SchedulableState, StateRef, now_micros
from ...flows.api import flow_registry
from ...serialization.codec import register


@register
@dataclass(frozen=True)
class ScheduledActivity:
    """What to run and when (reference: Structures.kt ScheduledActivity)."""

    flow_name: str
    flow_args: tuple
    at_micros: int


class NodeSchedulerService:
    def __init__(self, smm, vault_service):
        self._smm = smm
        self._scheduled: dict[StateRef, ScheduledActivity] = {}
        vault_service.subscribe(self._on_vault_update)
        # Startup: scan the vault for schedulable states — through the
        # paginated iterator, never a full snapshot copy.
        for sar in vault_service.iter_unconsumed():
            self._consider(sar)

    def _on_vault_update(self, update) -> None:
        for sar in update.consumed:
            self._scheduled.pop(sar.ref, None)
        for sar in update.produced:
            self._consider(sar)

    def _consider(self, sar) -> None:
        state = sar.state.data
        if not isinstance(state, SchedulableState):
            return
        activity = state.next_scheduled_activity(sar.ref, flow_registry.get)
        if activity is not None:
            self._scheduled[sar.ref] = activity

    @property
    def next_scheduled(self) -> tuple[StateRef, ScheduledActivity] | None:
        if not self._scheduled:
            return None
        return min(self._scheduled.items(), key=lambda kv: kv[1].at_micros)

    def tick(self, now: int | None = None) -> int:
        """Launch every due activity; returns how many started. Called from
        the node's run loop (NodeSchedulerService.kt:45-70 capability)."""
        now = now if now is not None else now_micros()
        started = 0
        for ref, activity in list(self._scheduled.items()):
            if activity.at_micros <= now:
                del self._scheduled[ref]
                logic = flow_registry.create(
                    activity.flow_name, tuple(activity.flow_args))
                self._smm.add(logic)
                started += 1
        return started
