"""Queryable state projections — typed rows from vault updates.

Capability match for the reference's schema tier (reference:
core/src/main/kotlin/net/corda/core/schemas/PersistentTypes.kt —
QueryableState/MappedSchema — node/.../schema/NodeSchemaService.kt and
HibernateObserver.kt:28 — vault updates map queryable states to ORM rows):
states that implement `to_schema_row()` get a relational projection in the
node's sqlite database, maintained on every vault update, so operational
queries ("all cash over X", "deals fixing this week") run as SQL instead of
deserializing the whole vault.

Row contract: (table_name, {column: int | float | str | bytes}). The
projection table gains `ref_txhash`/`ref_index`/`consumed` columns; rows are
marked consumed rather than deleted, preserving history for audit queries
(the reference keeps consumed rows the same way via vault state status).
"""

from __future__ import annotations

import re

# The projection protocol is duck-typed: a state participates by defining
# to_schema_row() -> (table_name, {column: value}) — no base class to
# inherit, so finance states need no node-tier import.

_IDENT = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _check_ident(name: str) -> str:
    if not _IDENT.match(name):
        raise ValueError(f"invalid SQL identifier {name!r}")
    return name


class SchemaObserver:
    """Maintains the projections from vault updates (HibernateObserver.kt
    capability, sqlite instead of Hibernate)."""

    def __init__(self, vault_service, db):
        self._db = db
        self._tables: set[str] = set()
        vault_service.subscribe(self._on_update)
        with self._db.lock:
            for sar in vault_service.iter_unconsumed():
                self._produce(sar)
            self._db.conn.commit()

    def _on_update(self, update) -> None:
        # One sqlite commit per vault update, not per state: this runs
        # synchronously inside record_transactions.
        with self._db.lock:
            for sar in update.produced:
                self._produce(sar)
            for sar in update.consumed:
                self._consume(sar)
            self._db.conn.commit()

    def _ensure_table(self, table: str, row: dict) -> None:
        if table in self._tables:
            return
        cols = ", ".join(
            f"{_check_ident(k)} {self._sql_type(v)}" for k, v in row.items())
        self._db.conn.execute(
            f"CREATE TABLE IF NOT EXISTS {_check_ident(table)} "
            f"(ref_txhash BLOB, ref_index INTEGER, consumed INTEGER "
            f"DEFAULT 0, {cols}, PRIMARY KEY (ref_txhash, ref_index))")
        self._tables.add(table)

    @staticmethod
    def _sql_type(value) -> str:
        if isinstance(value, bool) or isinstance(value, int):
            return "INTEGER"
        if isinstance(value, float):
            return "REAL"
        if isinstance(value, bytes):
            return "BLOB"
        return "TEXT"

    def _produce(self, sar) -> None:
        state = sar.state.data
        if not hasattr(state, "to_schema_row"):  # duck-typed: finance states
            return                               # need no node-tier import
        table, row = state.to_schema_row()
        self._ensure_table(table, row)
        cols = ", ".join(_check_ident(k) for k in row)
        marks = ", ".join("?" for _ in row)
        self._db.conn.execute(
            f"INSERT OR REPLACE INTO {_check_ident(table)} "
            f"(ref_txhash, ref_index, consumed, {cols}) "
            f"VALUES (?, ?, 0, {marks})",
            (sar.ref.txhash.bytes, sar.ref.index, *row.values()))

    def _consume(self, sar) -> None:
        state = sar.state.data
        if not hasattr(state, "to_schema_row"):
            return
        table, _row = state.to_schema_row()
        if table not in self._tables:
            return
        self._db.conn.execute(
            f"UPDATE {_check_ident(table)} SET consumed = 1 "
            f"WHERE ref_txhash = ? AND ref_index = ?",
            (sar.ref.txhash.bytes, sar.ref.index))

    def query(self, table: str, where: str = "", params: tuple = ()) -> list:
        """Read projection rows (dicts). `where` is a SQL fragment over the
        projection's own columns — operational tooling, not a wire surface."""
        sql = f"SELECT * FROM {_check_ident(table)}"
        if where:
            sql += f" WHERE {where}"
        with self._db.lock:
            cur = self._db.conn.execute(sql, params)
            names = [d[0] for d in cur.description]
            return [dict(zip(names, r)) for r in cur.fetchall()]
