"""Sharded notary: StateRef-partitioned uniqueness over N Raft groups.

One Raft group caps committed tx/s no matter how fast the verify plane gets
(ROADMAP item 3). This subsystem partitions the input-state space by
StateRef hash across N independent Raft groups — each group runs the full
PR 2 machinery (group commit, pipelined replication, coalesced frames)
over its own slice of the keyspace, so committed throughput scales with
shard count.

Shard map
---------
``shard_of(ref, n)`` is a pure function of the StateRef alone: the first 8
bytes of the ref's txhash (already uniform — it is a SHA-256 Merkle root)
XOR the output index, mod n. Every party computes it locally; the only
shared datum is the shard COUNT, which rides the netmap as one advertised
service string per shard member (``corda.notary.shard.<g>``), so clients
build the directory from the network map they already have.

Commit protocol
---------------
* Single-shard transaction whose owning group is the local member's group:
  the exact RaftUniquenessProvider path — same PutAllCommand, same group
  commit, same reply protocol. Semantics identical to the unsharded notary.
* Otherwise, a two-phase coordinator drives the owning groups:

    phase 1  ReserveCommand on every touched group, acquired strictly in
             sorted group order (the next group only once the previous
             hold is in hand — lock ordering, so live coordinators
             contending on the same groups serialize instead of
             deadlocking half-holds). Atomic per group: every input free
             (or held/committed by this tx) or none; rejected with a
             final conflict if committed by another tx, bounced BUSY if
             held by another unexpired 2PC.
    phase 2  all reserved -> CommitReservedCommand everywhere;
             any conflict  -> AbortReservedCommand everywhere (best effort)
             and the conflict surfaces to the caller.

  Reservations carry a TTL stamped by the coordinator (issued_at + ttl_s),
  and expiry is judged by comparing OTHER commands' issued_at stamps
  against it — replicas never consult a local clock, so the state machines
  cannot diverge (node/services/raft.py make_apply_command). A coordinator
  that crashes between phases therefore never wedges inputs: its holds
  become steals for any later command stamped past the expiry.

  A retried 2PC for the same tx_id converges: reserve treats
  committed-by-this-tx as success and CommitReserved is idempotent, so
  exactly-once holds across coordinator retries, keyed on tx_id — the same
  invariant the single-group path gets from first-committer-wins.

Cross-group transport rides the existing Raft client channel: the
coordinator sends ClientCommit(command, reply_to=<my member name>) to a
member of the target group, and decisions come back as ClientReply frames
into the local member's ``decided`` mailbox. The member resolves reply
addresses beyond its own peers through the netmap resolver the node
injects (RaftMember.resolve_addr).

Failure matrix: ARCHITECTURE.md "Sharded notary (round 9)".
"""

from __future__ import annotations

import os
import time as _time
from typing import Any, Callable, Sequence

from ...crypto.hashes import SecureHash
from ...crypto.party import Party
from ...obs import trace as _obs
from ...qos import context as _qos
from .api import UniquenessException, UniquenessProvider
from .raft import (
    AbortReservedCommand,
    ClientCommit,
    CommitReservedCommand,
    CommitTimeoutException,
    PutAllCommand,
    RaftMember,
    RaftUniquenessProvider,
    ReserveCommand,
)

# Netmap service-string prefix: member of shard group g advertises
# f"{SHARD_SERVICE_PREFIX}{g}of{count}" so clients recover both the group
# id and the total shard count from the directory they already sync.
SHARD_SERVICE_PREFIX = "corda.notary.shard."


def shard_of(ref, count: int) -> int:
    """Owning group of a StateRef — a pure function every party computes
    identically from the ref alone (txhash is a SHA-256 Merkle root, so the
    leading 8 bytes are already uniform; XOR the index so the outputs of
    one transaction spread instead of clustering on one group)."""
    if count <= 1:
        return 0
    return (int.from_bytes(ref.txhash.bytes[:8], "big") ^ ref.index) % count


def split_by_shard(refs, count: int) -> dict[int, tuple]:
    """Group refs by owning shard, preserving order within each group."""
    by_group: dict[int, list] = {}
    for ref in refs:
        by_group.setdefault(shard_of(ref, count), []).append(ref)
    return {g: tuple(v) for g, v in by_group.items()}


def shard_service_string(group: int, count: int) -> str:
    return f"{SHARD_SERVICE_PREFIX}{group}of{count}"


def parse_shard_service(service: str) -> tuple[int, int] | None:
    """(group, count) from an advertised service string, else None."""
    if not service.startswith(SHARD_SERVICE_PREFIX):
        return None
    tail = service[len(SHARD_SERVICE_PREFIX):]
    group_s, _, count_s = tail.partition("of")
    try:
        group, count = int(group_s), int(count_s)
    except ValueError:
        return None
    if count <= 0 or not 0 <= group < count:
        return None
    return group, count


class ShardedUniquenessProvider(UniquenessProvider):
    """RaftUniquenessProvider-compatible facade over N Raft groups.

    The local member belongs to exactly ONE group (its raft_cluster); this
    provider routes single-shard traffic for that group straight through
    the plain provider and coordinates the two-phase protocol for
    everything else. Poll-driven like commit_async everywhere else in the
    framework: the returned callable is parked on a ServiceRequest and the
    node's run loop drives it, so the notary flow never blocks the message
    pump that consensus (and the cross-group channel) rides on.
    """

    RESUBMIT_EVERY = 0.5  # sec; matches RaftUniquenessProvider pacing

    def __init__(self, member: RaftMember, pump: Callable[[], None],
                 shards, timeout: float = 25.0):
        self.member = member
        self._pump = pump
        self.timeout = timeout
        self._local = RaftUniquenessProvider(member, pump, timeout)
        self.count = int(shards.count)
        self.groups = tuple(tuple(g) for g in shards.groups)
        self.ttl_s = float(shards.reserve_ttl_s)
        self.my_group = next(
            (i for i, g in enumerate(self.groups) if member.name in g), None)
        # Per-group preferred target member for the cross-group channel:
        # starts at the group's first member, follows leader hints from
        # bounce replies (satellite-1 semantics: hints are PER GROUP — a
        # deposed leader's hint from group 0 never redirects group 1).
        self._targets: dict[int, str] = {
            g: members[0] for g, members in enumerate(self.groups) if members}
        self.metrics = {
            "single_shard": 0,    # fast-path commits routed locally
            "cross_shard": 0,     # two-phase coordinations started
            "remote_single": 0,   # single-group txs owned by another group
            "aborts_sent": 0,     # phase-1 failures unwound
            "reserve_retries": 0,  # busy/leaderless resubmissions, phase 1
        }

    # -- commit ------------------------------------------------------------

    def commit_async(self, states: Sequence, tx_id: SecureHash,
                     caller_identity: Party) -> Callable[[], bool | None]:
        refs = tuple(states)
        by_group = split_by_shard(refs, self.count)
        touched = set(by_group)
        if not touched or touched == {self.my_group}:
            # Fast path: everything this member's own group owns — the
            # exact unsharded protocol, byte-identical commands.
            self.metrics["single_shard"] += 1
            return self._local.commit_async(refs, tx_id, caller_identity)
        if len(touched) == 1:
            # Single foreign group: no atomicity to coordinate — one remote
            # PutAll through the cross-group channel (a 2PC would add a
            # round trip for nothing).
            self.metrics["remote_single"] += 1
            return self._remote_put_poll(next(iter(touched)),
                                         refs, tx_id, caller_identity)
        self.metrics["cross_shard"] += 1
        return self._two_phase_poll(by_group, tx_id, caller_identity)

    def commit(self, states: Sequence, tx_id: SecureHash,
               caller_identity: Party) -> None:
        poll = self.commit_async(states, tx_id, caller_identity)
        while True:
            outcome = poll()
            if outcome is not None:
                return
            self._pump()

    # -- op plumbing -------------------------------------------------------

    def _new_op(self, group: int) -> dict:
        return {"group": group, "rid": os.urandom(16), "submitted_at": 0.0,
                "done": False, "conflict": None}

    def _dispatch(self, op: dict, command) -> None:
        """Send one command toward its owning group: local group submits to
        the local member (the ordinary follower-forwarding path applies);
        remote groups get a ClientCommit frame addressed to the tracked
        target member, replies landing in the local member's mailbox."""
        if op["group"] == self.my_group:
            self.member.submit(command)
            return
        target = self._targets.get(op["group"])
        addr = self.member._peer_addr(target)
        if addr is None:
            # Target not resolvable yet (netmap lag): leave submitted_at so
            # the pacing loop retries; the periodic netmap refresh fills
            # the resolver.
            return
        self.member._send(addr, ClientCommit(command, self.member.name))

    def _poll_op(self, op: dict, make_command, now: float) -> None:
        """Advance one outstanding command: consume a decision if present,
        otherwise (re)submit on the RESUBMIT_EVERY pace with a fresh
        issued_at stamp (same rid — idempotent through leader changes and
        deterministic against reservation expiry)."""
        if op["done"] or op["conflict"] is not None:
            return
        reply = self.member.decided.pop(op["rid"], None)
        if reply is not None:
            if reply.ok:
                op["done"] = True
                return
            if reply.conflict is not None:
                op["conflict"] = reply.conflict
                return
            # Busy hold or leaderless bounce: follow the hint WITHIN this
            # group only, and let the pacing below resubmit.
            hint = reply.leader_hint
            if hint and hint in self.groups[op["group"]]:
                self._targets[op["group"]] = hint
            op["retries"] = op.get("retries", 0) + 1
        if (op["submitted_at"] == 0.0
                or now - op["submitted_at"] >= self.RESUBMIT_EVERY):
            self._dispatch(op, make_command(op))
            op["submitted_at"] = now

    def _send_aborts(self, by_group: dict[int, tuple], tx_id) -> None:
        """Best-effort unwind: one AbortReservedCommand per touched group.
        Fire-and-forget — a lost abort is exactly the crashed-coordinator
        case, and the reservation TTL releases the holds deterministically."""
        self.metrics["aborts_sent"] += 1
        for group, refs in by_group.items():
            op = self._new_op(group)
            self._dispatch(op, AbortReservedCommand(refs, tx_id,
                                                    op["rid"]))

    # -- poll machines -----------------------------------------------------

    def _remote_put_poll(self, group: int, refs, tx_id, caller):
        op = self._new_op(group)
        deadline = _time.monotonic() + self.timeout
        ctx = _obs.get_context() if _obs.ACTIVE is not None else None
        if ctx is not None:
            _obs.register_link(op["rid"], ctx[0], ctx[1])
            t0 = _obs.now()
        qctx = _qos.get_context() if _qos.ACTIVE is not None else None
        if qctx is not None:
            # QoS link beside the trace link: the owning group's leader
            # sees the lane/deadline when deciding whether to seal early.
            _qos.ACTIVE.register_link(op["rid"], qctx)

        def make_command(op):
            return PutAllCommand(
                refs, tx_id, caller, op["rid"],
                # lint: allow(no-wallclock-in-apply) coordinator stamping site: clock read once, carried in the command, applied identically by every replica
                issued_at=_time.time())

        def poll():
            now = _time.monotonic()
            self._poll_op(op, make_command, now)
            if op["conflict"] is not None:
                raise UniquenessException(op["conflict"])
            if op["done"]:
                if ctx is not None and _obs.ACTIVE is not None:
                    _obs.record("raft_commit", t0, _obs.now(),
                                trace_id=ctx[0], parent=ctx[1],
                                attrs={"ok": True, "remote_group": group})
                    _obs.pop_link(op["rid"])
                if qctx is not None and _qos.ACTIVE is not None:
                    _qos.ACTIVE.pop_link(op["rid"])
                return True
            if now >= deadline:
                raise CommitTimeoutException(
                    f"remote shard {group} did not decide {tx_id} within "
                    f"{self.timeout}s (target: {self._targets.get(group)})")
            return None

        return poll

    def _two_phase_poll(self, by_group: dict[int, tuple], tx_id, caller):
        groups = sorted(by_group)
        deadline = _time.monotonic() + self.timeout
        ctx = _obs.get_context() if _obs.ACTIVE is not None else None
        state = {
            "phase": "reserve",
            "ops": {g: self._new_op(g) for g in groups},
            "t_phase": _obs.now() if ctx is not None else 0.0,
        }
        if ctx is not None:
            for op in state["ops"].values():
                _obs.register_link(op["rid"], ctx[0], ctx[1])
        qctx = _qos.get_context() if _qos.ACTIVE is not None else None
        if qctx is not None:
            for op in state["ops"].values():
                _qos.ACTIVE.register_link(op["rid"], qctx)

        def reserve_command(op):
            return ReserveCommand(
                by_group[op["group"]], tx_id, caller, op["rid"],
                # lint: allow(no-wallclock-in-apply) coordinator stamping site: the TTL baseline rides the command; replicas compare stamps, never their own clocks
                issued_at=_time.time(), ttl_s=self.ttl_s)

        def commit_command(op):
            return CommitReservedCommand(by_group[op["group"]], tx_id,
                                         caller, op["rid"])

        def _record_phase(name: str) -> None:
            if ctx is not None and _obs.ACTIVE is not None:
                _obs.record(name, state["t_phase"], _obs.now(),
                            trace_id=ctx[0], parent=ctx[1],
                            attrs={"groups": len(groups)})
                state["t_phase"] = _obs.now()

        def poll():
            now = _time.monotonic()
            make = (reserve_command if state["phase"] == "reserve"
                    else commit_command)
            if state["phase"] == "reserve":
                # ORDERED acquisition: groups reserve strictly in sorted
                # order, the next group only after the previous hold is in
                # hand. Two live coordinators contending on the same groups
                # therefore serialize at the lowest contended group instead
                # of deadlocking half-holds against each other until both
                # TTL-steal simultaneously (a partial-commit window). Costs
                # one group RTT per extra group in phase 1; the TTL remains
                # the backstop for CRASHED coordinators only.
                for g in groups:
                    op = state["ops"][g]
                    before = op.get("retries", 0)
                    self._poll_op(op, make, now)
                    self.metrics["reserve_retries"] += (
                        op.get("retries", 0) - before)
                    if not op["done"] and op["conflict"] is None:
                        break
            else:
                for op in state["ops"].values():
                    self._poll_op(op, make, now)
            conflict = next((op["conflict"]
                             for op in state["ops"].values()
                             if op["conflict"] is not None), None)
            if conflict is not None:
                if state["phase"] == "reserve":
                    # Some input is finally spent elsewhere: release every
                    # hold this attempt may have taken, then surface the
                    # conflict (final — the client sees a double-spend).
                    self._send_aborts(by_group, tx_id)
                _record_phase("shard_reserve" if state["phase"] == "reserve"
                              else "shard_commit")
                raise UniquenessException(conflict)
            if all(op["done"] for op in state["ops"].values()):
                if state["phase"] == "reserve":
                    _record_phase("shard_reserve")
                    state["phase"] = "commit"
                    state["ops"] = {g: self._new_op(g) for g in groups}
                    if ctx is not None:
                        for op in state["ops"].values():
                            _obs.register_link(op["rid"], ctx[0], ctx[1])
                    if qctx is not None and _qos.ACTIVE is not None:
                        for op in state["ops"].values():
                            _qos.ACTIVE.register_link(op["rid"], qctx)
                    return None
                _record_phase("shard_commit")
                return True
            if now >= deadline:
                if state["phase"] == "reserve":
                    # Could not assemble the full reservation set in time:
                    # unwind (best effort; TTL is the deterministic
                    # backstop) and report retryable unavailability.
                    self._send_aborts(by_group, tx_id)
                # Phase 2 deadline: do NOT abort — some groups may already
                # have committed, and a retry of the same tx_id converges to
                # the full commit (reserve/commit are idempotent per tx).
                raise CommitTimeoutException(
                    f"cross-shard {state['phase']} of {tx_id} over groups "
                    f"{groups} not decided within {self.timeout}s")
            return None

        return poll

    # -- introspection -----------------------------------------------------

    @property
    def committed_count(self) -> int:
        (n,) = self.member.db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        return n

    def leader_hint(self) -> str | None:
        """The LOCAL group's believed leader (NotaryUnavailable replies are
        answered by a member of one group; its hint must only ever redirect
        clients within that group — flows/notary.py keys hints per group)."""
        return self.member.leader_name

    def stamp(self) -> dict:
        m = self.metrics
        return {
            "shards": self.count,
            "my_group": self.my_group,
            "single_shard": m["single_shard"],
            "remote_single": m["remote_single"],
            "cross_shard": m["cross_shard"],
            "aborts_sent": m["aborts_sent"],
            "reserve_retries": m["reserve_retries"],
        }
