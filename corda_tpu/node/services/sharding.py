"""Sharded notary: StateRef-partitioned uniqueness over N Raft groups.

One Raft group caps committed tx/s no matter how fast the verify plane gets
(ROADMAP item 3). This subsystem partitions the input-state space by
StateRef hash across N independent Raft groups — each group runs the full
PR 2 machinery (group commit, pipelined replication, coalesced frames)
over its own slice of the keyspace, so committed throughput scales with
shard count.

Shard map
---------
``shard_of(ref, n)`` is a pure function of the StateRef alone: the first 8
bytes of the ref's txhash (already uniform — it is a SHA-256 Merkle root)
XOR the output index, mod n. Every party computes it locally; the only
shared datum is the shard COUNT, which rides the netmap as one advertised
service string per shard member (``corda.notary.shard.<g>``), so clients
build the directory from the network map they already have.

Commit protocol
---------------
* Single-shard transaction whose owning group is the local member's group:
  the exact RaftUniquenessProvider path — same PutAllCommand, same group
  commit, same reply protocol. Semantics identical to the unsharded notary.
* Otherwise, a two-phase coordinator drives the owning groups:

    phase 1  ReserveCommand on every touched group, acquired strictly in
             sorted group order (the next group only once the previous
             hold is in hand — lock ordering, so live coordinators
             contending on the same groups serialize instead of
             deadlocking half-holds). Atomic per group: every input free
             (or held/committed by this tx) or none; rejected with a
             final conflict if committed by another tx, bounced BUSY if
             held by another unexpired 2PC.
    phase 2  all reserved -> CommitReservedCommand everywhere;
             any conflict  -> AbortReservedCommand everywhere (best effort)
             and the conflict surfaces to the caller.

  Reservations carry a TTL stamped by the coordinator (issued_at + ttl_s),
  and expiry is judged by comparing OTHER commands' issued_at stamps
  against it — replicas never consult a local clock, so the state machines
  cannot diverge (node/services/raft.py make_apply_command). A coordinator
  that crashes between phases therefore never wedges inputs: its holds
  become steals for any later command stamped past the expiry.

  A retried 2PC for the same tx_id converges: reserve treats
  committed-by-this-tx as success and CommitReserved is idempotent, so
  exactly-once holds across coordinator retries, keyed on tx_id — the same
  invariant the single-group path gets from first-committer-wins.

Cross-group transport rides the existing Raft client channel: the
coordinator sends ClientCommit(command, reply_to=<my member name>) to a
member of the target group, and decisions come back as ClientReply frames
into the local member's ``decided`` mailbox. The member resolves reply
addresses beyond its own peers through the netmap resolver the node
injects (RaftMember.resolve_addr).

Elastic resharding (round 13)
-----------------------------
The shard map is EPOCH'd: service strings carry ``@<epoch>`` past epoch 0
and clients prefer the highest *complete* epoch when building the
directory. A reshard is restricted to a doubling split (N -> 2N) or a
halving merge (2M -> M) because the hash is consistent under exactly those
moves: ``h % N == g`` implies ``h % 2N in {g, g+N}`` and
``h % M == g % M`` — every target group receives keys from exactly ONE
source group, so a single source leader can coordinate each handoff
without cross-source agreement.

The transition is a replicated fence + idempotent state stream
(node/services/raft.py ShardFenceCommand / InstallShardStateCommand):
the source group SEALS (its fence's log position linearizes the handoff
snapshot — moved refs bounce ``WrongShardEpoch`` from that entry on),
streams its moved ``committed_states``/``reserved_states`` rows to the
target group (first frame fences the target ``importing``), ACTIVATES the
target, then activates (or retires) itself — at which point activation
purges the moved rows, so the ledger-side audit (sum of per-group rows)
never double-counts. Every step is replicated and idempotent: a crashed
coordinator is survived by the next leader of the source group re-running
the whole sequence, and streamed reservations keep their original
coordinator-stamped ``expires_at`` so the TTL backstop carries across the
handoff unchanged. Clients that raced the transition get the retryable
``WrongShardEpochException`` and re-derive the directory
(flows/notary.py notarise_with_retry) — a p99 blip, not an outage.

Failure matrix: ARCHITECTURE.md "Elastic resharding (round 13)".
"""

from __future__ import annotations

import os
import time as _time
from typing import Any, Callable, Sequence

from ...crypto.hashes import SecureHash
from ...crypto.party import Party
from ...obs import trace as _obs
from ...qos import context as _qos
from ...serialization.codec import deserialize
from ...testing import faults as _faults
from . import integrity as _integrity
from .api import UniquenessException, UniquenessProvider
from .raft import (
    AbortReservedCommand,
    ClientCommit,
    CommitReservedCommand,
    CommitTimeoutException,
    InstallShardStateCommand,
    PutAllCommand,
    RaftMember,
    RaftUniquenessProvider,
    ReserveCommand,
    ShardFenceCommand,
    WrongShardEpochException,
)

__all__ = [  # re-exports: the fence exception is raised here and in raft
    "SHARD_SERVICE_PREFIX", "RESHARD_PLAN_PREFIX", "RESHARD_PLAN_ENTRY",
    "shard_of", "split_by_shard", "shard_service_string",
    "parse_shard_service", "parse_shard_service_full",
    "reshard_plan_string", "parse_reshard_plan", "publish_reshard_plan",
    "ShardedUniquenessProvider", "WrongShardEpochException",
]

# Netmap service-string prefix: member of shard group g advertises
# f"{SHARD_SERVICE_PREFIX}{g}of{count}" so clients recover both the group
# id and the total shard count from the directory they already sync.
# Past epoch 0 the string carries "@<epoch>" so a directory mixing old and
# new advertisements is disambiguated by epoch, not by count alone.
SHARD_SERVICE_PREFIX = "corda.notary.shard."

# Reshard plans ride the SAME network map, as one service string on a
# control pseudo-entry (name RESHARD_PLAN_ENTRY). Node.refresh_netmap
# skips "_"-prefixed entries when building the party directory and parses
# the plan out of them instead — no new channel, no new watcher.
RESHARD_PLAN_PREFIX = "corda.notary.reshard."
RESHARD_PLAN_ENTRY = "_reshard"


def shard_of(ref, count: int) -> int:
    """Owning group of a StateRef — a pure function every party computes
    identically from the ref alone (txhash is a SHA-256 Merkle root, so the
    leading 8 bytes are already uniform; XOR the index so the outputs of
    one transaction spread instead of clustering on one group)."""
    if count <= 1:
        return 0
    return (int.from_bytes(ref.txhash.bytes[:8], "big") ^ ref.index) % count


def split_by_shard(refs, count: int) -> dict[int, tuple]:
    """Group refs by owning shard, preserving order within each group."""
    by_group: dict[int, list] = {}
    for ref in refs:
        by_group.setdefault(shard_of(ref, count), []).append(ref)
    return {g: tuple(v) for g, v in by_group.items()}


def shard_service_string(group: int, count: int, epoch: int = 0) -> str:
    """Advertised service string for a group. Epoch 0 (the boot map) keeps
    the original bare format so pre-reshard directories stay byte-stable;
    later epochs append ``@<epoch>``."""
    base = f"{SHARD_SERVICE_PREFIX}{group}of{count}"
    return base if epoch <= 0 else f"{base}@{epoch}"


def parse_shard_service(service: str) -> tuple[int, int] | None:
    """(group, count) from an advertised service string, else None."""
    full = parse_shard_service_full(service)
    return None if full is None else full[:2]


def parse_shard_service_full(service: str) -> tuple[int, int, int] | None:
    """(group, count, epoch) from an advertised service string, else None.
    A bare (pre-reshard) string parses as epoch 0."""
    if not service.startswith(SHARD_SERVICE_PREFIX):
        return None
    tail = service[len(SHARD_SERVICE_PREFIX):]
    tail, _, epoch_s = tail.partition("@")
    group_s, _, count_s = tail.partition("of")
    try:
        group, count = int(group_s), int(count_s)
        epoch = int(epoch_s) if epoch_s else 0
    except ValueError:
        return None
    if count <= 0 or epoch < 0 or not 0 <= group < count:
        return None
    return group, count, epoch


def reshard_plan_string(epoch: int, from_count: int, to_count: int) -> str:
    return f"{RESHARD_PLAN_PREFIX}{epoch}:{from_count}to{to_count}"


def parse_reshard_plan(service: str) -> tuple[int, int, int] | None:
    """(epoch, from_count, to_count) from a plan service string, else None.
    Only shape-valid plans parse: a doubling split or a halving merge with
    a positive epoch (epoch 0 is the boot map and can never be a target)."""
    if not service.startswith(RESHARD_PLAN_PREFIX):
        return None
    tail = service[len(RESHARD_PLAN_PREFIX):]
    epoch_s, _, counts = tail.partition(":")
    from_s, _, to_s = counts.partition("to")
    try:
        epoch, from_count, to_count = int(epoch_s), int(from_s), int(to_s)
    except ValueError:
        return None
    if epoch <= 0 or from_count <= 0 or to_count <= 0:
        return None
    if to_count != 2 * from_count and from_count != 2 * to_count:
        return None  # only doubling splits / halving merges are consistent
    return epoch, from_count, to_count


def publish_reshard_plan(network_map: str, epoch: int, from_count: int,
                         to_count: int, owning_key) -> None:
    """Publish (or supersede) the reshard plan through the network map.
    The plan is one service string on a control pseudo-entry — every node
    picks it up on its ordinary netmap refresh cadence; the affected source
    group leaders start the handoff, everyone else just learns the epoch."""
    plan = reshard_plan_string(epoch, from_count, to_count)
    if parse_reshard_plan(plan) is None:
        raise ValueError(
            f"invalid reshard plan: epoch={epoch} {from_count}->{to_count} "
            f"(only doubling splits / halving merges; epoch must be > 0)")
    from ..config import netmap_register
    netmap_register(network_map, RESHARD_PLAN_ENTRY, "0.0.0.0", 0,
                    owning_key, (plan,))


class ShardedUniquenessProvider(UniquenessProvider):
    """RaftUniquenessProvider-compatible facade over N Raft groups.

    The local member belongs to exactly ONE group (its raft_cluster); this
    provider routes single-shard traffic for that group straight through
    the plain provider and coordinates the two-phase protocol for
    everything else. Poll-driven like commit_async everywhere else in the
    framework: the returned callable is parked on a ServiceRequest and the
    node's run loop drives it, so the notary flow never blocks the message
    pump that consensus (and the cross-group channel) rides on.
    """

    RESUBMIT_EVERY = 0.5  # sec; matches RaftUniquenessProvider pacing

    def __init__(self, member: RaftMember, pump: Callable[[], None],
                 shards, timeout: float = 25.0):
        self.member = member
        self._pump = pump
        self.timeout = timeout
        self._local = RaftUniquenessProvider(member, pump, timeout)
        self.count = int(shards.count)
        self.epoch = 0
        self.groups = tuple(tuple(g) for g in shards.groups)
        self.ttl_s = float(shards.reserve_ttl_s)
        # The groups list may be LONGER than count: groups >= count are
        # PENDING split targets, booted and electable but owning no keys
        # until a reshard epoch activates them.
        self.my_group = next(
            (i for i, g in enumerate(self.groups) if member.name in g), None)
        # At most one live handoff this member coordinates (source leader).
        self._reshard: dict | None = None
        # Replay a persisted fence (restart mid- or post-reshard): the
        # routing count/epoch must match what the group's state machine
        # already enforces, or every local fast-path commit would bounce.
        fence = self._read_fence()
        if fence is not None and fence.get("mode") in ("active", "retired"):
            self.count = int(fence["count"])
            self.epoch = int(fence["epoch"])
        # Per-group preferred target member for the cross-group channel:
        # starts at the group's first member, follows leader hints from
        # bounce replies (satellite-1 semantics: hints are PER GROUP — a
        # deposed leader's hint from group 0 never redirects group 1).
        self._targets: dict[int, str] = {
            g: members[0] for g, members in enumerate(self.groups) if members}
        self.metrics = {
            "single_shard": 0,    # fast-path commits routed locally
            "cross_shard": 0,     # two-phase coordinations started
            "remote_single": 0,   # single-group txs owned by another group
            "aborts_sent": 0,     # phase-1 failures unwound
            "reserve_retries": 0,  # busy/leaderless resubmissions, phase 1
            "wrong_epoch": 0,     # fence bounces surfaced to callers
            "handoff_frames": 0,  # InstallShardState frames acked (as src)
            "resharded": 0,       # handoffs this member coordinated to done
        }

    # -- commit ------------------------------------------------------------

    def commit_async(self, states: Sequence, tx_id: SecureHash,
                     caller_identity: Party) -> Callable[[], bool | None]:
        refs = tuple(states)
        by_group = split_by_shard(refs, self.count)
        touched = set(by_group)
        if not touched or touched == {self.my_group}:
            # Fast path: everything this member's own group owns — the
            # exact unsharded protocol, byte-identical commands. Only the
            # wrong_epoch accounting wraps it: a reshard fence can bounce
            # the local group too, and the bench counts every bounce.
            self.metrics["single_shard"] += 1
            inner = self._local.commit_async(refs, tx_id, caller_identity)

            def poll():
                try:
                    return inner()
                except WrongShardEpochException:
                    self.metrics["wrong_epoch"] += 1
                    raise

            return poll
        if len(touched) == 1:
            # Single foreign group: no atomicity to coordinate — one remote
            # PutAll through the cross-group channel (a 2PC would add a
            # round trip for nothing).
            self.metrics["remote_single"] += 1
            return self._remote_put_poll(next(iter(touched)),
                                         refs, tx_id, caller_identity)
        self.metrics["cross_shard"] += 1
        return self._two_phase_poll(by_group, tx_id, caller_identity)

    def commit(self, states: Sequence, tx_id: SecureHash,
               caller_identity: Party) -> None:
        poll = self.commit_async(states, tx_id, caller_identity)
        while True:
            outcome = poll()
            if outcome is not None:
                return
            self._pump()

    # -- op plumbing -------------------------------------------------------

    def _new_op(self, group: int) -> dict:
        return {"group": group, "rid": os.urandom(16), "submitted_at": 0.0,
                "done": False, "conflict": None, "wrong_epoch": False}

    def _dispatch(self, op: dict, command) -> None:
        """Send one command toward its owning group: local group submits to
        the local member (the ordinary follower-forwarding path applies);
        remote groups get a ClientCommit frame addressed to the tracked
        target member, replies landing in the local member's mailbox."""
        if op["group"] == self.my_group:
            self.member.submit(command)
            return
        target = self._targets.get(op["group"])
        addr = self.member._peer_addr(target)
        if addr is None:
            # Target not resolvable yet (netmap lag): leave submitted_at so
            # the pacing loop retries; the periodic netmap refresh fills
            # the resolver.
            return
        self.member._send(addr, ClientCommit(command, self.member.name))

    def _poll_op(self, op: dict, make_command, now: float) -> None:
        """Advance one outstanding command: consume a decision if present,
        otherwise (re)submit on the RESUBMIT_EVERY pace with a fresh
        issued_at stamp (same rid — idempotent through leader changes and
        deterministic against reservation expiry)."""
        if op["done"] or op["conflict"] is not None or op["wrong_epoch"]:
            return
        reply = self.member.decided.pop(op["rid"], None)
        if reply is not None:
            if reply.ok:
                op["done"] = True
                return
            if reply.conflict is not None:
                op["conflict"] = reply.conflict
                return
            if reply.wrong_epoch:
                # Reshard fence bounce: resubmitting to this group can
                # never succeed — flag it and stop the pacing loop; the
                # poll machine surfaces WrongShardEpochException.
                op["wrong_epoch"] = True
                return
            # Busy hold or leaderless bounce: follow the hint WITHIN this
            # group only, and let the pacing below resubmit.
            hint = reply.leader_hint
            if hint and hint in self.groups[op["group"]]:
                self._targets[op["group"]] = hint
            op["retries"] = op.get("retries", 0) + 1
        if (op["submitted_at"] == 0.0
                or now - op["submitted_at"] >= self.RESUBMIT_EVERY):
            self._dispatch(op, make_command(op))
            op["submitted_at"] = now

    def _send_aborts(self, by_group: dict[int, tuple], tx_id) -> None:
        """Best-effort unwind: one AbortReservedCommand per touched group.
        Fire-and-forget — a lost abort is exactly the crashed-coordinator
        case, and the reservation TTL releases the holds deterministically."""
        self.metrics["aborts_sent"] += 1
        for group, refs in by_group.items():
            op = self._new_op(group)
            self._dispatch(op, AbortReservedCommand(refs, tx_id,
                                                    op["rid"]))

    # -- poll machines -----------------------------------------------------

    def _remote_put_poll(self, group: int, refs, tx_id, caller):
        op = self._new_op(group)
        deadline = _time.monotonic() + self.timeout
        ctx = _obs.get_context() if _obs.ACTIVE is not None else None
        if ctx is not None:
            _obs.register_link(op["rid"], ctx[0], ctx[1])
            t0 = _obs.now()
        qctx = _qos.get_context() if _qos.ACTIVE is not None else None
        if qctx is not None:
            # QoS link beside the trace link: the owning group's leader
            # sees the lane/deadline when deciding whether to seal early.
            _qos.ACTIVE.register_link(op["rid"], qctx)

        def make_command(op):
            return PutAllCommand(
                refs, tx_id, caller, op["rid"],
                # lint: allow(no-wallclock-in-apply) coordinator stamping site: clock read once, carried in the command, applied identically by every replica
                issued_at=_time.time())

        def poll():
            now = _time.monotonic()
            self._poll_op(op, make_command, now)
            if op["conflict"] is not None:
                raise UniquenessException(op["conflict"])
            if op["wrong_epoch"]:
                self.metrics["wrong_epoch"] += 1
                if ctx is not None and _obs.ACTIVE is not None:
                    _obs.pop_link(op["rid"])
                if qctx is not None and _qos.ACTIVE is not None:
                    _qos.ACTIVE.pop_link(op["rid"])
                raise WrongShardEpochException(
                    f"group {group} fenced off {tx_id} (reshard in "
                    f"progress; re-derive the shard directory)")
            if op["done"]:
                if ctx is not None and _obs.ACTIVE is not None:
                    _obs.record("raft_commit", t0, _obs.now(),
                                trace_id=ctx[0], parent=ctx[1],
                                attrs={"ok": True, "remote_group": group})
                    _obs.pop_link(op["rid"])
                if qctx is not None and _qos.ACTIVE is not None:
                    _qos.ACTIVE.pop_link(op["rid"])
                return True
            if now >= deadline:
                raise CommitTimeoutException(
                    f"remote shard {group} did not decide {tx_id} within "
                    f"{self.timeout}s (target: {self._targets.get(group)})")
            return None

        return poll

    def _two_phase_poll(self, by_group: dict[int, tuple], tx_id, caller):
        groups = sorted(by_group)
        deadline = _time.monotonic() + self.timeout
        ctx = _obs.get_context() if _obs.ACTIVE is not None else None
        state = {
            "phase": "reserve",
            "ops": {g: self._new_op(g) for g in groups},
            "t_phase": _obs.now() if ctx is not None else 0.0,
        }
        if ctx is not None:
            for op in state["ops"].values():
                _obs.register_link(op["rid"], ctx[0], ctx[1])
        qctx = _qos.get_context() if _qos.ACTIVE is not None else None
        if qctx is not None:
            for op in state["ops"].values():
                _qos.ACTIVE.register_link(op["rid"], qctx)

        def reserve_command(op):
            return ReserveCommand(
                by_group[op["group"]], tx_id, caller, op["rid"],
                # lint: allow(no-wallclock-in-apply) coordinator stamping site: the TTL baseline rides the command; replicas compare stamps, never their own clocks
                issued_at=_time.time(), ttl_s=self.ttl_s)

        def commit_command(op):
            return CommitReservedCommand(by_group[op["group"]], tx_id,
                                         caller, op["rid"])

        def _record_phase(name: str) -> None:
            if ctx is not None and _obs.ACTIVE is not None:
                _obs.record(name, state["t_phase"], _obs.now(),
                            trace_id=ctx[0], parent=ctx[1],
                            attrs={"groups": len(groups)})
                state["t_phase"] = _obs.now()

        def poll():
            now = _time.monotonic()
            make = (reserve_command if state["phase"] == "reserve"
                    else commit_command)
            if state["phase"] == "reserve":
                # ORDERED acquisition: groups reserve strictly in sorted
                # order, the next group only after the previous hold is in
                # hand. Two live coordinators contending on the same groups
                # therefore serialize at the lowest contended group instead
                # of deadlocking half-holds against each other until both
                # TTL-steal simultaneously (a partial-commit window). Costs
                # one group RTT per extra group in phase 1; the TTL remains
                # the backstop for CRASHED coordinators only.
                for g in groups:
                    op = state["ops"][g]
                    before = op.get("retries", 0)
                    self._poll_op(op, make, now)
                    self.metrics["reserve_retries"] += (
                        op.get("retries", 0) - before)
                    if not op["done"] and op["conflict"] is None:
                        break
            else:
                for op in state["ops"].values():
                    self._poll_op(op, make, now)
            conflict = next((op["conflict"]
                             for op in state["ops"].values()
                             if op["conflict"] is not None), None)
            if conflict is not None:
                if state["phase"] == "reserve":
                    # Some input is finally spent elsewhere: release every
                    # hold this attempt may have taken, then surface the
                    # conflict (final — the client sees a double-spend).
                    self._send_aborts(by_group, tx_id)
                _record_phase("shard_reserve" if state["phase"] == "reserve"
                              else "shard_commit")
                raise UniquenessException(conflict)
            if any(op["wrong_epoch"] for op in state["ops"].values()):
                # A touched group resharded under this coordination. The
                # whole 2PC must re-route: release what phase 1 took (best
                # effort — an abort a sealed group bounces is covered by
                # the streamed reservation + TTL backstop) and surface the
                # retryable epoch error. A retry of the same tx_id at the
                # new directory CONVERGES: reserve treats held-by-this-tx
                # (including holds streamed during the handoff) as success
                # and commit-reserved is idempotent.
                if state["phase"] == "reserve":
                    self._send_aborts(by_group, tx_id)
                self.metrics["wrong_epoch"] += 1
                _record_phase("shard_reserve" if state["phase"] == "reserve"
                              else "shard_commit")
                raise WrongShardEpochException(
                    f"cross-shard {state['phase']} of {tx_id} bounced off "
                    f"a reshard fence; re-derive the shard directory")
            if all(op["done"] for op in state["ops"].values()):
                if state["phase"] == "reserve":
                    _record_phase("shard_reserve")
                    state["phase"] = "commit"
                    state["ops"] = {g: self._new_op(g) for g in groups}
                    if ctx is not None:
                        for op in state["ops"].values():
                            _obs.register_link(op["rid"], ctx[0], ctx[1])
                    if qctx is not None and _qos.ACTIVE is not None:
                        for op in state["ops"].values():
                            _qos.ACTIVE.register_link(op["rid"], qctx)
                    return None
                _record_phase("shard_commit")
                return True
            if now >= deadline:
                if state["phase"] == "reserve":
                    # Could not assemble the full reservation set in time:
                    # unwind (best effort; TTL is the deterministic
                    # backstop) and report retryable unavailability.
                    self._send_aborts(by_group, tx_id)
                # Phase 2 deadline: do NOT abort — some groups may already
                # have committed, and a retry of the same tx_id converges to
                # the full commit (reserve/commit are idempotent per tx).
                raise CommitTimeoutException(
                    f"cross-shard {state['phase']} of {tx_id} over groups "
                    f"{groups} not decided within {self.timeout}s")
            return None

        return poll

    # -- elastic resharding ------------------------------------------------

    def _read_fence(self) -> dict | None:
        """The group's APPLIED fence state (what its replicated state
        machine currently enforces), from the member's settings table."""
        import json
        raw = self.member.db.get_setting("shard_fence")
        return json.loads(raw) if raw else None

    def reconfigure(self, count: int, epoch: int) -> None:
        """Adopt a new shard-map epoch for ROUTING. Monotonic: an older or
        equal epoch is a no-op (directory races must never roll the router
        back). Correctness never depends on this — fences enforce; a stale
        router just buys bounces and retries."""
        if int(epoch) <= self.epoch:
            return
        self.count = int(count)
        self.epoch = int(epoch)

    def _reshard_role(self, from_count: int, to_count: int
                      ) -> tuple[int, int] | None:
        """(source_group, target_group) if this member's group hands state
        off under the plan, else None. Split g -> {g, g+N}: sources are the
        first N groups, targets the pending upper half. Merge: sources are
        the retiring upper half, each folding into group g - M."""
        g = self.my_group
        if g is None:
            return None
        if to_count == 2 * from_count and g < from_count:
            return g, g + from_count
        if from_count == 2 * to_count and to_count <= g < from_count:
            return g, g - to_count
        return None

    def _handoff_frames(self, target: int, to_count: int,
                        rows_per_frame: int = 256) -> list:
        """Snapshot the moved slice of this group's ledger, chunked for the
        client channel. Read AFTER the seal is applied locally: the seal's
        log position linearizes the snapshot — nothing can commit or
        reserve a moved ref behind it, so the read is complete. Always at
        least one (possibly empty) frame: the first frame is also what
        fences the target ``importing``."""
        db = self.member.db
        with db.lock:
            crows = db.conn.execute(
                "SELECT state_ref, consuming, crc FROM committed_states"
            ).fetchall()
            rrows = db.conn.execute(
                "SELECT state_ref, tx_id, expires_at, crc "
                "FROM reserved_states"
            ).fetchall()
        # Handoff doubles as an integrity sweep: every row leaving this
        # group is CRC-verified in passing. Detection only — the row still
        # streams (dropping a spent-input record would un-spend it on the
        # target, which is worse than forwarding a flagged one); repair is
        # the scrubber/fsck's job, and the counter makes the damage visible.
        for row in crows:
            if row[2] is not None and _integrity.committed_crc(
                    bytes(row[0]), bytes(row[1])) != int(row[2]):
                self.member.metrics["integrity_errors"] += 1
        for row in rrows:
            if row[3] is not None and _integrity.reserved_crc(
                    bytes(row[0]), bytes(row[1]),
                    float(row[2])) != int(row[3]):
                self.member.metrics["integrity_errors"] += 1
        moved_c = [(bytes(b), bytes(c)) for b, c, _crc in crows
                   if shard_of(deserialize(bytes(b)), to_count) == target]
        moved_r = [(bytes(b), bytes(t), float(e)) for b, t, e, _crc in rrows
                   if shard_of(deserialize(bytes(b)), to_count) == target]
        frames, i = [], 0
        while i < max(len(moved_c), len(moved_r)) or not frames:
            frames.append((tuple(moved_c[i:i + rows_per_frame]),
                           tuple(moved_r[i:i + rows_per_frame])))
            i += rows_per_frame
        return frames

    def reshard_tick(self, plan: tuple[int, int, int] | None,
                     now: float) -> None:
        """Advance (at most) one live handoff this member coordinates.
        Called every run-loop round by the node — non-blocking, one
        outstanding command at a time, paced by _poll_op.

        Only the CURRENT LEADER of a source group drives; followers and
        deposed leaders drop their local progress dict, because every step
        is replicated + idempotent and a new leader simply re-runs the
        whole seal -> stream -> activate-target -> activate-self sequence
        from its own applied state. Crash-mid-handoff (the
        ``shard.handoff`` fault point) is therefore survived by the next
        election, and streamed reservations keep their original
        expires_at, so a coordinator that dies forever still releases its
        holds by TTL."""
        if self.member.role != "leader":
            self._reshard = None
            return
        st = self._reshard
        if st is None:
            if plan is None:
                return
            epoch, from_count, to_count = plan
            if epoch <= self.epoch:
                return  # already adopted (or superseded): nothing to do
            fence = self._read_fence()
            if fence is not None and int(fence.get("epoch", 0)) >= epoch \
                    and fence.get("mode") in ("active", "retired"):
                # Applied state says the handoff finished (e.g. this member
                # just won an election after the old coordinator completed
                # everything but its own routing bump).
                self.reconfigure(int(fence["count"]), int(fence["epoch"]))
                return
            role = self._reshard_role(from_count, to_count)
            if role is None:
                return  # not a source group: fences/netmap carry the news
            src, target = role
            st = self._reshard = {
                "epoch": epoch, "from": from_count, "to": to_count,
                "src": src, "target": target, "stage": "seal",
                "op": None, "frames": None, "frame_idx": 0,
                "t0": _obs.now() if _obs.ACTIVE is not None else 0.0,
            }
        e, fc, tc = st["epoch"], st["from"], st["to"]
        if st["stage"] == "seal":
            if st["op"] is None:
                st["op"] = self._new_op(st["src"])
            self._poll_op(
                st["op"],
                lambda op: ShardFenceCommand(st["src"], fc, tc, e, "seal",
                                             op["rid"]),
                now)
            if st["op"]["done"]:
                st["stage"], st["op"] = "stream", None
            return
        if st["stage"] == "stream":
            if st["frames"] is None:
                st["frames"] = self._handoff_frames(st["target"], tc)
            if st["frame_idx"] >= len(st["frames"]):
                st["stage"], st["op"] = "activate_target", None
                return
            if st["op"] is None:
                st["op"] = self._new_op(st["target"])
                # Chaos hook, fired once per streamed frame: drop models a
                # lost frame (first send deferred one pacing interval —
                # the idempotent resubmit recovers), stall a slow link,
                # crash the coordinator-death-mid-handoff case.
                if _faults.ACTIVE is not None:
                    act = _faults.ACTIVE.fire("shard.handoff")
                    if act is not None:
                        action, delay_s = act
                        if action == "drop":
                            st["op"]["submitted_at"] = now
                        elif delay_s > 0.0:
                            _time.sleep(delay_s)
            committed, reserved = st["frames"][st["frame_idx"]]
            self._poll_op(
                st["op"],
                lambda op: InstallShardStateCommand(
                    committed, reserved, st["target"], fc, tc, e,
                    op["rid"]),
                now)
            if st["op"]["done"]:
                self.metrics["handoff_frames"] += 1
                st["frame_idx"] += 1
                st["op"] = None
            return
        if st["stage"] == "activate_target":
            if st["op"] is None:
                st["op"] = self._new_op(st["target"])
            self._poll_op(
                st["op"],
                lambda op: ShardFenceCommand(st["target"], fc, tc, e,
                                             "activate", op["rid"]),
                now)
            if st["op"]["done"]:
                st["stage"], st["op"] = "activate_self", None
            return
        if st["stage"] == "activate_self":
            # Target is durably active first: from here the moved rows
            # exist on the target's quorum, so purging them at our own
            # activation (raft.py _apply_fence) cannot lose state.
            if st["op"] is None:
                st["op"] = self._new_op(st["src"])
            self._poll_op(
                st["op"],
                lambda op: ShardFenceCommand(st["src"], fc, tc, e,
                                             "activate", op["rid"]),
                now)
            if st["op"]["done"]:
                if _obs.ACTIVE is not None:
                    _obs.record("shard_handoff", st["t0"], _obs.now(),
                                attrs={"epoch": e, "from": fc, "to": tc,
                                       "src": st["src"],
                                       "target": st["target"],
                                       "frames": len(st["frames"] or ())})
                self.metrics["resharded"] += 1
                self._reshard = None
                self.reconfigure(tc, e)

    # -- introspection -----------------------------------------------------

    @property
    def committed_count(self) -> int:
        (n,) = self.member.db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        return n

    def leader_hint(self) -> str | None:
        """The LOCAL group's believed leader (NotaryUnavailable replies are
        answered by a member of one group; its hint must only ever redirect
        clients within that group — flows/notary.py keys hints per group)."""
        return self.member.leader_name

    def stamp(self) -> dict:
        m = self.metrics
        return {
            "shards": self.count,
            "epoch": self.epoch,
            "my_group": self.my_group,
            "single_shard": m["single_shard"],
            "remote_single": m["remote_single"],
            "cross_shard": m["cross_shard"],
            "aborts_sent": m["aborts_sent"],
            "reserve_retries": m["reserve_retries"],
            "wrong_epoch": m["wrong_epoch"],
            "handoff_frames": m["handoff_frames"],
            "resharded": m["resharded"],
        }
