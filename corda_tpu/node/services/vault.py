"""The indexed vault plane: durable O(log n) queries over sqlite.

Capability match for the reference's DB-backed vault (reference:
node/src/main/kotlin/net/corda/node/services/vault/NodeVaultService.kt:39
over Services.kt:95 — the vault is a *database projection* of the ledger,
not an in-memory dict), built for the ROADMAP item-4 scale point:
millions of unconsumed states, thousands of parties.

Three tables in the node's single sqlite file (persistence.NodeDatabase):

  * ``vault_states`` — one row per unconsumed state: the (ref_txhash,
    ref_index) primary key, pushdown columns (state_type wire name,
    currency, amount for fungibles), the canonical-codec
    TransactionState blob, and a per-record CRC32C column following the
    PR 11 durability convention (verify-on-read, corrupt rows
    quarantined — a bitrot'd vault row becomes a visible repair event,
    never a silently wrong coin selection). Covering indexes on
    state_type and (currency, amount) make typed queries and coin
    selection index walks instead of full scans.
  * ``vault_participants`` — one row per (leaf public key, state) so
    participant-pushdown queries resolve through an index.
  * ``vault_balances`` — per-currency quantity aggregates maintained by
    delta UPSERTs on every vault update: balances are O(1) reads, the
    bounded-memory replacement for scanning observers.

**Watermark incremental boot**: every ``notify_all`` advances a
persisted ``vault_watermark`` setting to the highest ``transactions``
rowid it has folded in. A restarted node calls ``rebuild_from`` which
replays only ``rowid > watermark`` — the delta, not the ledger — in
bounded batches. Replay is idempotent by construction (produced rows
INSERT OR IGNORE, consumed rows DELETE-if-present, balance deltas only
applied when a row actually changed), so a crash between the watermark
and the vault rows re-runs cleanly.

**Soft-locked coin selection**: ``select_coins`` walks the
(currency, amount DESC) index and takes TTL'd in-process reservations on
the refs it returns, so two concurrent flows spending from the same
vault stop double-selecting (the loser skips the locked coin and picks
a different one instead of building a doomed double-spend that bounces
off the notary). Locks release on consumption, on explicit release, or
by TTL expiry — a crashed flow can never wedge a coin forever.

The legacy in-memory service stays the default engine; ``[vault]
indexed = true`` (or CORDA_TPU_VAULT_INDEXED=1) selects this one, and
tests/test_vault.py pins that both engines derive the identical
unconsumed set from the same update stream.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable

from ...contracts.structures import StateAndRef, StateRef
from ...crypto.hashes import SecureHash
from ...obs import telemetry as _tm
from ...obs import trace as _obs
from ...serialization.codec import (
    class_for_wire_name,
    deserialize,
    serialize,
    wire_name_of,
)
from . import integrity as _integrity
from .api import Vault, VaultService

__all__ = [
    "IndexedVaultService",
    "SoftLockManager",
    "VaultPage",
    "VaultQuery",
    "coin_of",
    "record_vault_stage",
    "seed_states",
]


def record_vault_stage(t0: float, attrs: dict) -> None:
    """Emit one vault_query span under the active trace (no-op when
    tracing is off); t0 came from _obs.now() at query entry."""
    if _obs.ACTIVE is None:
        return
    pctx = _obs.get_context()
    kw = {"attrs": attrs}
    if pctx is not None:
        kw.update(trace_id=pctx[0], parent=pctx[1])
    _obs.record("vault_query", t0, _obs.now(), **kw)


def coin_of(data) -> tuple[str | None, int | None]:
    """(currency, quantity) for a fungible state, (None, None) otherwise.

    Duck-typed like the schema projection: any state shaped
    ``.amount.token.product`` / ``.amount.quantity`` (CashState, every
    FungibleAsset) participates in the currency/amount pushdown columns
    and the balance aggregates with no node-tier import of finance."""
    amount = getattr(data, "amount", None)
    token = getattr(amount, "token", None)
    product = getattr(token, "product", None)
    quantity = getattr(amount, "quantity", None)
    if product is None or not isinstance(quantity, int) \
            or isinstance(quantity, bool):
        return (None, None)
    return (str(product), int(quantity))


@dataclass(frozen=True)
class VaultQuery:
    """Pushdown predicates + keyset cursor for one vault page.

    ``after`` is the keyset cursor — the (ref_txhash bytes, ref_index)
    of the last row of the previous page; pagination is stable under
    concurrent consumption because the cursor names a position in the
    (ref_txhash, ref_index) order, never an OFFSET that shifts when rows
    before it are consumed."""

    state_type: type | None = None
    currency: str | None = None
    min_amount: int | None = None
    max_amount: int | None = None
    participant: object | None = None  # PublicKey or CompositeKey
    after: tuple[bytes, int] | None = None
    page_size: int = 256


@dataclass(frozen=True)
class VaultPage:
    """One page of unconsumed states plus the cursor for the next."""

    states: tuple[StateAndRef, ...]
    next_cursor: tuple[bytes, int] | None


def _participant_leaves(key) -> tuple[bytes, ...]:
    """The leaf public-key encodings of a participant key (a CompositeKey
    exposes .keys; a bare PublicKey is its own single leaf)."""
    leaves = getattr(key, "keys", None)
    if leaves is None:
        encoded = getattr(key, "encoded", None)
        return (bytes(encoded),) if encoded is not None else ()
    return tuple(bytes(pk.encoded) for pk in leaves)


def _sort_key(sar: StateAndRef) -> tuple[bytes, int]:
    return (sar.ref.txhash.bytes, sar.ref.index)


class SoftLockManager:
    """TTL'd in-process coin reservations.

    Deliberately in-memory, not a table: a soft lock is advisory state
    scoped to the selecting process — the notary's first-committer-wins
    commit log stays the only double-spend authority, so a crash that
    loses the lock table loses nothing but a hint (and the TTL bounds
    how long a crashed flow's reservation can shadow a coin)."""

    def __init__(self, ttl_s: float = 5.0):
        self.ttl_s = float(ttl_s)
        self._locks: dict[StateRef, tuple[bytes, float]] = {}
        self._mu = threading.Lock()

    def sweep(self, now: float | None = None) -> int:
        """Drop expired reservations; returns how many were reaped."""
        now = time.monotonic() if now is None else now
        with self._mu:
            dead = [r for r, (_h, exp) in self._locks.items() if exp <= now]
            for ref in dead:
                del self._locks[ref]
        return len(dead)

    def try_lock(self, ref: StateRef, holder: bytes,
                 ttl_s: float | None = None,
                 now: float | None = None) -> bool:
        """Reserve ``ref`` for ``holder``; False if another live holder
        has it (re-locking your own reservation refreshes the TTL)."""
        now = time.monotonic() if now is None else now
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        with self._mu:
            held = self._locks.get(ref)
            if held is not None and held[1] > now and held[0] != holder:
                return False
            self._locks[ref] = (bytes(holder), now + ttl)
        return True

    def holder_of(self, ref: StateRef,
                  now: float | None = None) -> bytes | None:
        now = time.monotonic() if now is None else now
        with self._mu:
            held = self._locks.get(ref)
            return held[0] if held is not None and held[1] > now else None

    def release(self, refs: Iterable[StateRef],
                holder: bytes | None = None) -> None:
        """Drop reservations on ``refs`` (any holder when None — the
        consumption path: a spent coin's lock is moot whoever held it)."""
        with self._mu:
            for ref in refs:
                held = self._locks.get(ref)
                if held is not None and (holder is None
                                         or held[0] == holder):
                    del self._locks[ref]

    def __len__(self) -> int:
        return len(self._locks)


def _row_crc(ref_txhash: bytes, ref_index: int, blob: bytes) -> int:
    """PR 11 convention: one CRC32C per record, chained over the primary
    key and the payload so a row can never validate against another
    row's blob."""
    crc = _integrity.crc32c(ref_txhash)
    crc = _integrity.crc32c(ref_index.to_bytes(4, "big"), crc)
    return _integrity.crc32c(blob, crc)


_VAULT_SCHEMA = """
CREATE TABLE IF NOT EXISTS vault_states (
    ref_txhash BLOB NOT NULL,
    ref_index  INTEGER NOT NULL,
    state_type TEXT NOT NULL,
    currency   TEXT,
    amount     INTEGER,
    blob       BLOB NOT NULL,
    crc        INTEGER,
    PRIMARY KEY (ref_txhash, ref_index)
);
CREATE INDEX IF NOT EXISTS vault_states_by_type
    ON vault_states (state_type, ref_txhash, ref_index);
CREATE INDEX IF NOT EXISTS vault_states_by_coin
    ON vault_states (currency, amount DESC, ref_txhash, ref_index);
CREATE TABLE IF NOT EXISTS vault_participants (
    participant BLOB NOT NULL,
    ref_txhash  BLOB NOT NULL,
    ref_index   INTEGER NOT NULL,
    PRIMARY KEY (participant, ref_txhash, ref_index)
);
CREATE TABLE IF NOT EXISTS vault_balances (
    currency TEXT PRIMARY KEY,
    quantity INTEGER NOT NULL
);
"""

WATERMARK_KEY = "vault_watermark"


class IndexedVaultService(VaultService):
    """Durable sqlite vault engine: same notify/observe contract as the
    in-memory NodeVaultService, O(log n) queries, watermark boot."""

    def __init__(self, db, our_keys: Callable[[], set],
                 softlock_ttl_s: float = 5.0):
        self._db = db
        self._our_keys = our_keys
        self._observers: list[Callable[[Vault.Update], None]] = []
        self._softlocks = SoftLockManager(ttl_s=softlock_ttl_s)
        with db.lock:
            db.conn.executescript(_VAULT_SCHEMA)
            db.commit()

    # -- relevancy (identical semantics to the in-memory engine) --------

    def _is_relevant(self, state) -> bool:
        ours = self._our_keys()
        return any(
            bool(set(participant.keys) & ours)
            for participant in state.data.participants)

    # -- row <-> state --------------------------------------------------

    def _decode_row(self, ref_txhash, ref_index, blob, crc) \
            -> StateAndRef | None:
        ref_txhash, blob = bytes(ref_txhash), bytes(blob)
        if crc is not None and _row_crc(ref_txhash, int(ref_index),
                                        blob) != int(crc):
            self._quarantine(ref_txhash, int(ref_index), blob)
            return None
        return StateAndRef(deserialize(blob),
                           StateRef(SecureHash(ref_txhash), int(ref_index)))

    def _quarantine(self, ref_txhash: bytes, ref_index: int,
                    blob: bytes) -> None:
        """A corrupt vault row becomes a repair event, not a wrong
        answer: quarantined (counted), deleted, and its balance/
        participant shadow rows dropped with it."""
        with self._db.lock:
            _integrity.quarantine_row(
                self._db.conn, "vault_state",
                ref_txhash + ref_index.to_bytes(4, "big"), blob,
                "vault row crc mismatch")
            self._drop_row(ref_txhash, ref_index)
            self._db.commit()
        _integrity.bump("vault_rows_quarantined")

    # -- mutation -------------------------------------------------------

    def _insert_sar(self, sar: StateAndRef) -> bool:
        """INSERT one unconsumed state; False when the row already
        existed (idempotent replay). Balance/participant deltas apply
        only on a real insert so replays can never double-count."""
        conn = self._db.conn
        blob = serialize(sar.state).bytes
        currency, amount = coin_of(sar.state.data)
        key = (sar.ref.txhash.bytes, sar.ref.index)
        before = conn.total_changes
        conn.execute(
            "INSERT OR IGNORE INTO vault_states "
            "(ref_txhash, ref_index, state_type, currency, amount, blob, "
            "crc) VALUES (?, ?, ?, ?, ?, ?, ?)",
            (*key, self._type_name(type(sar.state.data)), currency, amount,
             blob, _row_crc(key[0], key[1], blob)))
        if conn.total_changes == before:
            return False
        for participant in sar.state.data.participants:
            for leaf in _participant_leaves(participant):
                conn.execute(
                    "INSERT OR IGNORE INTO vault_participants "
                    "(participant, ref_txhash, ref_index) VALUES (?, ?, ?)",
                    (leaf, *key))
        if currency is not None:
            conn.execute(
                "INSERT INTO vault_balances (currency, quantity) "
                "VALUES (?, ?) ON CONFLICT(currency) "
                "DO UPDATE SET quantity = quantity + excluded.quantity",
                (currency, amount))
        return True

    def _drop_row(self, ref_txhash: bytes, ref_index: int) -> bool:
        """DELETE one state row (+ shadows); False when absent."""
        conn = self._db.conn
        row = conn.execute(
            "SELECT currency, amount FROM vault_states "
            "WHERE ref_txhash = ? AND ref_index = ?",
            (ref_txhash, ref_index)).fetchone()
        if row is None:
            return False
        currency, amount = row
        conn.execute(
            "DELETE FROM vault_states WHERE ref_txhash = ? AND "
            "ref_index = ?", (ref_txhash, ref_index))
        conn.execute(
            "DELETE FROM vault_participants WHERE ref_txhash = ? AND "
            "ref_index = ?", (ref_txhash, ref_index))
        if currency is not None:
            conn.execute(
                "UPDATE vault_balances SET quantity = quantity - ? "
                "WHERE currency = ?", (amount, currency))
        return True

    @staticmethod
    def _type_name(cls: type) -> str:
        return wire_name_of(cls) or f"{cls.__module__}.{cls.__qualname__}"

    # -- the VaultService contract --------------------------------------

    @property
    def current_vault(self) -> Vault:
        """Full materialized snapshot — kept for the compat surface
        (RPC vault_snapshot, small tests); production paths use query()/
        iter_unconsumed() so a million-state vault is never copied."""
        return Vault(tuple(self.iter_unconsumed()))

    def iter_unconsumed(self, of_type: type | None = None,
                        batch: int = 512):
        """Bounded-memory iteration: keyset-paginated pages under the
        hood, one page of StateAndRefs in memory at a time."""
        cursor = None
        while True:
            page = self.query(VaultQuery(state_type=of_type, after=cursor,
                                         page_size=batch))
            yield from page.states
            cursor = page.next_cursor
            if cursor is None:
                return

    def unconsumed_states(self, of_type: type | None = None) -> list:
        """Compatibility shim over the paginated query API."""
        return list(self.iter_unconsumed(of_type))

    def notify_all(self, txns: Iterable) -> Vault:
        """Fold observed transactions into the vault. Same relevancy /
        update semantics as the in-memory engine; the whole call rides
        one transaction scope (the node thread's round batch when open),
        and the watermark advances with it."""
        with self._db.lock:
            max_rowid = 0
            for stx in txns:
                wtx = stx.tx if hasattr(stx, "tx") else stx
                consumed = []
                for ref in wtx.inputs:
                    sar = self._load(ref)
                    if sar is not None:
                        consumed.append(sar)
                produced = [
                    wtx.out_ref(i)
                    for i, out in enumerate(wtx.outputs)
                    if self._is_relevant(out)
                ]
                tx_id = getattr(stx, "id", None)
                if tx_id is not None:
                    row = self._db.conn.execute(
                        "SELECT rowid FROM transactions WHERE tx_id = ?",
                        (tx_id.bytes,)).fetchone()
                    if row is not None:
                        max_rowid = max(max_rowid, int(row[0]))
                update = Vault.Update(consumed=frozenset(consumed),
                                      produced=frozenset(produced))
                if update.is_empty:
                    continue
                for sar in consumed:
                    self._drop_row(sar.ref.txhash.bytes, sar.ref.index)
                fresh = []
                for sar in produced:
                    if self._insert_sar(sar):
                        fresh.append(sar)
                # A replayed tx whose rows were all already folded in
                # must not re-fire observers (the in-memory engine can't
                # see a replay; here idempotent replay is the contract).
                if not consumed and not fresh:
                    continue
                self.softlocks.release([sar.ref for sar in consumed])
                for obs in list(self._observers):
                    obs(update)
            if max_rowid:
                current = int(self._db.get_setting(WATERMARK_KEY) or 0)
                if max_rowid > current:
                    self._db.conn.execute(
                        "INSERT OR REPLACE INTO settings (key, value) "
                        "VALUES (?, ?)", (WATERMARK_KEY, str(max_rowid)))
            self._db.commit()
        return Vault(())

    def _load(self, ref: StateRef) -> StateAndRef | None:
        row = self._db.conn.execute(
            "SELECT blob, crc FROM vault_states WHERE ref_txhash = ? AND "
            "ref_index = ?", (ref.txhash.bytes, ref.index)).fetchone()
        if row is None:
            return None
        return self._decode_row(ref.txhash.bytes, ref.index, row[0], row[1])

    def subscribe(self, observer: Callable[[Vault.Update], None]) -> None:
        self._observers.append(observer)

    # -- incremental boot -----------------------------------------------

    @property
    def watermark(self) -> int:
        return int(self._db.get_setting(WATERMARK_KEY) or 0)

    def rebuild_from(self, storage, batch: int = 512) -> int:
        """Fold in the transactions the vault has not seen yet — the
        delta above the persisted watermark, streamed in bounded batches
        (never the full ledger in memory). Returns how many transactions
        were replayed. Crash-safe: each batch commits its vault rows and
        watermark atomically; a crash mid-rebuild resumes from the last
        durable watermark and replays idempotently."""
        replayed = 0
        chunk: list = []
        for _rowid, stx in storage.stream_since(self.watermark,
                                                batch=batch):
            chunk.append(stx)
            if len(chunk) >= batch:
                self.notify_all(chunk)
                replayed += len(chunk)
                chunk = []
        if chunk:
            self.notify_all(chunk)
            replayed += len(chunk)
        return replayed

    # -- queries ----------------------------------------------------------

    def _type_pushdown(self, of_type: type) \
            -> tuple[list[str], bool]:
        """(wire names to match, need_isinstance_guard). The guard stays
        on whenever some stored type name cannot be resolved to a class
        (states written by a process whose codec registered more types
        than ours) — those rows are included and filtered post-decode
        rather than silently dropped."""
        rows = self._db.conn.execute(
            "SELECT DISTINCT state_type FROM vault_states").fetchall()
        names: list[str] = []
        guard = False
        for (name,) in rows:
            cls = class_for_wire_name(name)
            if cls is None:
                names.append(name)
                guard = True
            elif issubclass(cls, of_type):
                names.append(name)
        return names, guard

    def query(self, q: VaultQuery) -> VaultPage:
        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        _tm.inc("vault_queries_total")
        wheres, params = [], []
        guard = False
        if q.state_type is not None:
            names, guard = self._type_pushdown(q.state_type)
            if not names:
                return VaultPage((), None)
            wheres.append(
                f"state_type IN ({','.join('?' * len(names))})")
            params.extend(names)
        if q.currency is not None:
            wheres.append("currency = ?")
            params.append(q.currency)
        if q.min_amount is not None:
            wheres.append("amount >= ?")
            params.append(int(q.min_amount))
        if q.max_amount is not None:
            wheres.append("amount <= ?")
            params.append(int(q.max_amount))
        if q.participant is not None:
            leaves = _participant_leaves(q.participant)
            if not leaves:
                return VaultPage((), None)
            wheres.append(
                "EXISTS (SELECT 1 FROM vault_participants p WHERE "
                "p.ref_txhash = vault_states.ref_txhash AND "
                "p.ref_index = vault_states.ref_index AND "
                f"p.participant IN ({','.join('?' * len(leaves))}))")
            params.extend(leaves)
        if q.after is not None:
            wheres.append("(ref_txhash, ref_index) > (?, ?)")
            params.extend((bytes(q.after[0]), int(q.after[1])))
        sql = ("SELECT ref_txhash, ref_index, blob, crc FROM vault_states"
               + (" WHERE " + " AND ".join(wheres) if wheres else "")
               + " ORDER BY ref_txhash, ref_index LIMIT ?")
        page = max(1, int(q.page_size))
        params.append(page + 1)
        with self._db.lock:
            rows = self._db.conn.execute(sql, params).fetchall()
        more = len(rows) > page
        rows = rows[:page]
        states = []
        for ref_txhash, ref_index, blob, crc in rows:
            sar = self._decode_row(ref_txhash, ref_index, blob, crc)
            if sar is None:
                continue
            if guard and q.state_type is not None \
                    and not isinstance(sar.state.data, q.state_type):
                continue
            states.append(sar)
        next_cursor = None
        if more and rows:
            last = rows[-1]
            next_cursor = (bytes(last[0]), int(last[1]))
        record_vault_stage(t0, attrs={"rows": len(states), "op": "query"})
        return VaultPage(tuple(states), next_cursor)

    def select_coins(self, currency: str, quantity: int,
                     holder: bytes = b"", ttl_s: float | None = None) \
            -> list[StateAndRef]:
        """Indexed coin selection: walk the (currency, amount DESC)
        covering index, skip refs soft-locked by other holders, reserve
        and return coins until ``quantity`` is covered. Insufficient
        funds release this call's reservations and return the partial
        set (the asset's generate_spend raises the same
        InsufficientBalanceException it always has)."""
        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        _tm.inc("vault_queries_total")
        expired = self.softlocks.sweep()
        if expired:
            _tm.inc("vault_softlock_expired_total", expired)
        holder = bytes(holder) or b"anon"
        gathered: list[StateAndRef] = []
        covered = 0
        with self._db.lock:
            cur = self._db.conn.execute(
                "SELECT ref_txhash, ref_index, amount, blob, crc "
                "FROM vault_states WHERE currency = ? "
                "ORDER BY amount DESC, ref_txhash, ref_index", (currency,))
            for ref_txhash, ref_index, amount, blob, crc in cur:
                ref = StateRef(SecureHash(bytes(ref_txhash)),
                               int(ref_index))
                if not self.softlocks.try_lock(ref, holder, ttl_s):
                    _tm.inc("vault_selection_conflicts_total")
                    continue
                sar = self._decode_row(ref_txhash, ref_index, blob, crc)
                if sar is None:
                    self.softlocks.release([ref], holder)
                    continue
                gathered.append(sar)
                covered += int(amount or 0)
                if covered >= quantity:
                    break
        if covered < quantity:
            # Don't shadow coins behind a selection that cannot spend.
            self.softlocks.release([sar.ref for sar in gathered], holder)
        record_vault_stage(t0, attrs={"rows": len(gathered), "op": "select"})
        return gathered

    def release_coins(self, refs: Iterable[StateRef],
                      holder: bytes = b"") -> None:
        self.softlocks.release(refs, bytes(holder) or b"anon")

    def balances(self) -> dict[str, int]:
        """Per-currency unconsumed quantities — one indexed aggregate
        read, O(#currencies), never a vault scan."""
        with self._db.lock:
            rows = self._db.conn.execute(
                "SELECT currency, quantity FROM vault_balances "
                "WHERE quantity != 0").fetchall()
        return {str(c): int(q) for c, q in rows}

    def __len__(self) -> int:
        (n,) = self._db.conn.execute(
            "SELECT COUNT(*) FROM vault_states").fetchone()
        return int(n)


def seed_states(vault: IndexedVaultService, states: Iterable[StateAndRef],
                chunk: int = 4096) -> int:
    """Bulk-load pre-built unconsumed states (bench / loadtest seeding —
    the 'bank day' pre-seed path). Rides the same idempotent insert as
    notify_all (balances and participants maintained per real insert)
    but skips update construction and observer fan-out; commits per
    chunk so a million-state seed never holds one giant transaction."""
    inserted = 0
    pending = 0
    with vault._db.lock:
        for sar in states:
            if vault._insert_sar(sar):
                inserted += 1
            pending += 1
            if pending >= chunk:
                vault._db.commit()
                pending = 0
        vault._db.commit()
    return inserted
