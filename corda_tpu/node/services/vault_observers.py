"""Vault-driven observers: balances as metrics.

Capability match for the reference's CashBalanceAsMetricsObserver (reference:
node/src/main/kotlin/net/corda/node/services/vault/
CashBalanceAsMetricsObserver.kt:11 — vault updates maintain a per-currency
cash-balance gauge in the node's metric registry)."""

from __future__ import annotations


class CashBalanceMetricsObserver:
    """Keeps metrics['balance.<currency>'] equal to the vault's unconsumed
    cash per currency (smallest units)."""

    def __init__(self, vault_service, metrics: dict):
        self._metrics = metrics
        self._balances: dict[str, int] = {}
        vault_service.subscribe(self._on_update)
        for sar in vault_service.iter_unconsumed():
            self._apply(sar, +1)
        self._publish()

    def _on_update(self, update) -> None:
        for sar in update.consumed:
            self._apply(sar, -1)
        for sar in update.produced:
            self._apply(sar, +1)
        self._publish()

    def _apply(self, sar, sign: int) -> None:
        from ...finance.cash import CashState

        state = sar.state.data
        if not isinstance(state, CashState):
            return
        currency = str(state.amount.token.product)
        self._balances[currency] = self._balances.get(currency, 0) \
            + sign * state.amount.quantity

    def _publish(self) -> None:
        for currency, quantity in self._balances.items():
            self._metrics[f"balance.{currency}"] = quantity


class IndexedBalanceMetricsObserver:
    """The indexed-engine twin: the vault already maintains per-currency
    aggregates in its vault_balances table, so publishing is one O(1)
    read of vault.balances() per update — no second in-memory tally that
    could drift from the durable one. Currencies that drain to zero keep
    publishing 0 (balances() omits them; the gauge must not go stale)."""

    def __init__(self, vault_service, metrics: dict):
        self._vault = vault_service
        self._metrics = metrics
        self._seen: set[str] = set()
        vault_service.subscribe(self._on_update)
        self._publish()

    def _on_update(self, update) -> None:
        self._publish()

    def _publish(self) -> None:
        balances = self._vault.balances()
        for currency in self._seen - set(balances):
            self._metrics[f"balance.{currency}"] = 0
        for currency, quantity in balances.items():
            self._metrics[f"balance.{currency}"] = quantity
            self._seen.add(currency)
