"""The state machine manager: flow scheduling, sessions, checkpoints, and the
micro-batched verification seam.

Capability match for the reference's StateMachineManager +
FlowStateMachineImpl (reference: node/src/main/kotlin/net/corda/node/services/
statemachine/StateMachineManager.kt, FlowStateMachineImpl.kt):

  * session wire protocol SessionInit/Confirm/Reject/Data/End exactly as the
    reference defines it (StateMachineManager.kt:443-482), carried on topic
    "platform.session" with the recipient's session id as the message session
    (StateMachineManager.kt:209-217);
  * flow-factory registration for service-initiated flows
    (onSessionInit, StateMachineManager.kt:257-286);
  * checkpoint on every suspension (updateCheckpoint,
    StateMachineManager.kt:399-408) — but instead of Kryo-serializing a fiber
    stack the checkpoint records (flow name, constructor args, ordered results
    of completed suspensions, session states); restore re-runs the flow
    generator and replays the recorded results (deterministic replay — the
    explicit-state-machine design SURVEY.md §7 stage 3 calls for);
  * restore-on-start (restoreFibersFromCheckpoints,
    StateMachineManager.kt:190-226).

TPU-first addition — the *verification pump*: flows suspend on VerifyTxRequest
and the manager aggregates every pending request across all concurrent flows
into ONE batched signature-verification call (the seam the reference lacks:
its per-tx loop at SignedTransaction.kt:83-87 becomes a cross-transaction
batch sized by concurrency). Single-threaded cooperative scheduling makes
this deterministic: flows run until all are parked, then the batch flushes.
"""

from __future__ import annotations

import hashlib
import inspect as _inspect
import logging
import os
import time as _time
from dataclasses import dataclass, field, replace
from typing import Any, Callable

from ..crypto.hashes import SecureHash
from ..crypto.keys import SignatureError
from ..crypto.party import Party
from ..crypto.provider import BatchVerifier, VerifyJob, get_verifier
from ..flows.api import (
    FlowException,
    FlowLogic,
    FlowSessionException,
    ReceiveRequest,
    SendAndReceiveRequest,
    SendRequest,
    ServiceRequest,
    UntrustworthyData,
    VerifySigRequest,
    VerifyTxRequest,
    flow_registry,
)
from ..obs import telemetry as _tm
from ..obs import trace as _obs
from ..qos import context as _qos
from ..serialization.codec import (
    DeserializationError,
    deserialize,
    register,
    serialize,
)
from ..serialization.tokens import TokenContext
from ..testing import faults as _faults
from ..utils.excheckpoint import record_exception, rebuild_exception
from .messaging.api import DEFAULT_SESSION_ID, Message, MessagingService, TopicSession

logger = logging.getLogger(__name__)

SESSION_TOPIC = "platform.session"


# ---------------------------------------------------------------------------
# Session wire messages (reference: StateMachineManager.kt:443-482)
# ---------------------------------------------------------------------------


@register
@dataclass(frozen=True)
class SessionInit:
    initiator_session_id: int
    flow_name: str
    initiator_party: Party
    first_payload: Any = None


@register
@dataclass(frozen=True)
class SessionConfirm:
    initiator_session_id: int
    initiated_session_id: int


@register
@dataclass(frozen=True)
class SessionReject:
    initiator_session_id: int
    error_message: str


@register
@dataclass(frozen=True)
class SessionData:
    recipient_session_id: int
    payload: Any


@register
@dataclass(frozen=True)
class SessionEnd:
    recipient_session_id: int


# ---------------------------------------------------------------------------
# Checkpoints
# ---------------------------------------------------------------------------


@register
@dataclass(frozen=True)
class SessionCheckpoint:
    """Serializable session state."""

    party: Party
    local_id: int
    peer_id: int | None
    state: str  # initiating | open | ended
    receive_buffer: tuple = ()
    outgoing_buffer: tuple = ()
    send_count: int = 0
    scope: str = ""


@register
@dataclass(frozen=True)
class Checkpoint:
    """One flow's durable state (reference: node/.../services/api/
    CheckpointStorage.kt:33 — here replay state instead of a fiber blob)."""

    run_id: bytes
    flow_name: str
    flow_args: tuple
    resolved: tuple = ()  # ('v', value) | ('e', exc_type_name, message)
    sessions: tuple = ()  # SessionCheckpoint...
    next_session_seq: int = 0

    @property
    def id(self) -> SecureHash:
        return SecureHash.sha256(serialize(self).bytes)


class CheckpointStorage:
    """Interface over serialized checkpoint blobs (reference:
    CheckpointStorage.kt:10-30). Blobs, not objects: serialization happens on
    every suspend (as in the reference), so unserializable flow state fails
    fast, and service references pass through the token context."""

    def update_checkpoint(self, run_id: bytes, blob: bytes) -> None:
        raise NotImplementedError

    def remove_checkpoint(self, run_id: bytes) -> None:
        raise NotImplementedError

    def checkpoints(self) -> list[bytes]:
        raise NotImplementedError


class InMemoryCheckpointStorage(CheckpointStorage):
    def __init__(self):
        self._by_run: dict[bytes, bytes] = {}

    def update_checkpoint(self, run_id: bytes, blob: bytes) -> None:
        self._by_run[run_id] = blob

    def remove_checkpoint(self, run_id: bytes) -> None:
        self._by_run.pop(run_id, None)

    def checkpoints(self) -> list[bytes]:
        return list(self._by_run.values())

    def __len__(self):
        return len(self._by_run)


# ---------------------------------------------------------------------------
# Futures
# ---------------------------------------------------------------------------


class EventLog:
    """Bounded append-only event feed with ABSOLUTE cursors: old events are
    evicted but cursor arithmetic stays valid, so RPC pollers
    (state_machine_changes) never index a shifted list. Events are tuples —
    ('add'|'remove', run_id) or ('progress', run_id, path)."""

    def __init__(self, keep: int = 10_000):
        self._keep = keep
        self.base = 0  # absolute index of _events[0]
        self._events: list[tuple] = []

    def append(self, event: tuple) -> None:
        self._events.append(event)
        overflow = len(self._events) - self._keep
        if overflow > 0:
            del self._events[:overflow]
            self.base += overflow

    def since(self, cursor: int) -> tuple[int, tuple]:
        """(new_cursor, events at absolute index >= cursor)."""
        start = max(cursor - self.base, 0)
        return (self.base + len(self._events), tuple(self._events[start:]))

    # list-compat conveniences (tests introspect the feed directly)
    def __len__(self):
        return self.base + len(self._events)

    def __iter__(self):
        return iter(self._events)

    def __getitem__(self, item):
        return self._events[item]


class FlowFuture:
    """Synchronous future resolved by the manager's pump."""

    def __init__(self):
        self._done = False
        self._result = None
        self._exception: BaseException | None = None
        self._callbacks: list[Callable] = []

    def set_result(self, value) -> None:
        self._done, self._result = True, value
        for cb in self._callbacks:
            cb(self)

    def set_exception(self, exc: BaseException) -> None:
        self._done, self._exception = True, exc
        for cb in self._callbacks:
            cb(self)

    @property
    def done(self) -> bool:
        return self._done

    def result(self):
        if not self._done:
            raise RuntimeError("flow not finished — pump the network first")
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self) -> BaseException | None:
        return self._exception

    def add_done_callback(self, cb: Callable) -> None:
        if self._done:
            cb(self)
        else:
            self._callbacks.append(cb)


@dataclass
class FlowHandle:
    run_id: bytes
    result: FlowFuture
    logic: FlowLogic


# ---------------------------------------------------------------------------
# Sessions (runtime form)
# ---------------------------------------------------------------------------


@dataclass
class FlowSession:
    party: Party
    local_id: int
    peer_id: int | None = None
    state: str = "initiating"
    receive_buffer: list = field(default_factory=list)
    outgoing_buffer: list = field(default_factory=list)  # payloads pre-confirm
    send_count: int = 0
    scope: str = ""

    @property
    def key(self) -> str:
        return f"{self.scope}|{self.party.name}"

    def to_checkpoint(self) -> SessionCheckpoint:
        return SessionCheckpoint(
            party=self.party,
            local_id=self.local_id,
            peer_id=self.peer_id,
            state=self.state,
            receive_buffer=tuple(self.receive_buffer),
            outgoing_buffer=tuple(self.outgoing_buffer),
            send_count=self.send_count,
            scope=self.scope,
        )

    @staticmethod
    def from_checkpoint(sc: SessionCheckpoint) -> "FlowSession":
        return FlowSession(
            party=sc.party,
            local_id=sc.local_id,
            peer_id=sc.peer_id,
            state=sc.state,
            receive_buffer=list(sc.receive_buffer),
            outgoing_buffer=list(sc.outgoing_buffer),
            send_count=sc.send_count,
            scope=sc.scope,
        )


# ---------------------------------------------------------------------------
# The per-flow state machine
# ---------------------------------------------------------------------------

_RUNNABLE = "runnable"
_WAIT_RECEIVE = "wait_receive"
_WAIT_VERIFY = "wait_verify"
_WAIT_SERVICE = "wait_service"
_DONE = "done"


class FlowStateMachine:
    """Drives one FlowLogic generator; owns its sessions and replay log."""

    def __init__(
        self,
        manager: "StateMachineManager",
        logic: FlowLogic,
        run_id: bytes,
        resolved: list | None = None,
        sessions: dict[str, FlowSession] | None = None,
        next_session_seq: int = 0,
    ):
        self.manager = manager
        self.logic = logic
        self.run_id = run_id
        self.resolved: list = resolved or []  # completed suspension results
        self.sessions: dict[str, FlowSession] = sessions or {}  # by scope|party
        self.next_session_seq = next_session_seq
        self._subflow_counter = 0
        self.future = FlowFuture()
        self.state = _RUNNABLE
        self.waiting_on: ReceiveRequest | None = None
        self.pending_value = None  # (kind, value) to feed into generator
        self._gen = None
        self._replay_cursor = 0
        self.created_at = _time.monotonic()  # per-flow timing
        # Tracing context (obs/trace.py). All None while disarmed; set by the
        # manager at creation when obs.ACTIVE is armed. trace_parent is the
        # initiating peer's span id for session-initiated flows.
        self.trace_id: bytes | None = None
        self.trace_span: bytes | None = None
        self.trace_parent: bytes | None = None
        self.trace_t0: float = 0.0  # epoch seconds (cross-process merge)
        # QoS context (qos/context.py): None while the plane is disarmed or
        # the flow is unlabelled; set at add() or joined at SessionInit.
        self.qos = None
        logic.state_machine = self
        logic.service_hub = manager.service_hub

    # -- session helpers ---------------------------------------------------

    def _session_id(self, seq: int) -> int:
        digest = hashlib.sha256(self.run_id + seq.to_bytes(4, "big")).digest()
        return int.from_bytes(digest[:8], "big") >> 1  # positive int64

    def allocate_subflow_scope(self) -> str:
        """Deterministic scope names for sub-flow sessions; replay re-derives
        the same values because sub_flow calls re-execute in order."""
        self._subflow_counter += 1
        return str(self._subflow_counter)

    def get_or_open_session(
        self, party: Party, scope: str = "", flow_name: str = "", first_payload=None
    ) -> FlowSession:
        key = f"{scope}|{party.name}"
        session = self.sessions.get(key)
        if session is not None:
            return session
        local_id = self._session_id(self.next_session_seq)
        self.next_session_seq += 1
        session = FlowSession(party=party, local_id=local_id, scope=scope)
        self.sessions[key] = session
        self.manager._register_session(self, session)
        if not self.replaying:
            self.manager._send_session_message(
                party,
                DEFAULT_SESSION_ID,
                SessionInit(
                    initiator_session_id=local_id,
                    flow_name=flow_name
                    or type(self.logic).flow_name
                    or type(self.logic).__qualname__,
                    initiator_party=self.manager.our_identity,
                    first_payload=first_payload,
                ),
            )
            if first_payload is not None:
                session.send_count += 1
        return session

    def open_initiated_session(self, party: Party, local_id: int, peer_id: int) -> FlowSession:
        session = FlowSession(party=party, local_id=local_id, peer_id=peer_id, state="open")
        self.sessions[session.key] = session
        self.manager._register_session(self, session)
        return session

    def _send_on_session(self, request) -> None:
        key = f"{request.scope}|{request.party.name}"
        session = self.sessions.get(key)
        if session is None:
            self.get_or_open_session(
                request.party, request.scope, request.flow_name,
                first_payload=request.payload,
            )
            return
        if self.replaying:
            return  # effect already happened before the checkpoint
        if session.state == "initiating":
            session.outgoing_buffer.append(request.payload)
        elif session.state == "open":
            self.manager._send_session_message(
                request.party,
                session.peer_id,
                SessionData(session.peer_id, request.payload),
            )
            session.send_count += 1
        else:
            raise FlowSessionException(f"session with {request.party} has ended")

    # -- replay ------------------------------------------------------------

    @property
    def replaying(self) -> bool:
        return self._replay_cursor < len(self.resolved)

    def _record(self, kind: str, value=None, err: BaseException | None = None):
        """Append a suspension result; returns the entry so callers feed the
        generator the SAME tuple live as replay will (payloads included —
        typed exceptions must rebuild identically on both paths)."""
        if kind == "v":
            entry = ("v", value)
        else:
            entry = record_exception(err)
        self.resolved.append(entry)
        self._replay_cursor = len(self.resolved)
        return entry

    def _next_feed(self):
        """What to send into the generator for the current step."""
        if self._replay_cursor < len(self.resolved):
            entry = self.resolved[self._replay_cursor]
            self._replay_cursor += 1
            return entry
        pv, self.pending_value = self.pending_value, None
        return pv

    # -- stepping ----------------------------------------------------------

    def step(self) -> None:
        """Advance the generator until it parks or finishes. Called only by
        the manager's pump (single-threaded)."""
        if self.state == _DONE:
            return
        qos_armed = _qos.ACTIVE is not None and self.qos is not None
        if qos_armed:
            # Session sends and service submissions this step makes carry
            # the flow's lane + deadline, exactly like trace context.
            _qos.set_context(self.qos)
        try:
            if _obs.ACTIVE is not None and self.trace_id is not None:
                # Everything this flow does while stepping — session sends,
                # service submissions — inherits its trace context.
                _obs.set_context(self.trace_id, self.trace_span)
                try:
                    self._step_inner()
                finally:
                    _obs.clear_context()
            else:
                self._step_inner()
        finally:
            if qos_armed:
                _qos.clear_context()

    def _step_inner(self) -> None:
        try:
            if self._gen is None:
                out = self.logic.call()
                if not _inspect.isgenerator(out):
                    self._finish(out)
                    return
                self._gen = out
                feed = None
            else:
                feed = self._next_feed()

            while True:
                if feed is None:
                    request = next(self._gen)
                elif feed[0] == "v":
                    request = self._gen.send(feed[1])
                else:
                    request = self._gen.throw(_rebuild_exception(feed))

                feed = self._handle_request(request)
                if feed is _PARKED:
                    return
        except StopIteration as stop:
            self._finish(stop.value)
        except BaseException as e:  # flow failed
            self._fail(e)

    def _handle_request(self, request):
        """Execute or park on a yielded request. Returns the next feed tuple,
        or _PARKED if the flow must suspend."""
        if isinstance(request, SendRequest):
            if self.replaying:
                self._send_on_session(request)  # suppressed
                return self._consume_replay_entry()
            self._send_on_session(request)
            self._record("v", None)
            self.manager._checkpoint(self)
            return ("v", None)
        if isinstance(request, SendAndReceiveRequest):
            self._send_on_session(request)
            return self._park_receive(
                ReceiveRequest(
                    request.party, request.expected_type, request.scope, request.flow_name
                )
            )
        if isinstance(request, ReceiveRequest):
            self.get_or_open_session(request.party, request.scope, request.flow_name)
            return self._park_receive(request)
        if isinstance(request, (VerifyTxRequest, VerifySigRequest)):
            if self.replaying:
                # Completed before the crash — replay the recorded outcome.
                return self._consume_replay_entry()
            # Crashed (or first reached) while pending: (re-)enqueue.
            self.state = _WAIT_VERIFY
            self.manager._enqueue_verify(self, request)
            return _PARKED
        if isinstance(request, ServiceRequest):
            if self.replaying:
                return self._consume_replay_entry()
            # Live (or restored): (re-)launch the async operation; the node's
            # run loop polls it. start() must be idempotent across restarts.
            self.state = _WAIT_SERVICE
            self.manager._enqueue_service(self, request.start())
            return _PARKED
        raise FlowException(f"flow yielded unknown request {request!r}")

    def _consume_replay_entry(self):
        entry = self.resolved[self._replay_cursor]
        self._replay_cursor += 1
        return entry

    def _park_receive(self, request: ReceiveRequest):
        if self.replaying:
            entry = self.resolved[self._replay_cursor]
            self._replay_cursor += 1
            return entry
        session = self.sessions[f"{request.scope}|{request.party.name}"]
        if session.receive_buffer:
            payload = session.receive_buffer.pop(0)
            return self._resolve_received(request, payload)
        self.state = _WAIT_RECEIVE
        self.waiting_on = request
        self.manager._checkpoint(self)
        return _PARKED

    def _resolve_received(self, request: ReceiveRequest, payload):
        """Type-check an inbound payload and produce the feed entry."""
        if isinstance(payload, _SessionEndedMarker):
            err = FlowSessionException(
                f"Counterparty flow on {request.party} has ended before sending data"
            )
            entry = self._record("e", err=err)
            self.manager._checkpoint(self)
            return entry
        if not isinstance(payload, request.expected_type):
            err = FlowSessionException(
                f"Expected {request.expected_type.__name__}, got {type(payload).__name__}"
            )
            entry = self._record("e", err=err)
            self.manager._checkpoint(self)
            return entry
        value = UntrustworthyData(payload)
        self._record("v", value)  # wrapped, so replay feeds the same shape
        self.manager._checkpoint(self)
        return ("v", value)

    # -- events from the manager ------------------------------------------

    def deliver_session_payload(self, session: FlowSession, payload) -> None:
        if (
            self.state == _WAIT_RECEIVE
            and self.waiting_on is not None
            and self.waiting_on.party.name == session.party.name
            and self.waiting_on.scope == session.scope
        ):
            request, self.waiting_on = self.waiting_on, None
            self.state = _RUNNABLE
            self.pending_value = self._resolve_received(request, payload)
            self.manager._mark_runnable(self)
        else:
            session.receive_buffer.append(payload)
            self.manager._checkpoint(self)

    def deliver_verify_result(self, ok: bool, error: BaseException | None) -> None:
        assert self.state == _WAIT_VERIFY
        self.state = _RUNNABLE
        if ok:
            self.pending_value = self._record("v", None)
        else:
            self.pending_value = self._record("e", err=error)
        self.manager._checkpoint(self)
        self.manager._mark_runnable(self)

    def deliver_service_result(self, value=None,
                               error: BaseException | None = None) -> None:
        assert self.state == _WAIT_SERVICE
        self.state = _RUNNABLE
        if error is None:
            self.pending_value = self._record("v", value)
        else:
            self.pending_value = self._record("e", err=error)
        self.manager._checkpoint(self)
        self.manager._mark_runnable(self)

    def session_confirmed(self, session: FlowSession) -> None:
        session.state = "open"
        for payload in session.outgoing_buffer:
            self.manager._send_session_message(
                session.party, session.peer_id, SessionData(session.peer_id, payload)
            )
            session.send_count += 1
        session.outgoing_buffer.clear()
        self.manager._checkpoint(self)

    def session_rejected(self, session: FlowSession, reason: str) -> None:
        session.state = "ended"
        self.deliver_session_payload(session, _SESSION_ENDED)

    def session_ended(self, session: FlowSession) -> None:
        session.state = "ended"
        if (
            self.state == _WAIT_RECEIVE
            and self.waiting_on is not None
            and self.waiting_on.party.name == session.party.name
            and self.waiting_on.scope == session.scope
        ):
            self.deliver_session_payload(session, _SESSION_ENDED)

    # -- completion --------------------------------------------------------

    def _finish(self, result) -> None:
        self.state = _DONE
        self._progress_done()
        self._record_root_span()
        self.manager._flow_finished(self)
        self.future.set_result(result)

    def _fail(self, exc: BaseException) -> None:
        self.state = _DONE
        self._progress_done()
        self._record_root_span(failed=True)
        logger.debug("flow %s failed: %s", self.run_id.hex()[:8], exc)
        self.manager._flow_finished(self)
        self.future.set_exception(exc)

    def _record_root_span(self, failed: bool = False) -> None:
        """The flow's whole-lifetime span — the end-to-end anchor a trace's
        stage breakdown is measured against (obs/collect.py)."""
        if _obs.ACTIVE is None or self.trace_id is None:
            return
        attrs = {"run_id": self.run_id.hex()}
        if failed:
            attrs["failed"] = True
        _obs.record(
            f"flow:{type(self.logic).__name__}",
            self.trace_t0, _obs.now(),
            trace_id=self.trace_id, span_id=self.trace_span,
            parent=self.trace_parent, attrs=attrs)

    def _progress_done(self) -> None:
        """The framework, not each flow, marks trackers Done on completion —
        success or failure — so observers never see a finished flow stuck on
        its last step."""
        tracker = self.logic.progress_tracker
        if tracker is not None:
            from ..utils.progress import DONE

            if tracker.current_step != DONE:
                tracker.current_step = DONE

    def to_checkpoint(self) -> Checkpoint:
        return Checkpoint(
            run_id=self.run_id,
            flow_name=type(self.logic).flow_name or type(self.logic).__qualname__,
            flow_args=self.logic.checkpoint_args(),
            resolved=tuple(self.resolved),
            sessions=tuple(s.to_checkpoint() for s in self.sessions.values()),
            next_session_seq=self.next_session_seq,
        )


class _Parked:
    pass


_PARKED = _Parked()


@register
@dataclass(frozen=True)
class _SessionEndedMarker:
    """Sentinel buffered when a peer ends/rejects; serializable because it can
    sit in a checkpointed receive buffer."""


_SESSION_ENDED = _SessionEndedMarker()


def _rebuild_exception(entry) -> BaseException:
    """Typed rebuild via the excheckpoint whitelist; unregistered types
    degrade to a generic FlowException with the original name in the text."""
    exc = rebuild_exception(entry)
    if exc is not None:
        return exc
    _, type_name, message, *_rest = entry
    return FlowException(f"{type_name}: {message}")


# ---------------------------------------------------------------------------
# The manager
# ---------------------------------------------------------------------------


class StateMachineManager:
    """Owns every live flow on a node; single-threaded cooperative pump."""

    def __init__(
        self,
        service_hub,
        messaging: MessagingService,
        checkpoint_storage: CheckpointStorage | None = None,
        verifier: BatchVerifier | None = None,
        our_identity: Party | None = None,
        token_context: "TokenContext | None" = None,
        defer_verify: bool = False,
        defer_checkpoints: bool = False,
    ):
        # defer_verify: leave VerifyTxRequests queued until the scheduler
        # calls flush_pending_verifies() — lets a node accumulate sig checks
        # across ALL messages delivered in a scheduling round, maximising the
        # TPU batch (the max-wait micro-batching of SURVEY.md §7 stage 6).
        self.defer_verify = defer_verify
        # defer_checkpoints: record WHICH flows changed and serialize/write
        # each one ONCE per scheduling round (flush_checkpoints), instead of
        # at every suspension — a flow suspends ~4-9 times per round on the
        # notary path, and each eager write re-serialized the whole growing
        # checkpoint. Sound because the design is replay-based: a crash
        # re-runs from the last durable checkpoint and the transport
        # redelivers anything un-ACKed (the node run loop flushes checkpoints
        # inside the same db round-transaction that holds the round's outbox
        # writes, and ACKs only after it commits).
        self.defer_checkpoints = defer_checkpoints
        self._dirty_checkpoints: dict[bytes, "FlowStateMachine"] = {}
        self.service_hub = service_hub
        self.messaging = messaging
        self.checkpoint_storage = (
            checkpoint_storage if checkpoint_storage is not None
            else InMemoryCheckpointStorage()  # ("or" would drop an empty storage)
        )
        self.token_context = token_context or TokenContext()
        self.verifier = verifier or get_verifier()
        self.our_identity = our_identity or (
            service_hub.my_info.legal_identity if service_hub and service_hub.my_info else None
        )
        self.flows: dict[bytes, FlowStateMachine] = {}
        self._sessions_by_local_id: dict[int, tuple[FlowStateMachine, FlowSession]] = {}
        self._session_handlers: dict[int, Any] = {}
        self._flow_factories: dict[str, Callable[[Party], FlowLogic]] = {}
        self._runnable: list[FlowStateMachine] = []
        self._verify_queue: list[tuple[FlowStateMachine, VerifyTxRequest]] = []
        self._verify_sig_count = 0
        self._verify_waiting_since = 0.0
        # QoS plane (qos/context.py), all inert while disarmed: pump pick
        # counter for the bulk anti-starvation ratio, and the earliest
        # interactive deadline among queued verify jobs (epoch ns, 0 =
        # none) driving the run loop's early micro-batch flush.
        self._qos_pick_counter = 0
        self._verify_qos_deadline_ns = 0
        self._service_queue: list[tuple[FlowStateMachine, Callable]] = []
        # Async verify pipeline (crypto/async_verify.AsyncVerifyService),
        # installed by the node assembly when batch.async_verify is on;
        # None = the classic synchronous flush path.
        self.async_verify = None
        self.recent_results: dict[bytes, FlowFuture] = {}
        self._pumping = False
        # Session-send coalescer (round 15): sends issued while the pump is
        # running are buffered and flushed at pump-end as per-destination
        # multi-frame bursts (transport send_many), so a burst of N flow
        # starts costs O(destinations) transport round-trips instead of N.
        # Each entry carries the obs/qos contexts captured at the flow step
        # that queued it — the transport stamps frames from thread-locals
        # at SEND time, so the flush re-installs them per group.
        self._send_buffer: list = []
        # Optional on-demand network-map refresh (set by the node assembly):
        # consulted once when a send target is missing from the cache.
        self.netmap_refresh: Callable[[], None] | None = None
        self.changes = EventLog()  # bounded flow/progress event feed
        # Metrics (reference: StateMachineManager.kt:105-113)
        self.metrics = {"started": 0, "finished": 0, "checkpointing_rate": 0,
                        "verify_batches": 0, "verify_sigs": 0,
                        # ServiceRequest seam (Raft commit_async etc.):
                        # completions per poll pass attribute how many
                        # commits a round hands the consensus group-commit
                        # buffer at once (the upstream half of the raft
                        # entries_per_batch stamp).
                        "service_polls": 0, "service_completions": 0,
                        "service_round_max": 0,
                        # Device-verifier failures absorbed by the host
                        # tier (degrade_device) instead of rejecting flows.
                        "verify_device_degraded": 0,
                        # Session handler deregistrations that raced flow
                        # teardown (handler already gone): counted, never
                        # silently swallowed.
                        "handler_remove_failures": 0,
                        # Durability plane: wire frames that failed codec
                        # decode (hostile or damaged bytes) and checkpoints
                        # quarantined at restore because their blob no
                        # longer decodes — each is a flow declared failed,
                        # never a silent drop.
                        "undecodable_messages": 0,
                        "checkpoints_quarantined": 0,
                        # Ingest plane: session sends issued from inside a
                        # pump (coalescer-eligible), bursts actually shipped
                        # via transport send_many, and frames those bursts
                        # carried — frames/burst is the client-side wire
                        # amortization the round-15 firehose relies on.
                        "session_sends": 0,
                        "session_bursts": 0,
                        "session_burst_frames": 0}
        # Per-flow-name timing aggregates (the JMX/Jolokia capability the
        # reference exports per-MBean, reference: Node.kt:313 — here over
        # RPC node_metrics + /api/metrics): count / total_ms / max_ms per
        # flow class, recorded at completion. Bounded: a pathological
        # stream of distinct flow names cannot grow it without limit.
        self.flow_timings: dict[str, dict] = {}
        self.FLOW_TIMINGS_MAX_NAMES = 256

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.messaging.add_message_handler(
            SESSION_TOPIC, DEFAULT_SESSION_ID, self._on_session_init_message
        )
        self._restore_checkpoints()
        self._pump()

    def register_flow_initiator(
        self, initiator_flow_name: str, factory: Callable[[Party], FlowLogic]
    ) -> None:
        """When a SessionInit for `initiator_flow_name` arrives, build the
        responding flow with the initiating party
        (reference: ServiceHubInternal.registerFlowInitiator)."""
        self._flow_factories[initiator_flow_name] = factory

    def add(self, logic: FlowLogic, qos=None) -> FlowHandle:
        """Start a new flow (reference: StateMachineManager.kt:381-397)."""
        # Random run ids: a counter would restart at 0 after a crash and
        # collide with checkpoint-restored flows.
        run_id = os.urandom(16)
        fsm = FlowStateMachine(self, logic, run_id)
        if _tm.ACTIVE is not None:
            _tm.inc("flows_started_total")
        if _obs.ACTIVE is not None:
            # A client-started flow roots a NEW trace; everything downstream
            # (sessions, verify batches, raft commits) stitches under it.
            fsm.trace_id = _obs.new_trace_id()
            fsm.trace_span = _obs.new_span_id()
            fsm.trace_t0 = _obs.now()
        if _qos.ACTIVE is not None:
            # Explicit lane wins; otherwise inherit the starting thread's
            # context (a flow started from inside another flow's step
            # shares its lane, same semantics as sub-flows).
            if qos is None:
                qos = _qos.get_context()
            fsm.qos = qos
            if qos is not None:
                lane_key = (f"{qos.lane}_flows"
                            if qos.lane in _qos.LANES else None)
                if lane_key is not None:
                    _qos.ACTIVE.counters[lane_key] += 1
        self.flows[run_id] = fsm
        self.metrics["started"] += 1
        self._subscribe_progress(logic, run_id)
        # Write-through even in deferred mode: a freshly added flow (RPC
        # start) must be durable before the caller learns its run id.
        self._write_checkpoint(fsm)
        self._mark_runnable(fsm)
        self.changes.append(("add", run_id))
        self._pump()
        return FlowHandle(run_id, fsm.future, logic)

    def _subscribe_progress(self, logic: FlowLogic, run_id: bytes) -> None:
        """Surface a flow's tracker steps on the manager's change feed (the
        reference streams these to RPC, CordaRPCOps.kt:66-67). Called at
        EVERY flow-creation site — add(), session-initiated factories and
        checkpoint restore — so restored flows keep reporting."""
        if logic.progress_tracker is not None:
            logic.progress_tracker.subscribe(
                lambda change, rid=run_id:
                self.changes.append(("progress", rid, change.path)))

    @property
    def in_flight_count(self) -> int:
        return len(self.flows)

    def _record_flow_timing(self, fsm: "FlowStateMachine") -> None:
        try:
            name = fsm.logic._my_flow_name()
        except Exception:
            name = type(fsm.logic).__name__
        timing = self.flow_timings.get(name)
        if timing is None:
            if len(self.flow_timings) >= self.FLOW_TIMINGS_MAX_NAMES:
                return  # bounded; established names keep aggregating
            timing = self.flow_timings[name] = {
                "count": 0, "total_ms": 0.0, "max_ms": 0.0}
        duration_ms = (_time.monotonic() - fsm.created_at) * 1e3
        timing["count"] += 1
        timing["total_ms"] = round(timing["total_ms"] + duration_ms, 3)
        timing["max_ms"] = round(max(timing["max_ms"], duration_ms), 3)
        if _tm.ACTIVE is not None:
            _tm.inc("flows_completed_total")

    # -- checkpoint & restore ---------------------------------------------

    def _checkpoint(self, fsm: FlowStateMachine) -> None:
        if fsm.state == _DONE:
            return
        if self.defer_checkpoints:
            self._dirty_checkpoints[fsm.run_id] = fsm
            return
        self._write_checkpoint(fsm)

    def _write_checkpoint(self, fsm: FlowStateMachine) -> None:
        if _faults.ACTIVE is not None:
            _faults.fire_fsync("checkpoint.write")
        self.metrics["checkpointing_rate"] += 1
        blob = self._serialize_checkpoint(fsm)
        self.checkpoint_storage.update_checkpoint(fsm.run_id, blob)

    def _serialize_checkpoint(self, fsm: FlowStateMachine) -> bytes:
        try:
            with self.token_context:
                return serialize(fsm.to_checkpoint()).bytes
        except Exception as e:
            # Unserializable flow state is a programming error; fail loudly.
            raise FlowException(f"cannot checkpoint flow: {e}") from e

    def flush_checkpoints(self) -> int:
        """Serialize + write every round-dirty flow checkpoint (deferred
        mode). Called by the node run loop inside the round transaction,
        before the transport ACKs the round's inbound messages.

        A flow whose state will not SERIALIZE is failed in place — exactly
        what an exception raised inside one of its handlers would do — and
        the round stays committable. Propagating instead would roll back the
        whole round and exit the node; on restart the flow would replay to
        the same unserializable state and crash it again — a permanent
        crash loop triggered by one bad flow (round-3 advisor finding).
        Storage-level write failures still abort the round: those compromise
        every flow's durability, not one flow's.
        """
        if not self._dirty_checkpoints:
            return 0
        dirty, self._dirty_checkpoints = self._dirty_checkpoints, {}
        n = 0
        for fsm in dirty.values():
            if fsm.state == _DONE:
                continue  # finished mid-round; checkpoint already removed
            self.metrics["checkpointing_rate"] += 1
            try:
                blob = self._serialize_checkpoint(fsm)
            except FlowException as e:
                logger.error(
                    "flow %s has unserializable state; failing the flow: %s",
                    fsm.run_id.hex()[:8], e)
                fsm._fail(e)
                continue
            self.checkpoint_storage.update_checkpoint(fsm.run_id, blob)
            n += 1
        return n

    def _restore_checkpoints(self) -> None:
        """Rebuild flows by deterministic replay
        (reference: StateMachineManager.kt:190-226)."""
        # items() (durability plane) yields CRC-verified (run_id, blob)
        # pairs and quarantines rows whose checksum fails before we ever
        # decode them; plain checkpoints() is the fallback for storage
        # implementations that predate it.
        items = getattr(self.checkpoint_storage, "items", None)
        pairs = items() if items is not None else [
            (None, blob) for blob in self.checkpoint_storage.checkpoints()]
        for run_id, blob in pairs:
            try:
                with self.token_context:
                    cp = deserialize(blob)
            except DeserializationError as e:
                # A checkpoint that passed (or predates) its CRC but no
                # longer decodes is damage the codec caught: quarantine it
                # so replay is never poisoned, and surface the loss — the
                # flow is failed, not silently forgotten.
                self.metrics["checkpoints_quarantined"] += 1
                quarantine = getattr(
                    self.checkpoint_storage, "quarantine", None)
                if quarantine is not None and run_id is not None:
                    quarantine(run_id, blob, f"undecodable checkpoint: {e}")
                logger.error("quarantined undecodable checkpoint: %s", e)
                continue
            try:
                logic = flow_registry.create(cp.flow_name, tuple(cp.flow_args))
            except FlowException:
                logger.error("dropping checkpoint for unknown flow %s", cp.flow_name)
                continue
            restored = [FlowSession.from_checkpoint(sc) for sc in cp.sessions]
            sessions = {s.key: s for s in restored}
            self._subscribe_progress(logic, cp.run_id)
            fsm = FlowStateMachine(
                self,
                logic,
                cp.run_id,
                resolved=list(cp.resolved),
                sessions=sessions,
                next_session_seq=cp.next_session_seq,
            )
            for session in sessions.values():
                self._register_session(fsm, session)
            self.flows[cp.run_id] = fsm
            self._mark_runnable(fsm)
            self.changes.append(("restore", cp.run_id))

    # -- scheduling --------------------------------------------------------

    def _mark_runnable(self, fsm: FlowStateMachine) -> None:
        if fsm not in self._runnable and fsm.state != _DONE:
            fsm.state = _RUNNABLE
            self._runnable.append(fsm)
            if (_qos.ACTIVE is not None and fsm.qos is not None
                    and _obs.ACTIVE is not None
                    and fsm.trace_id is not None):
                # Stamp for the lane_queue_wait span closed at pick time.
                fsm.qos_runnable_since = _obs.now()

    def _next_runnable(self) -> FlowStateMachine:
        """Pop the next flow step. Disarmed: strict FIFO (pop(0)), the
        pre-QoS behaviour. Armed: interactive and unlabelled flows form
        one priority class served FIFO ahead of bulk, with every
        ``bulk_every``'th pick taking the oldest bulk step when both
        classes are runnable (anti-starvation) — so a tree that never
        marks a lane still schedules in exact FIFO order."""
        plane = _qos.ACTIVE
        if plane is None:
            return self._runnable.pop(0)
        runnable = self._runnable
        pri_idx = bulk_idx = None
        for i, fsm in enumerate(runnable):
            ctx = fsm.qos
            if ctx is not None and ctx.lane == _qos.LANE_BULK:
                if bulk_idx is None:
                    bulk_idx = i
            elif pri_idx is None:
                pri_idx = i
            if pri_idx is not None and bulk_idx is not None:
                break
        if pri_idx is None or bulk_idx is None:
            idx = 0  # one class present: FIFO
        else:
            self._qos_pick_counter += 1
            if self._qos_pick_counter % plane.bulk_every == 0:
                idx = bulk_idx
                plane.counters["bulk_antistarvation_picks"] += 1
            else:
                idx = pri_idx
        fsm = runnable.pop(idx)
        since = getattr(fsm, "qos_runnable_since", None)
        if since is not None:
            fsm.qos_runnable_since = None
            if (_obs.ACTIVE is not None and fsm.trace_id is not None
                    and fsm.qos is not None):
                _obs.record("lane_queue_wait", since, _obs.now(),
                            trace_id=fsm.trace_id, parent=fsm.trace_span,
                            attrs={"lane": fsm.qos.lane})
        return fsm

    def _pump(self) -> None:
        """Run flows until everything is parked; then flush verify batches.
        Re-entrant calls fold into the outer pump."""
        if self._pumping:
            return
        self._pumping = True
        try:
            while True:
                while self._runnable:
                    fsm = self._next_runnable()
                    if fsm.state != _DONE:
                        fsm.step()
                if self._verify_queue and not self.defer_verify:
                    self._flush_verify_batch()
                    continue
                # Ship buffered session sends as coalesced bursts INSIDE
                # the loop: on the in-memory transport delivery is
                # synchronous and may mark flows runnable again — flushing
                # after the loop would strand them parked.
                if self._flush_session_sends():
                    continue
                break
        finally:
            self._pumping = False
            # Safety net: an exception mid-pump must not strand buffered
            # frames (their flows already suspended expecting delivery).
            # _pumping is already False, so re-entrant pumps from any
            # synchronous delivery run fresh.
            if self._send_buffer:
                self._flush_session_sends()

    def _flush_session_sends(self) -> bool:
        """Ship every buffered session send, grouped into per-destination
        multi-frame bursts (transport send_many) when contexts allow;
        returns True if anything was sent. Grouping key includes session
        topic, destination and the CAPTURED obs/qos contexts — per-session
        frame order is preserved (a session's frames stay in queue order
        inside one group) and traced/QoS-labelled frames keep their own
        stamps (they degrade to smaller groups rather than borrowing the
        flush thread's context)."""
        if not self._send_buffer:
            return False
        buffered, self._send_buffer = self._send_buffer, []
        groups: dict = {}  # key -> [address, obs_ctx, qos_ctx, items]
        order: list = []
        for ts, blob, address, obs_ctx, qos_ctx in buffered:
            key = (ts.topic, ts.session_id, str(address), obs_ctx,
                   id(qos_ctx))
            g = groups.get(key)
            if g is None:
                groups[key] = g = [address, obs_ctx, qos_ctx, []]
                order.append(key)
            g[3].append((ts, blob))
        send_many = getattr(self.messaging, "send_many", None)
        outer_obs = _obs.get_context()
        outer_qos = _qos.get_context()
        try:
            for key in order:
                address, obs_ctx, qos_ctx, items = groups[key]
                if obs_ctx is not None:
                    _obs.set_context(*obs_ctx)
                else:
                    _obs.clear_context()
                _qos.set_context(qos_ctx)
                if send_many is not None and len(items) > 1:
                    send_many(items[0][0], [blob for _, blob in items],
                              address)
                    self.metrics["session_bursts"] += 1
                    self.metrics["session_burst_frames"] += len(items)
                else:
                    for ts, blob in items:
                        self.messaging.send(ts, blob, address)
        finally:
            if outer_obs is not None:
                _obs.set_context(*outer_obs)
            else:
                _obs.clear_context()
            _qos.set_context(outer_qos)
        return True

    def flush_pending_verifies(self) -> int:
        """Flush the accumulated verify micro-batch (deferred mode); returns
        the number of requests satisfied."""
        n = len(self._verify_queue)
        if n:
            self._flush_verify_batch()
            self._pump()
        return n

    # -- the verification pump (TPU seam) ---------------------------------

    def _enqueue_verify(
        self, fsm: FlowStateMachine,
        request: "VerifyTxRequest | VerifySigRequest",
    ) -> None:
        if not self._verify_queue:
            self._verify_waiting_since = _time.monotonic()
        if _obs.ACTIVE is not None and fsm.trace_id is not None:
            # Stamp when this flow's request joined the micro-batch; the
            # verify_wait span closes when the batch flushes/submits.
            fsm.trace_verify_enq = _obs.now()
        if _qos.ACTIVE is not None:
            ctx = fsm.qos
            if (ctx is not None and ctx.lane == _qos.LANE_INTERACTIVE
                    and ctx.deadline_ns > 0
                    and (self._verify_qos_deadline_ns == 0
                         or ctx.deadline_ns < self._verify_qos_deadline_ns)):
                self._verify_qos_deadline_ns = ctx.deadline_ns
        self._verify_queue.append((fsm, request))
        if isinstance(request, VerifySigRequest):
            self._verify_sig_count += 1
            return
        # Count at least 1 per request: a zero-signature request (can't arise
        # from SignedTransaction today, which demands >=1 sig, but belt-and-
        # braces) must still trip the flush gate or its flow parks forever.
        self._verify_sig_count += max(len(request.stx.sigs), 1)

    @property
    def verify_pending_sigs(self) -> int:
        """Signatures waiting in the micro-batch (max-wait scheduler input)."""
        return self._verify_sig_count

    @property
    def verify_waiting_since(self) -> float:
        """monotonic() when the current micro-batch started accumulating."""
        return self._verify_waiting_since

    def verify_deadline_pressure(self) -> bool:
        """True when the earliest interactive deadline in the verify
        micro-batch is within the QoS guard window — the run loop flushes
        early instead of waiting out max_wait_ms (deadline-aware
        coalescing at queueing point 1 of 3)."""
        plane = _qos.ACTIVE
        if plane is None or not self._verify_queue:
            return False
        return plane.deadline_near_ns(self._verify_qos_deadline_ns)

    def qos_queue_depth(self) -> int:
        """Runnable backlog the admission watermark judges bulk against:
        ready flow steps + flows parked on a service poll (commit in
        flight) — the work interactive requests must traverse."""
        return len(self._runnable) + len(self._service_queue)

    def _qos_verify_hint(self) -> None:
        """Advisory (lane, deadline_ns) for the verifier client: a sidecar
        verifier forwards it on the wire so the SERVER's scheduler can
        deadline-flush across processes. Reset with the micro-batch."""
        plane = _qos.ACTIVE
        if plane is None:
            return
        dl = self._verify_qos_deadline_ns
        self.verifier.qos_hint = (
            (_qos.LANE_INTERACTIVE, dl) if dl > 0 else None)

    # -- async service polling (Raft commit etc.) --------------------------

    def _enqueue_service(self, fsm: FlowStateMachine, poll: Callable) -> None:
        self._service_queue.append((fsm, poll))

    @property
    def service_pending(self) -> int:
        """Flows parked on a ServiceRequest (e.g. awaiting a raft commit)."""
        return len(self._service_queue)

    def poll_services(self) -> int:
        """Poll every parked ServiceRequest; resume flows whose operation
        finished. Called from the node's run loop. Returns completions.

        This is the round -> group-submit seam of the commit pipeline: every
        commit_async poll that (re)submits during ONE pass lands in the raft
        leader's pending batch together, and flush_appends seals them into
        one group-commit entry right after (node.run_once ordering)."""
        if not self._service_queue:
            return 0
        done = 0
        still_pending = []
        traced = _obs.ACTIVE is not None
        qos_armed = _qos.ACTIVE is not None
        for fsm, poll in self._service_queue:
            if fsm.state != _WAIT_SERVICE:  # flow died/was restored elsewhere
                continue
            if traced and fsm.trace_id is not None:
                # commit_async submissions inside poll() must carry the
                # submitting flow's context (raft link registration).
                _obs.set_context(fsm.trace_id, fsm.trace_span)
            if qos_armed:
                # Same rule for the QoS link: a (re)submission this poll
                # makes must register under ITS flow's lane/deadline, so
                # set-or-clear per iteration, never inherit a neighbour's.
                _qos.set_context(fsm.qos)
            try:
                outcome = poll()
            except Exception as e:
                fsm.deliver_service_result(error=e)
                done += 1
                continue
            if outcome is None:
                still_pending.append((fsm, poll))
            else:
                fsm.deliver_service_result(value=outcome)
                done += 1
        if traced:
            _obs.clear_context()
        if qos_armed:
            _qos.clear_context()
        self._service_queue = still_pending
        self.metrics["service_polls"] += 1
        if done:
            self.metrics["service_completions"] += done
            self.metrics["service_round_max"] = max(
                self.metrics["service_round_max"], done)
            self._pump()
        return done

    def _flush_verify_batch(self) -> None:
        """One batched kernel call covering every parked VerifyTxRequest and
        VerifySigRequest (the synchronous path: verify on THIS thread)."""
        self._qos_verify_hint()
        batch, self._verify_queue = self._verify_queue, []
        self._verify_sig_count = 0
        self._verify_qos_deadline_ns = 0
        if _obs.ACTIVE is not None:
            self._record_verify_wait(batch)
        jobs, spans = self._build_verify_jobs(batch)
        ok = self.verifier.verify_batch(jobs) if jobs else []
        self.metrics["verify_batches"] += 1
        self.metrics["verify_sigs"] += len(jobs)
        if _tm.ACTIVE is not None:
            _tm.inc("verify_batches_total")
            _tm.inc("verify_sigs_total", len(jobs))
            _tm.observe("verify_batch_sigs", len(jobs))
        self._deliver_verify_results(spans, ok)

    def _record_verify_wait(self, batch) -> None:
        """Close each traced flow's verify_wait span: time from joining the
        verify micro-batch to the batch leaving the queue (flush or async
        submit) — the batching-delay component of notarise latency."""
        now = _obs.now()
        for fsm, _request in batch:
            enq = getattr(fsm, "trace_verify_enq", None)
            if fsm.trace_id is None or enq is None:
                continue
            fsm.trace_verify_enq = None
            _obs.record("verify_wait", enq, now,
                        trace_id=fsm.trace_id, parent=fsm.trace_span)

    def _build_verify_jobs(
        self, batch: "list[tuple[FlowStateMachine, Any]]",
    ) -> "tuple[list[VerifyJob], list[tuple[FlowStateMachine, Any, int, int]]]":
        """Flatten parked requests into one VerifyJob list plus per-request
        spans mapping result ranges back to the waiting flows."""
        jobs: list[VerifyJob] = []
        spans: list[tuple[FlowStateMachine, Any, int, int]] = []
        for fsm, request in batch:
            start = len(jobs)
            if isinstance(request, VerifySigRequest):
                jobs.append(VerifyJob(
                    pubkey=request.pubkey, message=request.message,
                    sig=request.sig_bytes))
            else:
                jobs.extend(
                    VerifyJob(
                        pubkey=sig.by.encoded,
                        message=request.stx.id.bytes,
                        sig=sig.bytes,
                    )
                    for sig in request.stx.sigs
                )
            spans.append((fsm, request, start, len(jobs)))
        return jobs, spans

    def _deliver_verify_results(self, spans, ok) -> None:
        """Resume every flow a finished batch was verifying. Flows that left
        _WAIT_VERIFY while an async batch was in flight (failed in place by
        checkpoint serialization, or torn down) are skipped — their park is
        gone and the result has nowhere to land."""
        for fsm, request, start, end in spans:
            if fsm.state != _WAIT_VERIFY:
                continue
            fsm_ok, error = True, None
            if isinstance(request, VerifySigRequest):
                if not all(ok[start:end]):
                    fsm_ok = False
                    error = SignatureError(
                        f"Signature did not match: {request.description}")
                fsm.deliver_verify_result(fsm_ok, error)
                continue
            if not all(ok[start:end]):
                fsm_ok = False
                bad = [
                    request.stx.sigs[i - start].by
                    for i in range(start, end)
                    if not ok[i]
                ]
                error = SignatureError(f"Signature did not match for keys: {bad}")
            else:
                # Math passed; check completeness on the host (cheap).
                try:
                    missing = request.stx.get_missing_signatures()
                    needed = missing - set(request.allowed_to_be_missing)
                    if needed:
                        from ..transactions.signed import SignaturesMissingException

                        fsm_ok = False
                        error = SignaturesMissingException(
                            needed, [], request.stx.id
                        )
                except Exception as e:
                    fsm_ok, error = False, e
            fsm.deliver_verify_result(fsm_ok, error)

    # -- the async pipeline (crypto/async_verify.py) -----------------------

    def submit_pending_verifies(self) -> int:
        """Hand the accumulated micro-batch to the async feeder thread and
        return immediately (the pipelined counterpart of
        flush_pending_verifies); returns the number of jobs submitted.
        The parked flows stay in _WAIT_VERIFY until drain_async_verifies
        delivers the completed batch on a later round."""
        self._qos_verify_hint()
        batch, self._verify_queue = self._verify_queue, []
        self._verify_sig_count = 0
        self._verify_qos_deadline_ns = 0
        if not batch:
            return 0
        if _obs.ACTIVE is not None:
            self._record_verify_wait(batch)
        jobs, spans = self._build_verify_jobs(batch)
        if _tm.ACTIVE is not None:
            _tm.inc("verify_batches_total")
            _tm.inc("verify_sigs_total", len(jobs))
            _tm.observe("verify_batch_sigs", len(jobs))
        self.async_verify.submit(jobs, spans)
        return len(jobs)

    def drain_async_verifies(self) -> int:
        """Deliver every batch the feeder thread has finished (run-loop
        thread only — flow state crosses back here and nowhere else).
        A batch whose verify RAISED rejects its waiting flows with the
        error instead of hanging them. Returns batches delivered."""
        svc = self.async_verify
        if svc is None:
            return 0
        done = 0
        for handle in svc.drain():
            done += 1
            self.metrics["verify_batches"] += 1
            self.metrics["verify_sigs"] += len(handle.jobs)
            if handle.error is not None:
                if self._degrade_and_reverify(handle):
                    continue
                for fsm, request, start, end in handle.context:
                    if fsm.state != _WAIT_VERIFY:
                        continue
                    fsm.deliver_verify_result(False, handle.error)
            else:
                self._deliver_verify_results(handle.context, handle.ok)
        if done:
            self._pump()
        return done

    def _degrade_and_reverify(self, handle) -> bool:
        """A raised verify on a DEVICE-backed verifier must not reject the
        waiting flows — an infrastructure fault is not a bad signature.
        Demote the device tier (crypto.provider.degrade_device installs the
        gate + cooldown re-probe) and re-verify this batch synchronously on
        the host tier, which has the same accept set. Returns True when the
        batch was delivered that way; False (verifier has no device tier,
        or the host re-verify itself raised) falls back to rejection."""
        verifier = getattr(self.async_verify, "verifier", None)
        if verifier is None or getattr(verifier, "device_min_sigs", None) is None:
            return False
        from ..crypto.provider import degrade_device, host_verify

        try:
            degrade_device(verifier)
            ok = host_verify(handle.jobs)
        except Exception:
            logging.getLogger(__name__).exception(
                "host re-verify after device degrade failed")
            return False
        self.metrics["verify_device_degraded"] += 1
        logging.getLogger(__name__).warning(
            "device verify failed (%s); batch of %d re-verified on host, "
            "device tier degraded pending re-probe",
            handle.error, len(handle.jobs))
        self._deliver_verify_results(handle.context, ok)
        return True

    # -- messaging ---------------------------------------------------------

    def _register_session(self, fsm: FlowStateMachine, session: FlowSession) -> None:
        self._sessions_by_local_id[session.local_id] = (fsm, session)
        # Route future messages addressed to this session id.
        registration = self.messaging.add_message_handler(
            SESSION_TOPIC, session.local_id, self._on_existing_session_message
        )
        self._session_handlers[session.local_id] = registration

    def _send_session_message(self, party: Party, session_id: int, payload) -> None:
        node = self.service_hub.network_map_cache.get_node_by_legal_identity(party)
        if node is None and self.netmap_refresh is not None:
            # A peer we've never heard of usually means OUR cache is stale,
            # not that the peer doesn't exist (e.g. a client that registered
            # after our last refresh sends us a SessionInit; the reply
            # address is missing). Refresh on demand and retry once before
            # failing — otherwise the reply is lost and the initiator stalls
            # in redelivery backoff until the periodic refresh catches up.
            self.netmap_refresh()
            node = self.service_hub.network_map_cache \
                .get_node_by_legal_identity(party)
        if node is None:
            raise FlowException(f"don't know where to send to {party}")
        ts = TopicSession(SESSION_TOPIC, session_id or DEFAULT_SESSION_ID)
        blob = serialize(payload).bytes
        if self._pumping:
            # Mid-pump: defer to the pump-end flush so a burst of flow
            # steps ships as ONE multi-frame transport call per
            # destination. Contexts are captured NOW (this flow's step
            # installed them); the flush re-installs them before sending.
            self.metrics["session_sends"] += 1
            self._send_buffer.append(
                (ts, blob, node.address, _obs.get_context(),
                 _qos.get_context()))
            return
        self.messaging.send(ts, blob, node.address)

    def _on_session_init_message(self, message: Message) -> None:
        try:
            payload = deserialize(message.data)
        except DeserializationError as e:
            # Hostile/corrupt bytes must not halt the delivery pump — but
            # ONLY codec rejections are droppable; anything else is a real
            # bug that must surface. Counted so node_metrics shows the rate.
            self.metrics["undecodable_messages"] += 1
            logger.warning("dropping undecodable init message: %s", e)
            return
        if not isinstance(payload, SessionInit):
            logger.warning("non-init message on init session: %r", payload)
            return
        factory = self._flow_factories.get(payload.flow_name)
        initiator = payload.initiator_party
        if factory is None:
            self._send_session_message(
                initiator,
                payload.initiator_session_id,
                SessionReject(
                    payload.initiator_session_id,
                    f"no flow registered for {payload.flow_name}",
                ),
            )
            self._pump()
            return
        logic = factory(initiator)
        run_id = os.urandom(16)
        self._subscribe_progress(logic, run_id)
        fsm = FlowStateMachine(self, logic, run_id)
        if _obs.ACTIVE is not None and message.trace is not None:
            # Session-initiated flow: JOIN the initiator's trace — its span
            # parents ours, which is how one tx's spans stitch across nodes.
            fsm.trace_id, fsm.trace_parent = message.trace
            fsm.trace_span = _obs.new_span_id()
            fsm.trace_t0 = _obs.now()
        if _qos.ACTIVE is not None and message.qos is not None:
            # Join the initiator's lane + deadline: the responder (the
            # notary) schedules this flow under the CLIENT's contract.
            fsm.qos = message.qos
        self.flows[run_id] = fsm
        self.metrics["started"] += 1
        local_id = fsm._session_id(fsm.next_session_seq)
        fsm.next_session_seq += 1
        session = fsm.open_initiated_session(
            initiator, local_id, payload.initiator_session_id
        )
        self._send_session_message(
            initiator,
            payload.initiator_session_id,
            SessionConfirm(payload.initiator_session_id, local_id),
        )
        if payload.first_payload is not None:
            session.receive_buffer.append(payload.first_payload)
        self._checkpoint(fsm)
        self._mark_runnable(fsm)
        self.changes.append(("add", run_id))
        self._pump()

    def _on_existing_session_message(self, message: Message) -> None:
        entry = self._sessions_by_local_id.get(message.topic_session.session_id)
        if entry is None:
            logger.warning("message for unknown session %s", message.topic_session)
            return
        fsm, session = entry
        try:
            payload = deserialize(message.data)
        except Exception as e:
            logger.warning("dropping undecodable session message: %s", e)
            return
        if isinstance(payload, SessionConfirm):
            session.peer_id = payload.initiated_session_id
            fsm.session_confirmed(session)
        elif isinstance(payload, SessionReject):
            fsm.session_rejected(session, payload.error_message)
        elif isinstance(payload, SessionData):
            fsm.deliver_session_payload(session, payload.payload)
        elif isinstance(payload, SessionEnd):
            fsm.session_ended(session)
        else:
            logger.warning("unknown session payload %r", payload)
        self._pump()

    # -- completion --------------------------------------------------------

    def _flow_finished(self, fsm: FlowStateMachine) -> None:
        self.flows.pop(fsm.run_id, None)
        self._dirty_checkpoints.pop(fsm.run_id, None)
        self.checkpoint_storage.remove_checkpoint(fsm.run_id)
        self.metrics["finished"] += 1
        self._record_flow_timing(fsm)
        # Bounded outcome cache so RPC clients can fetch results after the
        # flow leaves the registry (the reference returns a future over RPC).
        self.recent_results[fsm.run_id] = fsm.future
        while len(self.recent_results) > 1000:
            self.recent_results.pop(next(iter(self.recent_results)))
        self.changes.append(("remove", fsm.run_id))
        for session in fsm.sessions.values():
            self._sessions_by_local_id.pop(session.local_id, None)
            registration = self._session_handlers.pop(session.local_id, None)
            if registration is not None:
                try:
                    self.messaging.remove_message_handler(registration)
                except (LookupError, ValueError):
                    # Teardown race: the handler was already removed (node
                    # stop or duplicate finish). Count it — a nonzero rate
                    # here means deregistration logic regressed.
                    self.metrics["handler_remove_failures"] += 1
            if session.state == "open" and session.peer_id is not None:
                try:
                    self._send_session_message(
                        session.party, session.peer_id, SessionEnd(session.peer_id)
                    )
                except FlowException:
                    pass
            session.state = "ended"
