"""SidecarVerifier — the node-side client of the verification sidecar.

Plugs into the existing BatchVerifier seam unchanged: node assembly swaps it
in for the local provider when ``[batch] sidecar`` (or CORDA_TPU_SIDECAR)
names a server address, and the async feeder, SMM degrade path, metrics
stamps and adaptive crossover all keep working by duck type — it IS a
DeviceRoutedVerifier whose "device" is the host-local sidecar socket.

The crossover default is deliberately LOW (16, not 512): shipping a
micro-batch to the sidecar costs one local-socket round trip, and the
sidecar amortises the REAL device dispatch across every node process on the
host. Per-process batching (512 floor) is exactly what left device_batches
at 0 on the round-5 flagship; the sidecar exists so micro-batches flow out
and coalesce server-side.

Failure policy — never a wrong answer, never a hang:
  * Any transport/deadline/protocol failure raises SidecarError from
    ``_verify_ed25519_device``. The routing override catches it, demotes
    the sidecar tier through provider.degrade_device (shared gate +
    cooldown re-probe machinery) and answers the batch from the local host
    tier, which is oracle-exact. Infra faults degrade; they never reject.
  * The cooldown re-probe calls ``_verify_ed25519_device`` directly with a
    garbage batch and interprets "no exception" as healthy — which is why
    the device method must RAISE on failure rather than falling back
    internally: an internal fallback would re-open the gate while the
    sidecar is still dead.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Sequence

import numpy as np

from ..crypto import sidecar as wire
from ..crypto.provider import (CpuVerifier, DeviceRoutedVerifier, VerifyJob,
                               degrade_device)

# Size crossover for the SIDECAR tier (see module doc: low on purpose —
# the expensive device round trip happens server-side, amortised across
# processes; the client only pays a local socket RTT).
SIDECAR_MIN_SIGS_DEFAULT = 16


class SidecarError(RuntimeError):
    """The sidecar failed to answer: dead, deadline missed, or protocol
    error. Carries no verdicts — the caller re-verifies on the host."""


class SidecarVerifier(DeviceRoutedVerifier):
    """Verifies ed25519 batches through the per-host sidecar server."""

    name = "sidecar"  # must NOT start with "jax": the node's local warm
    #                   path and jax-only stamping do not apply here

    def __init__(self, address: str, deadline_ms: float = 2000.0,
                 device_min_sigs: int | None = None,
                 connect_timeout_s: float = 1.0,
                 reprobe_cooldown_s: float | None = None,
                 devices: int | None = None):
        if device_min_sigs is None:
            device_min_sigs = int(os.environ.get(
                "CORDA_TPU_SIDECAR_MIN_SIGS", SIDECAR_MIN_SIGS_DEFAULT))
        super().__init__(device_min_sigs=device_min_sigs)
        self.address = address
        self.deadline_s = float(deadline_ms) / 1e3
        self.connect_timeout_s = connect_timeout_s
        self.reprobe_cooldown_s = reprobe_cooldown_s
        # Mesh width the config SAYS the server owns ([batch]
        # sidecar_devices): stamped for attribution; the server snapshot
        # below carries the proven value.
        self.devices = devices or None
        # Server-stats cache keyed BY ENDPOINT, not a single slot: the
        # federation router (crypto/federation.py) holds one client per
        # host, and any address change (or a future shared cache) must
        # never serve one sidecar's stale snapshot as another's.
        self._server_snapshots: dict[str, tuple[float, dict | None]] = {}
        self._sock: socket.socket | None = None
        self._req_id = 0
        # Serialises the socket: the feeder thread and the degrade
        # re-probe thread may both round-trip; one framed request/reply
        # pair at a time keeps req_id matching trivial.
        self._io_lock = threading.Lock()
        self.sidecar_batches = 0
        self.sidecar_sigs = 0
        self.fallbacks = 0
        self.connects = 0
        self.rpc_s_total = 0.0
        # Server-reported timings of the newest answered batch; the async
        # feeder turns these into sidecar_wait/sidecar_verify spans.
        self.last_wait_s: float | None = None
        self.last_verify_s: float | None = None
        self.last_tier: str | None = None
        # QoS hint: (lane_code, deadline_ns) set by the SMM right before a
        # flush when the queued micro-batch contains an interactive request
        # with a live deadline. Advisory and racy-by-design — a stale hint
        # costs one early server flush, never correctness. When set, the
        # next batch ships as OP_VERIFY_QOS so the sidecar's deadline
        # scheduler can order/flush around it.
        self.qos_hint: tuple[int, int] | None = None

    def reset_window(self) -> None:
        """Cache-bust seam for back-to-back measurements (the autotune
        controller calls this between sweep candidates): drop every
        cached server snapshot so the next stats ride fetches fresh —
        the 5 s TTL would otherwise hand candidate N the stats of
        candidate N-1."""
        self._server_snapshots.clear()

    # -- routing ------------------------------------------------------------

    def _verify_ed25519(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        if (len(jobs) < self.device_min_sigs
                or (self.device_gate is not None
                    and not self.device_gate.is_set())):
            self.host_batches += 1
            return CpuVerifier._verify_ed25519_host(jobs)
        try:
            out = self._verify_ed25519_device(jobs)
        except SidecarError:
            # Hard fallback: demote the sidecar tier (gate + cooldown
            # re-probe) and answer from the oracle-exact host path.
            self.fallbacks += 1
            degrade_device(self, cooldown_s=self.reprobe_cooldown_s)
            self.host_batches += 1
            return CpuVerifier._verify_ed25519_host(jobs)
        self.device_batches += 1
        return out

    # -- the sidecar round trip --------------------------------------------

    def _verify_ed25519_device(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        """One framed OP_VERIFY round trip. Raises SidecarError on ANY
        failure — this method doubles as the degrade re-probe ("the
        sidecar answered a batch" == healthy), so it must never fall back
        internally."""
        # Wrong-length keys/sigs can't ride the fixed-width wire arrays;
        # they reject locally — identical semantics to the kernel path
        # (malformed input rejects, never raises).
        good_idx = [i for i, j in enumerate(jobs)
                    if len(j.pubkey) == 32 and len(j.sig) == 64]
        out = np.zeros(len(jobs), bool)
        if not good_idx:
            return out
        good = (list(jobs) if len(good_idx) == len(jobs)
                else [jobs[i] for i in good_idx])
        t0 = time.perf_counter()
        # lint: allow(no-blocking-under-lock) _io_lock exists to serialize request/reply framing on the one sidecar socket; callers that must not queue here use their own client instance
        with self._io_lock:
            deadline = time.perf_counter() + self.deadline_s
            try:
                sock = self._connect_maybe()
                self._req_id += 1
                req_id = self._req_id
                sock.settimeout(max(0.05, deadline - time.perf_counter()))
                hint = self.qos_hint
                if hint is not None:
                    lane_code, deadline_ns = hint
                    req = wire.encode_verify_request_qos(
                        req_id, good, lane_code, deadline_ns)
                else:
                    req = wire.encode_verify_request(req_id, good)
                wire.send_frame(sock, req)
                while True:
                    sock.settimeout(max(0.05,
                                        deadline - time.perf_counter()))
                    payload = wire.recv_frame(sock)
                    (op, rid, status, tier, wait_s,
                     verify_s) = wire._VERIFY_REPLY_HDR.unpack_from(payload)
                    if op == wire.OP_VERIFY and rid == req_id:
                        break  # anything else is a stale/odd frame: skip
                if status != wire.STATUS_OK:
                    detail = payload[wire._VERIFY_REPLY_HDR.size:].decode(
                        errors="replace")
                    raise SidecarError(
                        f"sidecar verify failed: {detail or 'error'}")
                flags = np.frombuffer(
                    payload, np.uint8,
                    offset=wire._VERIFY_REPLY_HDR.size).astype(bool)
                if len(flags) != len(good):
                    raise SidecarError("short sidecar reply")
            except (OSError, ConnectionError, socket.timeout, struct.error,
                    ValueError) as exc:
                # Half-answered streams can't be resumed; reconnect fresh
                # next time (also what makes the re-probe meaningful).
                self._drop_connection()
                raise SidecarError(
                    f"sidecar {self.address}: {exc}") from exc
        self.sidecar_batches += 1
        self.sidecar_sigs += len(good)
        self.rpc_s_total += time.perf_counter() - t0
        self.last_wait_s = float(wait_s)
        self.last_verify_s = float(verify_s)
        self.last_tier = "device" if tier else "host"
        if len(good_idx) == len(jobs):
            return flags
        out[good_idx] = flags
        return out

    def warm(self) -> None:
        """Ping the server (connectivity check; nothing to compile on the
        client side — the SERVER owns device warm-up)."""
        # lint: allow(no-blocking-under-lock) same socket-framing serialization lock as verify_batch: the ping must not interleave with an in-flight verify frame
        with self._io_lock:
            try:
                sock = self._connect_maybe()
                self._req_id += 1
                sock.settimeout(self.connect_timeout_s)
                wire.send_frame(
                    sock, wire._REQ_HDR.pack(wire.OP_PING, self._req_id))
                wire.recv_frame(sock)
            except (OSError, ConnectionError, struct.error) as exc:
                self._drop_connection()
                raise SidecarError(
                    f"sidecar {self.address}: {exc}") from exc

    def _connect_maybe(self) -> socket.socket:
        if self._sock is None:
            self._sock = wire.connect(self.address,
                                      timeout=self.connect_timeout_s)
            self.connects += 1
        return self._sock

    def _drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- stamping -----------------------------------------------------------

    def sidecar_stats(self) -> dict:
        """Client-side view for node_metrics / loadtest node_stamps."""
        gate = self.device_gate
        return {
            "address": self.address,
            "deadline_ms": self.deadline_s * 1e3,
            "min_sigs": self.device_min_sigs,
            "batches": self.sidecar_batches,
            "sigs": self.sidecar_sigs,
            "fallbacks": self.fallbacks,
            "connects": self.connects,
            "rpc_s_total": round(self.rpc_s_total, 6),
            "last_wait_s": self.last_wait_s,
            "last_verify_s": self.last_verify_s,
            "last_tier": self.last_tier,
            "gate_open": gate.is_set() if gate is not None else None,
            "degraded": self.degraded,
            "reprobes_ok": self.reprobes_ok,
            "reprobes_failed": self.reprobes_failed,
            "devices": self.devices,
            "server": self._server_stats_maybe(),
        }

    def _server_stats_maybe(self) -> dict | None:
        """Best-effort server-side snapshot (per-device occupancy, pad
        fraction, mesh size) riding the client stamp into node_metrics —
        fetched over a FRESH connection so it never contends with an
        in-flight verify, cached 5 s PER ENDPOINT so metrics polls stay
        cheap without one sidecar's snapshot masquerading as another's,
        and None (never an exception) when the server is unreachable."""
        now = time.monotonic()
        hit = self._server_snapshots.get(self.address)
        if hit is not None and now - hit[0] < 5.0:
            return hit[1]
        try:
            snap = fetch_sidecar_stats(self.address, timeout=0.5)
        except SidecarError:
            snap = None
        self._server_snapshots[self.address] = (now, snap)
        return snap


def fetch_sidecar_stats(address: str, timeout: float = 2.0) -> dict:
    """One-shot OP_STATS round trip on a fresh connection — harness-side
    artifact gathering (loadtest/bench). Raises SidecarError when the
    server is unreachable."""
    try:
        sock = wire.connect(address, timeout=timeout)
        try:
            sock.settimeout(timeout)
            wire.send_frame(sock, wire._REQ_HDR.pack(wire.OP_STATS, 1))
            payload = wire.recv_frame(sock)
            op, _, status = wire._REPLY_HDR.unpack_from(payload)
            if op != wire.OP_STATS or status != wire.STATUS_OK:
                raise ValueError("bad sidecar stats reply")
            import json

            return json.loads(payload[wire._REPLY_HDR.size:].decode())
        finally:
            try:
                sock.close()
            except OSError:
                pass
    except (OSError, ConnectionError, ValueError, struct.error) as exc:
        raise SidecarError(f"sidecar {address}: {exc}") from exc
