"""The node's HTTP API: status, metrics, attachment upload/download.

Capability match for the reference's web tier (reference:
node/src/main/kotlin/net/corda/node/internal/Node.kt:66-250 Jetty+Jersey,
node/.../api/APIServer.kt:27, servlets/DataUploadServlet.kt,
servlets/AttachmentDownloadServlet.kt, and the node-administration
endpoints): a small threaded HTTP server exposing

  GET  /api/status                 -> {"name", "address", "flows_in_flight"}
  GET  /api/metrics                -> the SMM metric registry + per-flow
                                      completion timings
  GET  /api/metrics/history        -> bounded counters time-series, newest
                                      first (the JMX/Jolokia capability,
                                      Node.kt:313)
  GET  /metrics                    -> the always-on telemetry registry in
                                      Prometheus text exposition format
                                      (obs/telemetry.py via obs/export.py)
  GET  /api/trace                  -> this node's span buffer (obs/trace.py)
                                      for the driver-side trace collector
  GET  /api/info                   -> identity + advertised services
  POST /upload/attachment          -> attachment id (content-addressed)
  GET  /attachments/<hex id>       -> the blob

Reads touch only thread-safe snapshots (metrics dict copies, sqlite-backed
attachment storage), so serving from the HTTP thread is safe next to the
node's single-threaded flow pump.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..crypto.hashes import SecureHash


class NodeWebServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 0):
        self.node = node
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as e:
                    self.send_error(500, str(e))

            def do_POST(self):
                try:
                    outer._post(self)
                except Exception as e:
                    self.send_error(500, str(e))

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"web-{self.port}")
        self._thread.start()

    def _json(self, handler, payload, status: int = 200) -> None:
        body = json.dumps(payload).encode()
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _get(self, handler) -> None:
        node = self.node
        path = handler.path.rstrip("/")
        if path == "/api/status":
            self._json(handler, {
                "name": node.config.name,
                "address": str(node.messaging.my_address),
                "flows_in_flight": node.smm.in_flight_count,
            })
        elif path == "/api/metrics":
            # dict() is one atomic C-level copy; iterating the live dict
            # from this (webserver) thread while the node thread inserts
            # a new flow name would raise mid-comprehension.
            timings = dict(node.smm.flow_timings)
            self._json(handler, dict(node.smm.metrics)
                       | {"flow_timings": {k: dict(v)
                                           for k, v in timings.items()}})
        elif path == "/api/metrics/history":
            # Bounded time-series ring sampled by the run loop (the
            # JMX/Jolokia counters-over-time capability, Node.kt:313).
            # Newest-first: a dashboard polling "what just happened"
            # reads element 0, not element N, and a truncating client
            # keeps the recent half.
            self._json(handler, list(node.metrics_history)[::-1])
        elif path == "/metrics":
            # Prometheus text exposition (obs/export.py): the always-on
            # telemetry registry — every registered counter/histogram,
            # including series that have not fired yet.
            from ..obs.export import CONTENT_TYPE, render_prometheus

            body = render_prometheus().encode()
            handler.send_response(200)
            handler.send_header("Content-Type", CONTENT_TYPE)
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif path == "/api/trace":
            # This node's span buffer (obs/trace.py), JSON-safe; the
            # driver-side collector merges many of these into one Chrome
            # trace artifact. Empty shell when tracing is disarmed so
            # pollers need no special case.
            from ..obs import trace as _obs

            rec = _obs.ACTIVE
            self._json(handler, {
                "node": node.config.name,
                "armed": rec is not None,
                "spans": rec.snapshot() if rec is not None else [],
                "stats": rec.stats() if rec is not None else None,
            })
        elif path == "/api/info":
            self._json(handler, {
                "legal_identity": node.identity.name,
                "owning_key": node.identity.owning_key.to_base58_string(),
                "advertised_services": [
                    str(s.type) for s in node.info.advertised_services],
            })
        elif path.startswith("/attachments/"):
            try:
                att_id = SecureHash.parse(path.rsplit("/", 1)[1])
            except ValueError:
                handler.send_error(400, "bad attachment id")
                return
            att = node.services.storage_service.attachments \
                .open_attachment(att_id)
            if att is None:
                handler.send_error(404, "no such attachment")
                return
            blob = att.open()
            handler.send_response(200)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header("Content-Length", str(len(blob)))
            handler.end_headers()
            handler.wfile.write(blob)
        else:
            handler.send_error(404)

    def _post(self, handler) -> None:
        if handler.path.rstrip("/") != "/upload/attachment":
            handler.send_error(404)
            return
        length = int(handler.headers.get("Content-Length", 0))
        if length <= 0 or length > 64 * 1024 * 1024:
            handler.send_error(400, "bad Content-Length")
            return
        blob = handler.rfile.read(length)
        att_id = self.node.services.storage_service.attachments \
            .import_attachment(blob)
        self._json(handler, {"id": att_id.hex()})

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
