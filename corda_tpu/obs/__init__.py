"""Observability: cross-node tracing + per-stage latency attribution.

Split like testing/faults.py so the hot path stays cheap:

  trace.py    the per-node SpanRecorder ring buffer, the module-global ACTIVE
              arming switch, trace-context propagation helpers (thread-local
              current span + the request-id link map the Raft layer uses to
              correlate batch entries back to flow traces).
  collect.py  driver-side: merge many nodes' span snapshots into one Chrome
              trace-event / Perfetto JSON artifact and compute the per-stage
              p50/p99 breakdown (queue_wait / verify_wait / device_verify /
              raft_append / fsync / replication / reply).
  telemetry.py  the ALWAYS-ON half: process-global counter/histogram
              registry (armed at import, one attribute check when a test
              disarms it), the round profiler feed (poll / verify_wait /
              seal / replicate / apply / reply), and the flight recorder
              that auto-dumps a JSON artifact on SLO breach, overload
              spike, fsck failure, or crash.
  export.py   Prometheus text exposition (GET /metrics, sidecar OP_METRICS)
              + the cluster collector merging per-node registry snapshots.

Everything here is stdlib-only on purpose: the transports and the state
machine import `trace` at module load, so it must never pull in jax, the
serialization codec, or anything else with import-order opinions.
"""

from . import trace  # noqa: F401  (re-export: corda_tpu.obs.trace)
