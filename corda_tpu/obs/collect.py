"""Driver-side trace collection: merge per-node span snapshots into one
Chrome trace-event / Perfetto JSON artifact and compute the per-stage
latency breakdown the bench report embeds.

Input shape: each node contributes a *snapshot* — either the dict served by
``/api/trace`` (``{"node": ..., "spans": [...], "stats": {...}}``) or a bare
list of span dicts (SpanRecorder.snapshot()). Span dicts are the JSON-safe
form from trace.Span.as_dict(): hex ids, epoch-second timestamps.

Stage attribution
-----------------
Per-transaction stages come from two kinds of spans:

  * per-trace spans carry the transaction's own trace_id directly
    (``verify_wait``, the ``flow:*`` roots, ``raft_commit``, ``notary_process``);
  * batch spans (``queue_wait``, ``device_verify``, ``raft_append``,
    ``fsync``, ``replication``) carry ``attrs["member_traces"]`` — every
    transaction that rode the batch inherits the batch span's duration,
    which is the honest cost model: a tx in a 64-wide device batch *waited*
    the whole batch wall time.

``reply`` is derived, not measured: root_end − max(end of any other stage
span attributed to the trace), clipped at 0 — the tail between the last
instrumented stage finishing and the client flow completing (reply
serialization + transport + final client-side validation). Deriving it makes
the stage sum track end-to-end by construction instead of leaving an
unattributed gap.
"""

from __future__ import annotations

import json

# Attribution tables come from the span-name registry (obs/stages.py) so
# the breakdown can never drift from the names recording sites are allowed
# to use (the trace-stage-registry analyzer rule enforces the other side).
from .stages import BATCH_STAGES, DIRECT_STAGES, MARKER_SPANS, STAGES


def _spans_of(snapshot) -> list[dict]:
    if isinstance(snapshot, dict):
        return list(snapshot.get("spans") or ())
    return list(snapshot or ())


def _node_of(snapshot, default: str) -> str:
    if isinstance(snapshot, dict):
        return str(snapshot.get("node") or default)
    return default


# ---------------------------------------------------------------------------
# Chrome trace-event merge
# ---------------------------------------------------------------------------


def merge_chrome_trace(snapshots) -> dict:
    """Merge node snapshots into one Chrome trace-event JSON object
    (loadable in chrome://tracing and ui.perfetto.dev). Nodes become
    processes; span names become named threads within each process so
    overlapping batch spans get their own rows instead of nesting wrongly."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    for i, snapshot in enumerate(snapshots):
        node = _node_of(snapshot, f"node-{i}")
        for span in _spans_of(snapshot):
            span_node = str(span.get("node") or node)
            pid = pids.setdefault(span_node, len(pids) + 1)
            name = str(span.get("name") or "span")
            lane = name.split(":", 1)[0]
            tid = tids.setdefault((pid, lane), len(tids) + 1)
            t0 = float(span.get("t_start") or 0.0)
            t1 = float(span.get("t_end") or t0)
            args = dict(span.get("attrs") or {})
            args["trace_id"] = span.get("trace_id")
            if span.get("parent"):
                args["parent"] = span.get("parent")
            events.append({
                "ph": "X",
                "name": name,
                "cat": "corda_tpu",
                "ts": t0 * 1e6,          # chrome ts unit is microseconds
                "dur": max(0.0, (t1 - t0)) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": args,
            })
    meta: list[dict] = []
    for node, pid in pids.items():
        meta.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                     "args": {"name": node}})
    for (pid, lane), tid in tids.items():
        meta.append({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                     "args": {"name": lane}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, snapshots) -> dict:
    doc = merge_chrome_trace(snapshots)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


# ---------------------------------------------------------------------------
# Per-stage latency breakdown
# ---------------------------------------------------------------------------


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


def stage_breakdown(snapshots) -> dict:
    """p50/p99/mean milliseconds per stage across all complete traces.

    A trace is *complete* when it has a root flow span (parent None,
    name ``flow:*``) — the end-to-end anchor. Stage durations missing from a
    trace count as 0.0 so per-stage percentiles stay comparable and the
    stage sum tracks end-to-end."""
    spans: list[dict] = []
    for snapshot in snapshots:
        spans.extend(_spans_of(snapshot))

    # trace_id -> {"root": span | None, stage -> accumulated seconds,
    #              "last_end": latest attributed stage end}
    traces: dict[str, dict] = {}

    def slot(trace_id: str) -> dict:
        entry = traces.get(trace_id)
        if entry is None:
            entry = {"root": None, "stages": dict.fromkeys(STAGES, 0.0),
                     "last_end": 0.0}
            traces[trace_id] = entry
        return entry

    for span in spans:
        name = span.get("name") or ""
        t0 = float(span.get("t_start") or 0.0)
        t1 = float(span.get("t_end") or t0)
        dur = max(0.0, t1 - t0)
        if name in BATCH_STAGES:
            for member in (span.get("attrs") or {}).get("member_traces") or ():
                entry = slot(member)
                entry["stages"][name] += dur
                entry["last_end"] = max(entry["last_end"], t1)
            continue
        trace_id = span.get("trace_id")
        if not trace_id:
            continue
        if name in DIRECT_STAGES:
            entry = slot(trace_id)
            entry["stages"][name] += dur
            entry["last_end"] = max(entry["last_end"], t1)
        elif name.startswith("flow:") and not span.get("parent"):
            entry = slot(trace_id)
            root = entry["root"]
            if root is None or t0 < float(root.get("t_start") or 0.0):
                entry["root"] = span
        elif name in MARKER_SPANS:
            # Stitch markers, not breakdown stages — but their ends bound
            # the derived reply tail.
            entry = slot(trace_id)
            entry["last_end"] = max(entry["last_end"], t1)

    per_stage: dict[str, list[float]] = {s: [] for s in STAGES}
    end_to_end: list[float] = []
    complete = 0
    for entry in traces.values():
        root = entry["root"]
        if root is None:
            continue
        complete += 1
        root_t0 = float(root.get("t_start") or 0.0)
        root_t1 = float(root.get("t_end") or root_t0)
        end_to_end.append(max(0.0, root_t1 - root_t0))
        last_end = entry["last_end"]
        entry["stages"]["reply"] = (
            max(0.0, root_t1 - last_end) if last_end else 0.0)
        for stage in STAGES:
            per_stage[stage].append(entry["stages"][stage])

    def summarize(values: list[float]) -> dict:
        return {
            "p50_ms": _percentile(values, 0.50) * 1e3,
            "p99_ms": _percentile(values, 0.99) * 1e3,
            "mean_ms": (sum(values) / len(values) * 1e3) if values else 0.0,
        }

    stages_out = {stage: summarize(per_stage[stage]) for stage in STAGES}
    return {
        "traces": complete,
        "spans": len(spans),
        "stages": stages_out,
        "end_to_end": summarize(end_to_end),
        # How well the attribution covers the measured end-to-end: the sum
        # of per-stage means over the end-to-end mean (reply is derived, so
        # this approaches 1.0 as instrumentation coverage improves).
        # sidecar_wait/sidecar_verify DECOMPOSE device_verify (same wall
        # window), so they stay out of the sum — counting them would push
        # coverage past 1.0 whenever the sidecar is active. Same for
        # shard_reserve/shard_commit: the 2PC phases wrap the underlying
        # per-group raft stages, not extend them.
        "stage_sum_over_e2e": (
            (sum(v["mean_ms"] for k, v in stages_out.items()
                 if k not in ("sidecar_wait", "sidecar_verify",
                              "shard_reserve", "shard_commit"))
             / max(1e-9, summarize(end_to_end)["mean_ms"]))
            if end_to_end else 0.0),
    }
