"""The performance doctor: cross-layer bottleneck attribution, the bench
trajectory store, and regression gating.

The other obs modules *collect* signals — trace spans (trace.py/collect.py),
the always-on telemetry registry + round profiler (telemetry.py/export.py),
sidecar occupancy/pad stats and transport/admission counters riding
node_metrics. None of them says what to DO next: the north-star metrics
(verified sigs/sec/chip, p99 notarise latency) bottom out in a diagnosis
problem, and until this module the diagnosis lived in a human re-reading
bench JSON by hand. Three pieces close that loop:

  * **Attribution** — ``diagnose`` fuses whatever signals a run produced
    into one machine-readable ``PerfVerdict``: a roofline (committed tx/s
    and e2e sigs/s against the measured kernel-stream ceiling, with the
    gap factored per layer) plus a ranked ``bottlenecks`` list where every
    entry carries the specific counters/stages that implicate it and the
    next experiment from the rule table below. ``stamp_attribution`` is
    the loadtest-facing subset over member stamps — the evidence-ranked
    replacement for the Counter-majority ``busiest_stage`` heuristic
    (``first_bottleneck`` now means "top of the doctor's ranked list").
  * **Trajectory store** — ``normalize_record`` hoists the
    schema-versioned key metrics out of any known artifact shape into one
    flat record; ``append_trajectory`` grows the append-only
    ``artifacts/TRAJECTORY.jsonl`` one record per bench run, and the
    backfill tool (tools/perfdoctor.py) ingests the checked-in history so
    the trajectory starts with every capture we already have.
  * **Gate** — ``gate`` compares each kind's newest record against its
    predecessor under a tolerance policy (per-metric direction + percent
    band) and reports regressions; ``perfdoctor --gate`` exits nonzero on
    any, which is the CI hook every subsequent perf PR is judged with.

The rule table (cause -> suggested next experiment) is deliberately
small and literal — each rule names the knob that exists in this tree:

  low ``device_occupancy``      -> coalesce/bucket ladder (sidecar window,
                                   adaptive_coalesce, bucket growth from
                                   the observed batch_sigs_hist)
  dominant ``seal``/``replicate`` round phases
                                -> round-loop amortization (group commit
                                   density, pipelined replication window)
  high ``pad_fraction``         -> bucket-ladder growth (mesh pad waste)
  shed-dominated admission      -> admission recalibration
                                   (qos/calibrate.calibrate_admission)
  busiest round stage majority  -> the stage's own knob (fsync -> group
                                   commit; verify -> device routing; the
                                   "rounds" wall -> per-round overhead)

Everything here is honest about missing evidence: no signal, no verdict —
``first_bottleneck`` stays None rather than guessing, and attribution
abstains below ``MIN_ATTRIBUTION_ROUNDS`` exactly like the legacy
heuristic did (a 2-sample stage must never steer a sweep verdict).

Stdlib-only like the rest of ``obs`` — the CLI and the analyzer import
this module from bare processes.
"""

from __future__ import annotations

import json
import os
import re

__all__ = [
    "DEFAULT_POLICY",
    "MIN_ATTRIBUTION_ROUNDS",
    "PIPELINED_RULE_SPECS",
    "RULES",
    "RULE_SPECS",
    "SCHEMA_VERSION",
    "append_trajectory",
    "diagnose",
    "extract_signals",
    "gate",
    "load_trajectory",
    "normalize_record",
    "stamp_attribution",
    "suggest_spec",
    "trajectory_delta",
]

SCHEMA_VERSION = 1

# Mirrors loadtest.BUSIEST_STAGE_MIN_ROUNDS (which now imports THIS
# constant): below this many profiled rounds every round-derived signal
# (busiest stage, round_breakdown shares) abstains.
MIN_ATTRIBUTION_ROUNDS = 20

# Occupancy below this is a routing bottleneck worth a verdict entry;
# at/above it the device tier is essentially fed.
_OCCUPANCY_HEALTHY = 0.9
# Mesh pad waste below this is noise; above it the bucket ladder is
# fighting the batch mix.
_PAD_WORTH_FLAGGING = 0.2
# A round phase must claim at least this share of attributed wall time
# to earn its own verdict entry.
_PHASE_DOMINANT_SHARE = 0.3
# Sheds below this fraction of admission decisions are the controller
# doing its job; above it the rates are mis-calibrated for the load.
_SHED_DOMINATED = 0.2
# Federation routing-share skew (max share - min share across hosts)
# worth flagging: below this the router's depth balancing is doing its
# job; above it one host is soaking the traffic — a slow host attracting
# hedged re-dispatches, or a depth signal gone stale.
_HOST_IMBALANCE_SKEW = 0.25
# Elections won plus leader step-downs summed across members at/above
# this count in one run is churn: a healthy loadtest elects each group's
# leader ONCE and keeps it (sum ~= group count, and sharded runs top out
# at 4 groups), so 5 clears every clean shape while real disturbance —
# partition flap, starved heartbeats, a rejoiner spinning terms — blows
# straight past it.
_ELECTION_CHURN_MIN = 5
# A flow stage (trace stage_breakdown) must claim at least this share of
# end-to-end flow wall time to nominate a cause — vault_query at/above
# it means coin selection/queries are scanning, not indexing.
_FLOW_STAGE_DOMINANT_SHARE = 0.25

# ---------------------------------------------------------------------------
# The rule table: cause -> the suggested next experiment. Causes either
# name a signal ("device_occupancy", "pad_fraction", "admission") or a
# round stage/phase ("rounds", "seal", "replicate", "fsync", ...); a
# stage with no entry gets the generic suggestion so an unknown stage
# still produces an actionable verdict instead of a KeyError.
# ---------------------------------------------------------------------------

RULES: dict = {
    "device_occupancy": (
        "grow the coalesce/bucket ladder from the observed "
        "batch_sigs_hist: raise the sidecar coalesce window (or arm "
        "adaptive_coalesce) so micro-batches reach device_min_sigs and "
        "chase device_occupancy -> 1.0"),
    "pad_fraction": (
        "grow the bucket ladder (ops pick_bucket) so coalesced batches "
        "land nearer bucket capacity — mesh pad lanes are burning chip "
        "time on zeros"),
    "admission": (
        "recalibrate admission from measured saturation "
        "(qos/calibrate.calibrate_admission over a fresh slo_sweep) — "
        "shed-dominated admission means the static rates are wrong for "
        "this load"),
    "rounds": (
        "amortize per-round overhead in the SMM round loop (the server "
        "wall): batch service polls, multi-core members, and re-run on "
        "hardware where the verify plane is not sharing one core"),
    "seal": (
        "round-loop amortization: raise group-commit density (raft "
        "group_commit / larger rounds) — the seal phase dominates the "
        "round"),
    "replicate": (
        "round-loop amortization: widen the pipelined-replication window "
        "/ append chunking (raft pipeline_window, append_chunk) — the "
        "replicate phase dominates the round"),
    "poll": (
        "the round loop is spinning on polls: coalesce service polls or "
        "raise the accumulation window (the loop is overhead-bound, not "
        "work-bound)"),
    "verify_wait": (
        "the round blocks on verification: raise async_verify depth / "
        "sidecar coalescing so the device pipeline overlaps the round"),
    "apply": (
        "the apply phase dominates: profile the uniqueness-provider "
        "commit path (sqlite batch writes, PutAllBatch sizing)"),
    "reply": (
        "the reply phase dominates: profile reply serialization and "
        "transport flush coalescing (send_many, bridge flush)"),
    "fsync": (
        "batch fsyncs through group commit (one fsync per sealed round) "
        "or move the log to faster storage — fsync dominates the round"),
    "verify": (
        "the verify stage dominates: raise device routing (sidecar "
        "cross-process coalescing, bucket ladder) so signatures leave "
        "the host tier"),
    "election_churn": (
        "harden leadership against disturbance: arm [raft] prevote=true "
        "(the pre-vote canvass stops a partitioned rejoiner deposing a "
        "live leader; check-quorum makes a quorumless leader cede) and "
        "A/B the partition_chaos bench — max_term_inflation should "
        "collapse to ~0 with prevote on while the noprevote leg tracks "
        "the cut count"),
    "host_imbalance": (
        "rebalance weights / raise hedge threshold: the federation "
        "router is concentrating verify traffic on a subset of hosts — "
        "check occupancy_by_host for a slow host soaking hedged "
        "re-dispatches, then rebalance the routing (drain/readmit the "
        "slow host) or raise CORDA_TPU_FEDERATION_HEDGE_MS so hedges "
        "stop amplifying the skew"),
    "vault_scan": (
        "vault queries dominate flow wall time — coin selection is "
        "scanning a vault that has outgrown the in-memory engine: arm "
        "[vault] indexed=true (sqlite IndexedVaultService — O(log n) "
        "covering-index queries, amount-ordered soft-locked coin "
        "selection, watermark incremental boot) and re-run; the "
        "vault_scaling bench section proves the crossover"),
}

_GENERIC_SUGGESTION = (
    "profile stage {cause!r} with --trace (obs/collect stage_breakdown) — "
    "no specific rule for it yet")

# Pipelined-commit-plane overlay (round 18): once the round loop overlaps
# (raft pipeline=true — mid-round seals, detached apply executor), the
# serial-loop suggestions above are ALREADY DONE. A run whose members
# stamp pipeline=true gets the NEXT experiment for these causes instead
# of re-suggesting round-loop amortization it has already applied.
PIPELINED_RULES: dict = {
    "rounds": (
        "the round loop is already pipelined — sweep the executor levers "
        "instead: [raft] apply_queue_depth (commit-queue bound) and the "
        "native commit_many columnar batch (CORDA_TPU_NO_NATIVE unset), "
        "then re-attribute; residual 'rounds' wall is scheduler/transport, "
        "not seal/apply serialization"),
    "seal": (
        "seals already overlap replication (mid-round seals) — tune "
        "append_chunk (mid-round seal trigger) and group-commit density "
        "rather than the round cadence"),
    "apply": (
        "apply is already detached onto the executor — sweep [raft] "
        "apply_queue_depth and profile the columnar commit_many path "
        "(set-wide conflict SELECTs, executemany inserts, native CRC "
        "batching)"),
}


def _suggest(cause: str, pipelined: bool = False) -> str:
    if pipelined:
        hit = PIPELINED_RULES.get(cause)
        if hit:
            return hit
    return RULES.get(cause) or _GENERIC_SUGGESTION.format(cause=cause)


# ---------------------------------------------------------------------------
# Structured rule specs (round 21): the machine-readable twin of each
# prose rule above — the experiment id, the autotune-registry knob names
# the experiment sweeps, and the harness that measures it. The autotune
# controller consumes THESE (never the prose, which stays a human
# rendering pinned byte-identical by test_perf_doctor.py); every knob
# name here must resolve in corda_tpu.autotune.space.KNOBS, which
# validates the cross-reference so the two tables cannot drift apart.
# An empty knobs tuple means the experiment is not a parameter sweep
# (profiling, A/B flag flips, operational rebalancing).
# ---------------------------------------------------------------------------

RULE_SPECS: dict = {
    "device_occupancy": {
        "experiment_id": "grow_coalesce_ladder",
        "knobs": ("sidecar.coalesce_us", "batch.device_min_sigs"),
        "harness": "slo_sweep"},
    "pad_fraction": {
        "experiment_id": "grow_bucket_ladder",
        "knobs": ("batch.max_sigs", "batch.device_min_sigs"),
        "harness": "slo_sweep"},
    "admission": {
        "experiment_id": "calibrate_admission",
        "knobs": ("qos.interactive_rate", "qos.bulk_rate",
                  "qos.queue_watermark"),
        "harness": "slo_sweep"},
    "rounds": {
        "experiment_id": "amortize_round_overhead",
        # notary_shards.count is the prose remedy's bigger hammer, but
        # it only applies to raft-* notaries — the ingest harness runs
        # a simple notary, so the sweepable levers are the accumulation
        # window and the apply-queue depth.
        "knobs": ("batch.coalesce_ms", "raft.apply_queue_depth"),
        "harness": "ingest_sweep"},
    "seal": {
        "experiment_id": "raise_group_commit_density",
        "knobs": ("batch.coalesce_ms", "raft.append_chunk"),
        "harness": "ingest_sweep"},
    "replicate": {
        "experiment_id": "widen_replication_window",
        "knobs": ("raft.pipeline_window", "raft.append_chunk"),
        "harness": "ingest_sweep"},
    "poll": {
        "experiment_id": "raise_accumulation_window",
        "knobs": ("batch.coalesce_ms",),
        "harness": "ingest_sweep"},
    "verify_wait": {
        "experiment_id": "deepen_async_verify",
        "knobs": ("batch.async_depth", "sidecar.coalesce_us"),
        "harness": "ingest_sweep"},
    "apply": {
        "experiment_id": "profile_apply_path",
        "knobs": ("raft.apply_queue_depth",),
        "harness": "ingest_sweep"},
    "reply": {
        "experiment_id": "profile_reply_path",
        "knobs": (),
        "harness": "trace"},
    "fsync": {
        "experiment_id": "batch_fsyncs",
        "knobs": ("batch.coalesce_ms",),
        "harness": "ingest_sweep"},
    "verify": {
        "experiment_id": "raise_device_routing",
        "knobs": ("sidecar.coalesce_us", "batch.device_min_sigs"),
        "harness": "slo_sweep"},
    "election_churn": {
        "experiment_id": "arm_prevote_ab",
        "knobs": (),
        "harness": "partition_chaos"},
    "host_imbalance": {
        "experiment_id": "rebalance_federation",
        "knobs": (),
        "harness": "federation"},
    "vault_scan": {
        "experiment_id": "arm_indexed_vault",
        "knobs": ("vault.indexed",),
        "harness": "ingest_sweep"},
}

# Pipelined overlay, mirroring PIPELINED_RULES: once the commit plane
# overlaps, the same cause implicates the executor levers instead.
PIPELINED_RULE_SPECS: dict = {
    "rounds": {
        "experiment_id": "sweep_executor_levers",
        "knobs": ("raft.apply_queue_depth",),
        "harness": "ingest_sweep"},
    "seal": {
        "experiment_id": "tune_midround_seal_trigger",
        "knobs": ("raft.append_chunk",),
        "harness": "ingest_sweep"},
    "apply": {
        "experiment_id": "sweep_apply_queue_depth",
        "knobs": ("raft.apply_queue_depth",),
        "harness": "ingest_sweep"},
}

_GENERIC_SPEC = {"experiment_id": "profile_stage", "knobs": (),
                 "harness": "trace"}


def suggest_spec(cause: str, pipelined: bool = False) -> dict:
    """The structured spec for a cause — same lookup/fallback order as
    ``_suggest`` so the machine-readable field on a bottleneck entry
    always describes the same experiment as its prose twin."""
    if pipelined:
        hit = PIPELINED_RULE_SPECS.get(cause)
        if hit:
            return dict(hit)
    return dict(RULE_SPECS.get(cause) or _GENERIC_SPEC)


def _finite(value) -> float | None:
    """A float if ``value`` is a real number (bools excluded), else None."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


# ---------------------------------------------------------------------------
# Candidate construction: every rule emits (cause, score, evidence).
# Scores share one scale so the ranking is meaningful across rules:
# wall-time evidence (busiest stage / dominant phase / shed fraction)
# scores 0.5 + 0.5*fraction — direct measurement outranks ratio
# inference — while ratio evidence (occupancy, pad) scores its own
# deficit in [0, 1].
# ---------------------------------------------------------------------------


def _occupancy_of(stamp: dict) -> float | None:
    occ = _finite(stamp.get("device_occupancy"))
    if occ is not None:
        return occ
    dev = stamp.get("device_batches")
    host = stamp.get("host_batches")
    if isinstance(dev, int) and isinstance(host, int) and (dev + host):
        return dev / (dev + host)
    return None


def _merge_federation(feds: list) -> dict | None:
    """Fold per-member federation stamps (FederatedVerifier
    ``federation_stats`` shape, riding each member's sidecar stamp) into
    one routing view: per-host dispatch counts sum across members,
    shares re-derive from the summed total, and each host's occupancy
    comes from its own server snapshot. None below two hosts or zero
    dispatches — no skew verdict without a real routing split."""
    by_host: dict = {}
    occ_by_host: dict = {}
    hedges = degraded = 0
    for f in feds:
        if not isinstance(f, dict):
            continue
        hedges += int(_finite(f.get("hedges")) or 0)
        degraded += int(_finite(f.get("host_degraded")) or 0)
        for addr, ch in (f.get("hosts") or {}).items():
            if not isinstance(ch, dict):
                continue
            by_host[addr] = (by_host.get(addr, 0)
                             + int(_finite(ch.get("dispatches")) or 0))
            server = ch.get("server")
            if isinstance(server, dict) and addr not in occ_by_host:
                occ = _occupancy_of(server)
                if occ is not None:
                    occ_by_host[addr] = round(occ, 3)
    total = sum(by_host.values())
    if not total or len(by_host) < 2:
        return None
    return {
        "routing_share_by_host": {a: round(n / total, 4)
                                  for a, n in sorted(by_host.items())},
        "occupancy_by_host": occ_by_host or None,
        "dispatches": total,
        "hedges": hedges,
        "host_degraded": degraded,
    }


def _merge_breakdowns(breakdowns: list) -> dict | None:
    """Fold per-member ``round_breakdown`` blocks (telemetry
    format_breakdown shape) into one: totals sum, shares re-derive from
    the summed wall. Members below MIN_ATTRIBUTION_ROUNDS are dropped —
    the abstention contract survives the merge."""
    rounds = 0
    wall = 0.0
    totals: dict = {}
    for b in breakdowns:
        if not isinstance(b, dict):
            continue
        if (b.get("rounds") or 0) < MIN_ATTRIBUTION_ROUNDS:
            continue
        rounds += b.get("rounds") or 0
        wall += _finite(b.get("wall_s")) or 0.0
        for phase, entry in (b.get("phases") or {}).items():
            totals[phase] = totals.get(phase, 0.0) + (
                _finite((entry or {}).get("total_s")) or 0.0)
    if rounds < MIN_ATTRIBUTION_ROUNDS or not wall:
        return None
    return {
        "rounds": rounds,
        "wall_s": round(wall, 6),
        "phases": {p: {"total_s": round(v, 6),
                       "share": round(v / wall, 4)}
                   for p, v in totals.items()},
    }


def _pipeline_enabled(stamps) -> bool:
    """True when some member's raft stamp says the pipelined commit plane
    is on — flips the stage rules to their PIPELINED_RULES overlay."""
    for s in stamps:
        raft = s.get("raft") if isinstance(s, dict) else None
        if isinstance(raft, dict) and raft.get("pipeline"):
            return True
    return False


def _merge_raft_health(stamps) -> dict | None:
    """Fold each member's nested raft stamp into one leadership-health
    view: elections won, step-downs, term spread and the prevote flag.
    None when no member carried a raft stamp (host-only sections)."""
    rafts = [s.get("raft") for s in stamps
             if isinstance(s, dict) and isinstance(s.get("raft"), dict)]
    if not rafts:
        return None

    def total(key):
        return sum(int(_finite(r.get(key)) or 0) for r in rafts)

    return {
        "members": len(rafts),
        "elections_won": total("elections_won"),
        "leader_stepdowns": total("leader_stepdowns"),
        "checkquorum_stepdowns": total("checkquorum_stepdowns"),
        "prevote_rejections": total("prevote_rejections"),
        "max_term": max(int(_finite(r.get("term")) or 0) for r in rafts),
        "max_commit_index": max(int(_finite(r.get("commit_index")) or 0)
                                for r in rafts),
        "prevote": any(bool(r.get("prevote")) for r in rafts),
    }


def _candidates(signals: dict) -> list[dict]:
    out: list[dict] = []
    pipelined = bool(signals.get("pipeline_enabled"))

    # Rule: low device occupancy -> coalesce/bucket ladder. Evidence is
    # the per-member routing split (the r05 regression shape: the device
    # answered but micro-batches never reached device_min_sigs).
    occs = signals.get("device_occupancy_by_member") or {}
    if occs:
        mean_occ = sum(occs.values()) / len(occs)
        if mean_occ < _OCCUPANCY_HEALTHY:
            evidence = {"device_occupancy_by_member":
                        {k: round(v, 3) for k, v in occs.items()},
                        "mean_occupancy": round(mean_occ, 3)}
            hist = signals.get("batch_sigs_hist")
            if hist:
                evidence["batch_sigs_hist"] = hist
            out.append({"cause": "device_occupancy",
                        "score": round(1.0 - mean_occ, 4),
                        "evidence": evidence,
                        "next_experiment": _suggest("device_occupancy"),
                        "experiment": suggest_spec("device_occupancy")})

    # Rule: busiest round stage majority across members (the legacy
    # heuristic, kept as one evidence stream among several — each value
    # here already honoured the <MIN_ATTRIBUTION_ROUNDS abstention at
    # stamp time).
    stages = [s for s in (signals.get("busiest_stages") or ()) if s]
    if stages:
        counts: dict = {}
        for s in stages:
            counts[s] = counts.get(s, 0) + 1
        # Deterministic: highest count, then alphabetical.
        top = min(counts, key=lambda s: (-counts[s], s))
        frac = counts[top] / len(stages)
        out.append({"cause": top,
                    "score": round(0.5 + 0.5 * frac, 4),
                    "evidence": {"busiest_stage_by_member_count": counts,
                                 "members_reporting": len(stages)},
                    "next_experiment": _suggest(top, pipelined),
                    "experiment": suggest_spec(top, pipelined)})

    # Rule: dominant round phase from the merged telemetry profiler
    # breakdown — the block that decomposes a "rounds" wall into
    # poll/verify_wait/seal/replicate/apply/reply.
    breakdown = signals.get("round_breakdown")
    if breakdown:
        phases = {p: (e or {}).get("share") or 0.0
                  for p, e in (breakdown.get("phases") or {}).items()}
        if phases:
            top = min(phases, key=lambda p: (-phases[p], p))
            if phases[top] >= _PHASE_DOMINANT_SHARE:
                out.append({
                    "cause": top,
                    "score": round(0.5 + 0.5 * phases[top], 4),
                    "evidence": {"round_breakdown_shares":
                                 {p: round(v, 4)
                                  for p, v in sorted(phases.items())},
                                 "rounds": breakdown.get("rounds")},
                    "next_experiment": _suggest(top, pipelined),
                    "experiment": suggest_spec(top, pipelined)})

    # Rule: high mesh pad fraction -> bucket growth.
    pad = _finite(signals.get("pad_fraction"))
    if pad is not None and pad > _PAD_WORTH_FLAGGING:
        out.append({"cause": "pad_fraction",
                    "score": round(pad, 4),
                    "evidence": {"pad_fraction": round(pad, 4),
                                 "batch_sigs_hist":
                                 signals.get("batch_sigs_hist")},
                    "next_experiment": _suggest("pad_fraction"),
                    "experiment": suggest_spec("pad_fraction")})

    # Rule: shed-dominated admission -> recalibration.
    adm = signals.get("admission") or {}
    admitted = _finite(adm.get("admitted")) or 0.0
    shed = _finite(adm.get("shed")) or 0.0
    if shed and (admitted + shed):
        frac = shed / (admitted + shed)
        if frac >= _SHED_DOMINATED:
            out.append({"cause": "admission",
                        "score": round(0.5 + 0.5 * frac, 4),
                        "evidence": {"admitted": admitted, "shed": shed,
                                     "shed_fraction": round(frac, 4)},
                        "next_experiment": _suggest("admission"),
                        "experiment": suggest_spec("admission")})

    # Rule: federation routing-share skew -> host rebalance. Evidence
    # pairs each host's share of routed batches with that host's own
    # server occupancy (a slow host both under-serves its share and
    # attracts the hedged re-dispatches that deepen the skew).
    fed = signals.get("federation") or {}
    shares = fed.get("routing_share_by_host") or {}
    if len(shares) >= 2:
        skew = max(shares.values()) - min(shares.values())
        if skew >= _HOST_IMBALANCE_SKEW:
            out.append({"cause": "host_imbalance",
                        "score": round(0.5 + 0.5 * min(1.0, skew), 4),
                        "evidence": {
                            "routing_share_by_host": {
                                k: round(v, 4)
                                for k, v in sorted(shares.items())},
                            "occupancy_by_host":
                                fed.get("occupancy_by_host"),
                            "dispatches": fed.get("dispatches"),
                            "hedges": fed.get("hedges")},
                        "next_experiment": _suggest("host_imbalance"),
                        "experiment": suggest_spec("host_imbalance")})

    # Rule: election churn -> prevote/check-quorum hardening. A healthy
    # run elects each group's leader once and keeps it; repeated
    # elections or step-downs mean leadership is being disturbed
    # (partition flap, starved heartbeats, a rejoiner forcing terms up).
    # Abstains below MIN_ATTRIBUTION_ROUNDS committed entries — a
    # near-idle cluster's bootstrap elections are not churn evidence.
    raft = signals.get("raft_health") or {}
    churn = ((raft.get("elections_won") or 0)
             + (raft.get("leader_stepdowns") or 0))
    if raft and churn >= _ELECTION_CHURN_MIN \
            and (raft.get("max_commit_index") or 0) \
            >= MIN_ATTRIBUTION_ROUNDS:
        out.append({
            "cause": "election_churn",
            "score": round(0.5 + 0.5 * min(1.0, churn / 10.0), 4),
            "evidence": {k: raft.get(k) for k in (
                "elections_won", "leader_stepdowns",
                "checkquorum_stepdowns", "prevote_rejections",
                "max_term", "members", "prevote")},
            "next_experiment": _suggest("election_churn"),
            "experiment": suggest_spec("election_churn")})

    # Rule: vault queries dominating flow wall time -> arm the indexed
    # vault engine. The shares come from the flagship trace breakdown
    # (stage mean over end-to-end mean); extraction already abstained
    # below MIN_ATTRIBUTION_ROUNDS traces, so a share here is evidence.
    shares = signals.get("flow_stage_shares") or {}
    vshare = _finite(shares.get("vault_query"))
    if vshare is not None and vshare >= _FLOW_STAGE_DOMINANT_SHARE:
        out.append({
            "cause": "vault_scan",
            "score": round(0.5 + 0.5 * min(1.0, vshare), 4),
            "evidence": {"flow_stage_shares":
                         {k: round(v, 4)
                          for k, v in sorted(shares.items())}},
            "next_experiment": _suggest("vault_scan"),
            "experiment": suggest_spec("vault_scan")})

    # Deterministic ranking: score desc, then cause name — two equal
    # scores can't flap the verdict between runs.
    out.sort(key=lambda c: (-c["score"], c["cause"]))
    # One entry per cause (busiest-stage and breakdown evidence can both
    # nominate the same stage; keep the higher-scored entry).
    seen: set = set()
    deduped = []
    for c in out:
        if c["cause"] not in seen:
            seen.add(c["cause"])
            deduped.append(c)
    return deduped


# ---------------------------------------------------------------------------
# The loadtest-facing attribution: member stamps in, ranked verdict out.
# ---------------------------------------------------------------------------


def stamp_attribution(node_stamps: dict | None) -> dict:
    """Evidence-ranked bottleneck attribution over loadtest member stamps
    (``_member_stamp`` dicts). This is the source of ``first_bottleneck``
    in sweep results — the Counter-majority ``busiest_stage`` heuristic
    survives inside it as ONE evidence stream (already min-rounds
    guarded at stamp time), joined by the round profiler's phase shares,
    device routing occupancy and admission counters. No evidence means
    an honest ``first_bottleneck: None``, never a guess."""
    stamps = [s for s in (node_stamps or {}).values()
              if isinstance(s, dict)]
    occs = {}
    breakdowns = []
    admitted = shed = 0.0
    for i, s in enumerate(stamps):
        occ = _occupancy_of(s)
        if occ is not None:
            occs[s.get("verifier") or f"member-{i}"] = occ
        if s.get("round_breakdown"):
            breakdowns.append(s["round_breakdown"])
        adm = s.get("admission") or {}
        admitted += _finite(adm.get("admitted_interactive")) or 0.0
        admitted += _finite(adm.get("admitted_bulk")) or 0.0
        admitted += _finite(adm.get("admitted")) or 0.0
        shed += _finite(adm.get("shed_interactive")) or 0.0
        shed += _finite(adm.get("shed_bulk")) or 0.0
        shed += _finite(adm.get("shed")) or 0.0
    signals = {
        "device_occupancy_by_member": occs,
        "busiest_stages": [s.get("busiest_stage") for s in stamps],
        "round_breakdown": _merge_breakdowns(breakdowns),
        "admission": {"admitted": admitted, "shed": shed},
        "pipeline_enabled": _pipeline_enabled(stamps),
        "raft_health": _merge_raft_health(stamps),
        "federation": _merge_federation(
            [(s.get("sidecar") or {}).get("federation") for s in stamps]),
    }
    bottlenecks = _candidates(signals)
    return {
        "schema": SCHEMA_VERSION,
        "first_bottleneck": (bottlenecks[0]["cause"] if bottlenecks
                             else None),
        "bottlenecks": bottlenecks,
        "members": len(stamps),
    }


# ---------------------------------------------------------------------------
# Signal extraction from artifact shapes.
# ---------------------------------------------------------------------------


def _classify(artifact: dict) -> str:
    """Which known artifact shape this is — the trajectory's ``kind``
    (gate comparisons never cross kinds; an ingest capture regressing
    against a multichip capture would be noise)."""
    if not isinstance(artifact, dict):
        return "unknown"
    if "autotune_schema" in artifact:
        return "autotune"
    if artifact.get("metric") == "verified_sigs_per_sec" \
            or "baseline_configs" in artifact:
        return "bench_report"
    if "raft_validating_3node_sidecar" in artifact:
        return "flagship_capture"
    if "multichip_scaling" in artifact:
        return "multichip_capture"
    if "peak_achieved_tx_s" in artifact or (
            "rates" in artifact and "workers" in artifact):
        return "ingest_sweep"
    return "unknown"


def _member_stamps_of(section: dict | None) -> dict:
    """node_stamps with the historical scalar pollution filtered out
    (pre-PR1 artifacts carried ``device_warm_wait_s`` as a sibling of
    the member dicts)."""
    return {k: v for k, v in ((section or {}).get("node_stamps")
                              or {}).items()
            if isinstance(v, dict)}


def _flagship_of(artifact: dict) -> dict | None:
    configs = artifact.get("baseline_configs") or {}
    for key in ("raft_validating_3node", "raft_notary_3node"):
        section = configs.get(key)
        if isinstance(section, dict) and "error" not in section:
            return section
    section = artifact.get("raft_validating_3node_sidecar")
    return section if isinstance(section, dict) else None


def _peak_ingest_row(section: dict | None) -> dict | None:
    rows = [r for r in ((section or {}).get("rates") or {}).values()
            if isinstance(r, dict) and "error" not in r]
    if not rows:
        return None
    return max(rows, key=lambda r: _finite(r.get("achieved_tx_s")) or 0.0)


def extract_signals(artifact: dict) -> dict:
    """Pull the doctor's signal bundle out of any known artifact shape —
    a full bench report, a flagship/multichip capture, or an ingest
    sweep. Every key is optional; downstream rules skip what is absent."""
    kind = _classify(artifact)
    signals: dict = {"kind": kind}

    flagship = _flagship_of(artifact)
    stamps = _member_stamps_of(flagship)

    # The measured ceiling: the kernel stream is the device's proven
    # sustained rate; kernel bucket peaks back it up, the host oracle is
    # the honest floor for host-only runs.
    for key, source in (("e2e_stream_sigs_per_sec", "kernel_stream"),
                        ("cpu_oracle_sigs_per_sec", "cpu_oracle")):
        ceiling = _finite(artifact.get(key))
        if ceiling:
            signals["ceiling_sigs_per_sec"] = ceiling
            signals["ceiling_source"] = source
            break
    kernel = artifact.get("kernel_sigs_per_sec") or {}
    peaks = [v for v in (_finite(x) for x in kernel.values()) if v]
    if peaks:
        signals["kernel_peak_sigs_per_sec"] = max(peaks)
        signals.setdefault("ceiling_sigs_per_sec", max(peaks))
        signals.setdefault("ceiling_source", "kernel_buckets")

    if flagship:
        signals["e2e_sigs_per_sec"] = _finite(
            flagship.get("loadtest_sigs_per_sec"))
        signals["committed_tx_per_sec"] = _finite(
            flagship.get("tx_per_sec"))
        signals["p99_ms"] = _finite(flagship.get("p99_ms"))
        side = flagship.get("sidecar")
        if isinstance(side, dict):
            signals["batch_sigs_hist"] = side.get("batch_sigs_hist")
            signals["pad_fraction"] = _finite(side.get("pad_fraction"))
        occ = _finite(flagship.get("device_occupancy"))
        if occ is not None and not stamps:
            signals["device_occupancy_by_member"] = {"flagship": occ}

    # Per-stage share of flow wall time from the flagship trace
    # breakdown: stage mean over end-to-end mean. Abstains below
    # MIN_ATTRIBUTION_ROUNDS traces — a handful of flows is noise, not
    # an attribution.
    breakdown = ((artifact.get("baseline_configs") or {})
                 .get("raft_open_loop_latency") or {}).get("stage_breakdown")
    if isinstance(breakdown, dict):
        e2e_mean = _finite((breakdown.get("end_to_end") or {})
                           .get("mean_ms"))
        traces = _finite(breakdown.get("traces")) or 0
        if e2e_mean and traces >= MIN_ATTRIBUTION_ROUNDS:
            shares = {}
            for stage, entry in (breakdown.get("stages") or {}).items():
                mean = _finite((entry or {}).get("mean_ms"))
                if mean is not None:
                    shares[stage] = min(1.0, mean / e2e_mean)
            if shares:
                signals["flow_stage_shares"] = shares

    if kind == "ingest_sweep":
        stamps = _member_stamps_of(artifact)
        peak = _peak_ingest_row(artifact)
        if peak:
            signals["committed_tx_per_sec"] = _finite(
                peak.get("achieved_tx_s"))
            signals["offered_tx_s"] = _finite(peak.get("offered_tx_s"))
            signals["p99_ms"] = _finite(peak.get("p99_ms"))

    if kind == "multichip_capture":
        section = artifact.get("multichip_scaling") or {}
        widths = [w for w in (section.get("devices") or {}).values()
                  if isinstance(w, dict)]
        rates = [v for v in (_finite(w.get("sigs_per_sec"))
                             for w in widths) if v]
        if rates:
            signals["e2e_sigs_per_sec"] = max(rates)
        pads = [v for v in (_finite(w.get("pad_fraction"))
                            for w in widths) if v is not None]
        if pads:
            signals["pad_fraction"] = max(pads)

    if stamps:
        occs = {}
        breakdowns = []
        for name, s in stamps.items():
            occ = _occupancy_of(s)
            if occ is not None:
                occs[name] = occ
            if s.get("round_breakdown"):
                breakdowns.append(s["round_breakdown"])
        if occs:
            signals["device_occupancy_by_member"] = occs
        signals["busiest_stages"] = [s.get("busiest_stage")
                                     for s in stamps.values()]
        merged = _merge_breakdowns(breakdowns)
        if merged:
            signals["round_breakdown"] = merged
        signals["pipeline_enabled"] = _pipeline_enabled(stamps.values())
        raft = _merge_raft_health(stamps.values())
        if raft:
            signals["raft_health"] = raft
    # Fall back to the roundtrip probe's routing split when the flagship
    # carried no stamps (the r05_a shape): it exercised the same verify
    # plane, so its device/host split is honest occupancy evidence.
    if not signals.get("device_occupancy_by_member"):
        rt = artifact.get("notary_roundtrip")
        if isinstance(rt, dict):
            occ = _occupancy_of(rt)
            if occ is not None:
                signals["device_occupancy_by_member"] = {
                    "notary_roundtrip": occ}
    return signals


# ---------------------------------------------------------------------------
# The verdict.
# ---------------------------------------------------------------------------


def _roofline(signals: dict) -> dict:
    """Committed tx/s and e2e sigs/s against the measured kernel-stream
    ceiling. ``gap_factor`` is ceiling/e2e (how far the framework path
    sits below what the chip proved it can stream); the per-layer split
    attributes the part the routing evidence explains — occupancy < 1
    multiplies the gap by 1/occupancy on its own — and leaves the rest
    as ``residual_factor`` rather than inventing precision."""
    ceiling = _finite(signals.get("ceiling_sigs_per_sec"))
    e2e = _finite(signals.get("e2e_sigs_per_sec"))
    out = {
        "ceiling_sigs_per_sec": ceiling,
        "ceiling_source": signals.get("ceiling_source"),
        "e2e_sigs_per_sec": e2e,
        "committed_tx_per_sec": _finite(
            signals.get("committed_tx_per_sec")),
        "p99_ms": _finite(signals.get("p99_ms")),
        "gap_factor": None,
        "layers": None,
    }
    if not ceiling or not e2e:
        return out
    gap = ceiling / e2e
    out["gap_factor"] = round(gap, 2)
    occs = signals.get("device_occupancy_by_member") or {}
    layers: dict = {}
    explained = 1.0
    if occs:
        mean_occ = sum(occs.values()) / len(occs)
        if 0.0 < mean_occ < 1.0:
            factor = min(1.0 / mean_occ, gap)
            layers["verify_routing_factor"] = round(factor, 2)
            explained *= factor
        elif mean_occ == 0.0:
            # Everything host-routed: the whole gap is the routing layer
            # as far as this evidence can tell.
            layers["verify_routing_factor"] = round(gap, 2)
            explained = gap
    layers["residual_factor"] = round(max(1.0, gap / explained), 2)
    out["layers"] = layers
    return out


def diagnose(signals: dict) -> dict:
    """Signals in, one machine-readable ``PerfVerdict`` out: the
    roofline, the evidence-ranked bottleneck list, and the headline
    ``first_bottleneck``. Pure and JSON-safe — callers stamp it into
    bench sections and trajectory records verbatim."""
    bottlenecks = _candidates(signals)
    return {
        "schema": SCHEMA_VERSION,
        "kind": signals.get("kind", "unknown"),
        "roofline": _roofline(signals),
        "bottlenecks": bottlenecks,
        "first_bottleneck": (bottlenecks[0]["cause"] if bottlenecks
                             else None),
    }


# ---------------------------------------------------------------------------
# Trajectory records.
# ---------------------------------------------------------------------------

_ROUND_RE = re.compile(r"r(\d+)")


def _round_of(artifact: dict, source: str) -> int | None:
    if isinstance(artifact.get("round"), int):
        return artifact["round"]
    m = _ROUND_RE.search(os.path.basename(source or ""))
    return int(m.group(1)) if m else None


def _hoist_metrics(artifact: dict, kind: str) -> dict:
    """The flat, numeric/bool key-metric dict the gate compares. Every
    key is hoisted only when its section exists — schema growth is
    additive, and the gate only compares keys present on BOTH sides."""
    m: dict = {}

    def put(key, value):
        v = _finite(value) if not isinstance(value, bool) else value
        if v is not None:
            m[key] = v

    if kind == "bench_report":
        put("value_sigs_per_sec", artifact.get("value"))
        put("vs_baseline", artifact.get("vs_baseline"))
        put("e2e_stream_sigs_per_sec",
            artifact.get("e2e_stream_sigs_per_sec"))
        kernel = artifact.get("kernel_sigs_per_sec") or {}
        peaks = [v for v in (_finite(x) for x in kernel.values()) if v]
        if peaks:
            put("kernel_peak_sigs_per_sec", max(peaks))
        put("cpu_oracle_sigs_per_sec",
            artifact.get("cpu_oracle_sigs_per_sec"))
        rt = artifact.get("notary_roundtrip")
        if isinstance(rt, dict):
            put("roundtrip_tx_per_sec", rt.get("tx_per_sec"))
        configs = artifact.get("baseline_configs") or {}
        flagship = _flagship_of(artifact)
        if flagship:
            put("flagship_tx_per_sec", flagship.get("tx_per_sec"))
            put("flagship_sigs_per_sec",
                flagship.get("loadtest_sigs_per_sec"))
            put("flagship_p99_ms", flagship.get("p99_ms"))
            occ = _finite(flagship.get("device_occupancy"))
            if occ is None:
                occs = [o for o in
                        (_occupancy_of(s) for s in
                         _member_stamps_of(flagship).values())
                        if o is not None]
                occ = (sum(occs) / len(occs)) if occs else None
            put("flagship_device_occupancy", occ)
        ingest = configs.get("ingest_sweep")
        if isinstance(ingest, dict) and "error" not in ingest:
            put("ingest_peak_achieved_tx_s",
                ingest.get("peak_achieved_tx_s"))
            delta = ingest.get("pipeline_delta")
            if isinstance(delta, dict):
                put("ingest_pipeline_speedup",
                    delta.get("pipeline_speedup"))
        slo = configs.get("slo_sweep")
        if isinstance(slo, dict):
            verdict = slo.get("verdict") or {}
            if isinstance(verdict.get("slo_met"), bool):
                m["slo_met"] = verdict["slo_met"]
        multi = configs.get("multichip_scaling")
        if isinstance(multi, dict):
            put("multichip_scaling_1_to_max",
                multi.get("scaling_1_to_max"))
        vault = configs.get("vault_scaling")
        if isinstance(vault, dict) and "error" not in vault:
            put("vault_coin_selection_p99_ratio",
                vault.get("vault_coin_selection_p99_ratio"))
            put("vault_boot_speedup", vault.get("vault_boot_speedup"))
            put("vault_query_p99_ms", vault.get("vault_query_p99_ms"))
            if isinstance(vault.get("vault_parity_ok"), bool):
                m["vault_parity_ok"] = vault["vault_parity_ok"]
        chaos = artifact.get("chaos")
        if isinstance(chaos, dict):
            put("leader_kill_recovery_s",
                chaos.get("leader_kill_recovery_s"))
        part = artifact.get("partition_chaos")
        if isinstance(part, dict):
            put("recovery_s", part.get("recovery_s"))
            put("max_term_inflation", part.get("max_term_inflation"))
            put("partition_minority_commits",
                part.get("minority_commits"))
            put("partition_lost_acks", part.get("lost_acks"))
            if isinstance(part.get("history_linearizable"), bool):
                m["history_linearizable"] = part["history_linearizable"]
    elif kind == "flagship_capture":
        flagship = artifact.get("raft_validating_3node_sidecar") or {}
        put("flagship_tx_per_sec", flagship.get("tx_per_sec"))
        put("flagship_sigs_per_sec",
            flagship.get("loadtest_sigs_per_sec"))
        put("flagship_p99_ms", flagship.get("p99_ms"))
        put("flagship_device_occupancy",
            flagship.get("device_occupancy"))
    elif kind == "ingest_sweep":
        put("peak_offered_tx_s", artifact.get("peak_offered_tx_s"))
        put("peak_achieved_tx_s", artifact.get("peak_achieved_tx_s"))
        if isinstance(artifact.get("exactly_once_all"), bool):
            m["exactly_once_all"] = artifact["exactly_once_all"]
        peak = _peak_ingest_row(artifact)
        if peak:
            put("p99_ms", peak.get("p99_ms"))
            ingest = peak.get("ingest") or {}
            put("tx_built_per_s", ingest.get("tx_built_per_s"))
            put("sigs_signed_per_s", ingest.get("sigs_signed_per_s"))
        delta = artifact.get("pipeline_delta")
        if isinstance(delta, dict):
            put("pipeline_speedup", delta.get("pipeline_speedup"))
            put("committed_tx_s_pipelined",
                delta.get("committed_tx_s_pipelined"))
    elif kind == "multichip_capture":
        section = artifact.get("multichip_scaling") or {}
        widths = [w for w in (section.get("devices") or {}).values()
                  if isinstance(w, dict)]
        rates = [v for v in (_finite(w.get("sigs_per_sec"))
                             for w in widths) if v]
        if rates:
            put("max_width_sigs_per_sec", max(rates))
        put("multichip_scaling_1_to_max",
            section.get("scaling_1_to_max"))
        parity = [w.get("parity_ok") for w in widths
                  if "parity_ok" in w]
        if parity:
            m["parity_ok_all"] = all(parity)
    elif kind == "autotune":
        # Controller provenance record (autotune/controller.py
        # run_autotune): the committed config's swept-metric value
        # against the hand-tuned incumbent, plus search accounting. The
        # best config's exactly-once verdict rides as the hard flag.
        put("autotune_best_value", artifact.get("best_value"))
        put("autotune_baseline_value", artifact.get("baseline_value"))
        put("autotune_candidates", artifact.get("candidates_evaluated"))
        put("autotune_gate_rejections", artifact.get("gate_rejections"))
        put("autotune_improvement_pct", artifact.get("improvement_pct"))
        best = ((artifact.get("best") or {}).get("metrics") or {})
        if isinstance(best.get("exactly_once_all"), bool):
            m["autotune_exactly_once_all"] = best["exactly_once_all"]
    return m


def _autotune_provenance(artifact: dict) -> dict:
    """The autotune record's provenance block: which verdict the loop
    consumed, every candidate tried (values moved, metrics measured,
    gate outcome), the decision sequence + seed that replay the search,
    and what — if anything — was committed."""
    candidates = []
    for c in artifact.get("candidates") or []:
        if not isinstance(c, dict):
            continue
        entry = {"id": c.get("id"), "knob": c.get("knob"),
                 "accepted": bool(c.get("accepted")),
                 "metrics": c.get("metrics")}
        if "from" in c:
            entry["from"] = c["from"]
            entry["to"] = c.get("to")
        g = c.get("gate")
        if isinstance(g, dict):
            entry["gate_ok"] = bool(g.get("ok"))
            if g.get("hard_vetoes"):
                entry["hard_vetoes"] = [h.get("metric")
                                        for h in g["hard_vetoes"]]
            if g.get("soft_regressions"):
                entry["regressions"] = [h.get("metric")
                                        for h in g["soft_regressions"]]
        candidates.append(entry)
    return {
        "experiment_id": artifact.get("experiment_id"),
        "cause": artifact.get("cause"),
        "harness": artifact.get("harness"),
        "metric": artifact.get("metric"),
        "seed": artifact.get("seed"),
        "budget": artifact.get("budget"),
        "knobs": artifact.get("knobs"),
        "verdict_consumed": artifact.get("verdict_consumed"),
        "decision_sequence": artifact.get("decision_sequence"),
        "candidates": candidates,
        "committed": bool(artifact.get("committed")),
        "committed_values": (artifact.get("overlay") or {}).get("values"),
    }


def normalize_record(artifact: dict, source: str = "") -> dict:
    """One schema-versioned trajectory record: the artifact's kind, its
    flat key metrics, and the doctor's verdict over it — everything the
    gate and the trend tooling need without re-opening the artifact.
    Autotune records additionally carry the full search provenance
    (verdict consumed, candidates tried with per-candidate metrics and
    gate outcomes, the replay seed) — the loop's audit trail lives in
    the store, not in a side file."""
    kind = _classify(artifact)
    verdict = diagnose(extract_signals(artifact))
    record = {
        "schema": SCHEMA_VERSION,
        "kind": kind,
        "source": os.path.basename(source) if source else "",
        "round": _round_of(artifact, source),
        "metrics": _hoist_metrics(artifact, kind),
        "verdict": {
            "first_bottleneck": verdict["first_bottleneck"],
            "bottlenecks": [b["cause"] for b in verdict["bottlenecks"]],
            "gap_factor": verdict["roofline"]["gap_factor"],
        },
    }
    if kind == "autotune":
        record["autotune"] = _autotune_provenance(artifact)
    return record


def append_trajectory(path: str, record: dict) -> None:
    """Append one record to the JSONL store (created on first use)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")


def load_trajectory(path: str) -> list[dict]:
    """Every record in append order; a missing store is an empty
    trajectory, a malformed line raises (the store is machine-written —
    silent tolerance would let corruption hide a regression)."""
    if not os.path.exists(path):
        return []
    records = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(
                    f"{path}:{i + 1}: malformed trajectory record: "
                    f"{exc}") from None
            if not isinstance(rec, dict):
                raise ValueError(
                    f"{path}:{i + 1}: trajectory record is not an object")
            records.append(rec)
    return records


# ---------------------------------------------------------------------------
# The regression gate.
# ---------------------------------------------------------------------------

# Per-metric tolerance policy: direction ("higher" is better / "lower"
# is better / "equal" must hold) + the percent band a worse value may
# drift before it counts as a regression. 20% absorbs the measured
# run-to-run noise of the checked-in history (the r05 d->e flagship p99
# moved 16.8% on an idle host) while a real regression — the synthetic
# fixtures use 20-25% — still trips.
DEFAULT_POLICY: dict = {
    "value_sigs_per_sec": {"direction": "higher", "pct": 20.0},
    "e2e_stream_sigs_per_sec": {"direction": "higher", "pct": 20.0},
    "kernel_peak_sigs_per_sec": {"direction": "higher", "pct": 20.0},
    "flagship_tx_per_sec": {"direction": "higher", "pct": 20.0},
    "flagship_sigs_per_sec": {"direction": "higher", "pct": 20.0},
    "flagship_p99_ms": {"direction": "lower", "pct": 20.0},
    "peak_achieved_tx_s": {"direction": "higher", "pct": 20.0},
    "tx_built_per_s": {"direction": "higher", "pct": 20.0},
    "sigs_signed_per_s": {"direction": "higher", "pct": 20.0},
    "p99_ms": {"direction": "lower", "pct": 20.0},
    "ingest_peak_achieved_tx_s": {"direction": "higher", "pct": 20.0},
    # Pipelined-vs-serial commit-plane delta (round 18): the speedup
    # ratio AND the pipelined path's absolute committed-tx/s are both
    # banded, so a change that flattens the overlap win fails the gate
    # even while serial throughput holds.
    "pipeline_speedup": {"direction": "higher", "pct": 20.0},
    "committed_tx_s_pipelined": {"direction": "higher", "pct": 20.0},
    "ingest_pipeline_speedup": {"direction": "higher", "pct": 20.0},
    "max_width_sigs_per_sec": {"direction": "higher", "pct": 20.0},
    "multichip_scaling_1_to_max": {"direction": "higher", "pct": 20.0},
    "exactly_once_all": {"direction": "equal"},
    "parity_ok_all": {"direction": "equal"},
    "slo_met": {"direction": "equal"},
    # Partition plane (round 20): heal-to-first-commit recovery and the
    # prevote term-inflation bound are banded; the history auditor's
    # verdict is a hard flag — a run that stops being linearizable is a
    # regression regardless of magnitude. minority_commits / lost_acks
    # regress when they grow above a prior zero, but a zero prior passes
    # _compare vacuously, so the auditor flag is the real gate bit.
    "recovery_s": {"direction": "lower", "pct": 20.0},
    "max_term_inflation": {"direction": "lower", "pct": 20.0},
    "partition_minority_commits": {"direction": "lower", "pct": 20.0},
    "partition_lost_acks": {"direction": "lower", "pct": 20.0},
    "history_linearizable": {"direction": "equal"},
    # Autotune plane (round 21): the loop's committed and baseline
    # swept-metric values are banded a little wider than raw throughput
    # (25%) — short sweep candidates are noisier than full bench runs —
    # while the best config's exactly-once verdict is a hard flag: an
    # autotune round whose winner stops being exactly-once is a
    # regression regardless of how fast it got.
    "autotune_best_value": {"direction": "higher", "pct": 25.0},
    "autotune_baseline_value": {"direction": "higher", "pct": 25.0},
    "autotune_exactly_once_all": {"direction": "equal"},
    # Vault scaling (round 22): the coin-selection p99 ratio
    # (largest-store p99 over smallest-store p99) is the sublinearity
    # headline — it growing means indexed selection degraded toward a
    # scan; the boot speedup (full replay over incremental rebuild) is
    # the watermark win; query p99 is banded like the autotune sweeps
    # (25%, short in-process runs are noisy); engine parity is a hard
    # flag — the two engines disagreeing on the unconsumed set is a
    # correctness regression regardless of speed.
    "vault_coin_selection_p99_ratio": {"direction": "lower", "pct": 25.0},
    "vault_boot_speedup": {"direction": "higher", "pct": 25.0},
    "vault_query_p99_ms": {"direction": "lower", "pct": 25.0},
    "vault_parity_ok": {"direction": "equal"},
}


def _compare(metric: str, prev, new, rule: dict) -> dict | None:
    """One metric check -> a regression dict or None. Only keys present
    and comparable on BOTH records are judged (schema growth must never
    fail the gate retroactively)."""
    direction = rule.get("direction", "higher")
    if direction == "equal":
        if isinstance(prev, bool) and isinstance(new, bool) \
                and prev and not new:
            return {"metric": metric, "prev": prev, "new": new,
                    "direction": direction,
                    "detail": "flag flipped false"}
        return None
    p, n = _finite(prev), _finite(new)
    if p is None or n is None or p <= 0:
        return None
    pct = float(rule.get("pct", 20.0))
    change = (n - p) / p * 100.0
    if direction == "higher" and change < -pct:
        return {"metric": metric, "prev": p, "new": n,
                "direction": direction, "change_pct": round(change, 2),
                "band_pct": pct}
    if direction == "lower" and change > pct:
        return {"metric": metric, "prev": p, "new": n,
                "direction": direction, "change_pct": round(change, 2),
                "band_pct": pct}
    return None


def gate(records: list[dict], policy: dict | None = None) -> dict:
    """Each kind's NEWEST record against its predecessor of the same
    kind under the tolerance policy. Cross-kind comparison would be
    noise (an ingest capture is not a multichip capture); a kind with a
    single record has no predecessor and passes vacuously — the verdict
    says so under ``unpaired`` instead of hiding it."""
    policy = policy or DEFAULT_POLICY
    by_kind: dict = {}
    for rec in records:
        if isinstance(rec, dict):
            by_kind.setdefault(rec.get("kind", "unknown"), []).append(rec)
    regressions = []
    compared = {}
    unpaired = []
    for kind in sorted(by_kind):
        chain = by_kind[kind]
        if len(chain) < 2:
            unpaired.append(kind)
            continue
        prev, new = chain[-2], chain[-1]
        compared[kind] = {"prev": prev.get("source") or "prev",
                          "new": new.get("source") or "new"}
        pm = prev.get("metrics") or {}
        nm = new.get("metrics") or {}
        for metric in sorted(set(pm) & set(nm) & set(policy)):
            hit = _compare(metric, pm[metric], nm[metric], policy[metric])
            if hit:
                hit["kind"] = kind
                regressions.append(hit)
    return {
        "schema": SCHEMA_VERSION,
        "ok": not regressions,
        "regressions": regressions,
        "compared": compared,
        "unpaired": unpaired,
        "records": len(records),
    }


def trajectory_delta(prior: list[dict], record: dict) -> dict | None:
    """The newest record against the LAST prior record of its kind:
    per-metric percent change for the bench report's one-line contract.
    None when the store holds no predecessor of this kind."""
    prev = None
    for rec in prior:
        if isinstance(rec, dict) and rec.get("kind") == record.get("kind"):
            prev = rec
    if prev is None:
        return None
    pm = prev.get("metrics") or {}
    nm = record.get("metrics") or {}
    deltas = {}
    for metric in sorted(set(pm) & set(nm)):
        p, n = _finite(pm[metric]), _finite(nm[metric])
        if p and n is not None:
            deltas[metric] = {"prev": p, "new": n,
                              "change_pct": round((n - p) / p * 100.0, 2)}
    return {"vs": prev.get("source") or "prev", "metrics": deltas}
