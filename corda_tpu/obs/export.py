"""Prometheus text exposition + the cluster collector.

The registry (obs/telemetry.py) stores; this module ships. Three
surfaces share the one renderer:

  * ``GET /metrics`` on every node webserver (node/webserver.py),
  * ``OP_METRICS`` on the sidecar stats port (crypto/sidecar.py),
  * ``collect_cluster`` — the harness-side collector that merges
    per-node registry snapshots into one cluster view for
    loadtest/bench artifacts.

Render format is Prometheus text exposition 0.0.4: ``# TYPE`` lines,
cumulative ``_bucket{le="..."}`` series ending in ``+Inf``, ``_sum`` and
``_count`` per histogram. ``parse_prometheus`` is the exact inverse for
the subset this renderer emits — it exists so tests (and
bench_telemetry's self-check) can prove the endpoint serves every
registered metric in valid form without a real Prometheus binary in the
container.
"""

from __future__ import annotations

import json
import struct
import urllib.request

from . import telemetry

__all__ = [
    "collect_cluster",
    "fetch_sidecar_metrics",
    "merge_snapshots",
    "parse_prometheus",
    "render_prometheus",
    "scrape",
]

PREFIX = "corda_tpu_"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v: float) -> str:
    """Integral floats render as integers (Prometheus accepts either;
    integral keeps counter lines greppable)."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


def render_prometheus(reg=None, prefix: str = PREFIX) -> str:
    """The active registry (or a snapshot dict from
    ``TelemetryRegistry.snapshot()``) as exposition text. Every
    registered metric is always present — a counter that never fired
    still exports 0, so dashboards never see series flap in and out."""
    if reg is None:
        reg = telemetry.ACTIVE
    if reg is None:
        return "# telemetry disarmed\n"
    snap = reg if isinstance(reg, dict) else reg.snapshot()
    out: list[str] = []
    for name in sorted(snap.get("counters", {})):
        value = snap["counters"][name]
        full = prefix + name
        out.append(f"# TYPE {full} counter")
        out.append(f"{full} {_fmt(float(value))}")
    for name in sorted(snap.get("histograms", {})):
        h = snap["histograms"][name]
        full = prefix + name
        scale = h.get("scale", 1)
        buckets = {int(i): n for i, n in (h.get("buckets") or {}).items()}
        out.append(f"# TYPE {full} histogram")
        run = 0
        for idx in sorted(buckets):
            run += buckets[idx]
            le = (1 << idx) / scale
            out.append(f'{full}_bucket{{le="{_fmt(float(le))}"}} {run}')
        out.append(f'{full}_bucket{{le="+Inf"}} {h.get("count", 0)}')
        out.append(f"{full}_sum {_fmt(float(h.get('sum', 0.0)))}")
        out.append(f"{full}_count {h.get('count', 0)}")
    return "\n".join(out) + "\n"


def parse_prometheus(text: str, prefix: str = PREFIX) -> dict:
    """Inverse of ``render_prometheus`` for the subset it emits ->
    {"counters": {name: value}, "histograms": {name: {"count", "sum",
    "buckets": [(le, cumulative_count), ...]}}}. Raises ValueError on a
    malformed sample line — that IS the validity check the tests rely
    on."""
    counters: dict = {}
    hists: dict = {}
    types: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed sample line: {line!r}")
        value = float(value_part)  # ValueError on garbage = the check
        label = None
        if "{" in name_part:
            name_part, _, rest = name_part.partition("{")
            body = rest.rstrip("}")
            key, _, raw = body.partition("=")
            if key != "le":
                raise ValueError(f"unexpected label in {line!r}")
            label = raw.strip('"')
        if not name_part.startswith(prefix):
            raise ValueError(f"unprefixed metric: {line!r}")
        short = name_part[len(prefix):]
        if label is not None:
            base = short[:-len("_bucket")]
            le = float("inf") if label == "+Inf" else float(label)
            hists.setdefault(base, {"count": 0, "sum": 0.0,
                                    "buckets": []})
            hists[base]["buckets"].append((le, int(value)))
        elif short.endswith("_sum") and \
                types.get(name_part[:-len("_sum")]) == "histogram":
            base = short[:-len("_sum")]
            hists.setdefault(base, {"count": 0, "sum": 0.0,
                                    "buckets": []})
            hists[base]["sum"] = value
        elif short.endswith("_count") and \
                types.get(name_part[:-len("_count")]) == "histogram":
            base = short[:-len("_count")]
            hists.setdefault(base, {"count": 0, "sum": 0.0,
                                    "buckets": []})
            hists[base]["count"] = int(value)
        else:
            counters[short] = value
    for base, h in hists.items():
        les = [le for le, _ in h["buckets"]]
        if les != sorted(les) or not les or les[-1] != float("inf"):
            raise ValueError(
                f"histogram {base!r}: buckets not cumulative-ordered "
                "or missing +Inf")
        cums = [c for _, c in h["buckets"]]
        if cums != sorted(cums):
            raise ValueError(f"histogram {base!r}: non-monotonic buckets")
    return {"counters": counters, "histograms": hists}


# ---------------------------------------------------------------------------
# Cluster collection
# ---------------------------------------------------------------------------


def merge_snapshots(snapshots: list[dict]) -> dict:
    """Fold per-node ``TelemetryRegistry.snapshot()`` dicts into one:
    counters sum; histograms merge bucket-wise (the sparse power-of-two
    indices align across processes by construction, so the merge is
    exact, not approximate)."""
    out = {"counters": {}, "histograms": {}}
    for snap in snapshots:
        if not snap:
            continue
        for name, v in (snap.get("counters") or {}).items():
            out["counters"][name] = out["counters"].get(name, 0.0) + v
        for name, h in (snap.get("histograms") or {}).items():
            m = out["histograms"].setdefault(
                name, {"count": 0, "sum": 0.0,
                       "scale": h.get("scale", 1), "buckets": {}})
            m["count"] += h.get("count", 0)
            m["sum"] = round(m["sum"] + h.get("sum", 0.0), 9)
            for idx, n in (h.get("buckets") or {}).items():
                m["buckets"][idx] = m["buckets"].get(idx, 0) + n
    for h in out["histograms"].values():
        h["buckets"] = {i: h["buckets"][i]
                        for i in sorted(h["buckets"], key=int)}
    return out


def collect_cluster(snapshots: dict[str, dict | None]) -> dict:
    """{node_name: snapshot-or-None} -> {"nodes": per-node, "merged":
    the cluster fold, "missing": nodes that served nothing} — the shape
    loadtest/bench embed in artifacts."""
    present = {k: v for k, v in snapshots.items() if v}
    return {
        "nodes": present,
        "missing": sorted(k for k, v in snapshots.items() if not v),
        "merged": merge_snapshots(list(present.values())),
    }


def scrape(url: str, timeout: float = 5.0) -> dict:
    """GET a /metrics endpoint and parse it — the HTTP half of the
    collector (nodes with a webserver)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus(resp.read().decode())


def fetch_sidecar_metrics(address: str, timeout: float = 2.0) -> str:
    """One-shot OP_METRICS round trip on a fresh connection: the
    sidecar's stats port speaks frames, not HTTP, so its Prometheus
    text rides the same framing OP_STATS uses. Returns the exposition
    text; raises the client's SidecarError when unreachable (same
    contract as fetch_sidecar_stats)."""
    from ..crypto import sidecar as wire
    from ..node.verify_client import SidecarError

    try:
        sock = wire.connect(address, timeout=timeout)
        try:
            sock.settimeout(timeout)
            wire.send_frame(sock, wire._REQ_HDR.pack(wire.OP_METRICS, 1))
            payload = wire.recv_frame(sock)
            op, _, status = wire._REPLY_HDR.unpack_from(payload)
            if op != wire.OP_METRICS or status != wire.STATUS_OK:
                raise ValueError("bad sidecar metrics reply")
            return payload[wire._REPLY_HDR.size:].decode()
        finally:
            try:
                sock.close()
            except OSError:
                pass
    except (OSError, ConnectionError, ValueError, struct.error,
            json.JSONDecodeError) as exc:
        raise SidecarError(f"sidecar {address}: {exc}") from exc
