"""The span-name registry: every stage name the tracing subsystem records.

``collect.stage_breakdown`` attributes latency by matching span names
against fixed tuples; a span recorded under a name missing from those
tuples is silently invisible in the breakdown — the failure mode is not an
error but a stage that never shows up in the bench report. This module is
the single source of truth both sides key on:

  * ``collect.py`` builds its attribution tables from these tuples, so the
    breakdown can never drift from the registry;
  * the static invariant analyzer (``corda_tpu.analysis``, rule
    ``trace-stage-registry``) checks every literal span name passed to
    ``_obs.record(...)`` anywhere in the tree against ``SPAN_NAMES`` /
    ``SPAN_NAME_PREFIXES``, so an instrumentation site with a typo'd or
    unregistered name fails tier-1 instead of silently dropping out of
    ``stage_breakdown``.

Adding a stage is therefore a two-line change HERE (name + ordering slot),
after which the analyzer permits the recording site and the breakdown
reports it.

Stdlib-only like the rest of ``obs`` — the analyzer imports this module
from a bare CLI process.
"""

from __future__ import annotations

__all__ = [
    "BATCH_STAGES",
    "DIRECT_STAGES",
    "DERIVED_STAGES",
    "STAGES",
    "MARKER_SPANS",
    "SPAN_NAME_PREFIXES",
    "SPAN_NAMES",
]

# Batch-level stages: recorded once per batch, attributed to every trace in
# attrs["member_traces"]. sidecar_wait/sidecar_verify DECOMPOSE
# device_verify for sidecar-routed batches (crypto/sidecar.py);
# federation_route/remote_verify decompose it one level further for
# federation-routed batches (crypto/federation.py): the routing decision
# and the winning host's full round trip, which CONTAINS that host's
# sidecar_wait/sidecar_verify.
BATCH_STAGES = ("queue_wait", "device_verify", "federation_route",
                "remote_verify", "sidecar_wait",
                "sidecar_verify", "raft_append", "fsync", "replication")

# Per-trace measured stage spans. shard_reserve/shard_commit are the two
# phases of the cross-shard 2PC coordinator (node/services/sharding.py).
# admission_wait is the client-side backoff park after an OverloadedError
# shed (flows/notary.py); epoch_wait is the same park when the request
# bounced off a reshard fence (WrongShardEpoch) and the client re-derives
# the shard directory; lane_queue_wait is time spent runnable behind
# the QoS lane scheduler before the pump picked the flow (statemachine).
# scrub is one online-scrubber / fsck verification pass over a store's
# integrity-framed tables (node/services/integrity.py); repair is one
# self-healing action — a raft-log truncate/compact or a checkpoint
# quarantine (raft._heal_corrupt_entry, persistence.quarantine).
# vault_query is one vault read — a VaultQuery page or a select_coins
# walk (node/services/vault.py, attrs["op"] names which); when it
# dominates a flow's breakdown the doctor's vault_scan rule suggests
# arming the indexed engine.
DIRECT_STAGES = ("verify_wait", "admission_wait", "epoch_wait",
                 "lane_queue_wait", "shard_reserve", "shard_commit",
                 "scrub", "repair", "vault_query")

# Derived by stage_breakdown, never recorded: the reply tail is
# root_end - max(attributed stage end).
DERIVED_STAGES = ("reply",)

# Full breakdown order the bench report presents.
STAGES = ("admission_wait", "epoch_wait", "queue_wait", "lane_queue_wait",
          "vault_query", "verify_wait",
          "device_verify", "federation_route", "remote_verify",
          "sidecar_wait", "sidecar_verify",
          "shard_reserve", "shard_commit",
          "raft_append", "fsync", "replication",
          "scrub", "repair", "reply")

# Stitch markers: recorded per trace to bound the derived reply tail and
# anchor cross-node correlation, but not themselves breakdown stages.
# qos_flush marks a deadline-triggered early flush/seal at one of the
# three QoS queueing points (attrs["point"] names which); shard_handoff
# is recorded once per completed reshard handoff by the source-group
# coordinator (attrs carry epoch/from/to/frames); election is recorded
# by the NEW leader once per won election, spanning candidacy start to
# the win (attrs carry term/prevote — partition plane, round 20).
MARKER_SPANS = ("raft_commit", "notary_process", "qos_flush",
                "shard_handoff", "election")

# Dynamic span families: a recorded name may start with one of these
# prefixes (the root flow span is f"flow:{FlowClassName}").
SPAN_NAME_PREFIXES = ("flow:",)

# Every literal name a recording site may pass to SpanRecorder.record().
SPAN_NAMES = frozenset(BATCH_STAGES) | frozenset(DIRECT_STAGES) \
    | frozenset(MARKER_SPANS)
