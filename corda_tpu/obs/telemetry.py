"""Always-on telemetry: counters, log-bucketed histograms, and the
flight recorder.

The trace subsystem (obs/trace.py) answers "where did THIS request go"
— but only when an operator re-runs with tracing armed. This module is
the other half of the observability plane: a process-global metric
registry that is armed at import time, cheap enough to leave on in the
flagship path, and captured automatically at the moment something goes
wrong.

Three pieces:

  * ``TelemetryRegistry`` — monotonic counters plus HDR-style
    power-of-two latency histograms. Every metric name comes from the
    single-source-of-truth tuples below (``COUNTER_NAMES`` /
    ``HISTOGRAM_NAMES``), the same registry pattern ``obs/stages.py``
    uses for span names; the analyzer's ``trace-stage-registry`` rule
    enforces it at every ``inc(...)`` / ``observe(...)`` site so a
    typo'd metric cannot silently vanish from every dashboard.
  * the **round profiler feed** — ``observe_round`` takes one wall-time
    plus the per-phase deltas the node run loop measures
    (``ROUND_PHASES``: poll, verify_wait, seal, replicate, apply,
    reply) and fans them into the per-phase counters/histograms through
    pre-interned handles: one attribute check when disarmed, a handful
    of dict-free adds when armed.
  * ``FlightRecorder`` — a bounded ring of recent metric deltas and
    notes that dumps ONE JSON artifact per trigger reason (SLO breach,
    overload spike, fsck failure, crash) so post-hoc diagnosis never
    requires reproducing the run.

Concurrency contract: counters and histograms are update-racy by design
("lock-light"). A counter ``+=`` from two threads can drop an increment;
that is an accepted monitoring-grade error bound — the round loop owns
almost every hot metric single-threaded, and the few cross-thread
writers (sidecar executor, admission controller) tolerate last-writer
drift. Nothing here is consensus state. The flight recorder's dump latch
IS locked: "exactly one artifact per reason" is a contract, not a trend.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = [
    "ACTIVE",
    "COUNTER_NAMES",
    "HISTOGRAM_NAMES",
    "METRIC_NAMES",
    "ROUND_PHASES",
    "Counter",
    "FlightRecorder",
    "Histogram",
    "TelemetryRegistry",
    "arm",
    "disarm",
    "ensure_flight",
    "flight_note",
    "flight_trigger",
    "format_breakdown",
    "inc",
    "observe",
    "observe_round",
    "snapshot",
]

# ---------------------------------------------------------------------------
# The metric name registry (single source of truth — the analyzer's
# trace-stage-registry rule checks every literal inc()/observe() name in
# the tree against these tuples, exactly as it checks span names against
# obs/stages.py).
# ---------------------------------------------------------------------------

# The round loop's named sub-phases, in breakdown display order. Every
# phase owns one `round_phase_<p>_seconds_total` counter and one
# `round_phase_<p>_seconds` histogram below.
ROUND_PHASES = ("poll", "verify_wait", "seal", "replicate", "apply",
                "reply")

COUNTER_NAMES = (
    # Round profiler (node.run_once): rounds and attributed wall time.
    "rounds_total",
    "round_wall_seconds_total",
    "round_phase_poll_seconds_total",
    "round_phase_verify_wait_seconds_total",
    "round_phase_seal_seconds_total",
    "round_phase_replicate_seconds_total",
    "round_phase_apply_seconds_total",
    "round_phase_reply_seconds_total",
    # Flow lifecycle (statemachine.py).
    "flows_started_total",
    "flows_completed_total",
    # Verify plane (statemachine micro-batches; sigs = signatures).
    "verify_batches_total",
    "verify_sigs_total",
    # Raft leader seal path (services/raft.py).
    "raft_seals_total",
    "raft_seal_entries_total",
    # Pipelined commit plane (services/raft.py apply executor): wall time
    # the executor overlapped under the consensus thread (kept OUT of the
    # round_phase_* family so phase coverage never double-counts it),
    # executor batches completed, and submissions shed off a full queue.
    "round_overlap_apply_seconds_total",
    "raft_apply_batches_total",
    "raft_apply_shed_total",
    # Admission controller (qos/admission.py).
    "admission_admitted_total",
    "admission_shed_total",
    # Sidecar server (crypto/sidecar.py).
    "sidecar_requests_total",
    "sidecar_batches_total",
    "sidecar_sigs_total",
    # Federation router (crypto/federation.py): batches dispatched to a
    # host channel, hedged re-dispatches fired, and per-host quarantine
    # events (a host demoted to its cooldown re-probe).
    "federation_dispatches_total",
    "federation_hedges_total",
    "federation_host_degraded_total",
    # The recorder's own audit trail.
    "flight_dumps_total",
    # The performance doctor (obs/doctor.py, bench.bench_doctor):
    # verdicts produced, and regressions the trajectory gate flagged.
    "doctor_runs_total",
    "doctor_gate_regressions_total",
    # Partition plane (round 20): pre-vote canvasses run / rejected
    # (services/raft.py), leaders deposed by check-quorum, and partition
    # cut activations from the fault engine (testing/faults.py).
    "raft_prevotes_total",
    "raft_prevote_rejections_total",
    "raft_checkquorum_stepdowns_total",
    "partition_cuts_total",
    # Autotune plane (round 21, corda_tpu/autotune/): sweep candidates
    # measured, candidates the incumbent gate vetoed, and runtime-leg
    # hard reverts (the revert-on-regression guard firing).
    "autotune_candidates_total",
    "autotune_gate_rejections_total",
    "autotune_reverts_total",
    # Indexed vault plane (round 22, node/services/vault.py): queries
    # answered (pages + coin selections), coins skipped because another
    # flow's soft lock held them, and expired reservations reaped by the
    # TTL sweep (each reap re-admits a coin a crashed flow had shadowed).
    "vault_queries_total",
    "vault_selection_conflicts_total",
    "vault_softlock_expired_total",
)

HISTOGRAM_NAMES = (
    "round_wall_seconds",
    "round_phase_poll_seconds",
    "round_phase_verify_wait_seconds",
    "round_phase_seal_seconds",
    "round_phase_replicate_seconds",
    "round_phase_apply_seconds",
    "round_phase_reply_seconds",
    "verify_batch_sigs",
    "raft_seal_entries",
    "raft_apply_batch_commands",
    "sidecar_batch_sigs",
)

METRIC_NAMES = frozenset(COUNTER_NAMES) | frozenset(HISTOGRAM_NAMES)

# ---------------------------------------------------------------------------
# Counters and histograms
# ---------------------------------------------------------------------------


class Counter:
    """Monotonic counter. ``add`` is one float add — intentionally
    unlocked (see the module concurrency contract)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def add(self, n: float = 1.0) -> None:
        self.value += n


# Histograms bucket by power of two (HDR-style): bucket i holds values v
# with int(v * scale).bit_length() == i, i.e. v*scale in [2**(i-1), 2**i).
# Seconds-valued histograms scale to microseconds first so sub-second
# latencies spread over ~20 buckets instead of collapsing into one;
# count-valued histograms (batch sizes) use the raw integer. 64 buckets
# cover every representable magnitude — the index is clamped, never
# dropped.
_SECONDS_SCALE = 1_000_000
_MAX_BUCKET = 63


class Histogram:
    """Log-bucketed (power-of-two) histogram with exact count and sum.

    ``buckets`` is a sparse {index: count} dict; the upper bound of
    bucket i is ``2**i / scale`` (cumulative over indices <= i), which
    is what the Prometheus renderer in obs/export.py emits as ``le``."""

    __slots__ = ("name", "scale", "count", "sum", "buckets")

    def __init__(self, name: str):
        self.name = name
        self.scale = _SECONDS_SCALE if name.endswith("_seconds") else 1
        self.count = 0
        self.sum = 0.0
        self.buckets: dict[int, int] = {}

    def observe(self, value: float) -> None:
        idx = int(value * self.scale).bit_length()
        if idx > _MAX_BUCKET:
            idx = _MAX_BUCKET
        self.count += 1
        self.sum += value
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def bucket_upper(self, idx: int) -> float:
        return (1 << idx) / self.scale

    def quantile(self, q: float) -> float | None:
        """Approximate quantile: the upper bound of the bucket where the
        cumulative count crosses q — an over-estimate by at most 2x
        (one power-of-two bucket), which is the HDR trade."""
        if not self.count:
            return None
        target = q * self.count
        run = 0
        for idx in sorted(self.buckets):
            run += self.buckets[idx]
            if run >= target:
                return self.bucket_upper(idx)
        return self.bucket_upper(max(self.buckets))

    def snap(self) -> dict:
        return {"count": self.count, "sum": round(self.sum, 9),
                "scale": self.scale,
                "buckets": {str(i): n for i, n in sorted(
                    self.buckets.items())}}


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------


class TelemetryRegistry:
    """All registered counters/histograms, pre-interned at construction.

    Lookups by unregistered name raise — the runtime closes the same
    drop-a-metric hole the analyzer closes lexically (a dynamic name
    built outside obs/ cannot sneak past the literal-name rule)."""

    def __init__(self):
        self.counters = {n: Counter(n) for n in COUNTER_NAMES}
        self.histograms = {n: Histogram(n) for n in HISTOGRAM_NAMES}
        # Optional FlightRecorder, attached by ensure_flight(); None
        # means triggers are no-ops (the default for tests and ad-hoc
        # processes that configured no dump directory).
        self.flight: FlightRecorder | None = None
        # Pre-interned handles for the per-round fast path: one tuple
        # per phase, resolved once, so observe_round never does a name
        # lookup. (Dynamic name construction is fine HERE — obs/ is the
        # registry module and is excluded from the lexical rule.)
        self._rounds = self.counters["rounds_total"]
        self._round_wall_c = self.counters["round_wall_seconds_total"]
        self._round_wall_h = self.histograms["round_wall_seconds"]
        self._round_handles = tuple(
            (p, self.counters[f"round_phase_{p}_seconds_total"],
             self.histograms[f"round_phase_{p}_seconds"])
            for p in ROUND_PHASES)

    def counter(self, name: str) -> Counter:
        try:
            return self.counters[name]
        except KeyError:
            raise ValueError(
                f"telemetry counter {name!r} is not registered in "
                "obs/telemetry.py COUNTER_NAMES") from None

    def histogram(self, name: str) -> Histogram:
        try:
            return self.histograms[name]
        except KeyError:
            raise ValueError(
                f"telemetry histogram {name!r} is not registered in "
                "obs/telemetry.py HISTOGRAM_NAMES") from None

    def observe_round(self, wall_s: float, phases: dict) -> None:
        self._rounds.value += 1
        self._round_wall_c.value += wall_s
        self._round_wall_h.observe(wall_s)
        for name, counter, hist in self._round_handles:
            v = phases.get(name, 0.0)
            counter.value += v
            hist.observe(v)

    def snapshot(self) -> dict:
        """JSON-safe copy: {"counters": {name: value}, "histograms":
        {name: {count, sum, scale, buckets}}}. The exact shape
        obs/export.py renders, parses, and merges."""
        return {
            "counters": {n: round(c.value, 9)
                         for n, c in self.counters.items()},
            "histograms": {n: h.snap()
                           for n, h in self.histograms.items()},
        }

    def reset(self) -> None:
        for c in self.counters.values():
            c.value = 0.0
        for h in self.histograms.values():
            h.count = 0
            h.sum = 0.0
            h.buckets.clear()


# ---------------------------------------------------------------------------
# The flight recorder
# ---------------------------------------------------------------------------

FLIGHT_ENV = "CORDA_TPU_FLIGHT_DIR"


class FlightRecorder:
    """Bounded ring of recent metric deltas + notes; dumps one JSON
    artifact per trigger REASON and latches (a crash loop or a sustained
    overload produces one dump, not a disk-filling stream).

    ``tick`` entries are the "recent history" half of the artifact: the
    caller feeds whatever per-window snapshot it has (the driver feeds
    per-rate sweep rows, a node could feed metric samples) and the
    recorder stores the numeric deltas vs the previous tick, so the
    window reads as rates, not lifetime totals."""

    def __init__(self, dump_dir: str, node: str = "",
                 capacity: int = 256):
        self.dump_dir = str(dump_dir)
        self.node = node
        self.ring: deque = deque(maxlen=int(capacity))
        self.dumped: dict[str, str] = {}  # reason -> artifact path
        self._last_tick: dict | None = None
        self._lock = threading.Lock()

    def tick(self, sample: dict) -> None:
        prev = self._last_tick or {}
        delta = {}
        for k, v in sample.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and isinstance(prev.get(k), (int, float)):
                delta[k] = round(v - prev[k], 9)
        self._last_tick = dict(sample)
        self.ring.append({"t": round(time.time(), 3), "kind": "tick",
                          "sample": sample, "delta": delta or None})

    def note(self, kind: str, **payload) -> None:
        self.ring.append({"t": round(time.time(), 3), "kind": kind,
                          **payload})

    def stats(self) -> dict:
        return {"dir": self.dump_dir, "node": self.node,
                "ring": len(self.ring),
                "dumped": dict(self.dumped)}

    def trigger(self, reason: str, extra: dict | None = None,
                spans: list | None = None) -> str | None:
        """Dump the artifact for ``reason`` (latched: the first trigger
        per reason writes, every later one returns the same path).
        Never raises — a broken disk must not take down the round loop
        it is trying to explain."""
        with self._lock:
            if reason in self.dumped:
                return self.dumped[reason]
            # Reserve the latch before the slow write so a concurrent
            # trigger can't double-dump.
            path = os.path.join(
                self.dump_dir,
                f"flight-{self.node or 'node'}-{reason}-{os.getpid()}"
                ".json")
            self.dumped[reason] = path
        try:
            if spans is None:
                from . import trace as _obs

                rec = _obs.ACTIVE
                spans = rec.snapshot()[-200:] if rec is not None else []
            reg = ACTIVE
            artifact = {
                "reason": reason,
                "ts": round(time.time(), 3),
                "node": self.node,
                "pid": os.getpid(),
                "window": list(self.ring),
                "metrics": reg.snapshot() if reg is not None else None,
                "spans": spans,
                "extra": extra,
            }
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(artifact, f, default=str)
            os.replace(tmp, path)
            inc("flight_dumps_total")
            return path
        # lint: allow(no-silent-except) flight recorder is best-effort diagnostics: a full disk or unserializable extra must never crash (or recurse into) the failing path that triggered the dump
        except Exception:
            return None


# ---------------------------------------------------------------------------
# Module-level arming + hot-path helpers
# ---------------------------------------------------------------------------

# Always-on: armed at import, unlike trace/faults/qos which arm on
# request. ``disarm()`` exists for tests that need to prove the
# one-attribute-check cost bound.
ACTIVE: TelemetryRegistry | None = TelemetryRegistry()


def arm() -> TelemetryRegistry:
    """Install a FRESH registry (and return it) — test/bench isolation;
    production processes keep the import-time instance."""
    global ACTIVE
    ACTIVE = TelemetryRegistry()
    return ACTIVE


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def inc(name: str, n: float = 1.0) -> None:
    reg = ACTIVE
    if reg is None:
        return
    reg.counter(name).value += n


def observe(name: str, value: float) -> None:
    reg = ACTIVE
    if reg is None:
        return
    reg.histogram(name).observe(value)


def observe_round(wall_s: float, phases: dict) -> None:
    reg = ACTIVE
    if reg is None:
        return
    reg.observe_round(wall_s, phases)


def snapshot() -> dict | None:
    reg = ACTIVE
    return reg.snapshot() if reg is not None else None


def ensure_flight(dump_dir: str | None = None,
                  node: str = "") -> FlightRecorder | None:
    """Attach a FlightRecorder to the active registry (idempotent).
    ``dump_dir`` falls back to $CORDA_TPU_FLIGHT_DIR; with neither set
    this is a no-op and every trigger stays a no-op."""
    reg = ACTIVE
    if reg is None:
        return None
    if reg.flight is not None:
        return reg.flight
    dump_dir = dump_dir or os.environ.get(FLIGHT_ENV)
    if not dump_dir:
        return None
    reg.flight = FlightRecorder(dump_dir, node=node)
    return reg.flight


def flight_note(kind: str, **payload) -> None:
    reg = ACTIVE
    if reg is not None and reg.flight is not None:
        reg.flight.note(kind, **payload)


def flight_trigger(reason: str, extra: dict | None = None,
                   spans: list | None = None) -> str | None:
    reg = ACTIVE
    if reg is None or reg.flight is None:
        return None
    return reg.flight.trigger(reason, extra=extra, spans=spans)


# ---------------------------------------------------------------------------
# Round-breakdown formatting (shared by rpc.node_metrics, the node's
# metric history sampler, loadtest stamps, and bench_telemetry — one
# formatter so the artifact shape can't fork).
# ---------------------------------------------------------------------------


def format_breakdown(round_phase_s: dict | None) -> dict | None:
    """``round_phase_s`` (node.run_once accumulators: the six ROUND_PHASES
    plus "wall" and "rounds") -> the ``round_breakdown`` block:
    per-phase totals and wall-time shares, plus ``coverage`` — the
    fraction of measured round wall time the named phases attribute
    (the >= 0.9 acceptance bound)."""
    rp = round_phase_s or {}
    rounds = rp.get("rounds", 0)
    if not rounds:
        return None
    wall = rp.get("wall", 0.0) or 0.0
    phases = {}
    covered = 0.0
    for p in ROUND_PHASES:
        v = rp.get(p, 0.0) or 0.0
        covered += v
        phases[p] = {"total_s": round(v, 6),
                     "share": round(v / wall, 4) if wall else None}
    out = {
        "rounds": rounds,
        "wall_s": round(wall, 6),
        "phases": phases,
        "coverage": round(covered / wall, 4) if wall else None,
        "busiest_phase": max(ROUND_PHASES,
                             key=lambda p: rp.get(p, 0.0) or 0.0),
    }
    # Pipelined commit plane: executor wall time that ran UNDER the six
    # in-loop phases. Reported beside them, never inside — coverage stays
    # a partition of the consensus thread's wall time (no double counts),
    # and vs_wall > 0 is the self-describing proof rounds overlapped.
    overlap = rp.get("overlap_apply", 0.0) or 0.0
    if overlap:
        out["overlap"] = {"apply": {
            "total_s": round(overlap, 6),
            "vs_wall": round(overlap / wall, 4) if wall else None,
        }}
    return out
