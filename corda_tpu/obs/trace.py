"""Per-node span recording + trace-context propagation (Dapper-style).

Arming mirrors testing/faults.py exactly: a module-level ``ACTIVE`` recorder
that every instrumentation point guards with ``if _obs.ACTIVE is not None:``
— the disarmed cost of the whole subsystem is that one attribute check, and
tests assert it (tests/test_obs_trace.py overhead guard).

Span model
----------
A span is ``(trace_id, span_id, parent, name, node, t_start, t_end, attrs)``.
ids are 8 random bytes; timestamps are epoch ``time.time()`` seconds so spans
recorded in different OS processes merge onto one driver-side timeline without
clock translation (perf_counter would be per-process). ``attrs`` is a small
dict; batch-level spans (device verify, raft append/fsync/replication) carry
``attrs["member_traces"]`` — the hex trace ids of every transaction that rode
the batch — which is how fan-in stages attribute back to individual traces.

The recorder is a fixed-capacity ring: when full it overwrites the oldest
span and counts the drop. Appends take no lock — the node is single-threaded
except for the verify feeder, and list.append / index assignment are atomic
under the GIL; ``snapshot()`` copies before reading.

Context propagation
-------------------
The current (trace_id, span_id) rides a thread-local, set by the state
machine around each flow step / service poll, read by the transports when
stamping outbound messages. Cross-process it rides two extra fields on the
TCP wire frame; in-process it rides ``Message.trace``. The request-id link
map lets RaftMember (which sees only PutAllCommand.request_id at batch-seal
time) recover the submitting flow's trace without plumbing trace arguments
through the consensus API.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "ACTIVE",
    "Span",
    "SpanRecorder",
    "arm",
    "disarm",
    "arm_from_env",
    "new_trace_id",
    "new_span_id",
    "set_context",
    "get_context",
    "clear_context",
    "record",
    "register_link",
    "pop_link",
]

ENV_VAR = "CORDA_TPU_TRACE"
DEFAULT_CAPACITY = 65536
LINK_MAP_MAX = 16384

# THE switch. Hot paths guard every tracing touch with
# `if _obs.ACTIVE is not None:` — disarmed cost is this one attribute check.
ACTIVE: "SpanRecorder | None" = None


def new_trace_id() -> bytes:
    return os.urandom(8)


def new_span_id() -> bytes:
    return os.urandom(8)


class Span:
    """One timed operation. Slotted: a loaded node records tens of
    thousands of these per second when armed."""

    __slots__ = ("trace_id", "span_id", "parent", "name", "node",
                 "t_start", "t_end", "attrs")

    def __init__(self, trace_id, span_id, parent, name, node,
                 t_start, t_end, attrs=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent = parent
        self.name = name
        self.node = node
        self.t_start = t_start
        self.t_end = t_end
        self.attrs = attrs

    def as_dict(self) -> dict:
        """JSON-safe form (ids hex-encoded) for /api/trace + RPC export."""
        return {
            "trace_id": self.trace_id.hex(),
            "span_id": self.span_id.hex(),
            "parent": self.parent.hex() if self.parent else None,
            "name": self.name,
            "node": self.node,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "attrs": self.attrs or {},
        }


class SpanRecorder:
    """Fixed-size ring of spans for one node (or one in-process network —
    MockNetwork nodes share the process-global recorder and distinguish
    themselves via the per-span ``node`` field)."""

    def __init__(self, node_name: str = "", capacity: int = DEFAULT_CAPACITY):
        self.node_name = node_name
        self.capacity = max(1, int(capacity))
        self._ring: list = []
        self._next = 0          # overwrite cursor once the ring is full
        self.dropped = 0        # spans that overwrote an unread slot
        self.recorded = 0
        # request_id -> (trace_id, span_id): the flow→raft correlation map.
        self._links: dict[bytes, tuple] = {}

    # -- recording ---------------------------------------------------------

    def record(self, name: str, t_start: float, t_end: float, *,
               trace_id: bytes | None = None, span_id: bytes | None = None,
               parent: bytes | None = None, node: str | None = None,
               attrs: dict | None = None) -> Span:
        span = Span(
            trace_id if trace_id is not None else new_trace_id(),
            span_id if span_id is not None else new_span_id(),
            parent, name,
            node if node is not None else self.node_name,
            t_start, t_end, attrs,
        )
        ring = self._ring
        if len(ring) < self.capacity:
            ring.append(span)
        else:
            cursor = self._next
            ring[cursor] = span
            self._next = (cursor + 1) % self.capacity
            self.dropped += 1
        self.recorded += 1
        return span

    # -- raft correlation --------------------------------------------------

    def register_link(self, request_id: bytes, trace_id: bytes,
                      span_id: bytes) -> None:
        """Remember which flow trace submitted `request_id` so the raft
        batch seal can stamp member_traces without API plumbing. Bounded:
        a wedged consensus round must not grow this forever."""
        links = self._links
        if len(links) >= LINK_MAP_MAX:
            links.clear()  # rare; losing correlation beats losing memory
        links[request_id] = (trace_id, span_id)

    def pop_link(self, request_id: bytes):
        return self._links.pop(request_id, None)

    def peek_link(self, request_id: bytes):
        return self._links.get(request_id)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """JSON-safe copy of every buffered span (oldest-first)."""
        ring = list(self._ring)
        if len(ring) == self.capacity and self._next:
            ring = ring[self._next:] + ring[:self._next]
        return [s.as_dict() for s in ring]

    def stats(self) -> dict:
        return {
            "recorded": self.recorded,
            "buffered": len(self._ring),
            "dropped": self.dropped,
            "capacity": self.capacity,
            "links": len(self._links),
        }

    def clear(self) -> None:
        self._ring.clear()
        self._next = 0
        self._links.clear()


# ---------------------------------------------------------------------------
# Module-level conveniences over ACTIVE (callers still guard on ACTIVE first)
# ---------------------------------------------------------------------------


def record(name: str, t_start: float, t_end: float, **kw) -> "Span | None":
    rec = ACTIVE
    if rec is None:
        return None
    return rec.record(name, t_start, t_end, **kw)


def register_link(request_id: bytes, trace_id: bytes, span_id: bytes) -> None:
    rec = ACTIVE
    if rec is not None:
        rec.register_link(request_id, trace_id, span_id)


def pop_link(request_id: bytes):
    rec = ACTIVE
    if rec is None:
        return None
    return rec.pop_link(request_id)


# ---------------------------------------------------------------------------
# Current-context: which (trace_id, span_id) is executing on this thread
# ---------------------------------------------------------------------------

_ctx = threading.local()


def set_context(trace_id: bytes, span_id: bytes) -> None:
    _ctx.current = (trace_id, span_id)


def get_context() -> "tuple | None":
    return getattr(_ctx, "current", None)


def clear_context() -> None:
    _ctx.current = None


# ---------------------------------------------------------------------------
# Arming (mirrors faults.arm / disarm / arm_from_env)
# ---------------------------------------------------------------------------


def arm(node_name: str = "", capacity: int = DEFAULT_CAPACITY) -> SpanRecorder:
    global ACTIVE
    recorder = SpanRecorder(node_name, capacity)
    ACTIVE = recorder
    return recorder


def disarm() -> None:
    global ACTIVE
    ACTIVE = None
    clear_context()


def arm_from_env(node_name: str = "") -> "SpanRecorder | None":
    """Arm tracing in a freshly exec'd node process when CORDA_TPU_TRACE is
    set (the driver/loadtest --trace vector; called from node.main() next to
    faults.arm_from_env). Value is "1"/"on" for the default buffer or an
    integer span capacity."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    raw = raw.strip().lower()
    capacity = DEFAULT_CAPACITY
    if raw not in ("1", "on", "true", "yes"):
        try:
            capacity = int(raw)
        except ValueError:
            return None
    return arm(node_name, capacity)


def now() -> float:
    """Epoch seconds — the one clock every span uses so multi-process
    snapshots merge without skew handling beyond NTP's."""
    return time.time()
