"""JAX/XLA kernels — the TPU data plane of corda_tpu.

Batched field arithmetic (fe25519), Ed25519 signature verification
(ed25519_jax) and SHA-256 Merkle hashing (sha256_jax) replace the sequential
per-signature JVM loops on the reference's notary hot path (reference:
core/src/main/kotlin/net/corda/core/transactions/SignedTransaction.kt:83-87).
"""

import os as _os
import sys as _sys


def last_backend_if_loaded():
    """Which kernel backend ("pallas" | "xla" | None) served the newest
    ed25519 verify call — read WITHOUT importing the kernel module. Every
    stamping site (RPC node_metrics, bench config stamps) must use this:
    stamping must never be the thing that pulls jax into a host-only
    process, especially on a host whose accelerator tunnel can wedge."""
    mod = _sys.modules.get("corda_tpu.ops.ed25519_jax")
    if mod is None:
        return None
    try:
        return mod.last_backend()
    except Exception:
        return None


_CPU_SIG: str | None = None


def host_cpu_signature() -> str:
    """Stable 8-hex signature of THIS host's CPU feature set.

    XLA's persistent cache stores AOT-compiled HOST code alongside device
    executables: an entry compiled on a machine with (say) AVX-512 and
    loaded on one without it is a latent SIGILL — MULTICHIP r05's tail was
    full of cpu_aot_loader "Target machine feature ... not supported on the
    host machine" warnings because one shared cache dir served two machine
    types. Every default cache dir (here, the driver's node env, the
    multichip entrypoints) is keyed by this signature so each machine type
    gets its own partition; an explicit CORDA_TPU_JAX_CACHE still wins."""
    global _CPU_SIG
    if _CPU_SIG is None:
        import hashlib
        import platform

        feats = ""
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    # x86 "flags", arm64 "Features"; sorted so kernel
                    # ordering changes don't shift the key.
                    if line.startswith(("flags", "Features")):
                        feats = " ".join(sorted(
                            line.split(":", 1)[1].split()))
                        break
        except OSError:
            pass  # non-procfs platform: machine arch alone partitions
        raw = f"{platform.machine()}|{feats}"
        _CPU_SIG = hashlib.sha256(raw.encode()).hexdigest()[:8]
    return _CPU_SIG


def default_jax_cache_dir() -> str:
    """The shared per-uid, per-machine-type XLA cache path — the ONE
    default used by enable_persistent_compile_cache, the driver's spawned
    node env and the bench/multichip entrypoints, so warm-ups in one
    process hit from every other on the same machine."""
    return f"/tmp/corda_tpu_jax_cache_{_os.getuid()}_{host_cpu_signature()}"


def enable_persistent_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a machine-local dir so
    the kernel zoo compiles once per MACHINE, not once per process. Every
    node process calls this lazily before its first kernel build: a cold
    in-process compile of the Ed25519 graph stalls the node's run loop for
    tens of seconds — long enough to trip RPC timeouts — and a 5-process
    driver cluster would pay it five times over. Idempotent; disable by
    setting CORDA_TPU_JAX_CACHE to an empty string."""
    cache_dir = _os.environ.get("CORDA_TPU_JAX_CACHE")
    if cache_dir is None:
        # Per-uid (a world-predictable shared /tmp path would let another
        # local user plant compiled-code artifacts) and per-CPU-signature
        # (see host_cpu_signature: cross-machine-type reuse risks SIGILL).
        cache_dir = default_jax_cache_dir()
    if not cache_dir:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        want_locations = _os.environ.get(
            "CORDA_TPU_FULL_TRACEBACK_LOCATIONS", "")
        if want_locations.strip().lower() not in ("", "0", "false", "no"):
            jax.config.update("jax_include_full_tracebacks_in_locations",
                              True)
        else:
            # Caller tracebacks embed in the lowered module's debug
            # locations, and for Pallas kernels those locations reach the
            # serialized Mosaic payload — so the CACHE KEY depended on the
            # call site's line numbers (measured: 37 distinct keys for one
            # identical kernel; every source edit or new call site forced
            # a full ~25 s recompile per process, and the cache never hit
            # across differently-shaped callers). Location-free lowering
            # makes the key a function of the kernel alone. Trade-off:
            # XLA error messages lose caller frames — set
            # CORDA_TPU_FULL_TRACEBACK_LOCATIONS=1 when debugging a
            # lowering failure.
            jax.config.update("jax_include_full_tracebacks_in_locations",
                              False)
    # lint: allow(no-silent-except) best-effort config knobs: an older jax without them must not fail import — the cost is slower compiles, not wrong answers
    except Exception:
        pass  # older jax without the knobs: just compile in-process
