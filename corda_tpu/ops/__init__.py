"""JAX/XLA kernels — the TPU data plane of corda_tpu.

Batched field arithmetic (fe25519), Ed25519 signature verification
(ed25519_jax) and SHA-256 Merkle hashing (sha256_jax) replace the sequential
per-signature JVM loops on the reference's notary hot path (reference:
core/src/main/kotlin/net/corda/core/transactions/SignedTransaction.kt:83-87).
"""
