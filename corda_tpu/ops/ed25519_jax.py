"""Batched Ed25519 signature verification as a single JAX/XLA graph.

This is the TPU execution backend for the reference's notary hot loop — the
sequential `for (sig in sigs) EdDSAEngine.verify(...)` at reference:
core/src/main/kotlin/net/corda/core/transactions/SignedTransaction.kt:83-87
(engine built at core/.../crypto/CryptoUtilities.kt:63-96) — re-designed as a
data-parallel kernel: N signatures ride the minor axis of every array and the
whole verification (point decompression, 256-bit double-scalar multiplication,
canonical re-encoding, byte compare) is one jit-compiled graph with static
shapes and `lax.scan` loops.

Semantics are bit-identical to the conformance oracle
(corda_tpu/crypto/ref_ed25519.py — cofactorless ref10 verify, no S<L range
check, silent y mod p reduction on decompression, encode-compare against the
raw R bytes). Golden-vector tests enforce the match.

The SHA-512 challenge h = H(R || A || M) mod L is computed on the host
(hashlib; messages are short and variable-length — a poor fit for fixed-shape
XLA, and a few microseconds per signature against a millisecond-scale kernel).
The elliptic-curve math — ~7700 field multiplies per signature — is where the
time goes, and it is all on-device int32 vector math.
"""

from __future__ import annotations

import hashlib

import numpy as np

import jax
import jax.numpy as jnp

from . import fe25519 as fe
from ..crypto import ref_ed25519 as ref

__all__ = ["verify_batch", "precompute_batch", "verify_arrays", "pick_bucket"]

_D = ref.D
_2D = (2 * ref.D) % ref.P
_SQRT_M1 = pow(2, (ref.P - 1) // 4, ref.P)
_L = ref.L

# Base point in extended coordinates as (20, 1) broadcastable constants.
_BX, _BY = ref.B


def _c(x: int):
    return jnp.asarray(fe.limbs_of_int(x % ref.P), fe.I32)[:, None]


_B_EXT = (_c(_BX), _c(_BY), _c(1), _c(_BX * _BY % ref.P))
_K_D = _c(_D)
_K_2D = _c(_2D)
_K_SQRT_M1 = _c(_SQRT_M1)
_ONE = _c(1)


def _ext_add(p, q):
    """Unified a=-1 twisted-Edwards addition (add-2008-hwcd-3), complete on
    edwards25519 — no exceptional cases, so SIMD lanes never diverge."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, t2), jnp.broadcast_to(_K_2D, t1.shape))
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _psel(mask, p, q):
    return tuple(fe.select(mask, a, b) for a, b in zip(p, q))


def _double_scalar_mult_sub(s_bits, h_bits, neg_a):
    """[s]B + [h](-A) via MSB-first Strauss double-and-add in a lax.scan.

    s may be a full 256-bit integer (no range check — oracle semantics).
    """
    batch = s_bits.shape[1:]
    acc0 = tuple(jnp.broadcast_to(c, (fe.NLIMBS,) + batch)
                 for c in (_c(0), _ONE, _ONE, _c(0)))
    b_ext = tuple(jnp.broadcast_to(c, (fe.NLIMBS,) + batch) for c in _B_EXT)

    def step(acc, bits):
        sb, hb = bits
        acc = _ext_add(acc, acc)
        acc = _psel(sb > 0, _ext_add(acc, b_ext), acc)
        acc = _psel(hb > 0, _ext_add(acc, neg_a), acc)
        return acc, None

    xs = jnp.stack([s_bits, h_bits], axis=1)  # (256, 2, *batch)
    acc, _ = jax.lax.scan(step, acc0, xs)
    return acc


@jax.jit
def verify_arrays(a_limbs, a_sign, r_limbs, r_sign, s_bits, h_bits):
    """The whole-batch verification graph.

    Args (all int32, batch minor):
      a_limbs (20, N): low 255 bits of the A encoding (y, possibly >= p)
      a_sign  (N,):    bit 255 of A
      r_limbs (20, N): low 255 bits of the R encoding — raw, NOT reduced
      r_sign  (N,):    bit 255 of R
      s_bits  (256, N) / h_bits (256, N): scalars, MSB first
    Returns bool (N,): accept/reject per signature.
    """
    one = jnp.broadcast_to(_ONE, a_limbs.shape)

    # --- decompress A (ref10 ge_frombytes semantics) ---
    y = a_limbs
    yy = fe.sq(y)
    u = fe.sub(yy, one)
    v = fe.add(fe.mul(yy, jnp.broadcast_to(_K_D, yy.shape)), one)
    v3 = fe.mul(fe.sq(v), v)
    v7 = fe.mul(fe.sq(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    vxx = fe.mul(v, fe.sq(x))
    ok_direct = fe.eq(vxx, u)
    ok_flip = fe.eq(vxx, fe.neg(u))
    x = fe.select(ok_flip & ~ok_direct,
                  fe.mul(x, jnp.broadcast_to(_K_SQRT_M1, x.shape)), x)
    point_ok = ok_direct | ok_flip
    parity = fe.freeze(x)[0] & 1
    x = fe.select(parity != a_sign, fe.neg(x), x)

    # --- R' = [s]B - [h]A ---
    nx = fe.neg(x)
    neg_a = (nx, y, one, fe.mul(nx, y))
    rx, ry, rz, _ = _double_scalar_mult_sub(s_bits, h_bits, neg_a)

    # --- canonical encode R' and compare with the raw R bytes ---
    zi = fe.inv(rz)
    xr = fe.freeze(fe.mul(rx, zi))
    yr = fe.freeze(fe.mul(ry, zi))
    enc_ok = jnp.all(yr == r_limbs, axis=0) & ((xr[0] & 1) == r_sign)
    return point_ok & enc_ok


def pick_bucket(n: int, buckets=(64, 256, 1024, 4096, 16384)) -> int:
    """Static batch-size bucket: jit caches one executable per bucket instead
    of recompiling per request size (p99 protection on the notary path)."""
    for b in buckets:
        if n <= b:
            return b
    return -(-n // buckets[-1]) * buckets[-1]


def precompute_batch(pubkeys, msgs, sigs, bucket: int | None = None):
    """Host-side packing: 32-byte keys + messages + 64-byte sigs -> kernel arrays.

    Computes h = SHA-512(R_enc || A_enc || M) mod L with the ORIGINAL encodings
    (ref10: the pk bytes go straight into the hash) and pads to the bucket size.
    """
    n = len(sigs)
    b = bucket or pick_bucket(n)
    pk = np.zeros((b, 32), np.uint8)
    r_enc = np.zeros((b, 32), np.uint8)
    s_raw = np.zeros((b, 32), np.uint8)
    h_raw = np.zeros((b, 32), np.uint8)
    for i in range(n):
        pk[i] = np.frombuffer(bytes(pubkeys[i]), np.uint8)
        sig = bytes(sigs[i])
        r_enc[i] = np.frombuffer(sig[:32], np.uint8)
        s_raw[i] = np.frombuffer(sig[32:64], np.uint8)
        h = int.from_bytes(
            hashlib.sha512(sig[:32] + bytes(pubkeys[i]) + bytes(msgs[i])).digest(),
            "little") % _L
        h_raw[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
    a_limbs, a_sign = fe.pack_le_bytes(pk)
    r_limbs, r_sign = fe.pack_le_bytes(r_enc)
    return (a_limbs, a_sign, r_limbs, r_sign,
            fe.scalar_bits_msb(s_raw), fe.scalar_bits_msb(h_raw)), n


def verify_batch(pubkeys, msgs, sigs) -> np.ndarray:
    """End-to-end batched verify: returns bool (len(sigs),).

    Malformed inputs (wrong lengths, junk bytes) reject — never raise —
    matching the reference where verify exceptions surface as rejection
    (reference: core/.../transactions/SignedTransaction.kt:83-87).
    """
    n = len(sigs)
    ok_shape = np.zeros(n, bool)
    good = [i for i in range(n)
            if len(bytes(pubkeys[i])) == 32 and len(bytes(sigs[i])) == 64]
    if not good:
        return ok_shape
    arrays, _ = precompute_batch([pubkeys[i] for i in good],
                                 [msgs[i] for i in good],
                                 [sigs[i] for i in good])
    out = np.asarray(verify_arrays(*arrays))
    for j, i in enumerate(good):
        ok_shape[i] = out[j]
    return ok_shape
