"""Batched Ed25519 signature verification as a single JAX/XLA graph.

This is the TPU execution backend for the reference's notary hot loop — the
sequential `for (sig in sigs) EdDSAEngine.verify(...)` at reference:
core/src/main/kotlin/net/corda/core/transactions/SignedTransaction.kt:83-87
(engine built at core/.../crypto/CryptoUtilities.kt:63-96) — re-designed as a
data-parallel kernel: N signatures ride the minor axis of every array and the
whole verification (point decompression, 4-bit-windowed 256-bit double-scalar
multiplication, canonical re-encoding, byte compare) is one jit graph with
static shapes.

Semantics are bit-identical to the conformance oracle
(corda_tpu/crypto/ref_ed25519.py — cofactorless ref10 verify, no S<L range
check, silent y mod p reduction on decompression, encode-compare against the
raw R bytes). Golden-vector tests enforce the match.

Layout: inputs ship to the device as (8, N) uint32 little-endian words
(128 B/signature over PCIe/the axon tunnel); limb/window unpacking happens
on device. The verification core (`verify_core`) is shape-polymorphic in the
batch dims so the same math runs under plain XLA here and inside the Pallas
VMEM-resident kernel (corda_tpu/ops/ed25519_pallas.py) on (8, 128) vector
blocks.

The SHA-512 challenge h = H(R || A || M) mod L is computed on the host
(hashlib; messages are short and variable-length — a poor fit for fixed-shape
XLA, and a few microseconds per signature against the millisecond-scale curve
math, which is ~3,800 field multiplies per signature on device).
"""

from __future__ import annotations

import hashlib

import numpy as np

import jax
import jax.numpy as jnp

from . import enable_persistent_compile_cache
from . import fe25519 as fe

# Importing this module means kernels are coming: share compiled graphs
# across processes (a driver cluster spawns five nodes; each would
# otherwise pay the cold compile).
enable_persistent_compile_cache()
from ..crypto import ref_ed25519 as ref

__all__ = ["verify_batch", "precompute_batch", "verify_arrays", "pick_bucket",
           "verify_core", "last_pallas_error", "last_backend",
           "reset_pallas_state"]

_D = ref.D
_2D = (2 * ref.D) % ref.P
_SQRT_M1 = pow(2, (ref.P - 1) // 4, ref.P)
_L = ref.L


# Field constants are materialised with fe.fill_limbs (scalar fills) rather
# than module-level jnp arrays: Pallas kernels cannot close over array
# constants, and XLA constant-folds the fills to literals anyway.


def _ext_add(p, q):
    """Unified a=-1 twisted-Edwards addition (add-2008-hwcd-3), complete on
    edwards25519 — no exceptional cases, so SIMD lanes never diverge."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, t2), fe.fill_limbs(_2D, t1.shape[1:]))
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _ext_dbl(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 8 field muls, complete."""
    x1, y1, z1, _ = p
    a = fe.sq(x1)
    b = fe.sq(y1)
    c = fe.mul_small(fe.sq(z1), 2)
    # a_coeff=-1: D = -A; G = D + B = B - A; H = D - B = -(A + B)
    e = fe.sub(fe.sub(fe.sq(fe.add(x1, y1)), a), b)
    g = fe.sub(b, a)
    f = fe.sub(g, c)
    h = fe.neg(fe.add(a, b))
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _masked_sum_entry(table_coords, idx):
    """Per-lane 16-way table lookup as a static mask-sum (no gather; VPU
    elementwise only, so it works identically under XLA and Pallas).

    table_coords: tuple of 4 arrays (16, 20, *batch); idx: (*batch,) int32.
    """
    out = []
    for coord in table_coords:
        acc = coord[0] * (idx == 0).astype(fe.I32)[None]
        for k in range(1, 16):
            acc = acc + coord[k] * (idx == k).astype(fe.I32)[None]
        out.append(acc)
    return tuple(out)


def _build_a_table(neg_a):
    """[0..15]·(-A) as a tuple of 4 stacked (16, 20, *batch) arrays.

    Entries come from the unified add so every one is a valid extended point
    (entry 0 = identity)."""
    x, y, z, t = neg_a
    batch = x.shape[1:]
    zero = fe.fill_limbs(0, batch)
    one = fe.fill_limbs(1, batch)
    entries = [(zero, one, one, zero), neg_a]
    for _ in range(14):
        entries.append(_ext_add(entries[-1], neg_a))
    return tuple(jnp.stack([e[c] for e in entries]) for c in range(4))


# Fixed-base table for B precomputed on host: affine (x, y, t) with z = 1.
def _host_b_table():
    entries = []
    for k in range(16):
        if k == 0:
            entries.append((0, 1, 0))
        else:
            x, y = ref.scalar_mult(k, ref.B)
            entries.append((x, y, x * y % ref.P))
    tab = np.zeros((3, 16, fe.NLIMBS), np.int32)
    for k, (x, y, t) in enumerate(entries):
        tab[0, k] = fe.limbs_of_int(x % ref.P)
        tab[1, k] = fe.limbs_of_int(y % ref.P)
        tab[2, k] = fe.limbs_of_int(t % ref.P)
    return tab


_B_TABLE = _host_b_table()  # (3, 16, 20) int32; z == 1 for every entry


def _b_entry(idx, one, b_table):
    """B-table lookup: static mask-sum, built limb-by-limb from SCALAR table
    entries (scalar * (*batch,) mask broadcasts everywhere, including inside
    Mosaic, which cannot broadcast a (20,) vector along new minor dims).
    b_table indexes like a (3, 16, 20) array — a jnp constant on the XLA
    path, an SMEM ref in the Pallas kernel."""
    masks = [(idx == k).astype(fe.I32) for k in range(16)]
    coords = []
    for c in range(3):
        rows = []
        for limb in range(fe.NLIMBS):
            acc = None
            for k in range(16):
                term = b_table[c, k, limb] * masks[k]
                acc = term if acc is None else acc + term
            rows.append(acc)
        coords.append(jnp.stack(rows))
    return (coords[0], coords[1], one, coords[2])


def _double_scalar_mult_sub(s_nibs, h_nibs, neg_a, b_table,
                            unroll: bool = False):
    """[s]B + [h](-A) via 4-bit windowed Strauss: 64 windows of (4 doublings
    + 2 table adds) — ~2x fewer field multiplies than bit-serial.

    s may be a full 256-bit integer (no range check — oracle semantics).
    s_nibs/h_nibs: (64, *batch) int32 windows, MSB first.
    unroll: trace the 64 windows inline (Pallas) instead of lax.scan (XLA).
    """
    batch = s_nibs.shape[1:]
    a_table = _build_a_table(neg_a)
    one = fe.fill_limbs(1, batch)
    zero = fe.fill_limbs(0, batch)
    acc0 = (zero, one, one, zero)

    def window(acc, s_nib, h_nib):
        for _ in range(4):
            acc = _ext_dbl(acc)
        acc = _ext_add(acc, _b_entry(s_nib, one, b_table))
        acc = _ext_add(acc, _masked_sum_entry(a_table, h_nib))
        return acc

    if unroll:
        acc = acc0
        for t in range(64):
            acc = window(acc, s_nibs[t], h_nibs[t])
        return acc

    def step(acc, nibs):
        return window(acc, nibs[0], nibs[1]), None

    xs = jnp.stack([s_nibs, h_nibs], axis=1)  # (64, 2, *batch)
    acc, _ = jax.lax.scan(step, acc0, xs)
    return acc


# ---------------------------------------------------------------------------
# Device-side unpacking of 32-byte encodings shipped as (8, N) uint32 words.
# Host→device traffic is 8 words per value instead of 256 unpacked int32
# bits / 20 limbs — host packing cost and PCIe/tunnel bytes drop ~18x, and
# the shift/mask unpack fuses into the head of the verify graph.
# ---------------------------------------------------------------------------

def _unpack_limbs(words):
    """(8, *batch) uint32 LE words -> ((20, *batch) int32 limbs of bits
    0..254, (*batch,) int32 sign bit 255).

    Static per-limb loop (Python ints for indices/shifts) — no captured
    index-array constants, so the same code lowers inside Pallas kernels.
    """
    limbs = []
    for i in range(fe.NLIMBS):
        word, shift = (13 * i) // 32, (13 * i) % 32
        lo = words[word] >> jnp.uint32(shift)
        if shift > 19:  # 13 bits spill into the next word
            hi = (words[word + 1] << jnp.uint32(32 - shift)
                  if word + 1 < 8 else jnp.zeros_like(lo))
            lo = lo | hi
        mask = 0xFF if i == fe.NLIMBS - 1 else fe.MASK  # drop bits >= 255
        limbs.append(lo & jnp.uint32(mask))
    sign = (words[7] >> jnp.uint32(31)).astype(jnp.int32)
    return jnp.stack(limbs).astype(fe.I32), sign


def _nibbles_msb(words):
    """(8, *batch) uint32 LE words -> (64, *batch) int32 4-bit windows,
    MSB first. Static per-window loop (Pallas-compatible, as above)."""
    nibs = []
    for j in range(64):
        bit = 255 - 4 * j - 3
        word, shift = bit // 32, bit % 32
        nibs.append((words[word] >> jnp.uint32(shift)) & jnp.uint32(0xF))
    return jnp.stack(nibs).astype(jnp.int32)


def decompress_neg_a(y, a_sign):
    """ref10 ge_frombytes + negate: (point_ok (*batch,), -A extended)."""
    batch = y.shape[1:]
    one = fe.fill_limbs(1, batch)
    yy = fe.sq(y)
    u = fe.sub(yy, one)
    v = fe.add(fe.mul(yy, fe.fill_limbs(_D, batch)), one)
    v3 = fe.mul(fe.sq(v), v)
    v7 = fe.mul(fe.sq(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    vxx = fe.mul(v, fe.sq(x))
    ok_direct = fe.eq(vxx, u)
    ok_flip = fe.eq(vxx, fe.neg(u))
    x = fe.select(ok_flip & ~ok_direct,
                  fe.mul(x, fe.fill_limbs(_SQRT_M1, batch)), x)
    point_ok = ok_direct | ok_flip
    parity = fe.freeze(x)[0] & 1
    x = fe.select(parity != a_sign, fe.neg(x), x)
    nx = fe.neg(x)
    return point_ok, (nx, y, one, fe.mul(nx, y))


def encode_compare(rpoint, r_limbs, r_sign, point_ok):
    """Canonical-encode R' and compare against the raw R bytes."""
    rx, ry, rz, _ = rpoint
    zi = fe.inv(rz)
    xr = fe.freeze(fe.mul(rx, zi))
    yr = fe.freeze(fe.mul(ry, zi))
    enc_ok = jnp.all(yr == r_limbs, axis=0) & ((xr[0] & 1) == r_sign)
    return point_ok & enc_ok


def verify_core(y, a_sign, r_limbs, r_sign, s_nibs, h_nibs,
                b_table=None, unroll: bool = False):
    """The verification math on unpacked values; shape-polymorphic in the
    batch dims (XLA path: batch = (N,); Pallas path: batch = (8, 128)).

    y/(r_limbs): (20, *batch) canonical limbs; signs (*batch,);
    nibs (64, *batch); b_table (3, 16, 20) (defaults to the module constant —
    Pallas passes it as a kernel input). Returns bool (*batch,).
    """
    if b_table is None:
        b_table = jnp.asarray(_B_TABLE)
    point_ok, neg_a = decompress_neg_a(y, a_sign)
    rpoint = _double_scalar_mult_sub(s_nibs, h_nibs, neg_a, b_table, unroll)
    return encode_compare(rpoint, r_limbs, r_sign, point_ok)


@jax.jit
def verify_arrays(a_words, r_words, s_words, h_words):
    """The whole-batch verification graph (plain XLA path).

    Args (all (8, N) uint32, little-endian words, batch minor):
      a_words: the 32-byte A (public key) encodings
      r_words: the 32-byte R encodings — raw, NOT reduced
      s_words: the S scalars (no range check — oracle semantics)
      h_words: SHA-512(R||A||M) mod L, computed on host
    Returns bool (N,): accept/reject per signature.
    """
    y, a_sign = _unpack_limbs(a_words)
    r_limbs, r_sign = _unpack_limbs(r_words)
    return verify_core(y, a_sign, r_limbs, r_sign,
                       _nibbles_msb(s_words), _nibbles_msb(h_words))


def pick_bucket(n: int, buckets=(64, 256, 1024, 4096, 16384, 65536)) -> int:
    """Static batch-size bucket: jit caches one executable per bucket instead
    of recompiling per request size (p99 protection on the notary path)."""
    for b in buckets:
        if n <= b:
            return b
    return -(-n // buckets[-1]) * buckets[-1]


def _words_of(enc: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 little-endian encodings -> (8, B) uint32 words."""
    return np.ascontiguousarray(enc).view("<u4").T.copy()


def precompute_batch(pubkeys, msgs, sigs, bucket: int | None = None):
    """Host-side packing: 32-byte keys + messages + 64-byte sigs -> four
    (8, bucket) uint32 word arrays (A, R, S, h) for verify_arrays.

    Computes h = SHA-512(R_enc || A_enc || M) mod L with the ORIGINAL encodings
    (ref10: the pk bytes go straight into the hash) and pads to the bucket
    size. All bit/limb unpacking happens on device.
    """
    n = len(sigs)
    b = bucket or pick_bucket(n)
    pk_cat, sig_cat, pk, r_enc, s_raw = _pack_pk_rs(pubkeys, sigs, n, b)
    h_raw = np.zeros((b, 32), np.uint8)
    # Per-signature SHA-512 + big-int mod L: both are C-speed (hashlib and
    # CPython long division); a fully vectorized numpy mod-L was measured
    # SLOWER at 64k-signature batches, so the simple loop stays.
    sha512 = hashlib.sha512
    h_rows = h_raw[:n]
    for i in range(n):
        digest = sha512(sig_cat[64 * i:64 * i + 32]
                        + pk_cat[32 * i:32 * i + 32]
                        + bytes(msgs[i])).digest()
        h = int.from_bytes(digest, "little") % _L
        h_rows[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
    return (_words_of(pk), _words_of(r_enc),
            _words_of(s_raw), _words_of(h_raw)), n


def _pack_pk_rs(pubkeys, sigs, n: int, b: int):
    """Shared byte packing: keys + signatures -> padded (b, 32) uint8 arrays
    for A, R, S. Bulk concatenation + one frombuffer per array: ~10x faster
    than per-row numpy assignment at notary batch sizes."""
    pk_cat = b"".join(bytes(k) for k in pubkeys)
    sig_cat = b"".join(bytes(s) for s in sigs)
    pk = np.zeros((b, 32), np.uint8)
    r_enc = np.zeros((b, 32), np.uint8)
    s_raw = np.zeros((b, 32), np.uint8)
    pk[:n] = np.frombuffer(pk_cat, np.uint8).reshape(n, 32)
    sg = np.frombuffer(sig_cat, np.uint8).reshape(n, 64)
    r_enc[:n] = sg[:, :32]
    s_raw[:n] = sg[:, 32:]
    return pk_cat, sig_cat, pk, r_enc, s_raw


_PALLAS_STATE = {
    "available": None,        # None = unprobed; platform capability only
    "consecutive_failures": 0,
    "failures_total": 0,
    "last_error": None,       # formatted traceback of the newest failure
    "last_backend": None,     # "pallas" | "xla": backend of the newest call
}
# After this many failures IN A ROW stop retrying the Pallas kernel for the
# rest of the process (a broken Mosaic toolchain would otherwise pay a full
# recompile per call). One success resets the counter, so a transient
# runtime failure (e.g. a device-allocator stall) demotes only its own call
# — not the whole process, which is what silently cost round 3 its headline.
PALLAS_MAX_CONSECUTIVE_FAILURES = 3

_log = __import__("logging").getLogger("corda_tpu.ops.ed25519")


def _pallas_available() -> bool:
    """The Mosaic kernel needs a real TPU backend (CPU runs the XLA graph);
    CORDA_TPU_NO_PALLAS=1 forces the XLA path for A/B comparison."""
    import os

    if os.environ.get("CORDA_TPU_NO_PALLAS"):
        return False
    if _PALLAS_STATE["available"] is None:
        try:
            _PALLAS_STATE["available"] = jax.devices()[0].platform != "cpu"
        except Exception:
            _PALLAS_STATE["available"] = False
    return (_PALLAS_STATE["available"]
            and _PALLAS_STATE["consecutive_failures"]
            < PALLAS_MAX_CONSECUTIVE_FAILURES)


def last_pallas_error() -> str | None:
    """Formatted traceback of the most recent Pallas failure (None if the
    kernel has never failed). Bench stamps this into its report so a
    fallback is always attributable."""
    return _PALLAS_STATE["last_error"]


def last_backend() -> str | None:
    """Which backend ("pallas"/"xla") the most recent verify_arrays_auto
    call actually dispatched to."""
    return _PALLAS_STATE["last_backend"]


def reset_pallas_state() -> None:
    """Forget failure history (tests; or an operator re-enabling Pallas
    after a fixed environment)."""
    _PALLAS_STATE.update(available=None, consecutive_failures=0,
                         failures_total=0, last_error=None,
                         last_backend=None)


def verify_arrays_auto(a_words, r_words, s_words, h_words):
    """Best available backend for the word-array contract: the VMEM-resident
    Pallas kernel on TPU (batch must be a multiple of 1024), the plain XLA
    graph otherwise.

    A Pallas failure falls back to XLA for THIS call only, loudly: the
    exception is logged with its stack and kept in last_pallas_error().
    Only PALLAS_MAX_CONSECUTIVE_FAILURES failures in a row disable the
    kernel for the rest of the process.
    """
    n = a_words.shape[1]
    if _pallas_available() and n % 1024 == 0:
        from . import ed25519_pallas

        try:
            out = ed25519_pallas.verify_arrays_pallas(
                a_words, r_words, s_words, h_words)
            _PALLAS_STATE["consecutive_failures"] = 0
            _PALLAS_STATE["last_backend"] = "pallas"
            return out
        except Exception:
            import traceback

            _PALLAS_STATE["consecutive_failures"] += 1
            _PALLAS_STATE["failures_total"] += 1
            _PALLAS_STATE["last_error"] = traceback.format_exc()
            _log.exception(
                "Pallas verify failed (n=%d, consecutive failure %d/%d); "
                "falling back to the XLA graph for this call",
                n, _PALLAS_STATE["consecutive_failures"],
                PALLAS_MAX_CONSECUTIVE_FAILURES)
    _PALLAS_STATE["last_backend"] = "xla"
    return verify_arrays(a_words, r_words, s_words, h_words)


def verify_batch(pubkeys, msgs, sigs) -> np.ndarray:
    """End-to-end batched verify: returns bool (len(sigs),).

    Malformed inputs (wrong lengths, junk bytes) reject — never raise —
    matching the reference where verify exceptions surface as rejection
    (reference: core/.../transactions/SignedTransaction.kt:83-87).
    """
    n = len(sigs)
    ok_shape = np.zeros(n, bool)
    good = [i for i in range(n)
            if len(bytes(pubkeys[i])) == 32 and len(bytes(sigs[i])) == 64]
    if not good:
        return ok_shape
    bucket = pick_bucket(len(good))
    if _pallas_available():
        bucket = max(bucket, 1024)  # Pallas blocks are 1024 lanes
    gp = [pubkeys[i] for i in good]
    gm = [msgs[i] for i in good]
    gs = [sigs[i] for i in good]
    verify_fn, arrays, _ = _precompute_auto(gp, gm, gs, bucket)
    out = np.asarray(verify_fn(*arrays))
    for j, i in enumerate(good):
        ok_shape[i] = out[j]
    return ok_shape


def precompute_batch_device(pubkeys, msgs, sigs, bucket: int | None = None):
    """Host packing for the fully-on-device path: NO host hashing. All
    messages must be exactly 32 bytes (the notary workload: tx ids). Returns
    ((a_words, r_words, s_words, m_words), n) for verify_arrays_hashed —
    the per-signature SHA-512 + mod-L loop of precompute_batch becomes a
    batched device graph (ops/sha512_jax.py).

    Packing runs in the native core when available (`_cverify.c
    pack_words`, GIL released): the numpy path's per-item bytes() +
    join + transpose-copy was the measured bottleneck of the depth-2
    streaming pipeline (host pack rate < kernel rate starved the device).
    Identical semantics either way — byte-for-byte equal word arrays,
    same ValueError on non-32-byte messages (parity suite:
    tests/test_ed25519_jax.py::test_native_pack_parity)."""
    n = len(sigs)
    b = bucket or pick_bucket(n)
    native = _cpack_module()
    if native is not None:
        raw_a, raw_r, raw_s, raw_m = native.pack_words(
            pubkeys, msgs, sigs, b)

        def words(raw: bytes) -> np.ndarray:
            return np.frombuffer(raw, "<u4").reshape(8, b)

        return (words(raw_a), words(raw_r), words(raw_s), words(raw_m)), n
    # Per-ITEM checks, not aggregate: mixed lengths summing to the right
    # total would silently re-split at fixed boundaries and verify against
    # scrambled lanes (round-2 advisor finding). Same order and messages
    # as the native packer's want_len loop (pk -> msg -> sig per item) so
    # either path rejects malformed input identically.
    raw = [bytes(m) for m in msgs]
    if len(raw) != n or len(pubkeys) != n:
        raise ValueError("pubkeys, msgs and sigs must have equal length")
    if b < n:
        raise ValueError("bucket smaller than batch")
    for pk, m, s in zip(pubkeys, raw, sigs):
        if len(bytes(pk)) != 32:
            raise ValueError("pubkeys must be 32 bytes")
        if len(m) != 32:
            raise ValueError("device-hash path requires 32-byte messages")
        if len(bytes(s)) != 64:
            raise ValueError("sigs must be 64 bytes")
    m_cat = b"".join(raw)
    _, _, pk, r_enc, s_raw = _pack_pk_rs(pubkeys, sigs, n, b)
    m_raw = np.zeros((b, 32), np.uint8)
    m_raw[:n] = np.frombuffer(m_cat, np.uint8).reshape(n, 32)
    return (_words_of(pk), _words_of(r_enc),
            _words_of(s_raw), _words_of(m_raw)), n


_CPACK_CACHE: list = []


def _cpack_module():
    """The native packer, or None (no toolchain / no libcrypto): the numpy
    path below is the behavioural authority and permanent fallback."""
    if not _CPACK_CACHE:
        try:
            from ..native import load_cverify

            mod = load_cverify()
            _CPACK_CACHE.append(
                mod if mod is not None and hasattr(mod, "pack_words")
                else None)
        except Exception:
            _CPACK_CACHE.append(None)
    return _CPACK_CACHE[0]


def verify_arrays_hashed(a_words, r_words, s_words, m_words):
    """End-to-end device verification for 32-byte messages: the challenge
    h = SHA-512(R||A||M) mod L is computed on device, then fed to the best
    available verify backend (Pallas on TPU, XLA otherwise)."""
    from . import sha512_jax

    h_words = sha512_jax.challenge_words(r_words, a_words, m_words)
    return verify_arrays_auto(a_words, r_words, s_words, h_words)


def device_hash_eligible(msgs) -> bool:
    """The ONE dispatch predicate for host- vs device-hashed verification
    (shared by the single-chip and sharded tiers): all-32-byte messages
    (tx ids) hash on device."""
    return all(len(bytes(m)) == 32 for m in msgs)


def _precompute_auto(pubkeys, msgs, sigs, bucket: int | None):
    """Dispatch per device_hash_eligible. Returns (verify_fn, arrays, n)."""
    if device_hash_eligible(msgs):
        arrays, n = precompute_batch_device(pubkeys, msgs, sigs,
                                            bucket=bucket)
        return verify_arrays_hashed, arrays, n
    arrays, n = precompute_batch(pubkeys, msgs, sigs, bucket=bucket)
    return verify_arrays_auto, arrays, n


def verify_stream(batches, bucket: int | None = None, depth: int = 2):
    """Pipelined streaming verify: yields one bool array per input batch,
    in order.

    ``batches`` is an iterable of (pubkeys, msgs, sigs) triples. JAX
    dispatch is asynchronous, so while up to ``depth`` batches are in
    flight on the device the host packs the next one — host packing,
    host->device transfer and kernel execution all overlap, which is
    exactly the shape of a notary pump under sustained load. Peak device
    residency is ``depth + 1`` batches (4 word arrays each): ``depth``
    already dispatched plus the one being dispatched while the oldest is
    read back. 2 suffices when transfer is fast; deeper helps when the
    link is slow.
    """
    import collections

    import jax

    pending = collections.deque()  # (device_out, n), oldest first
    for pubkeys, msgs, sigs in batches:
        verify_fn, arrays, n = _precompute_auto(pubkeys, msgs, sigs, bucket)
        pending.append((verify_fn(*jax.device_put(arrays)), n))
        if len(pending) > depth:
            prev_out, prev_n = pending.popleft()
            yield np.asarray(prev_out)[:prev_n]
    while pending:
        prev_out, prev_n = pending.popleft()
        yield np.asarray(prev_out)[:prev_n]
