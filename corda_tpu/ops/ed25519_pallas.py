"""VMEM-resident Pallas kernel for batched Ed25519 verification.

The plain-XLA verify graph (corda_tpu/ops/ed25519_jax.py) decomposes into
tens of thousands of small elementwise ops on (N,) lanes; at notary batch
sizes XLA's fusion boundaries leave it kernel-launch/HBM bound, an order of
magnitude off VPU peak. This kernel runs the SAME field math (it composes
ed25519_jax's shape-polymorphic pieces: decompress_neg_a, the windowed
Strauss loop, encode_compare) inside one `pl.pallas_call`: each grid step
loads a (8, 128)-lane block's words into VMEM, and every intermediate limb
array lives in VMEM/VREGs for the whole verification — no HBM round trips
between field ops.

Mosaic-specific shapes of the shared code:
  * the 64-window loop is a fori_loop reading per-window nibbles from VMEM
    scratch refs (lax.scan lowers to dynamic_slice, which Mosaic lacks);
  * the field convolution uses the streaming "rows" lowering (fe.CONV_MODE);
  * the B table arrives as a kernel input (Pallas kernels cannot close over
    array constants).

Block anatomy (per 1024-lane block):
  * inputs: 4 x (8, 8, 128) uint32 word arrays (A, R, S, h) = 128 KiB
  * the -A window table: 16 entries x 4 coords x (20, 8, 128) int32 ~ 5 MiB
  * nibble scratch: 2 x (64, 8, 128) int32 = 512 KiB
  * output: (8, 128) int32 accept mask

Semantics are bit-identical to the oracle and to verify_arrays (the
conformance tests run this kernel in interpreter mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import ed25519_jax as ej
from . import fe25519 as fe

__all__ = ["verify_arrays_pallas", "LANES_PER_BLOCK"]

SUBLANES = 8
LANES = 128
LANES_PER_BLOCK = SUBLANES * LANES  # 1024
_BATCH = (SUBLANES, LANES)


def _kernel(a_ref, r_ref, s_ref, h_ref, btab_ref, ok_ref,
            snib_ref, hnib_ref):
    # Trace-time switch: inside the kernel every value lives in VMEM, so the
    # streaming "rows" convolution is strictly better than the gather form
    # (Mosaic has no XLA-simplifier pathology on the unrolled adds).
    prev, fe.CONV_MODE = fe.CONV_MODE, "rows"
    try:
        y, a_sign = ej._unpack_limbs(a_ref[0])
        r_limbs, r_sign = ej._unpack_limbs(r_ref[0])
        snib_ref[:] = ej._nibbles_msb(s_ref[0])
        hnib_ref[:] = ej._nibbles_msb(h_ref[0])
        btab = btab_ref  # SMEM ref; _b_entry reads scalars from it directly

        point_ok, neg_a = ej.decompress_neg_a(y, a_sign)
        a_table = ej._build_a_table(neg_a)
        one = fe.fill_limbs(1, _BATCH)
        zero = fe.fill_limbs(0, _BATCH)

        def window(t, acc):
            for _ in range(4):
                acc = ej._ext_dbl(acc)
            s_nib = snib_ref[pl.ds(t, 1)][0]  # dynamic VMEM load, not slice
            h_nib = hnib_ref[pl.ds(t, 1)][0]
            acc = ej._ext_add(acc, ej._b_entry(s_nib, one, btab))
            acc = ej._ext_add(acc, ej._masked_sum_entry(a_table, h_nib))
            return acc

        rpoint = jax.lax.fori_loop(0, 64, window, (zero, one, one, zero))
        ok = ej.encode_compare(rpoint, r_limbs, r_sign, point_ok)
        ok_ref[0] = ok.astype(jnp.int32)
    finally:
        fe.CONV_MODE = prev


@functools.partial(jax.jit, static_argnames=("interpret",))
def verify_arrays_pallas(a_words, r_words, s_words, h_words,
                         interpret: bool = False):
    """Same contract as ed25519_jax.verify_arrays — (8, N) uint32 words in,
    bool (N,) out — executed as one VMEM-resident kernel per 1024-lane block.
    N must be a multiple of 1024 (pick_bucket sizes >= 1024 all are).
    """
    n = a_words.shape[1]
    if n % LANES_PER_BLOCK:
        raise ValueError(f"batch {n} not a multiple of {LANES_PER_BLOCK}")
    nb = n // LANES_PER_BLOCK

    def shape_in(w):  # (8, N) -> (nb, 8, 8, 128), blocks major
        return w.reshape(8, nb, SUBLANES, LANES).transpose(1, 0, 2, 3)

    ins = [shape_in(w) for w in (a_words, r_words, s_words, h_words)]
    in_spec = pl.BlockSpec((1, 8, SUBLANES, LANES), lambda i: (i, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    btab_spec = pl.BlockSpec((3, 16, 20), lambda i: (0, 0, 0),
                             memory_space=pltpu.SMEM)
    out = pl.pallas_call(
        _kernel,
        grid=(nb,),
        in_specs=[in_spec] * 4 + [btab_spec],
        out_specs=pl.BlockSpec((1, SUBLANES, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb, SUBLANES, LANES), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((64, SUBLANES, LANES), jnp.int32),
            pltpu.VMEM((64, SUBLANES, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(*ins, jnp.asarray(ej._B_TABLE))
    return out.reshape(n).astype(bool)
