"""Batched GF(2^255-19) arithmetic in int32 limbs for TPU.

This is the field layer under the batched Ed25519 verify kernel
(corda_tpu/ops/ed25519_jax.py) — the TPU-native replacement for the
per-signature Java bigint math the reference runs sequentially on the notary hot
path (reference: core/src/main/kotlin/net/corda/core/transactions/
SignedTransaction.kt:83-87 via core/.../crypto/CryptoUtilities.kt:90-96).

Representation
--------------
A field element is 20 limbs of 13 bits in int32, **limb-major**: an array of
shape ``(20, *batch)`` so the batch dimension is minor and rides the TPU VPU
lanes at full width. Values are redundant (any value < 2^260 congruent mod p);
``freeze`` produces the canonical representative in [0, p).

Why radix 2^13 / int32: TPUs have no native 64-bit multiply and JAX runs
x64-disabled; 13-bit limbs give products <= 2^26 whose 20-term convolution
sums stay under 2^31, so everything lives in ordinary int32 lanes. 2^260 ===
608 (mod p) folds the high half of products back down (608 = 19 * 2^5).

All functions are shape-polymorphic in the batch dims and jit/vmap/shard_map
friendly (static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

RADIX = 13
NLIMBS = 20
MASK = (1 << RADIX) - 1
NCOEF = 2 * NLIMBS - 1  # 39
P = 2**255 - 19
FOLD = 608  # 2^260 mod p

I32 = jnp.int32


def limbs_of_int(x: int) -> np.ndarray:
    """Python int (0 <= x < 2^260) -> (20,) int32 limb array (numpy, host)."""
    if not 0 <= x < 1 << (RADIX * NLIMBS):
        raise ValueError("value out of limb range")
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)], np.int32)


def int_of_limbs(limbs) -> int:
    """(20, ...) limb array -> python int(s); host-side test helper."""
    arr = np.asarray(limbs)
    return sum(int(arr[i]) << (RADIX * i) for i in range(arr.shape[0]))


def const(x: int, batch_shape=()) -> jnp.ndarray:
    """Broadcast a host integer constant to a (20, *batch) field element."""
    c = jnp.asarray(limbs_of_int(x % P), I32)
    return jnp.broadcast_to(c.reshape((NLIMBS,) + (1,) * len(batch_shape)),
                            (NLIMBS,) + tuple(batch_shape))


def _carry(x: jnp.ndarray):
    """Signed carry propagation along axis 0. Returns (limbs in [0,2^13), carry_out).

    Works for negative inputs: `& MASK` / arithmetic `>> RADIX` implement
    floor-division semantics in two's complement.
    """
    out = []
    c = jnp.zeros(x.shape[1:], I32)
    for i in range(x.shape[0]):
        t = x[i] + c
        out.append(t & MASK)
        c = t >> RADIX
    return jnp.stack(out), c


def reduce(x: jnp.ndarray) -> jnp.ndarray:
    """Weak-reduce an (n, *batch) signed limb array (n in [20, 39]) to 20 limbs
    in [0, 2^13), value congruent mod p, value < 2^260."""
    limbs, c = _carry(x)
    if x.shape[0] > NLIMBS:
        # Fold limbs at positions >= 20 (weight 2^(260+13k) === 608*2^13k).
        # Carry out of an n-limb input has weight 2^(13n): it sits right after
        # limbs[20:n] in the folded vector, before any zero padding.
        pad = NCOEF - x.shape[0]
        high = jnp.concatenate([limbs[NLIMBS:], c[None]])
        if pad:
            high = jnp.concatenate([high, jnp.zeros((pad,) + x.shape[1:], I32)])
        v = limbs[:NLIMBS] + FOLD * high
        limbs, c = _carry(v)
    # Fold the (possibly negative) carry-out at weight 2^260 twice; the second
    # pass always lands with zero carry (|c| shrinks by ~2^13 per round).
    for _ in range(2):
        v = jnp.concatenate([(limbs[0] + FOLD * c)[None], limbs[1:]])
        limbs, c = _carry(v)
    return limbs


def add(a, b):
    return reduce(a + b)


def sub(a, b):
    return reduce(a - b)


def neg(a):
    return reduce(-a)


def mul(a, b):
    """Field multiply. Inputs must be weak-reduced (limbs in [0, 2^13))."""
    batch = a.shape[1:]
    acc = jnp.zeros((NCOEF,) + batch, I32)
    for i in range(NLIMBS):
        seg = acc[i:i + NLIMBS] + a[i] * b
        acc = jnp.concatenate([acc[:i], seg, acc[i + NLIMBS:]])
    return reduce(acc)


def sq(a):
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small host constant k (k*2^13*20 must fit int32)."""
    return reduce(a * np.int32(k))


def _pow_bits(x, exponent: int):
    """x^exponent via MSB-first square-and-multiply inside a lax.scan
    (keeps the XLA graph ~2 muls instead of ~2*255 unrolled)."""
    bits = [int(b) for b in bin(exponent)[2:]]
    bits_arr = jnp.asarray(bits[1:], I32)  # leading 1 -> start acc = x

    def step(acc, bit):
        acc = mul(acc, acc)
        withx = mul(acc, x)
        acc = jnp.where(bit > 0, withx, acc)
        return acc, None

    acc, _ = jax.lax.scan(step, x, bits_arr)
    return acc


def inv(a):
    """a^(p-2); inv(0) = 0 (no division, malformed lanes stay finite)."""
    return _pow_bits(a, P - 2)


def pow_p58(a):
    """a^((p-5)/8) — the candidate-root exponent for decompression."""
    return _pow_bits(a, (P - 5) // 8)


# Precomputed k*p limb constants for the freeze ladder (k*p < 2^260 for k<=32).
_KP = {k: jnp.asarray(limbs_of_int(k * P), I32) for k in (32, 16, 8, 4, 2, 1)}


def freeze(a):
    """Canonical representative in [0, p) of a weak-reduced element.

    Binary ladder of conditional subtractions: value < 2^260 < 64p, so
    subtracting k*p for k = 32,16,...,1 whenever value >= k*p lands in [0,p).
    """
    v = a
    batch_nd = a.ndim - 1
    for k in (32, 16, 8, 4, 2, 1):
        kp = _KP[k].reshape((NLIMBS,) + (1,) * batch_nd)
        d, c = _carry(v - kp)
        v = jnp.where((c < 0)[None], v, d)
    return v


def is_zero(a):
    """Boolean batch mask: a === 0 (mod p). Input weak-reduced."""
    return jnp.all(freeze(a) == 0, axis=0)


def eq(a, b):
    return is_zero(sub(a, b))


def select(mask, a, b):
    """Per-lane select: mask has batch shape, a/b are field elements."""
    return jnp.where(mask[None], a, b)


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy; feed the kernel from 32-byte encodings)
# ---------------------------------------------------------------------------

_LIMB_WEIGHTS = (1 << np.arange(RADIX, dtype=np.int64)).astype(np.int32)


def pack_le_bytes(enc: np.ndarray):
    """(N, 32) uint8 little-endian encodings -> (limbs (20, N) int32 of the low
    255 bits, sign (N,) int32 of bit 255). Vectorized, no Python ints."""
    enc = np.ascontiguousarray(enc, np.uint8)
    bits = np.unpackbits(enc, axis=1, bitorder="little")  # (N, 256)
    sign = bits[:, 255].astype(np.int32)
    low = np.concatenate(
        [bits[:, :255], np.zeros((enc.shape[0], NLIMBS * RADIX - 255), np.uint8)],
        axis=1,
    )
    limbs = low.reshape(-1, NLIMBS, RADIX).astype(np.int32) @ _LIMB_WEIGHTS
    return limbs.T.copy(), sign


def scalar_bits_msb(raw: np.ndarray):
    """(N, 32) uint8 little-endian scalars -> (256, N) int32 bits, MSB first."""
    bits = np.unpackbits(np.ascontiguousarray(raw, np.uint8), axis=1,
                         bitorder="little")
    return bits[:, ::-1].T.astype(np.int32).copy()
