"""Batched GF(2^255-19) arithmetic in int32 limbs for TPU.

This is the field layer under the batched Ed25519 verify kernel
(corda_tpu/ops/ed25519_jax.py) — the TPU-native replacement for the
per-signature Java bigint math the reference runs sequentially on the notary hot
path (reference: core/src/main/kotlin/net/corda/core/transactions/
SignedTransaction.kt:83-87 via core/.../crypto/CryptoUtilities.kt:90-96).

Representation
--------------
A field element is 20 limbs of 13 bits in int32, **limb-major**: an array of
shape ``(20, *batch)`` so the batch dimension is minor and rides the TPU VPU
lanes at full width. Values are redundant (any value < 2^260 congruent mod p);
``freeze`` produces the canonical representative in [0, p).

Why radix 2^13 / int32: TPUs have no native 64-bit multiply and JAX runs
x64-disabled; 13-bit limbs give products <= 2^26 whose 20-term convolution
sums stay under 2^31, so everything lives in ordinary int32 lanes. 2^260 ===
608 (mod p) folds the high half of products back down (608 = 19 * 2^5).

All functions are shape-polymorphic in the batch dims and jit/vmap/shard_map
friendly (static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

RADIX = 13
NLIMBS = 20
MASK = (1 << RADIX) - 1
NCOEF = 2 * NLIMBS - 1  # 39
P = 2**255 - 19
FOLD = 608  # 2^260 mod p

I32 = jnp.int32


def limbs_of_int(x: int) -> np.ndarray:
    """Python int (0 <= x < 2^260) -> (20,) int32 limb array (numpy, host)."""
    if not 0 <= x < 1 << (RADIX * NLIMBS):
        raise ValueError("value out of limb range")
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)], np.int32)


def int_of_limbs(limbs) -> int:
    """(20, ...) limb array -> python int(s); host-side test helper."""
    arr = np.asarray(limbs)
    return sum(int(arr[i]) << (RADIX * i) for i in range(arr.shape[0]))


def const(x: int, batch_shape=()) -> jnp.ndarray:
    """Broadcast a host integer constant to a (20, *batch) field element."""
    c = jnp.asarray(limbs_of_int(x % P), I32)
    return jnp.broadcast_to(c.reshape((NLIMBS,) + (1,) * len(batch_shape)),
                            (NLIMBS,) + tuple(batch_shape))


def _carry(x: jnp.ndarray):
    """Signed carry propagation along axis 0. Returns (limbs in [0,2^13), carry_out).

    Works for negative inputs: `& MASK` / arithmetic `>> RADIX` implement
    floor-division semantics in two's complement.
    """
    out = []
    c = jnp.zeros(x.shape[1:], I32)
    for i in range(x.shape[0]):
        t = x[i] + c
        out.append(t & MASK)
        c = t >> RADIX
    return jnp.stack(out), c


def normalize(x: jnp.ndarray) -> jnp.ndarray:
    """Exact weak reduction: (20, *batch) signed bounded limbs -> limbs in
    [0, 2^13), value congruent mod p, value < 2^260.

    Uses the sequential carry chain — precise but expensive to compile, so it
    runs ONLY at comparison/canonicalisation points (freeze/eq/is_zero); the
    arithmetic interior uses the vectorized lazy `_settle` rounds instead.
    """
    limbs, c = _carry(x)
    # Fold the (possibly negative) carry-out at weight 2^260 twice; the second
    # pass always lands with zero carry (|c| shrinks by ~2^13 per round).
    for _ in range(2):
        v = jnp.concatenate([(limbs[0] + FOLD * c)[None], limbs[1:]])
        limbs, c = _carry(v)
    return limbs


def _settle(x: jnp.ndarray) -> jnp.ndarray:
    """One vectorized lazy-carry round on (20, *batch) signed limbs: split
    off the 13-bit residue, push carries up one limb, fold the top carry
    (weight 2^260 === 608) back to limb 0. All elementwise — compiles to a
    handful of fused ops, no sequential chain.

    Bound: |x| <= M  ->  output in (-609*M/8192, 8192 + 609*M/8192).
    Fixed point ~8850, so two rounds bring any |x| <= 19000 below the
    mul-input bound (see `mul`).
    """
    hi = x >> RADIX  # arithmetic shift: floor semantics for negatives
    lo = x & MASK
    # Static indices only: hi[-1]/hi[:-1] would lower to dynamic_slice,
    # which Mosaic (Pallas TPU) does not implement.
    top = x.shape[0] - 1
    up = jnp.concatenate([(hi[top] * FOLD)[None], hi[0:top]])
    return lo + up


# Lazy-arithmetic contract:
#   * every op below returns limbs bounded by ~|9500| (usually ~8900);
#   * `mul` accepts limb magnitudes up to 10000 (20 * 10000^2 < 2^31);
#   * canonical form exists only after normalize()/freeze().


def add(a, b):
    return _settle(_settle(a + b))


def sub(a, b):
    return _settle(_settle(a - b))


def neg(a):
    return _settle(_settle(-a))


# Static gather pattern for the 20x20 schoolbook convolution: coefficient k
# sums O[i, k-i] over valid i.
_CONV_K = np.arange(NCOEF)[:, None]          # (39, 1)
_CONV_I = np.arange(NLIMBS)[None, :]         # (1, 20)
_CONV_J = np.clip(_CONV_K - _CONV_I, 0, NLIMBS - 1)  # (39, 20)
_CONV_VALID = ((_CONV_K - _CONV_I >= 0) & (_CONV_K - _CONV_I < NLIMBS)
               ).astype(np.int32)            # (39, 20)

# Two convolution lowerings with identical semantics:
#   "gather": one outer product + static gather + masked reduce — tiny HLO
#             graph (compiles fast), at the cost of a (20, 20, *batch)
#             intermediate the backend must fuse or spill;
#   "rows":   39 unrolled row sums of elementwise products — large HLO graph
#             (slow XLA compile) but pure streaming VPU ops.
# The Pallas kernel (everything in VMEM) uses "rows"; the plain XLA path
# defaults to "gather".
CONV_MODE = "gather"


def _conv_sum(a, b):
    if CONV_MODE == "rows":
        rows = []
        for k in range(NCOEF):
            terms = [a[i] * b[k - i]
                     for i in range(max(0, k - NLIMBS + 1), min(NLIMBS, k + 1))]
            s = terms[0]
            for t in terms[1:]:
                s = s + t
            rows.append(s)
        return jnp.stack(rows)
    outer = a[:, None] * b[None, :]          # (20, 20, *batch)
    gathered = outer[_CONV_I.ravel()[None, :].repeat(NCOEF, 0), _CONV_J]
    mask = jnp.asarray(_CONV_VALID).reshape(
        (NCOEF, NLIMBS) + (1,) * (a.ndim - 1))
    return jnp.sum(gathered * mask, axis=1)


def mul(a, b):
    """Field multiply: limbs |.| <= 10000 in, limbs in (-1500, 8900) out.

    Schoolbook convolution (see _conv_sum) followed by vectorized carry
    rounds — no sequential carry chain, no scatter.
    """
    acc = _conv_sum(a, b)                     # (39, *batch), |.| < 2^31
    # Two carry rounds over the 41 coefficient positions (carries out of the
    # top ride along), bringing every position under ~2^13.01 ...
    ext = jnp.concatenate(
        [acc, jnp.zeros((2,) + acc.shape[1:], I32)])  # (41, *batch)
    for _ in range(2):
        hi = ext >> RADIX
        ext = (ext & MASK) + jnp.concatenate(
            [jnp.zeros((1,) + hi.shape[1:], I32), hi[0:ext.shape[0] - 1]])
    # ... then fold positions 20..40 down (2^(260+13k) === 608 * 2^13k;
    # position 40 === 608^2 at position 0) and settle.
    v = ext[:NLIMBS] + FOLD * ext[NLIMBS:2 * NLIMBS]
    top = jnp.concatenate(
        [(FOLD * FOLD * ext[2 * NLIMBS])[None],
         jnp.zeros((NLIMBS - 1,) + v.shape[1:], I32)])
    v = v + top
    for _ in range(5):
        v = _settle(v)
    return v


def sq(a):
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small host constant k (|k| <= 16: k * 9500 * 609/8192
    settles below the mul bound in three rounds)."""
    v = a * np.int32(k)
    for _ in range(3):
        v = _settle(v)
    return v


def _pow_bits(x, exponent: int):
    """x^exponent via MSB-first square-and-multiply in a fori_loop (keeps
    the graph ~2 muls instead of ~2*255 unrolled; fori rather than scan so
    the same code lowers inside Pallas kernels).

    The bit at each step is computed from the loop index by comparing against
    the exponent's zero positions — scalar arithmetic only, no captured bit
    array (Pallas kernels cannot close over array constants). Efficient for
    the near-all-ones exponents used here (p-2 has two zero bits, (p-5)/8
    has one).
    """
    bits = [int(b) for b in bin(exponent)[2:]][1:]  # leading 1 -> acc = x
    zero_positions = [i for i, b in enumerate(bits) if b == 0]

    def step(i, acc):
        acc = mul(acc, acc)
        withx = mul(acc, x)
        bit = jnp.bool_(True)
        for z in zero_positions:
            bit = bit & (i != z)
        return jnp.where(bit, withx, acc)

    return jax.lax.fori_loop(0, len(bits), step, x)


def inv(a):
    """a^(p-2); inv(0) = 0 (no division, malformed lanes stay finite)."""
    return _pow_bits(a, P - 2)


def pow_p58(a):
    """a^((p-5)/8) — the candidate-root exponent for decompression."""
    return _pow_bits(a, (P - 5) // 8)


def fill_limbs(value: int, batch_shape) -> jnp.ndarray:
    """(20, *batch) constant built from scalar fills — usable inside Pallas
    kernels, which cannot close over array constants; XLA constant-folds it
    to the same thing as a literal array."""
    host = limbs_of_int(value % (1 << (RADIX * NLIMBS)))
    return jnp.stack([jnp.full(tuple(batch_shape), int(l), I32) for l in host])


# k*p limb values for the freeze ladder (k*p < 2^260 for k <= 32).
_KP_INT = {k: k * P for k in (32, 16, 8, 4, 2, 1)}


def freeze(a):
    """Canonical representative in [0, p) of a (possibly lazy) element.

    Normalizes to exact weak-reduced form first, then a binary ladder of
    conditional subtractions: value < 2^260 < 64p, so subtracting k*p for
    k = 32,16,...,1 whenever value >= k*p lands in [0,p).
    """
    v = normalize(a)
    batch = a.shape[1:]
    for k in (32, 16, 8, 4, 2, 1):
        d, c = _carry(v - fill_limbs(_KP_INT[k], batch))
        v = jnp.where((c < 0)[None], v, d)
    return v


def is_zero(a):
    """Boolean batch mask: a === 0 (mod p). Input weak-reduced."""
    return jnp.all(freeze(a) == 0, axis=0)


def eq(a, b):
    return is_zero(sub(a, b))


def select(mask, a, b):
    """Per-lane select: mask has batch shape, a/b are field elements."""
    return jnp.where(mask[None], a, b)


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy; feed the kernel from 32-byte encodings)
# ---------------------------------------------------------------------------

_LIMB_WEIGHTS = (1 << np.arange(RADIX, dtype=np.int64)).astype(np.int32)


def pack_le_bytes(enc: np.ndarray):
    """(N, 32) uint8 little-endian encodings -> (limbs (20, N) int32 of the low
    255 bits, sign (N,) int32 of bit 255). Vectorized, no Python ints."""
    enc = np.ascontiguousarray(enc, np.uint8)
    bits = np.unpackbits(enc, axis=1, bitorder="little")  # (N, 256)
    sign = bits[:, 255].astype(np.int32)
    low = np.concatenate(
        [bits[:, :255], np.zeros((enc.shape[0], NLIMBS * RADIX - 255), np.uint8)],
        axis=1,
    )
    limbs = low.reshape(-1, NLIMBS, RADIX).astype(np.int32) @ _LIMB_WEIGHTS
    return limbs.T.copy(), sign


def scalar_bits_msb(raw: np.ndarray):
    """(N, 32) uint8 little-endian scalars -> (256, N) int32 bits, MSB first."""
    bits = np.unpackbits(np.ascontiguousarray(raw, np.uint8), axis=1,
                         bitorder="little")
    return bits[:, ::-1].T.astype(np.int32).copy()
