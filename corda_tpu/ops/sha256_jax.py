"""Batched SHA-256 as a JAX kernel — the second notary hot spot.

The reference computes every transaction id as a Merkle root over
per-component SHA-256 hashes, sequentially on the JVM (reference:
core/src/main/kotlin/net/corda/core/transactions/WireTransaction.kt:45-52,
core/.../transactions/MerkleTransaction.kt:26-38,62-99).  At notary batch
sizes that is thousands of small hashes per micro-batch; on TPU they all ride
one fixed-shape graph: the 64-round compression runs in a ``lax.scan`` with
the batch axis minor, so N messages hash in lock-step on the VPU lanes.

Layout mirrors fe25519: words are uint32, arrays are word-major / batch-minor
(``(16, N)`` words per block), all shapes static.  Messages of equal padded
block count share one executable; the host packer buckets by block count.

Byte-identical to hashlib.sha256 — golden-vector tests enforce it.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "sha256_blocks", "pack_messages", "sha256_fixed", "sha256_many",
    "sha256_pair_words", "merkle_root_device", "merkle_roots_device",
    "hash_many_auto",
]

# Below this many messages one hashlib loop beats the kernel end-to-end:
# the device win is batch-parallelism, and host packing + transfer overhead
# amortises only at scale. Measured on the axon-tunnelled v5e (2026-07-30):
# kernel-resident crosses hashlib at ~64k hashes (534k/s vs 442k/s), while
# TRANSFER-inclusive e2e stays host-bound on the ~5 MB/s tunnel; a directly-
# attached chip (PCIe/ICI, GB/s) moves the crossover down by orders of
# magnitude. Override with CORDA_TPU_SHA256_DEVICE_MIN.
DEVICE_MIN_HASHES_DEFAULT = 65536

U32 = jnp.uint32

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], np.uint32)


def _rotr(x, n):
    return (x >> U32(n)) | (x << U32(32 - n))


def _compress(state, block):
    """One compression: state (8, N) uint32, block (16, N) uint32."""

    def round_step(carry, k):
        (a, b, c, d, e, f, g, h), win = carry
        w = win[0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + w
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        # W[t+16] = s1(W[t+14]) + W[t+9] + s0(W[t+1]) + W[t]
        ls0 = _rotr(win[1], 7) ^ _rotr(win[1], 18) ^ (win[1] >> U32(3))
        ls1 = _rotr(win[14], 17) ^ _rotr(win[14], 19) ^ (win[14] >> U32(10))
        neww = ls1 + win[9] + ls0 + win[0]
        win = jnp.concatenate([win[1:], neww[None]])
        return ((t1 + t2, a, b, c, d + t1, e, f, g), win), None

    init = (tuple(state[i] for i in range(8)), block)
    (vars_, _), _ = jax.lax.scan(round_step, init, jnp.asarray(_K, U32))
    return state + jnp.stack(vars_)


@partial(jax.jit, static_argnames=())
def sha256_blocks(blocks):
    """Full hash over pre-padded blocks: (nblocks, 16, N) uint32 -> (8, N).

    The block axis is scanned (sequential chaining is inherent to SHA-256);
    all batch-wise parallelism is in the minor axis.
    """
    n = blocks.shape[-1]
    state0 = jnp.broadcast_to(jnp.asarray(_H0, U32)[:, None], (8, n))

    def step(state, block):
        return _compress(state, block), None

    state, _ = jax.lax.scan(step, state0, blocks)
    return state


def pack_messages(msgs: np.ndarray) -> np.ndarray:
    """Pad equal-length messages: (N, L) uint8 -> (nblocks, 16, N) uint32.

    Standard SHA-256 padding (0x80, zeros, 64-bit big-endian bit length).
    """
    msgs = np.ascontiguousarray(msgs, np.uint8)
    n, length = msgs.shape
    nblocks = (length + 8) // 64 + 1
    padded = np.zeros((n, nblocks * 64), np.uint8)
    padded[:, :length] = msgs
    padded[:, length] = 0x80
    padded[:, -8:] = np.frombuffer(
        (length * 8).to_bytes(8, "big"), np.uint8)
    words = padded.reshape(n, nblocks, 16, 4)
    words = (words[..., 0].astype(np.uint32) << 24
             | words[..., 1].astype(np.uint32) << 16
             | words[..., 2].astype(np.uint32) << 8
             | words[..., 3].astype(np.uint32))
    return np.transpose(words, (1, 2, 0)).copy()  # (nblocks, 16, N)


def _digest_bytes(state) -> np.ndarray:
    """(8, N) uint32 device state -> (N, 32) uint8 big-endian digests."""
    st = np.asarray(state).T  # (N, 8)
    return np.ascontiguousarray(st.astype(">u4")).view(np.uint8).reshape(-1, 32)


def sha256_fixed(msgs: np.ndarray) -> np.ndarray:
    """Batched digest of equal-length messages: (N, L) uint8 -> (N, 32) uint8."""
    return _digest_bytes(sha256_blocks(jnp.asarray(pack_messages(msgs), U32)))


def sha256_many(msgs: list[bytes]) -> list[bytes]:
    """Digest a ragged batch, bucketed by padded block count.

    Messages sharing a block count run as one kernel call (their individual
    length padding is applied on the host, so in-bucket lengths may differ).
    """
    out: list[bytes | None] = [None] * len(msgs)
    buckets: dict[int, list[int]] = {}
    for i, m in enumerate(msgs):
        buckets.setdefault((len(m) + 8) // 64 + 1, []).append(i)
    for nblocks, idxs in buckets.items():
        packed = np.zeros((len(idxs), nblocks, 16), np.uint32)
        for j, i in enumerate(idxs):
            m = msgs[i]
            sub = pack_messages(np.frombuffer(m, np.uint8)[None])
            packed[j] = sub[:, :, 0]
        blocks = jnp.asarray(np.transpose(packed, (1, 2, 0)), U32)
        digests = _digest_bytes(sha256_blocks(blocks))
        for j, i in enumerate(idxs):
            out[i] = digests[j].tobytes()
    return out  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# Merkle tree reduction on device
# ---------------------------------------------------------------------------

# A 64-byte message is exactly one data block plus one constant padding block.
_PAD_BLOCK_64 = pack_messages(np.zeros((1, 64), np.uint8))[1, :, 0]  # (16,)


@jax.jit
def sha256_pair_words(left, right):
    """Merkle node hash sha256(l || r) fully in words.

    left/right: (8, N) uint32 digests -> (8, N) uint32 digest.
    """
    n = left.shape[-1]
    block1 = jnp.concatenate([left, right])  # (16, N)
    state = _compress(jnp.broadcast_to(jnp.asarray(_H0, U32)[:, None], (8, n)),
                      block1)
    pad = jnp.broadcast_to(jnp.asarray(_PAD_BLOCK_64, U32)[:, None], (16, n))
    return _compress(state, pad)


def hash_many_auto(msgs: list[bytes],
                   device_min: int | None = None) -> tuple[list[bytes], str]:
    """(digests, backend): hashlib below the crossover batch size, the
    batched device kernel at or above it. The ONE dispatch predicate for
    framework bulk hashing (the resolve path's tx-id recomputation); the
    host path is also the fallback if the kernel fails — semantics are
    bit-identical either way."""
    import hashlib
    import os

    if device_min is None:
        device_min = int(os.environ.get("CORDA_TPU_SHA256_DEVICE_MIN",
                                        DEVICE_MIN_HASHES_DEFAULT))
    if len(msgs) >= device_min:
        try:
            return sha256_many(msgs), "device"
        except Exception:
            import logging

            logging.getLogger("corda_tpu.ops.sha256").exception(
                "device sha256 failed for %d messages; host fallback",
                len(msgs))
    return [hashlib.sha256(m).digest() for m in msgs], "host"


def merkle_roots_device(leaf_digest_groups: list[list[bytes]]) -> list[bytes]:
    """Many Merkle roots (odd-duplicate rule) in batched device calls.

    Trees are bucketed by leaf count; every same-count tree reduces
    level-by-level TOGETHER (one sha256_pair_words call hashes the level's
    nodes of every tree in the bucket). The per-tree semantics match
    crypto.merkle.MerkleTree.build bit-for-bit.
    """
    out: list[bytes | None] = [None] * len(leaf_digest_groups)
    buckets: dict[int, list[int]] = {}
    for i, leaves in enumerate(leaf_digest_groups):
        if not leaves:
            raise ValueError("Cannot calculate Merkle root on empty hash list.")
        buckets.setdefault(len(leaves), []).append(i)
    for n_leaves, idxs in buckets.items():
        m = len(idxs)
        flat = b"".join(b"".join(leaf_digest_groups[i]) for i in idxs)
        arr = np.frombuffer(flat, np.uint8).reshape(m * n_leaves, 32)
        words = np.ascontiguousarray(arr).view(">u4").astype(np.uint32)
        level = jnp.asarray(words.reshape(m, n_leaves, 8).transpose(2, 0, 1),
                            U32)  # (8, m, L)
        width = n_leaves
        while width > 1:
            if width % 2:
                level = jnp.concatenate([level, level[:, :, -1:]], axis=2)
                width += 1
            left = level[:, :, 0::2].reshape(8, -1)
            right = level[:, :, 1::2].reshape(8, -1)
            level = sha256_pair_words(left, right).reshape(8, m, width // 2)
            width //= 2
        digests = _digest_bytes(level.reshape(8, m))
        for j, i in enumerate(idxs):
            out[i] = digests[j].tobytes()
    return out  # type: ignore[return-value]


def merkle_root_device(leaf_hashes: list[bytes]) -> bytes:
    """Merkle root with the reference's odd-node-duplicate rule, reduced
    level-by-level on device (MerkleTransaction.kt:62-99 semantics — matches
    corda_tpu.crypto.merkle.MerkleTree.build bit-for-bit).
    """
    if not leaf_hashes:
        raise ValueError("Cannot calculate Merkle root on empty hash list.")
    arr = np.frombuffer(b"".join(leaf_hashes), np.uint8).reshape(-1, 32)
    words = np.ascontiguousarray(arr).view(">u4").astype(np.uint32).T  # (8, N)
    level = jnp.asarray(words, U32)
    while level.shape[1] > 1:
        if level.shape[1] % 2:
            level = jnp.concatenate([level, level[:, -1:]], axis=1)
        level = sha256_pair_words(level[:, 0::2], level[:, 1::2])
    return _digest_bytes(level)[0].tobytes()
