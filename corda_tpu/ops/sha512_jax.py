"""Batched single-block SHA-512 + mod-L scalar reduction on device.

This moves the last host-side stage of Ed25519 verification onto the TPU.
The challenge scalar h = SHA-512(R || A || M) mod L was computed per
signature in Python (``ed25519_jax.precompute_batch``) — at 64k-signature
buckets that loop is as expensive as the whole device kernel. For the
notary workload the message is always a 32-byte transaction id (reference:
core/.../transactions/SignedTransaction.kt:83-87 signs/verifies over
``stx.id.bytes``; id is the Merkle root, WireTransaction.kt:45-52), so
R||A||M is a fixed 96 bytes — exactly one padded SHA-512 block — and both
the hash and the reduction become fixed-shape batched graphs.

Representation: TPUs have no 64-bit lanes (and JAX runs x64-disabled), so a
64-bit SHA-512 word is an (hi, lo) pair of uint32 arrays, batch minor —
the same layout discipline as fe25519/sha256_jax. The scalar reduction uses
43 limbs of 12 bits in int32 (252 = 21*12, so the split at 2^252 is
limb-aligned) with the identity 2^252 ≡ -delta (mod L), L = 2^252 + delta.

Byte-identical to hashlib.sha512 + python int % L — golden tests enforce it.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["sha512_96_words", "sc_reduce_words", "challenge_words"]

U32 = jnp.uint32
I32 = jnp.int32

_K512 = [
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f,
    0xe9b5dba58189dbbc, 0x3956c25bf348b538, 0x59f111f1b605d019,
    0x923f82a4af194f9b, 0xab1c5ed5da6d8118, 0xd807aa98a3030242,
    0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235,
    0xc19bf174cf692694, 0xe49b69c19ef14ad2, 0xefbe4786384f25e3,
    0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65, 0x2de92c6f592b0275,
    0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f,
    0xbf597fc7beef0ee4, 0xc6e00bf33da88fc2, 0xd5a79147930aa725,
    0x06ca6351e003826f, 0x142929670a0e6e70, 0x27b70a8546d22ffc,
    0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6,
    0x92722c851482353b, 0xa2bfe8a14cf10364, 0xa81a664bbc423001,
    0xc24b8b70d0f89791, 0xc76c51a30654be30, 0xd192e819d6ef5218,
    0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99,
    0x34b0bcb5e19b48a8, 0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb,
    0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3, 0x748f82ee5defb2fc,
    0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915,
    0xc67178f2e372532b, 0xca273eceea26619c, 0xd186b8c721c0c207,
    0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178, 0x06f067aa72176fba,
    0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc,
    0x431d67c49c100d4c, 0x4cc5d4becb3e42b6, 0x597f299cfc657e2a,
    0x5fcb6fab3ad6faec, 0x6c44198c4a475817,
]
_K_HI = np.array([k >> 32 for k in _K512], np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K512], np.uint32)

_H0_512 = [
    0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b,
    0xa54ff53a5f1d36f1, 0x510e527fade682d1, 0x9b05688c2b3e6c1f,
    0x1f83d9abfb41bd6b, 0x5be0cd19137e2179,
]
_H0_HI = np.array([h >> 32 for h in _H0_512], np.uint32)
_H0_LO = np.array([h & 0xFFFFFFFF for h in _H0_512], np.uint32)


# --- 64-bit ops on (hi, lo) uint32 pairs -----------------------------------


def _add64(a, b):
    ahi, alo = a
    bhi, blo = b
    lo = alo + blo
    carry = (lo < alo).astype(U32)
    return ahi + bhi + carry, lo


def _add64_many(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = _add64(out, x)
    return out


def _xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _and64(a, b):
    return a[0] & b[0], a[1] & b[1]


def _not64(a):
    return ~a[0], ~a[1]


def _rotr64(x, n: int):
    hi, lo = x
    if n == 32:
        return lo, hi
    if n < 32:
        nh, nl = hi, lo
    else:
        nh, nl = lo, hi
        n -= 32
    return ((nh >> U32(n)) | (nl << U32(32 - n)),
            (nl >> U32(n)) | (nh << U32(32 - n)))


def _shr64(x, n: int):
    hi, lo = x
    if n < 32:
        return hi >> U32(n), (lo >> U32(n)) | (hi << U32(32 - n))
    return jnp.zeros_like(hi), hi >> U32(n - 32)


def _big_s0(x):
    return _xor64(_xor64(_rotr64(x, 28), _rotr64(x, 34)), _rotr64(x, 39))


def _big_s1(x):
    return _xor64(_xor64(_rotr64(x, 14), _rotr64(x, 18)), _rotr64(x, 41))


def _small_s0(x):
    return _xor64(_xor64(_rotr64(x, 1), _rotr64(x, 8)), _shr64(x, 7))


def _small_s1(x):
    return _xor64(_xor64(_rotr64(x, 19), _rotr64(x, 61)), _shr64(x, 6))


def _compress512(state, block):
    """One SHA-512 compression. state: (8, N) hi + (8, N) lo; block: 16 words
    as ((16, N) hi, (16, N) lo). The 80 rounds ride a lax.scan with the
    16-word message window carried, exactly like sha256_jax._compress."""
    shi, slo = state
    bhi, blo = block

    def round_step(carry, k):
        vars_, whi, wlo = carry
        a, b, c, d, e, f, g, h = vars_
        khi, klo = k
        w = (whi[0], wlo[0])
        s1 = _big_s1(e)
        ch = _xor64(_and64(e, f), _and64(_not64(e), g))
        t1 = _add64_many(h, s1, ch, (khi, klo), w)
        s0 = _big_s0(a)
        maj = _xor64(_xor64(_and64(a, b), _and64(a, c)), _and64(b, c))
        t2 = _add64(s0, maj)
        neww = _add64_many(_small_s1((whi[14], wlo[14])), (whi[9], wlo[9]),
                           _small_s0((whi[1], wlo[1])), w)
        whi = jnp.concatenate([whi[1:], neww[0][None]])
        wlo = jnp.concatenate([wlo[1:], neww[1][None]])
        newvars = (_add64(t1, t2), a, b, c, _add64(d, t1), e, f, g)
        return (newvars, whi, wlo), None

    init_vars = tuple((shi[i], slo[i]) for i in range(8))
    ks = (jnp.asarray(_K_HI, U32), jnp.asarray(_K_LO, U32))
    (vars_, _, _), _ = jax.lax.scan(round_step, (init_vars, bhi, blo), ks)
    out_hi = jnp.stack([_add64((shi[i], slo[i]), vars_[i])[0]
                        for i in range(8)])
    out_lo = jnp.stack([_add64((shi[i], slo[i]), vars_[i])[1]
                        for i in range(8)])
    return out_hi, out_lo


def _bswap32(x):
    return ((x & U32(0xFF)) << U32(24)) | ((x & U32(0xFF00)) << U32(8)) \
        | ((x >> U32(8)) & U32(0xFF00)) | (x >> U32(24))


def sha512_96_words(r_words, a_words, m_words):
    """SHA-512(R||A||M) for 32-byte R, A, M given as (8, N) uint32
    little-endian word arrays. Returns the digest as ((8, N), (8, N)) uint32
    (hi, lo) pairs of the eight big-endian 64-bit state words."""
    n = r_words.shape[-1]

    def words64_of(le_words):
        # bytes are little-endian in le_words; SHA block words are 64-bit
        # big-endian reads -> hi = bswap(even word), lo = bswap(odd word)
        return (_bswap32(le_words[0::2]), _bswap32(le_words[1::2]))

    rhi, rlo = words64_of(r_words)
    ahi, alo = words64_of(a_words)
    mhi, mlo = words64_of(m_words)
    zeros = jnp.zeros((1, n), U32)
    pad_hi = jnp.full((1, n), 0x80000000, U32)  # byte 96 = 0x80
    len_lo = jnp.full((1, n), 96 * 8, U32)  # 768-bit length, low word
    bhi = jnp.concatenate([rhi, ahi, mhi, pad_hi, zeros, zeros, zeros])
    blo = jnp.concatenate([rlo, alo, mlo, zeros, zeros, zeros, len_lo])
    state = (jnp.broadcast_to(jnp.asarray(_H0_HI, U32)[:, None], (8, n)),
             jnp.broadcast_to(jnp.asarray(_H0_LO, U32)[:, None], (8, n)))
    return _compress512(state, (bhi, blo))


# --- scalar reduction mod L ------------------------------------------------

SC_RADIX = 12
SC_MASK = (1 << SC_RADIX) - 1
SC_NLIMBS = 43  # ceil(512 / 12)
SC_SPLIT = 21  # 252 = 21 * 12: limbs >= 21 carry the 2^252 overflow
from ..crypto.ref_ed25519 import L  # noqa: E402  (single source of truth)

DELTA = L - 2**252  # 125 bits -> 11 limbs
_DELTA_LIMBS = [(DELTA >> (SC_RADIX * i)) & SC_MASK for i in range(11)]


def _sc_limbs_of_int(x: int, nlimbs: int) -> np.ndarray:
    return np.array([(x >> (SC_RADIX * i)) & SC_MASK for i in range(nlimbs)],
                    np.int32)


def _sc_carry(limbs, nlimbs: int):
    """Propagate carries to canonical [0, 2^12) limbs (arithmetic shifts give
    floor semantics, so intermediate negative limbs are fine as long as the
    represented value is non-negative). Returns exactly `nlimbs` limbs; the
    final carry-out must be zero by the caller's bound analysis."""
    out = []
    carry = jnp.zeros_like(limbs[0])
    for i in range(limbs.shape[0]):
        v = limbs[i] + carry
        out.append(v & SC_MASK)
        carry = v >> SC_RADIX  # arithmetic: floor division by 2^12
    while len(out) < nlimbs:
        out.append(carry & SC_MASK)
        carry = carry >> SC_RADIX
    return jnp.stack(out[:nlimbs])


def _sc_mul_delta(hi):
    """delta * hi for hi of shape (H, N) canonical limbs -> (H+11, N) limb
    products (each < 2^28: 11 terms of 24-bit products — int32-safe)."""
    h = hi.shape[0]
    out = jnp.zeros((h + 11, hi.shape[-1]), I32)
    for j, d in enumerate(_DELTA_LIMBS):
        if d:
            out = out.at[j:j + h].add(hi * I32(d))
    return out


def _sc_fold(limbs, nlimbs_out: int, guard_bits: int):
    """One folding step: value = lo + 2^252*hi  ≡  lo + (2^guard)*L - delta*hi
    (mod L), computed non-negatively. Input limbs canonical; output canonical
    with `nlimbs_out` limbs."""
    lo, hi = limbs[:SC_SPLIT], limbs[SC_SPLIT:]
    prod = _sc_mul_delta(hi)
    width = max(SC_SPLIT, prod.shape[0]) + guard_bits // SC_RADIX + 2
    guard = _sc_limbs_of_int((1 << guard_bits) * L, width)
    acc = jnp.broadcast_to(
        jnp.asarray(guard, I32)[:, None], (width, limbs.shape[-1])
    ).astype(I32)
    acc = acc.at[:SC_SPLIT].add(lo)
    acc = acc.at[:prod.shape[0]].add(-prod)
    return _sc_carry(acc, nlimbs_out)


def sc_reduce_words(digest_hi, digest_lo):
    """(8, N)+(8, N) uint32 SHA-512 state -> (8, N) uint32 little-endian
    words of h mod L (the Ed25519 challenge scalar; the digest byte stream is
    interpreted little-endian, ref10 sc_reduce semantics)."""
    # 1. The digest byte stream: word i (big-endian 64-bit) contributes
    # stream bytes 8i..8i+7 = hi>>24, hi>>16, hi>>8, hi, lo>>24, ..., lo.
    # h is the LITTLE-endian integer of that stream: stream byte j has
    # weight 2^(8j).
    byte_rows = []
    for i in range(8):
        for w in (digest_hi[i], digest_lo[i]):
            byte_rows.extend([
                (w >> U32(24)) & U32(0xFF), (w >> U32(16)) & U32(0xFF),
                (w >> U32(8)) & U32(0xFF), w & U32(0xFF),
            ])
    b = jnp.stack(byte_rows).astype(I32)  # (64, N), stream order
    # 2. bytes -> 43 limbs of 12 bits (2 limbs per 3 bytes)
    limbs = []
    for t in range(SC_NLIMBS):
        bit = SC_RADIX * t
        byte, off = bit // 8, bit % 8
        # a 12-bit limb spans at most 2 bytes (8-off bits of b[byte] plus up
        # to 12-(8-off) bits of the next byte)
        v = b[byte] >> I32(off)
        if byte + 1 < 64:
            v = v | (b[byte + 1] << I32(8 - off))
        limbs.append(v & I32(SC_MASK))
    h = jnp.stack(limbs)  # canonical 43 limbs, < 2^512

    # 3. fold twice, non-negatively, then a signed fold with select:
    # fold 1: hi = h>>252 < 2^264 (22 limbs), delta*hi < 2^389;
    #         guard 2^140*L > 2^392 keeps the value positive; out < 2^393.
    t1 = _sc_fold(h, 34, guard_bits=140)  # 34 limbs = 408 bits headroom
    # fold 2: hi = t1>>252 < 2^156, delta*hi < 2^281; guard 2^32*L > 2^284.
    t2 = _sc_fold(t1, 25, guard_bits=32)  # out < 2^285 < 2^300
    # fold 3: hi = t2>>252 < 2^48, delta*hi < 2^173:
    #         t3 = lo - delta*hi + 2L  in  (2L - 2^173, 2^252 + 2L) ⊂ (0, 3L)
    lo3, hi3 = t2[:SC_SPLIT], t2[SC_SPLIT:]
    prod3 = _sc_mul_delta(hi3)
    width3 = SC_SPLIT + 2  # 23 limbs = 276 bits
    acc = jnp.broadcast_to(
        jnp.asarray(_sc_limbs_of_int(2 * L, width3), I32)[:, None],
        (width3, t2.shape[-1])).astype(I32)
    acc = acc.at[:SC_SPLIT].add(lo3)
    acc = acc.at[:prod3.shape[0]].add(-prod3)
    out = _sc_carry(acc, width3)

    # 4. canonicalise from [0, 3L): conditionally subtract L twice. The
    # unselected lanes' subtraction results are garbage (negative totals) —
    # jnp.where keeps only lanes where out >= L, for which the carry
    # analysis holds.
    l_limbs = jnp.asarray(_sc_limbs_of_int(L, width3), I32)[:, None]
    for _ in range(2):
        ge = _sc_ge(out, l_limbs)
        out = jnp.where(ge[None, :], _sc_carry(out - l_limbs, width3), out)
    # 5. limbs (canonical 12-bit, < L < 2^253) -> (8, N) uint32 LE words
    return _limbs_to_words(out)


def _sc_ge(a, l_limbs):
    """Lexicographic >= comparison of canonical limb arrays (a: (W, N),
    l_limbs: (W, 1)) from the most significant limb down."""
    gt = jnp.zeros(a.shape[-1], bool)
    eq = jnp.ones(a.shape[-1], bool)
    for i in range(a.shape[0] - 1, -1, -1):
        gt = gt | (eq & (a[i] > l_limbs[i]))
        eq = eq & (a[i] == l_limbs[i])
    return gt | eq


def _limbs_to_words(limbs):
    """(>=22, N) canonical 12-bit limbs -> (8, N) uint32 LE words."""
    l = limbs.astype(U32)
    words = []
    for w in range(8):
        bit = 32 * w
        t, off = bit // SC_RADIX, bit % SC_RADIX
        v = l[t] >> U32(off)
        used = SC_RADIX - off
        while used < 32:
            t += 1
            if t < l.shape[0]:
                v = v | (l[t] << U32(used))
            used += SC_RADIX
        words.append(v & U32(0xFFFFFFFF))
    return jnp.stack(words)


@jax.jit
def challenge_words(r_words, a_words, m_words):
    """h = SHA-512(R||A||M) mod L fully on device, for 32-byte messages:
    (8, N) uint32 LE words in, (8, N) uint32 LE words of the scalar out."""
    hi, lo = sha512_96_words(r_words, a_words, m_words)
    return sc_reduce_words(hi, lo)
