"""Multi-chip sharding of the batched Ed25519 verify kernel.

The reference's whitepaper singles out signature verification as the
embarrassingly-parallel hotspot ("signatures can easily be verified in
parallel", reference: docs/source/whitepaper/corda-technical-whitepaper.tex:
1597-1604).  On TPU the natural realisation is SPMD over a device mesh: the
signature batch axis — the minor axis of every kernel array — is sharded
across a 1-D ``jax.sharding.Mesh`` with ``jax.shard_map``, so each chip
decompresses and double-scalar-multiplies its own slice of the batch.  No
collectives are needed on the verify path itself (each lane is an independent
signature); the outputs come back sharded and XLA gathers them only if the
host reads the full array.

The same code runs on a single chip (mesh of 1), an 8-device virtual CPU mesh
(tests / the driver's dry-run), or a real multi-chip slice — the mesh is the
only degree of freedom.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import ed25519_jax, fe25519 as fe

__all__ = ["make_mesh", "sharded_verify_fn", "verify_batch_sharded", "pad_to_devices"]

BATCH_AXIS = "sigs"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (all if None)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}; "
                    "set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    "JAX_PLATFORMS=cpu for a virtual CPU mesh"
                )
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (BATCH_AXIS,))


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices >= max(n, n_devices)."""
    return -(-max(n, 1) // n_devices) * n_devices


# Kernel array layout: four (8, N) uint32 word arrays, batch minor.
_IN_SPECS = (P(None, BATCH_AXIS),) * 4
_OUT_SPEC = P(BATCH_AXIS)


_FN_CACHE: dict[Mesh, object] = {}


def sharded_verify_fn(mesh: Mesh):
    """jit-compiled SPMD verify over ``mesh``: same signature/semantics as
    ``ed25519_jax.verify_arrays`` but with the batch axis sharded.

    The batch size must be a multiple of the mesh size (use
    :func:`pad_to_devices`; padded lanes simply verify to False).
    Compiled executables are cached per mesh.
    """
    fn = _FN_CACHE.get(mesh)
    if fn is None:
        # check_vma=False: the scan carry seeds from device-invariant curve
        # constants which the VMA checker would otherwise force us to pcast;
        # the kernel is per-lane independent so replication analysis adds
        # nothing here.
        inner = jax.shard_map(
            ed25519_jax.verify_arrays.__wrapped__,  # undecorated graph fn
            mesh=mesh, in_specs=_IN_SPECS, out_specs=_OUT_SPEC,
            check_vma=False,
        )
        fn = _FN_CACHE[mesh] = jax.jit(inner)
    return fn


def verify_batch_sharded(pubkeys, msgs, sigs, mesh: Mesh) -> np.ndarray:
    """End-to-end sharded verify: bool[len(sigs)], malformed inputs reject.

    Host packing is shared with the single-chip path
    (``ed25519_jax.precompute_batch``); the bucket is rounded up to a multiple
    of the mesh size so every device gets an equal slice.
    """
    n = len(sigs)
    ok = np.zeros(n, bool)
    good = [i for i in range(n)
            if len(bytes(pubkeys[i])) == 32 and len(bytes(sigs[i])) == 64]
    if not good:
        return ok
    ndev = mesh.devices.size
    bucket = pad_to_devices(ed25519_jax.pick_bucket(len(good)), ndev)
    arrays, _ = ed25519_jax.precompute_batch(
        [pubkeys[i] for i in good], [msgs[i] for i in good],
        [sigs[i] for i in good], bucket=bucket)
    out = np.asarray(sharded_verify_fn(mesh)(*arrays))
    for j, i in enumerate(good):
        ok[i] = out[j]
    return ok
