"""Multi-chip sharding of the batched Ed25519 verify kernel.

The reference's whitepaper singles out signature verification as the
embarrassingly-parallel hotspot ("signatures can easily be verified in
parallel", reference: docs/source/whitepaper/corda-technical-whitepaper.tex:
1597-1604).  On TPU the natural realisation is SPMD over a device mesh: the
signature batch axis — the minor axis of every kernel array — is sharded
across a 1-D ``jax.sharding.Mesh`` with ``jax.shard_map``, so each chip
decompresses and double-scalar-multiplies its own slice of the batch.  No
collectives are needed on the verify path itself (each lane is an independent
signature); the outputs come back sharded and XLA gathers them only if the
host reads the full array.

The same code runs on a single chip (mesh of 1), an 8-device virtual CPU mesh
(tests / the driver's dry-run), or a real multi-chip slice — the mesh is the
only degree of freedom.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import ed25519_jax, fe25519 as fe

if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _NO_CHECK = {"check_vma": False}
else:  # jax < 0.6: experimental path, and the kwarg was named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _NO_CHECK = {"check_rep": False}

__all__ = ["make_mesh", "sharded_verify_fn", "sharded_verify_hashed_fn",
           "verify_batch_sharded", "pad_to_devices",
           "pack_batch_sharded", "dispatch_packed", "PackedShardedBatch"]

BATCH_AXIS = "sigs"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (all if None)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}; "
                    "set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    "JAX_PLATFORMS=cpu for a virtual CPU mesh"
                )
            devices = devices[:n_devices]
    # lint: allow(no-jit-in-hotpath) make_mesh IS the mesh constructor; every caller memoises its result (provider.mesh, bench setup) — it never runs per batch
    return Mesh(np.array(devices), (BATCH_AXIS,))


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices >= max(n, n_devices)."""
    return -(-max(n, 1) // n_devices) * n_devices


# Kernel array layout: four (8, N) uint32 word arrays, batch minor.
_IN_SPECS = (P(None, BATCH_AXIS),) * 4
_OUT_SPEC = P(BATCH_AXIS)


_FN_CACHE: dict[tuple, object] = {}


def _sharded_fn(graph_fn, mesh: Mesh):
    """shard_map + jit a per-lane verify graph over ``mesh``, cached per
    (graph, mesh). check_vma=False: the scan carry seeds from
    device-invariant curve constants which the VMA checker would otherwise
    force us to pcast; the kernels are per-lane independent so replication
    analysis adds nothing here."""
    key = (graph_fn, mesh)
    fn = _FN_CACHE.get(key)
    if fn is None:
        # Route the sharded compiles through the host_cpu_signature()-keyed
        # persistent cache (MULTICHIP_r05 tail: "Compile machine features
        # ... doesn't match" — an XLA:CPU AOT artifact compiled on one
        # machine type was loaded on another; the keyed dir partitions the
        # cache per CPU feature set so stale artifacts are never loaded).
        from . import enable_persistent_compile_cache

        enable_persistent_compile_cache()
        # lint: allow(no-jit-in-hotpath) this IS the keyed executable cache the rule routes hot paths through: one shard_map+jit per (graph, mesh), stored in _FN_CACHE above
        inner = _shard_map(
            graph_fn, mesh=mesh, in_specs=_IN_SPECS, out_specs=_OUT_SPEC,
            **_NO_CHECK,
        )
        # lint: allow(no-jit-in-hotpath) cache-miss arm of _FN_CACHE: compiled once per key, then every dispatch reuses the stored executable
        fn = _FN_CACHE[key] = jax.jit(inner)
    return fn


def sharded_verify_fn(mesh: Mesh):
    """jit-compiled SPMD verify over ``mesh``: same signature/semantics as
    ``ed25519_jax.verify_arrays`` but with the batch axis sharded.

    The batch size must be a multiple of the mesh size (use
    :func:`pad_to_devices`; padded lanes simply verify to False).
    """
    return _sharded_fn(ed25519_jax.verify_arrays.__wrapped__, mesh)


def _verify_hashed_graph(a_words, r_words, s_words, m_words):
    """Undecorated fully-on-device graph: SHA-512 challenge + mod-L + verify.
    Per-lane independent, so sharding the batch axis needs no collectives —
    each device hashes and verifies its own slice. Reuses the single-chip
    challenge graph (not a copy) so the tiers cannot drift."""
    from . import sha512_jax

    h_words = sha512_jax.challenge_words.__wrapped__(
        r_words, a_words, m_words)
    return ed25519_jax.verify_arrays.__wrapped__(
        a_words, r_words, s_words, h_words)


def sharded_verify_hashed_fn(mesh: Mesh):
    """SPMD twin of ``ed25519_jax.verify_arrays_hashed``: batch axis sharded
    over ``mesh``, challenge hashing included on device (32-byte messages)."""
    return _sharded_fn(_verify_hashed_graph, mesh)


class PackedShardedBatch:
    """Host-packed kernel arrays awaiting a mesh dispatch.

    The pack half (CPU: decompress limbs, radix-split words, pad to the
    bucket) and the dispatch half (device: the sharded verify executable)
    are split so a pipelined caller — the sidecar's depth-2 executor — can
    pack batch N+1 on the host while batch N runs on the mesh."""

    __slots__ = ("n", "good", "arrays", "fn", "bucket", "n_devices")

    def __init__(self, n, good, arrays, fn, bucket, n_devices):
        self.n = n                  # total lanes requested (incl. malformed)
        self.good = good            # indices packed into the arrays
        self.arrays = arrays        # four (8, bucket) uint32 word arrays
        self.fn = fn                # jit(shard_map) executable, mesh-bound
        self.bucket = bucket        # padded lane count actually dispatched
        self.n_devices = n_devices

    @property
    def pad_lanes(self) -> int:
        """Lanes dispatched that carry no real signature (bucket ladder
        round-up + pad_to_devices) — the waste the stats attribute."""
        return self.bucket - len(self.good)


def pack_batch_sharded(pubkeys, msgs, sigs,
                       mesh: Mesh) -> "PackedShardedBatch | None":
    """Host half of the sharded verify: filter malformed lanes, pick the
    bucket (rounded to a multiple of the mesh size so every device gets an
    equal slice), and columnar-pack the kernel arrays. Returns None when no
    lane is well-formed (the caller answers all-False without a dispatch).

    The returned executable is the cached jit(shard_map) for this mesh —
    in/out shardings are fixed by _IN_SPECS/_OUT_SPEC, so repeated
    dispatches at the same bucket reuse one executable and never
    re-partition."""
    n = len(sigs)
    good = [i for i in range(n)
            if len(bytes(pubkeys[i])) == 32 and len(bytes(sigs[i])) == 64]
    if not good:
        return None
    ndev = mesh.devices.size
    bucket = pad_to_devices(ed25519_jax.pick_bucket(len(good)), ndev)
    gp = [pubkeys[i] for i in good]
    gm = [msgs[i] for i in good]
    gs = [sigs[i] for i in good]
    if ed25519_jax.device_hash_eligible(gm):
        arrays, _ = ed25519_jax.precompute_batch_device(gp, gm, gs,
                                                        bucket=bucket)
        fn = sharded_verify_hashed_fn(mesh)
    else:
        arrays, _ = ed25519_jax.precompute_batch(gp, gm, gs, bucket=bucket)
        fn = sharded_verify_fn(mesh)
    return PackedShardedBatch(n, good, arrays, fn, bucket, ndev)


def dispatch_packed(packed: PackedShardedBatch) -> np.ndarray:
    """Device half: run the mesh executable and scatter lane results back
    to the caller's index space (padded lanes verify False and are never
    visible — bool[packed.n] covers exactly the requested lanes)."""
    ok = np.zeros(packed.n, bool)
    out = np.asarray(packed.fn(*packed.arrays))
    for j, i in enumerate(packed.good):
        ok[i] = out[j]
    return ok


def verify_batch_sharded(pubkeys, msgs, sigs, mesh: Mesh) -> np.ndarray:
    """End-to-end sharded verify: bool[len(sigs)], malformed inputs reject.

    Host packing and path dispatch are shared with the single-chip tier:
    all-32-byte messages (tx ids) hash on device; the bucket is rounded up to
    a multiple of the mesh size so every device gets an equal slice.
    """
    packed = pack_batch_sharded(pubkeys, msgs, sigs, mesh)
    if packed is None:
        return np.zeros(len(sigs), bool)
    return dispatch_packed(packed)
