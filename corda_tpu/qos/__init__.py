"""corda_tpu.qos — priority lanes, deadlines, and admission control.

See context.py for the propagation model (mirrors obs/trace arming) and
admission.py for the entry-point shed policy. Import the submodule
directly at instrumentation points (``from ..qos import context as
_qos``) so the one-attribute disarmed check stays cheap and explicit.
"""

from .admission import AdmissionController, TokenBucket
from .calibrate import apply_calibration, calibrate_admission
from .context import (LANES, LANE_BULK, LANE_INTERACTIVE, QosContext,
                      QosPlane, arm, arm_from_env, clear_context, disarm,
                      get_context, set_context)

# NOTE: ``ACTIVE`` is deliberately NOT re-exported — a from-import would
# freeze the binding at import time. Instrumentation points import the
# submodule (``from ..qos import context as _qos``) and read
# ``_qos.ACTIVE`` so arming is always seen.

__all__ = [
    "AdmissionController",
    "apply_calibration",
    "calibrate_admission",
    "LANES",
    "LANE_BULK",
    "LANE_INTERACTIVE",
    "QosContext",
    "QosPlane",
    "TokenBucket",
    "arm",
    "arm_from_env",
    "clear_context",
    "disarm",
    "get_context",
    "set_context",
]
