"""Admission control at the notarise entry point.

The QoS plane's third leg: priority lanes reorder work that is already
admitted; admission control keeps the backlog short enough that
reordering can still save the interactive p99. Policy, per request, at
``NotaryServiceFlow.call``:

  * every lane owns a token bucket (rate + burst; rate 0 = unlimited).
    An empty bucket sheds the request.
  * the BULK lane additionally sheds above a queue-depth watermark — when
    the notary's runnable backlog exceeds the watermark, bulk is turned
    away even with tokens in hand, because every admitted bulk step
    lengthens the queue interactive work must traverse.
  * interactive is never watermark-shed: its protection is its own bucket
    (operator-set ceiling), and the lanes' buckets are independent so a
    bulk flood can never starve interactive admission.

A shed becomes a retryable ``OverloadedError`` carrying ``retry_after_ms``
(time until the lane's bucket refills one token, bounded) — the client's
``notarise_with_retry`` backs off and retries, which under sustained
overload converts bulk load shedding into client-side pacing instead of
server-side queue collapse.

Unlabelled requests admit through the interactive bucket: arming QoS over
a tree that never marks a lane changes nothing (the interactive bucket
defaults to unlimited).
"""

from __future__ import annotations

import threading
import time

from ..obs import telemetry as _tm
from .context import LANE_BULK, LANE_INTERACTIVE

__all__ = ["AdmissionController", "TokenBucket"]

# Never tell a client to wait longer than this for one token; sustained
# overload is paced by repeated shed/retry rounds, not one giant sleep.
MAX_RETRY_AFTER_S = 2.0

# OverloadedError-spike detection for the flight recorder: this many
# sheds inside one sliding window triggers a (latched) "overload_spike"
# dump — the moment the controller starts turning work away in bulk is
# exactly the moment worth capturing, not reproducing.
SPIKE_WINDOW_S = 5.0
SPIKE_SHEDS = 50


class TokenBucket:
    """Classic token bucket; ``rate <= 0`` means unlimited (always
    admits). Not thread-safe on its own — the controller's lock covers
    refill + take."""

    def __init__(self, rate_per_s: float, burst: float):
        self.rate = float(rate_per_s)
        self.burst = max(1.0, float(burst)) if self.rate > 0 else 0.0
        self.tokens = self.burst
        self._t_last = time.monotonic()

    def try_take(self, now: float | None = None) -> bool:
        if self.rate <= 0:
            return True
        if now is None:
            now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t_last) * self.rate)
        self._t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def retry_after_s(self) -> float:
        """Seconds until one token refills (post-``try_take`` estimate)."""
        if self.rate <= 0:
            return 0.0
        deficit = max(0.0, 1.0 - self.tokens)
        return min(MAX_RETRY_AFTER_S, deficit / self.rate)


class AdmissionController:
    """Per-lane token buckets + bulk queue-depth watermark."""

    def __init__(self, interactive_rate: float = 0.0,
                 interactive_burst: float = 32.0,
                 bulk_rate: float = 0.0, bulk_burst: float = 32.0,
                 queue_watermark: int = 0):
        self._lock = threading.Lock()
        self._buckets = {
            LANE_INTERACTIVE: TokenBucket(interactive_rate,
                                          interactive_burst),
            LANE_BULK: TokenBucket(bulk_rate, bulk_burst),
        }
        # Runnable-backlog ceiling above which bulk sheds; 0 disables.
        self.queue_watermark = int(queue_watermark)
        self.counters = {
            "admitted_interactive": 0,
            "admitted_bulk": 0,
            "shed_interactive": 0,
            "shed_bulk": 0,
            "watermark_sheds": 0,
        }
        # Shed-spike sliding window (flight recorder trigger state).
        self._spike_t0 = 0.0
        self._spike_n = 0

    def admit(self, lane: str, queue_depth: int = 0) -> float | None:
        """None when admitted; otherwise the suggested client retry-after
        in SECONDS (the shed verdict)."""
        if lane not in self._buckets:
            lane = LANE_INTERACTIVE
        with self._lock:
            bucket = self._buckets[lane]
            if (lane == LANE_BULK and self.queue_watermark > 0
                    and queue_depth > self.queue_watermark):
                self.counters["shed_bulk"] += 1
                self.counters["watermark_sheds"] += 1
                self._note_shed(queue_depth)
                # Depth drains at commit pace, not token pace: a short,
                # fixed pause is the honest hint.
                return min(MAX_RETRY_AFTER_S,
                           max(0.05, bucket.retry_after_s()))
            if bucket.try_take():
                self.counters[f"admitted_{lane}"] += 1
                if _tm.ACTIVE is not None:
                    _tm.inc("admission_admitted_total")
                return None
            self.counters[f"shed_{lane}"] += 1
            self._note_shed(queue_depth)
            return max(0.01, bucket.retry_after_s())

    def _note_shed(self, queue_depth: int) -> None:
        """Called under self._lock on every shed: count telemetry and
        detect an OverloadedError spike (>= SPIKE_SHEDS sheds within
        SPIKE_WINDOW_S) for the latched flight-recorder dump."""
        if _tm.ACTIVE is None:
            return
        _tm.inc("admission_shed_total")
        now = time.monotonic()
        if now - self._spike_t0 > SPIKE_WINDOW_S:
            self._spike_t0 = now
            self._spike_n = 0
        self._spike_n += 1
        if self._spike_n == SPIKE_SHEDS:
            # Latched inside the recorder: sustained overload dumps once.
            # trigger never raises and the artifact write happens at most
            # once per process, so doing it under the admission lock is a
            # bounded, once-ever cost.
            _tm.flight_trigger("overload_spike", extra={
                "window_s": SPIKE_WINDOW_S, "sheds_in_window": self._spike_n,
                "queue_depth": queue_depth, **self.counters})

    def reconfigure(self, interactive_rate: float | None = None,
                    interactive_burst: float | None = None,
                    bulk_rate: float | None = None,
                    bulk_burst: float | None = None,
                    queue_watermark: int | None = None) -> None:
        """Swap in new rates live (measured-saturation calibration: the
        rates come from an observed slo_sweep, qos/calibrate.py, not static
        TOML). Each lane's bucket is REPLACED, not mutated — a fresh bucket
        starts full at the new burst, so a recalibration never inherits a
        deficit accumulated under the old (possibly wrong) rate. ``None``
        keeps the current value for that knob; counters are preserved."""
        with self._lock:
            for lane, rate, burst in (
                    (LANE_INTERACTIVE, interactive_rate, interactive_burst),
                    (LANE_BULK, bulk_rate, bulk_burst)):
                if rate is None and burst is None:
                    continue
                old = self._buckets[lane]
                self._buckets[lane] = TokenBucket(
                    old.rate if rate is None else rate,
                    (old.burst or 32.0) if burst is None else burst)
            if queue_watermark is not None:
                self.queue_watermark = int(queue_watermark)

    def stats(self) -> dict:
        with self._lock:
            return {
                "queue_watermark": self.queue_watermark,
                "interactive_rate": self._buckets[LANE_INTERACTIVE].rate,
                "bulk_rate": self._buckets[LANE_BULK].rate,
                **self.counters,
            }
