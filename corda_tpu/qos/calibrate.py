"""Measured-saturation admission calibration.

The admission rates (PR 9) were static TOML: an operator guessed each
lane's ceiling. This module closes the loop — it derives the
:class:`~corda_tpu.qos.admission.AdmissionController` knobs from an
OBSERVED ``slo_sweep`` (tools/loadtest.run_slo_sweep), which already
measures, per offered rate, the per-lane committed throughput and the
interactive p99:

  * the **saturation rate** is the highest offered rate whose interactive
    p99 still met the SLO — one step past it the sweep measured the tail
    collapsing, so admitting that much again would break the SLO the
    controller exists to protect;
  * each lane's rate is its *measured committed share at the saturation
    point*, scaled by a safety factor (headroom for the calibration run
    and the protected run differing);
  * the bulk queue watermark follows Little's law: the backlog that can
    drain within one SLO window at the measured committed pace — any
    deeper and an interactive request admitted behind it has already
    missed its deadline while queued.

The output is a plain dict so it stamps straight into bench artifacts,
and :func:`apply_calibration` pushes it into a live controller via
``AdmissionController.reconfigure`` (each group of a sharded notary
calibrates from its own sweep — groups on asymmetric hosts get asymmetric
ceilings, which is the point).

Stdlib-only, like the rest of ``qos``.
"""

from __future__ import annotations

from .admission import AdmissionController

__all__ = ["calibrate_admission", "apply_calibration"]

# Floor under the Little's-law watermark: a watermark below the typical
# coalesce batch would shed bulk on ordinary micro-batch ripples.
MIN_WATERMARK = 8

# Floor under a derived lane rate: a sweep that measured ~0 committed for
# a lane (e.g. bulk_rate=0 in the calibration run) must not derive a
# 0-rate bucket, which means UNLIMITED to the token bucket — the one
# wrong direction. One tx/s keeps the lane alive but firmly capped.
MIN_RATE = 1.0


def _field(result, name: str, default: float = 0.0) -> float:
    """Read a lane result field from either a FirehoseResult-like object
    or a plain dict (bench artifacts round-trip through JSON)."""
    if isinstance(result, dict):
        value = result.get(name, default)
    else:
        value = getattr(result, name, default)
    try:
        return float(value)
    except (TypeError, ValueError):
        return float(default)


def calibrate_admission(results, slo_ms: float, safety: float = 0.8,
                        interactive_burst: float = 32.0,
                        bulk_burst: float = 32.0) -> dict:
    """Derive admission knobs from slo_sweep observations.

    ``results`` is the SweepResult.results mapping: offered rate ->
    {"interactive": lane-result, "bulk": lane-result} where a lane result
    carries at least ``p99_ms`` and ``tx_per_sec`` (object attributes or
    dict keys — JSON round-trips work).

    Scans offered rates in ascending order and stops at the first one
    whose interactive p99 misses ``slo_ms``: rates beyond a miss are past
    the knee, and a later rate that happens to sneak under the SLO again
    is measurement noise, not recovered capacity. Returns a dict with the
    derived knobs plus provenance — ``met_slo`` False means NO swept rate
    met the SLO and the calibration fell back to the lowest offered rate
    (maximally conservative; the operator should sweep lower).
    """
    saturation = None
    met_slo = False
    for rate in sorted(results):
        lanes = results[rate]
        inter = (lanes.get("interactive") if isinstance(lanes, dict)
                 else getattr(lanes, "interactive", None))
        if inter is None:
            continue
        if _field(inter, "p99_ms") <= float(slo_ms):
            saturation = rate
            met_slo = True
        else:
            break
    if saturation is None:
        rates = sorted(results)
        if not rates:
            raise ValueError("calibrate_admission: empty sweep results")
        saturation = rates[0]
    lanes = results[saturation]

    def lane(name):
        return (lanes.get(name) if isinstance(lanes, dict)
                else getattr(lanes, name, None))

    inter_tx = _field(lane("interactive"), "tx_per_sec")
    bulk_tx = _field(lane("bulk"), "tx_per_sec")
    total_tx = inter_tx + bulk_tx
    watermark = max(MIN_WATERMARK, int(total_tx * float(slo_ms) / 1e3))
    return {
        "interactive_rate": max(MIN_RATE, safety * inter_tx),
        "interactive_burst": float(interactive_burst),
        "bulk_rate": max(MIN_RATE, safety * bulk_tx),
        "bulk_burst": float(bulk_burst),
        "queue_watermark": watermark,
        # provenance — stamped into bench artifacts beside the knobs
        "saturation_rate": float(saturation),
        "measured_interactive_tx_per_sec": inter_tx,
        "measured_bulk_tx_per_sec": bulk_tx,
        "slo_ms": float(slo_ms),
        "safety": float(safety),
        "met_slo": met_slo,
    }


def apply_calibration(controller: AdmissionController,
                      calibration: dict) -> None:
    """Push calibrated knobs into a live controller (counters survive)."""
    controller.reconfigure(
        interactive_rate=calibration["interactive_rate"],
        interactive_burst=calibration.get("interactive_burst"),
        bulk_rate=calibration["bulk_rate"],
        bulk_burst=calibration.get("bulk_burst"),
        queue_watermark=calibration["queue_watermark"],
    )
