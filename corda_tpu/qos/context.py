"""QosContext — the request-class + deadline plane, propagated like trace
context.

The QoS plane mirrors the obs/trace arming pattern exactly, and for the
same reason: one module-level ``ACTIVE`` object guarded by a single
attribute check at every instrumentation point. Disarmed (``ACTIVE is
None``, the default) every touch point short-circuits before building
anything — no context objects, no extra wire fields, no scheduling
deviation — which is what makes the ``qos = false`` config path
bit-identical to the pre-QoS tree.

Armed, a :class:`QosContext` travels with a flow exactly the way trace
context does:

  * stamped onto the FlowStateMachine at ``add()`` (flow start),
  * pushed into a thread-local around ``step()`` / service polls,
  * picked up by both transports at ``send()`` and carried on the wire
    (in-memory: the object rides the Message; TCP: one 17-byte
    ``<BQQ`` field appended to the frame tuple),
  * joined by the responder's FSM at SessionInit,
  * linked to Raft ``request_id``s through the plane's bounded link map so
    batch formation can see the deadline of each buffered command.

Deadlines are EPOCH nanoseconds (``time.time_ns``) so they remain
meaningful across process boundaries (client node -> notary node ->
sidecar), same rationale as the epoch stamps in obs spans. Deadline
*evaluation* lives here — ``QosPlane.near_deadline`` — so consensus
modules never read a clock themselves: the scheduling decision ("seal this
batch early") is leader/coordinator-side and never taken inside an apply
path, preserving the determinism contract.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from dataclasses import dataclass

__all__ = [
    "ACTIVE",
    "ENV_VAR",
    "LANES",
    "LANE_BULK",
    "LANE_INTERACTIVE",
    "QosContext",
    "QosPlane",
    "arm",
    "arm_from_env",
    "clear_context",
    "disarm",
    "get_context",
    "lane_code",
    "now_ns",
    "set_context",
]

LANE_INTERACTIVE = "interactive"
LANE_BULK = "bulk"
LANES = (LANE_INTERACTIVE, LANE_BULK)

_LANE_CODES = {LANE_INTERACTIVE: 0, LANE_BULK: 1}
_LANE_NAMES = {code: name for name, code in _LANE_CODES.items()}

ENV_VAR = "CORDA_TPU_QOS"

# One wire field: lane code (u8), deadline_ns (u64), admitted_ns (u64).
_WIRE = struct.Struct("<BQQ")
WIRE_SIZE = _WIRE.size

# Bounded request_id -> QosContext map (same sizing discipline as the obs
# link map: a leaked link must never grow without bound, so the map clears
# wholesale when full — losing priority attribution for in-flight requests
# is strictly better than losing the process).
LINK_MAP_MAX = 16384


def now_ns() -> int:
    """Epoch nanoseconds — the QoS deadline clock (cross-process)."""
    return time.time_ns()


def lane_code(lane: str) -> int:
    return _LANE_CODES.get(lane, 0)


@dataclass(frozen=True)
class QosContext:
    """One request's class and latency contract.

    ``deadline_ns`` / ``admitted_ns`` are epoch nanoseconds; 0 means "no
    deadline" / "not stamped". An unlabelled request (no context at all)
    schedules exactly like interactive — the plane deprioritizes only what
    is explicitly marked bulk, so arming QoS over unlabelled traffic
    changes nothing.
    """

    lane: str = LANE_INTERACTIVE
    deadline_ns: int = 0
    admitted_ns: int = 0

    def to_wire(self) -> bytes:
        return _WIRE.pack(_LANE_CODES.get(self.lane, 0),
                          self.deadline_ns & 0xFFFFFFFFFFFFFFFF,
                          self.admitted_ns & 0xFFFFFFFFFFFFFFFF)

    @staticmethod
    def from_wire(raw) -> "QosContext | None":
        """Decode one wire field; None (never an exception) on junk —
        transports drop malformed frames, they do not crash readers."""
        if not isinstance(raw, (bytes, bytearray)) or len(raw) != WIRE_SIZE:
            return None
        code, deadline_ns, admitted_ns = _WIRE.unpack(bytes(raw))
        lane = _LANE_NAMES.get(code)
        if lane is None:
            return None
        return QosContext(lane, deadline_ns, admitted_ns)


class QosPlane:
    """The armed QoS plane: scheduler parameters + counters + the bounded
    request_id link map. One instance per process (module ``ACTIVE``)."""

    def __init__(self, node_name: str = "", slo_ms: float = 50.0,
                 deadline_guard_ms: float = 5.0, bulk_every: int = 4):
        self.node_name = node_name
        self.slo_ms = float(slo_ms)
        self.deadline_guard_ns = int(float(deadline_guard_ms) * 1e6)
        # Anti-starvation ratio: when both classes are runnable, every
        # bulk_every'th pick takes the oldest bulk step.
        self.bulk_every = max(2, int(bulk_every))
        self._links: dict[bytes, QosContext] = {}
        self._links_lock = threading.Lock()
        self.counters = {
            "interactive_flows": 0,
            "bulk_flows": 0,
            "bulk_antistarvation_picks": 0,
            "verify_early_flushes": 0,
            "links_dropped": 0,
        }

    # -- deadline evaluation (the one place QoS reads a clock) -------------

    def near_deadline(self, ctx: QosContext | None) -> bool:
        """True when ``ctx`` is an interactive request whose deadline is
        within the guard window — the signal every queueing point uses to
        stop coalescing and flush."""
        return (ctx is not None
                and ctx.lane == LANE_INTERACTIVE
                and ctx.deadline_ns > 0
                and time.time_ns() + self.deadline_guard_ns
                >= ctx.deadline_ns)

    def deadline_near_ns(self, deadline_ns: int) -> bool:
        """Same check for call sites that track only the minimum
        interactive deadline (SMM verify micro-batch)."""
        return (deadline_ns > 0
                and time.time_ns() + self.deadline_guard_ns >= deadline_ns)

    def new_context(self, lane: str, slo_ms: float | None = None,
                    admitted_ns: int | None = None) -> QosContext:
        """Entry-point constructor: stamp admitted-at now and derive the
        deadline from the lane's SLO (interactive only — bulk carries no
        deadline; it is the sheddable class)."""
        t = now_ns() if admitted_ns is None else admitted_ns
        if lane == LANE_INTERACTIVE:
            ms = self.slo_ms if slo_ms is None else float(slo_ms)
            deadline = t + int(ms * 1e6) if ms > 0 else 0
        else:
            deadline = 0
        return QosContext(lane, deadline, t)

    # -- request_id links (Raft/shard commit attribution) ------------------

    def register_link(self, request_id: bytes, ctx: QosContext) -> None:
        with self._links_lock:
            if len(self._links) >= LINK_MAP_MAX:
                self.counters["links_dropped"] += len(self._links)
                self._links.clear()
            self._links[request_id] = ctx

    def pop_link(self, request_id: bytes) -> QosContext | None:
        with self._links_lock:
            return self._links.pop(request_id, None)

    def peek_link(self, request_id: bytes) -> QosContext | None:
        return self._links.get(request_id)

    # -- stamping ----------------------------------------------------------

    def stats(self) -> dict:
        return {
            "slo_ms": self.slo_ms,
            "deadline_guard_ms": self.deadline_guard_ns / 1e6,
            "bulk_every": self.bulk_every,
            "links": len(self._links),
            **self.counters,
        }


# ---------------------------------------------------------------------------
# Module state: the armed plane + per-thread current context
# ---------------------------------------------------------------------------

ACTIVE: QosPlane | None = None

_ctx = threading.local()


def set_context(ctx: QosContext | None) -> None:
    _ctx.current = ctx


def get_context() -> QosContext | None:
    return getattr(_ctx, "current", None)


def clear_context() -> None:
    _ctx.current = None


def arm(node_name: str = "", slo_ms: float = 50.0,
        deadline_guard_ms: float = 5.0, bulk_every: int = 4) -> QosPlane:
    global ACTIVE
    ACTIVE = QosPlane(node_name, slo_ms=slo_ms,
                      deadline_guard_ms=deadline_guard_ms,
                      bulk_every=bulk_every)
    return ACTIVE


def disarm() -> None:
    global ACTIVE
    ACTIVE = None
    clear_context()


def arm_from_env(node_name: str = "") -> QosPlane | None:
    """Arm from ``CORDA_TPU_QOS``: unset/empty/"0"/"off" stays disarmed;
    "1"/"on" arms with defaults; otherwise a comma-separated k=v list
    (``slo_ms=50,guard_ms=5,bulk_every=4``). Process-wide, like the obs
    arming — driver-spawned nodes arm from their [qos] config instead."""
    raw = os.environ.get(ENV_VAR, "").strip()
    if not raw or raw.lower() in ("0", "off", "false"):
        return None
    kwargs: dict[str, float] = {}
    if raw.lower() not in ("1", "on", "true"):
        for part in raw.split(","):
            if "=" not in part:
                continue
            key, _, value = part.partition("=")
            try:
                val = float(value)
            except ValueError:
                continue
            key = key.strip()
            if key in ("slo_ms", "deadline_guard_ms", "bulk_every"):
                kwargs[key] = val
            elif key == "guard_ms":
                kwargs["deadline_guard_ms"] = val
    if "bulk_every" in kwargs:
        kwargs["bulk_every"] = int(kwargs["bulk_every"])
    return arm(node_name, **kwargs)
