"""Canonical deterministic serialization (wire + checkpoint format)."""

from .codec import (  # noqa: F401
    SerializedBytes,
    register,
    register_class,
    serialize,
    deserialize,
    serialized_hash,
    DeserializationError,
)
