"""Canonical, deterministic, whitelisted binary serialization.

Capability match for the reference's Kryo layer (reference:
core/src/main/kotlin/net/corda/core/serialization/Kryo.kt:41-507): one format
serves the wire protocol, transaction-component hashing and flow checkpoints,
with a registration whitelist so deserialization can never instantiate
unexpected classes (the reference gets this from registered Kryo serializers
and its attack-surface notes).

Unlike Kryo this format is *canonical* in both directions: a value has exactly
one encoding (sorted dict/set entries, minimal varints), and the decoder
*rejects* any non-canonical byte string (non-minimal varints, unsorted or
duplicate entries) — so distinct blobs never decode to equal values and every
stored blob is tamper-evident by re-hash. This matters because transaction ids
are Merkle roots over serialized components (reference:
core/.../transactions/WireTransaction.kt:45-52, MerkleTransaction.kt:26-38)
and must be stable across processes, hosts and framework versions. Design:

  tag byte, then payload:
    0x00 None        0x01 False        0x02 True
    0x03 int         zigzag varint (arbitrary precision)
    0x04 bytes       varint length + raw
    0x05 str         varint length + utf-8
    0x06 list/tuple  varint count + items
    0x07 dict        varint count + alternating key/value, entries sorted by
                     encoded key (canonical regardless of insertion order)
    0x08 object      registered type name (str payload) + varint field count
                     + field values in dataclass field order
    0x09 frozenset   varint count + items sorted by their encodings
    0x0A float       8-byte IEEE-754 big-endian; finite only, -0.0
                     normalized to 0.0 (one encoding per equal value)

Dataclasses register with `@register` (or `register_class`); the registry maps
a stable wire name to the class. Deserializing an unregistered name raises
DeserializationError — the whitelist seam that mirrors the reference's
controlled Kryo registration.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Type, TypeVar

T = TypeVar("T")

_TAG_NONE = 0x00
_TAG_FALSE = 0x01
_TAG_TRUE = 0x02
_TAG_INT = 0x03
_TAG_BYTES = 0x04
_TAG_STR = 0x05
_TAG_LIST = 0x06
_TAG_DICT = 0x07
_TAG_OBJECT = 0x08
_TAG_FROZENSET = 0x09
_TAG_FLOAT = 0x0A


class DeserializationError(Exception):
    pass


_BY_NAME: dict[str, type] = {}
_BY_TYPE: dict[type, str] = {}
_CUSTOM_ENC: dict[type, Callable[[Any], tuple]] = {}
_CUSTOM_DEC: dict[str, Callable[[tuple], Any]] = {}
# type -> (encoded wire-name bytes, tuple of field names) — computed once per
# class; dataclasses.fields() + str.encode() per encode call was the hottest
# line in the notary-roundtrip profile.
_ENC_PLAN: dict[type, tuple[bytes, tuple[str, ...]]] = {}
# wire name -> (cls, ((field name, is_list_typed), ...)) for decode.
_DEC_PLAN: dict[str, tuple[type, tuple[tuple[str, bool], ...]]] = {}
# Immutable value types whose full encoding may be memoized on the instance
# (attribute _codec_enc). Opt-in via mark_cacheable: the type must be deeply
# immutable plain data (no service tokens), so the bytes stay valid for the
# object's lifetime. SignedTransaction in a flow's checkpoint args was being
# re-encoded on every suspension.
_CACHEABLE: set[type] = set()


def mark_cacheable(*classes: type) -> None:
    """Enable instance-level encoding memoization for immutable value types."""
    _CACHEABLE.update(classes)


def register_class(
    cls: Type[T],
    name: str | None = None,
    encode: Callable[[Any], tuple] | None = None,
    decode: Callable[[tuple], Any] | None = None,
) -> Type[T]:
    """Whitelist a class for serialization.

    Dataclasses need no encode/decode: their fields (in declaration order) are
    the wire representation. Other classes supply encode (instance -> tuple of
    serializable values) and decode (tuple -> instance).
    """
    wire_name = name or f"{cls.__module__.removeprefix('corda_tpu.')}.{cls.__qualname__}"
    if wire_name in _BY_NAME and _BY_NAME[wire_name] is not cls:
        raise ValueError(f"wire name {wire_name!r} already registered")
    _BY_NAME[wire_name] = cls
    _BY_TYPE[cls] = wire_name
    if encode is not None:
        _CUSTOM_ENC[cls] = encode
    if decode is not None:
        _CUSTOM_DEC[wire_name] = decode
    elif not dataclasses.is_dataclass(cls):
        raise ValueError(f"{cls} is not a dataclass; provide encode/decode")
    return cls


def register(cls: Type[T]) -> Type[T]:
    """Decorator form of register_class for dataclasses."""
    return register_class(cls)


def wire_name_of(cls: type) -> str | None:
    """The registered wire name of a class, None when unregistered.

    Public read-side of the registry for stores that index rows by state
    type (the vault's state_type pushdown column): the wire name is the
    one type identifier that is stable across processes and refactors,
    unlike __qualname__ paths."""
    return _BY_TYPE.get(cls)


def class_for_wire_name(name: str) -> type | None:
    """The class registered under a wire name, None when unknown."""
    return _BY_NAME.get(name)


# Resolved lazily on the first object encode (.tokens imports this module,
# so a top-level import would be circular); a per-call `from .tokens import`
# in the encode hot path showed up in profiles at firehose load.
SerializeAsToken = None
current_token_context = None


def _write_varint(out: bytearray, n: int) -> None:
    if n < 0:
        raise ValueError("varint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise DeserializationError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            # Canonicality: a multi-byte varint whose final group is zero has
            # a shorter encoding — reject so every int has exactly one form.
            if b == 0 and shift > 0:
                raise DeserializationError("non-minimal varint")
            return result, pos
        shift += 7


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> (n.bit_length() + 1)) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _encode(out: bytearray, value: Any) -> None:
    if value is None:
        out.append(_TAG_NONE)
    elif value is False:
        out.append(_TAG_FALSE)
    elif value is True:
        out.append(_TAG_TRUE)
    elif isinstance(value, int):
        out.append(_TAG_INT)
        _write_varint(out, _zigzag(value))
    elif isinstance(value, float):
        import math
        import struct as _struct

        if not math.isfinite(value):
            raise TypeError("non-finite floats are not serializable")
        if value == 0.0:
            value = 0.0  # normalize -0.0: equal values, one encoding
        out.append(_TAG_FLOAT)
        out.extend(_struct.pack(">d", value))
    elif isinstance(value, bytes):
        out.append(_TAG_BYTES)
        _write_varint(out, len(value))
        out.extend(value)
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out.append(_TAG_STR)
        _write_varint(out, len(raw))
        out.extend(raw)
    elif isinstance(value, (list, tuple)):
        out.append(_TAG_LIST)
        _write_varint(out, len(value))
        for item in value:
            _encode(out, item)
    elif isinstance(value, dict):
        entries = []
        for k, v in value.items():
            kbuf = bytearray()
            _encode(kbuf, k)
            vbuf = bytearray()
            _encode(vbuf, v)
            entries.append((bytes(kbuf), bytes(vbuf)))
        entries.sort()  # canonical: equal dicts encode identically
        out.append(_TAG_DICT)
        _write_varint(out, len(entries))
        for kenc, venc in entries:
            out.extend(kenc)
            out.extend(venc)
    elif isinstance(value, frozenset):
        encs = []
        for item in value:
            buf = bytearray()
            _encode(buf, item)
            encs.append(bytes(buf))
        encs.sort()
        out.append(_TAG_FROZENSET)
        _write_varint(out, len(encs))
        for e in encs:
            out.extend(e)
    else:
        # ONE semantic authority for the object branch (_object_parts):
        # registry/whitelist, service tokens, custom encoders and the memo
        # all live there, shared with the native encoder's callback.
        parts = _object_parts(value)
        if isinstance(parts, bytes):  # memo hit / pre-encoded token
            out.extend(parts)
            return
        name_raw, fields, cacheable = parts
        start = len(out)
        out.append(_TAG_OBJECT)
        _write_varint(out, len(name_raw))
        out.extend(name_raw)
        _write_varint(out, len(fields))
        for f in fields:
            _encode(out, f)
        if cacheable:
            _memo_store(value, bytes(out[start:]))


_MAX_DEPTH = 64  # hostile nesting must exhaust this, not the Python stack


def _decode(data: bytes, pos: int, depth: int = 0) -> tuple[Any, int]:
    if depth > _MAX_DEPTH:
        raise DeserializationError("nesting too deep")
    if pos >= len(data):
        raise DeserializationError("truncated data")
    tag = data[pos]
    pos += 1
    if tag == _TAG_NONE:
        return None, pos
    if tag == _TAG_FALSE:
        return False, pos
    if tag == _TAG_TRUE:
        return True, pos
    if tag == _TAG_INT:
        n, pos = _read_varint(data, pos)
        return _unzigzag(n), pos
    if tag == _TAG_FLOAT:
        import math
        import struct as _struct

        if pos + 8 > len(data):
            raise DeserializationError("truncated float")
        (value,) = _struct.unpack(">d", data[pos:pos + 8])
        if not math.isfinite(value):
            raise DeserializationError("non-finite float")
        if value == 0.0 and data[pos] != 0:
            raise DeserializationError("non-canonical negative zero")
        return value, pos + 8
    if tag == _TAG_BYTES:
        n, pos = _read_varint(data, pos)
        if pos + n > len(data):
            raise DeserializationError("truncated bytes")
        return data[pos : pos + n], pos + n
    if tag == _TAG_STR:
        n, pos = _read_varint(data, pos)
        if pos + n > len(data):
            raise DeserializationError("truncated string")
        try:
            return data[pos : pos + n].decode("utf-8"), pos + n
        except UnicodeDecodeError as e:
            raise DeserializationError(f"invalid utf-8 string: {e}") from e
    if tag == _TAG_LIST:
        n, pos = _read_varint(data, pos)
        if n > len(data) - pos:  # every item needs >= 1 byte: cheap DoS gate
            raise DeserializationError("collection count exceeds data")
        items = []
        for _ in range(n):
            item, pos = _decode(data, pos, depth + 1)
            items.append(item)
        return tuple(items), pos
    if tag == _TAG_DICT:
        n, pos = _read_varint(data, pos)
        if n > len(data) - pos:
            raise DeserializationError("collection count exceeds data")
        d = {}
        prev_kenc: bytes | None = None
        for _ in range(n):
            kstart = pos
            k, pos = _decode(data, pos, depth + 1)
            kenc = data[kstart:pos]
            v, pos = _decode(data, pos, depth + 1)
            # Canonicality: KEY encodings must arrive strictly increasing —
            # strictness on the key alone also rejects duplicate keys (a
            # duplicate with a larger value encoding would otherwise pass a
            # (key, value)-pair comparison), so distinct byte strings can
            # never decode to equal dicts.
            if prev_kenc is not None and kenc <= prev_kenc:
                raise DeserializationError("non-canonical dict entry order")
            prev_kenc = kenc
            try:
                d[k] = v
            except TypeError as e:  # unhashable key (e.g. a dict)
                raise DeserializationError(f"unhashable dict key: {e}") from e
        return d, pos
    if tag == _TAG_FROZENSET:
        n, pos = _read_varint(data, pos)
        if n > len(data) - pos:
            raise DeserializationError("collection count exceeds data")
        items = []
        prev_enc: bytes | None = None
        for _ in range(n):
            start = pos
            item, pos = _decode(data, pos, depth + 1)
            enc = data[start:pos]
            if prev_enc is not None and enc <= prev_enc:
                raise DeserializationError("non-canonical frozenset order")
            prev_enc = enc
            items.append(item)
        try:
            return frozenset(items), pos
        except TypeError as e:  # unhashable member (e.g. a dict)
            raise DeserializationError(f"unhashable set member: {e}") from e
    if tag == _TAG_OBJECT:
        n, pos = _read_varint(data, pos)
        if pos + n > len(data):
            raise DeserializationError("truncated wire name")
        try:
            wire_name = data[pos : pos + n].decode("utf-8")
        except UnicodeDecodeError as e:
            raise DeserializationError(f"invalid wire name: {e}") from e
        pos += n
        nfields, pos = _read_varint(data, pos)
        if nfields > len(data) - pos:
            raise DeserializationError("collection count exceeds data")
        values = []
        for _ in range(nfields):
            v, pos = _decode(data, pos, depth + 1)
            values.append(v)
        return _construct(wire_name, tuple(values)), pos
    raise DeserializationError(f"unknown tag 0x{tag:02x}")


def _construct(wire_name: str, values: tuple) -> Any:
    """Registry lookup + construction for a decoded object — shared by the
    pure-Python decoder above and the native decode core (which decodes the
    wire structure in C and calls back here, so the whitelist and
    construction semantics live in exactly one place)."""
    if wire_name == "__svc_token__":
        from .tokens import current_token_context

        if len(values) != 1:
            raise DeserializationError("malformed service token")
        token_name = values[0]
        if not isinstance(token_name, str):
            # An unhashable/wrong-typed name must reject, not TypeError
            # out of the registry lookup.
            raise DeserializationError("service token name must be a string")
        ctx = current_token_context()
        if ctx is None:
            raise DeserializationError(
                f"service token {token_name!r} outside a TokenContext"
            )
        try:
            return ctx.resolve(token_name)
        except KeyError as e:
            raise DeserializationError(str(e)) from e
    cls = _BY_NAME.get(wire_name)
    if cls is None:
        raise DeserializationError(f"type {wire_name!r} is not whitelisted")
    dec = _CUSTOM_DEC.get(wire_name)
    if dec is not None:
        try:
            return dec(values)
        except Exception as e:  # malformed payloads must not crash callers
            raise DeserializationError(
                f"cannot decode {wire_name}: {e}") from e
    plan = _DEC_PLAN.get(wire_name)
    if plan is None:
        plan = _DEC_PLAN[wire_name] = (cls, tuple(
            (f.name, str(f.type).startswith(("list", "List")))
            for f in dataclasses.fields(cls)))
    _, field_plan = plan
    if len(values) != len(field_plan):
        raise DeserializationError(
            f"{wire_name}: expected {len(field_plan)} fields, "
            f"got {len(values)}"
        )
    kwargs = {}
    for (fname, is_list), v in zip(field_plan, values):
        # Tuples are the wire form of all sequences; convert back per the
        # declared field so list-typed fields round-trip.
        if is_list and isinstance(v, tuple):
            v = list(v)
        kwargs[fname] = v
    try:
        return cls(**kwargs)
    except Exception as e:  # malformed payloads must not crash callers
        raise DeserializationError(f"cannot construct {wire_name}: {e}") from e


@dataclasses.dataclass(frozen=True)
class SerializedBytes:
    """A typed wrapper over a serialized blob (reference: Kryo.kt:76-81)."""

    bytes: bytes

    @property
    def hash(self):
        from ..crypto.hashes import SecureHash

        return SecureHash.sha256(self.bytes)

    def deserialize(self) -> Any:
        return deserialize(self.bytes)

    def __len__(self) -> int:
        return len(self.bytes)


def serialize(value: Any) -> SerializedBytes:
    if _ccodec is not None:
        return SerializedBytes(_ccodec.encode(value))
    out = bytearray()
    _encode(out, value)
    return SerializedBytes(bytes(out))


def _object_parts(value: Any):
    """The object branch's single semantic authority, shared by the pure
    encoder (_encode's tail) and the native encoder's callback. Returns
    bytes to splice verbatim (memo hits, service tokens, wide integers the
    C core punts on) OR (wire_name_bytes, fields_tuple, memoize_bool) for
    the caller to encode."""
    if isinstance(value, (int, float)):  # wide-int fallback from C
        out = bytearray()
        _encode(out, value)
        return bytes(out)
    global SerializeAsToken, current_token_context
    if SerializeAsToken is None:  # lazy: .tokens imports this module
        from .tokens import SerializeAsToken, current_token_context
    if isinstance(value, SerializeAsToken):
        # Long-lived services become named tokens in checkpoints
        # (reference: SerializationToken.kt:25-133). Valid only inside an
        # active TokenContext. Encoded directly here (NOT via _encode,
        # whose object tail would recurse back into this function).
        ctx = current_token_context()
        if ctx is None:
            raise TypeError(
                f"{type(value).__qualname__} is a service token; it can "
                "only be serialized inside a checkpoint TokenContext"
            )
        out = bytearray()
        out.append(_TAG_OBJECT)
        raw = b"__svc_token__"
        _write_varint(out, len(raw))
        out.extend(raw)
        _write_varint(out, 1)
        _encode(out, value.token_name)
        return bytes(out)
    cls = type(value)
    cacheable = cls in _CACHEABLE
    if cacheable:
        # getattr, not value.__dict__: a __slots__ class has no instance
        # dict and must skip the memo on the read side too (the write
        # side already guards; round-3 advisor).
        cached = getattr(value, "_codec_enc", None)
        if cached is not None:
            return cached
    plan = _ENC_PLAN.get(cls)
    if plan is None:
        wire_name = _BY_TYPE.get(cls)
        if wire_name is None:
            raise TypeError(
                f"type {cls.__qualname__} is not registered for serialization")
        name_raw = wire_name.encode("utf-8")
        names = (() if cls in _CUSTOM_ENC else
                 tuple(f.name for f in dataclasses.fields(cls)))
        plan = _ENC_PLAN[cls] = (name_raw, names)
    name_raw, names = plan
    enc = _CUSTOM_ENC.get(cls)
    if enc is not None:
        fields = tuple(enc(value))
    else:
        fields = tuple(getattr(value, n) for n in names)
    return (name_raw, fields, cacheable)


def _memo_store(value: Any, enc: bytes) -> None:
    try:
        object.__setattr__(value, "_codec_enc", enc)
    except AttributeError:
        pass  # __slots__ types simply skip the memo


def deserialize(data: bytes | SerializedBytes) -> Any:
    raw = data.bytes if isinstance(data, SerializedBytes) else data
    if _ccodec is not None:
        return _ccodec.decode(raw)
    value, pos = _decode(raw, 0)
    if pos != len(raw):
        raise DeserializationError(f"{len(raw) - pos} trailing bytes")
    return value


# Native decode core (corda_tpu/native/_ccodec.c): decodes the wire
# structure in C and calls _construct for objects. Loaded lazily with a
# silent fallback — the pure-Python decoder above stays the semantic
# authority, and the conformance suite runs both against the same corpus.
_ccodec = None


def _load_native() -> bool:
    """Try to enable the native decode core; True if active."""
    global _ccodec
    if _ccodec is not None:
        return True
    try:
        from ..native import load_ccodec

        module = load_ccodec()
    except Exception:
        return False
    if module is None:
        return False
    module.init(DeserializationError, _construct, _object_parts, _memo_store)
    _ccodec = module
    return True


_load_native()


def serialized_hash(value: Any):
    """Hash of the canonical serialization — the Merkle leaf function
    (reference: MerkleTransaction.kt:35-38)."""
    from ..crypto.hashes import SecureHash

    return SecureHash(hashlib.sha256(serialize(value).bytes).digest())
