"""Service-reference tokenization for checkpoints.

Capability match for the reference's SerializeAsToken machinery (reference:
core/src/main/kotlin/net/corda/core/serialization/SerializationToken.kt:25-133,
used by the state machine manager at node/.../statemachine/
StateMachineManager.kt:288-305): long-lived node services referenced from flow
state must not be serialized into checkpoints — they serialize as named
tokens, and deserialization resolves the token against the current node's
service registry.

A node builds a TokenContext of its singleton services; the state machine
manager activates it (context manager) around checkpoint serialize/restore.
"""

from __future__ import annotations

import contextvars
from typing import Any

_current_context: contextvars.ContextVar["TokenContext | None"] = contextvars.ContextVar(
    "corda_tpu_token_context", default=None
)


class SerializeAsToken:
    """Mixin: instances serialize as their `token_name` inside checkpoints."""

    @property
    def token_name(self) -> str:
        return type(self).__qualname__


class TokenContext:
    """A node's registry of tokenizable singleton services."""

    def __init__(self):
        self._by_name: dict[str, Any] = {}

    def register(self, service: SerializeAsToken) -> SerializeAsToken:
        name = service.token_name
        existing = self._by_name.get(name)
        if existing is not None and existing is not service:
            raise ValueError(f"token {name!r} already registered to a different service")
        self._by_name[name] = service
        return service

    def resolve(self, name: str) -> Any:
        if name not in self._by_name:
            raise KeyError(f"no service registered for token {name!r}")
        return self._by_name[name]

    def __enter__(self) -> "TokenContext":
        self._reset = _current_context.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        _current_context.reset(self._reset)
        return False


def current_token_context() -> TokenContext | None:
    return _current_context.get()
