"""Wire-format whitelist registrations for core types.

The analogue of the reference's central Kryo registration block (reference:
core/src/main/kotlin/net/corda/core/serialization/Kryo.kt:400-507): one place
that whitelists every type allowed on the wire / in checkpoints. Importing
this module (via corda_tpu/__init__.py) makes the core types serializable;
higher layers register their own types at definition with @register.
"""

from __future__ import annotations

from ..crypto.composite import CompositeKeyLeaf, CompositeKeyNode
from ..crypto.hashes import SecureHash
from ..crypto.keys import DigitalSignature, PrivateKey, PublicKey
from ..crypto.merkle import (
    PartialIncludedLeaf,
    PartialLeaf,
    PartialMerkleTree,
    PartialNode,
)
from ..crypto.party import Party, PartyAndReference
from ..crypto.signed_data import SignedData
from ..utils.bytes import OpaqueBytes
from .codec import SerializedBytes, mark_cacheable, register_class

for _cls in (
    SecureHash,
    OpaqueBytes,
    SerializedBytes,
    PublicKey,
    PrivateKey,
    DigitalSignature,
    DigitalSignature.WithKey,
    DigitalSignature.LegallyIdentifiable,
    CompositeKeyLeaf,
    CompositeKeyNode,
    Party,
    PartyAndReference,
    SignedData,
    PartialIncludedLeaf,
    PartialLeaf,
    PartialNode,
    PartialMerkleTree,
):
    register_class(_cls)

# Deeply-immutable plain-data types on the checkpoint/message hot path:
# their canonical encoding is memoized per instance (codec._CACHEABLE).
mark_cacheable(
    SecureHash,
    SerializedBytes,
    PublicKey,
    DigitalSignature,
    DigitalSignature.WithKey,
    DigitalSignature.LegallyIdentifiable,
    CompositeKeyLeaf,
    CompositeKeyNode,
    Party,
    PartyAndReference,
)
