"""Test infrastructure: dummy contracts, canned identities, mock services,
deterministic in-memory network (MockNetwork), ledger DSL, fault injection.

Submodules are loaded lazily (PEP 562).  Production modules (the TCP
transport, Raft, the state machine) import ``corda_tpu.testing.faults``
for their injection hooks; an eager ``from .mock_network import ...``
here would drag ``node.messaging`` back in and create an import cycle.
"""

_EXPORTS = {
    # dummies
    "DummyContract": "dummies",
    "DummySingleOwnerState": "dummies",
    "DummyMultiOwnerState": "dummies",
    "DUMMY_PROGRAM_ID": "dummies",
    "DummyCreate": "dummies",
    "DummyMove": "dummies",
    # identities
    "ALICE": "identities",
    "ALICE_KEY": "identities",
    "BOB": "identities",
    "BOB_KEY": "identities",
    "CHARLIE": "identities",
    "CHARLIE_KEY": "identities",
    "DUMMY_NOTARY": "identities",
    "DUMMY_NOTARY_KEY": "identities",
    "MEGA_CORP": "identities",
    "MEGA_CORP_KEY": "identities",
    "MINI_CORP": "identities",
    "MINI_CORP_KEY": "identities",
    # mock network
    "MockNetwork": "mock_network",
    "MockNode": "mock_network",
    # ledger DSL / expectations / simulation
    "ledger": "ledger_dsl",
    "expect": "expect",
    "expect_events": "expect",
    "parallel": "expect",
    "sequence": "expect",
    "Simulation": "simulation",
    "TradeSimulation": "simulation",
}

_SUBMODULES = {"dummies", "identities", "mock_network", "ledger_dsl",
               "expect", "simulation", "faults", "driver", "generators"}

__all__ = sorted(_EXPORTS) + sorted(_SUBMODULES)


def __getattr__(name):
    import importlib

    if name in _EXPORTS:
        module = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        value = getattr(module, name)
        globals()[name] = value
        return value
    if name in _SUBMODULES:
        module = importlib.import_module(f".{name}", __name__)
        globals()[name] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return __all__
