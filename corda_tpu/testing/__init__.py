"""Test infrastructure: dummy contracts, canned identities, mock services,
deterministic in-memory network (MockNetwork), ledger DSL."""

from .dummies import (  # noqa: F401
    DummyContract,
    DummySingleOwnerState,
    DummyMultiOwnerState,
    DUMMY_PROGRAM_ID,
    DummyCreate,
    DummyMove,
)
from .identities import (  # noqa: F401
    ALICE,
    ALICE_KEY,
    BOB,
    BOB_KEY,
    CHARLIE,
    CHARLIE_KEY,
    DUMMY_NOTARY,
    DUMMY_NOTARY_KEY,
    MEGA_CORP,
    MEGA_CORP_KEY,
    MINI_CORP,
    MINI_CORP_KEY,
)
from .mock_network import MockNetwork, MockNode  # noqa: F401
from .ledger_dsl import ledger  # noqa: F401
from .expect import expect, expect_events, parallel, sequence  # noqa: F401
from .simulation import Simulation, TradeSimulation  # noqa: F401
