"""The multi-process driver: spawn REAL node processes for integration tests.

Capability match for the reference's driver DSL (reference:
node/src/main/kotlin/net/corda/node/driver/Driver.kt:56-107 — spawns real
node JVMs with real transport + network-map registration, hands back handles;
used by DriverTests, DistributedNotaryTests and every demo). Here each node
is a `python -m corda_tpu.node.node <config.toml>` subprocess over real
sockets and its own sqlite; the driver writes configs, waits for the "up at"
banner, and exposes RPC handles and kill/restart for disruption tests.

Usage:
    with driver(tmp_path) as d:
        notary = d.start_node("Notary", notary="simple")
        party = d.start_node("Alice", cordapps=[...], rpc=True)
        client = party.rpc("demo", "s3cret")
        handle = client.start_flow("IssueAndNotariseFlow", 7)
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

DEFAULT_RPC_USER = {"username": "demo", "password": "s3cret",
                    "permissions": ["ALL"]}


def _toml_escape(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    raise TypeError(f"cannot TOML-encode {v!r}")


@dataclass
class NodeProcess:
    name: str
    base_dir: Path
    config_path: Path
    process: subprocess.Popen
    address: tuple[str, int] | None = None
    rpc_users: list = field(default_factory=list)

    def wait_up(self, timeout: float = 60.0) -> "NodeProcess":
        """Block until the node prints its startup banner; parse the port."""
        deadline = time.monotonic() + timeout
        assert self.process.stdout is not None
        while time.monotonic() < deadline:
            if self.process.poll() is not None:
                raise RuntimeError(
                    f"node {self.name} exited with {self.process.returncode}")
            line = self.process.stdout.readline()
            if not line:
                time.sleep(0.02)
                continue
            text = line.decode(errors="replace").strip()
            if text.startswith(f"node {self.name} up at "):
                host, port = text.rsplit(" ", 1)[-1].rsplit(":", 1)
                self.address = (host, int(port))
                return self
        raise TimeoutError(f"node {self.name} did not come up in {timeout}s")

    def rpc(self, user: str, password: str, timeout: float = 20.0):
        from ..node.messaging.tcp import TcpAddress
        from ..node.rpc import RpcClient

        assert self.address is not None, "wait_up first"
        return RpcClient(TcpAddress(*self.address), user, password,
                         timeout=timeout)

    def kill(self) -> None:
        """SIGKILL — the Disruption.kt:18-60 'kill the process' primitive."""
        self.process.kill()
        self.process.wait(timeout=10)

    def terminate(self) -> None:
        self.process.terminate()
        try:
            self.process.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=5)


class Driver:
    def __init__(self, base_dir: Path):
        self.base_dir = Path(base_dir)
        self.nodes: list[NodeProcess] = []
        self.netmap = self.base_dir / "netmap.json"

    def start_node(self, name: str, notary: str = "none",
                   cordapps: tuple[str, ...] = (), rpc: bool = False,
                   raft_cluster: tuple[str, ...] = (),
                   wait: bool = True, extra_toml: str = "") -> NodeProcess:
        node_dir = self.base_dir / name
        node_dir.mkdir(parents=True, exist_ok=True)
        lines = [
            f"name = {_toml_escape(name)}",
            f"base_dir = {_toml_escape(str(node_dir))}",
            f"network_map = {_toml_escape(str(self.netmap))}",
            f"notary = {_toml_escape(notary)}",
        ]
        if raft_cluster:
            lines.append(
                "raft_cluster = ["
                + ", ".join(_toml_escape(n) for n in raft_cluster) + "]")
        if cordapps:
            lines.append(
                "cordapps = ["
                + ", ".join(_toml_escape(c) for c in cordapps) + "]")
        rpc_users = [DEFAULT_RPC_USER] if rpc else []
        for user in rpc_users:
            lines.append("[[rpc_users]]")
            lines.append(f"username = {_toml_escape(user['username'])}")
            lines.append(f"password = {_toml_escape(user['password'])}")
            lines.append("permissions = ["
                         + ", ".join(_toml_escape(p)
                                     for p in user["permissions"]) + "]")
        if extra_toml:
            lines.append(extra_toml)
        config_path = node_dir / "node.toml"
        config_path.write_text("\n".join(lines) + "\n")

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")  # node processes don't need TPU
        process = subprocess.Popen(
            [sys.executable, "-m", "corda_tpu.node.node", str(config_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd="/root/repo", env=env)
        handle = NodeProcess(name, node_dir, config_path, process,
                             rpc_users=rpc_users)
        self.nodes.append(handle)
        if wait:
            handle.wait_up()
        return handle

    def stop_all(self) -> None:
        for node in self.nodes:
            if node.process.poll() is None:
                node.terminate()


@contextmanager
def driver(base_dir: str | Path):
    d = Driver(Path(base_dir))
    try:
        yield d
    finally:
        d.stop_all()
