"""The multi-process driver: spawn REAL node processes for integration tests.

Capability match for the reference's driver DSL (reference:
node/src/main/kotlin/net/corda/node/driver/Driver.kt:56-107 — spawns real
node JVMs with real transport + network-map registration, hands back handles;
used by DriverTests, DistributedNotaryTests and every demo). Here each node
is a `python -m corda_tpu.node.node <config.toml>` subprocess over real
sockets and its own sqlite; the driver writes configs, waits for the "up at"
banner, and exposes RPC handles and kill/restart for disruption tests.

Usage:
    with driver(tmp_path) as d:
        notary = d.start_node("Notary", notary="simple")
        party = d.start_node("Alice", cordapps=[...], rpc=True)
        client = party.rpc("demo", "s3cret")
        handle = client.start_flow("IssueAndNotariseFlow", 7)
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

DEFAULT_RPC_USER = {"username": "demo", "password": "s3cret",
                    "permissions": ["ALL"]}


def _toml_escape(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    raise TypeError(f"cannot TOML-encode {v!r}")


@dataclass
class NodeProcess:
    name: str
    base_dir: Path
    config_path: Path
    process: subprocess.Popen
    address: tuple[str, int] | None = None
    rpc_users: list = field(default_factory=list)
    device: str = "cpu"  # "cpu" | "accelerator" — survives restart_node

    @property
    def log_path(self) -> Path:
        return self.base_dir / "node.log"

    def wait_up(self, timeout: float = 60.0) -> "NodeProcess":
        """Block until the node logs its startup banner; parse the port.
        Output goes to base_dir/node.log (NOT a pipe: an undrained pipe
        would eventually block the node on a full buffer, and the log
        survives for post-mortem)."""
        deadline = time.monotonic() + timeout
        prefix = f"node {self.name} up at "
        while time.monotonic() < deadline:
            if self.process.poll() is not None:
                tail = ""
                try:
                    tail = self.log_path.read_text(errors="replace")[-2000:]
                except OSError:
                    pass
                raise RuntimeError(
                    f"node {self.name} exited with {self.process.returncode}:"
                    f"\n{tail}")
            try:
                text = self.log_path.read_text(errors="replace")
            except OSError:
                text = ""
            for line in text.splitlines():
                if line.startswith(prefix):
                    host, port = line.rsplit(" ", 1)[-1].rsplit(":", 1)
                    self.address = (host, int(port))
                    return self
            time.sleep(0.02)
        raise TimeoutError(f"node {self.name} did not come up in {timeout}s")

    def rpc(self, user: str, password: str, timeout: float = 20.0):
        from ..node.messaging.tcp import TcpAddress
        from ..node.rpc import RpcClient

        assert self.address is not None, "wait_up first"
        return RpcClient(TcpAddress(*self.address), user, password,
                         timeout=timeout)

    def kill(self) -> None:
        """SIGKILL — the Disruption.kt:18-60 'kill the process' primitive."""
        self.process.kill()
        self.process.wait(timeout=10)

    def sigstop(self) -> None:
        """SIGSTOP — the 'hang' primitive (Disruption.kt strainer): the
        process is frozen, not dead; peers see an unresponsive node whose
        sockets stay open — a different failure mode than a clean kill."""
        import signal

        self.process.send_signal(signal.SIGSTOP)

    def sigcont(self) -> None:
        import signal

        self.process.send_signal(signal.SIGCONT)

    def strain(self, seconds: float = 5.0, duty: float = 0.8,
               period: float = 0.1) -> "threading.Thread":
        """CPU-strain disruption (reference: Disruption.kt strainCpu): the
        node is made SLOW-BUT-ALIVE — frozen for `duty` of every `period`
        via SIGSTOP/SIGCONT duty-cycling on a background thread, the
        portable equivalent of the reference's openssl busy-loop siblings.
        Sockets stay open; peers see a node that responds, late — the
        failure mode that exposes timeout tuning, distinct from both a
        clean kill and a full hang. Returns the (daemon) thread; join it to
        wait the strain out."""
        import threading

        def cycle():
            end = time.monotonic() + seconds
            while time.monotonic() < end and self.process.poll() is None:
                try:
                    self.sigstop()
                    time.sleep(duty * period)
                    self.sigcont()
                    time.sleep((1.0 - duty) * period)
                except (OSError, ValueError):
                    return  # process gone mid-cycle
            try:  # never leave the node frozen
                self.sigcont()
            except (OSError, ValueError):
                pass

        t = threading.Thread(target=cycle, daemon=True,
                             name=f"strain-{self.name}")
        t.start()
        return t

    def terminate(self) -> None:
        self.process.terminate()
        try:
            self.process.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=5)


def render_node_config(name: str, node_dir, netmap, notary: str = "none",
                       raft_cluster: tuple[str, ...] = (),
                       cordapps: tuple[str, ...] = (),
                       extra_toml: str = "",
                       rpc_users: list | None = None) -> str:
    """The node.toml the driver writes for a child. Ordering is
    load-bearing: extra_toml goes BEFORE any [[rpc_users]] table — TOML
    keys after a table header belong to that table, so a trailing
    `verifier = ...` would silently become an rpc_users field and the node
    would run the default verifier (observed: every RPC-enabled node
    ignored its configured verifier)."""
    lines = [
        f"name = {_toml_escape(name)}",
        f"base_dir = {_toml_escape(str(node_dir))}",
        f"network_map = {_toml_escape(str(netmap))}",
        f"notary = {_toml_escape(notary)}",
    ]
    if raft_cluster:
        lines.append(
            "raft_cluster = ["
            + ", ".join(_toml_escape(n) for n in raft_cluster) + "]")
    if cordapps:
        lines.append(
            "cordapps = ["
            + ", ".join(_toml_escape(c) for c in cordapps) + "]")
    if extra_toml:
        lines.append(extra_toml)
    for user in rpc_users or []:
        lines.append("[[rpc_users]]")
        lines.append(f"username = {_toml_escape(user['username'])}")
        lines.append(f"password = {_toml_escape(user['password'])}")
        lines.append("permissions = ["
                     + ", ".join(_toml_escape(p)
                                 for p in user["permissions"]) + "]")
    return "\n".join(lines) + "\n"


def _node_env(device: str) -> dict:
    """Per-node device policy (the production topology: only the notary
    process owns the accelerator; every other child stays on the host
    path — one tunnel chip cannot be shared by five processes).

    * "cpu": pin the child to the host platform.
    * "accelerator": strip any inherited platform pin / virtual-mesh flags
      so the child initialises the real backend lazily, on its first
      verify batch (node startup never blocks on a wedged tunnel).
    """
    env = dict(os.environ)
    if device == "accelerator":
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
    else:
        env.setdefault("JAX_PLATFORMS", "cpu")
    return env


class Driver:
    def __init__(self, base_dir: Path):
        self.base_dir = Path(base_dir)
        self.nodes: list[NodeProcess] = []
        self._deferred: list = []  # cleanup callbacks (run first in stop_all)
        self.netmap = self.base_dir / "netmap.json"

    def start_node(self, name: str, notary: str = "none",
                   cordapps: tuple[str, ...] = (), rpc: bool = False,
                   raft_cluster: tuple[str, ...] = (),
                   wait: bool = True, extra_toml: str = "",
                   device: str = "cpu") -> NodeProcess:
        node_dir = self.base_dir / name
        node_dir.mkdir(parents=True, exist_ok=True)
        rpc_users = [DEFAULT_RPC_USER] if rpc else []
        config_path = node_dir / "node.toml"
        config_path.write_text(render_node_config(
            name=name, node_dir=node_dir, netmap=self.netmap, notary=notary,
            raft_cluster=raft_cluster, cordapps=cordapps,
            extra_toml=extra_toml, rpc_users=rpc_users))

        env = _node_env(device)
        log = open(node_dir / "node.log", "ab")
        process = subprocess.Popen(
            [sys.executable, "-m", "corda_tpu.node.node", str(config_path)],
            stdout=log, stderr=subprocess.STDOUT,
            cwd="/root/repo", env=env)
        log.close()  # the child owns the fd now
        handle = NodeProcess(name, node_dir, config_path, process,
                             rpc_users=rpc_users, device=device)
        self.nodes.append(handle)
        if wait:
            handle.wait_up()
        return handle

    def restart_node(self, handle: NodeProcess,
                     wait: bool = True) -> NodeProcess:
        """Re-spawn a (killed) node over its existing base_dir + config —
        rebirth purely from disk (the kill/restart Disruption primitive)."""
        env = _node_env(handle.device)
        log = open(handle.base_dir / "node.log", "ab")
        process = subprocess.Popen(
            [sys.executable, "-m", "corda_tpu.node.node",
             str(handle.config_path)],
            stdout=log, stderr=subprocess.STDOUT,
            cwd="/root/repo", env=env)
        log.close()
        reborn = NodeProcess(handle.name, handle.base_dir, handle.config_path,
                             process, rpc_users=handle.rpc_users,
                             device=handle.device)
        self.nodes.append(reborn)
        if wait:
            reborn.wait_up()
        return reborn

    def defer(self, cleanup) -> None:
        """Register a cleanup (e.g. an RpcClient.close) to run at driver
        exit, BEFORE nodes are stopped — success or exception alike."""
        self._deferred.append(cleanup)

    def stop_all(self) -> None:
        for cleanup in self._deferred:
            try:
                cleanup()
            except Exception:
                pass
        self._deferred.clear()
        for node in self.nodes:
            if node.process.poll() is None:
                try:
                    node.sigcont()  # un-freeze SIGSTOP'd nodes so they exit
                except (OSError, ValueError):
                    pass
                node.terminate()


@contextmanager
def driver(base_dir: str | Path):
    d = Driver(Path(base_dir))
    try:
        yield d
    finally:
        d.stop_all()
