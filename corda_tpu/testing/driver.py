"""The multi-process driver: spawn REAL node processes for integration tests.

Capability match for the reference's driver DSL (reference:
node/src/main/kotlin/net/corda/node/driver/Driver.kt:56-107 — spawns real
node JVMs with real transport + network-map registration, hands back handles;
used by DriverTests, DistributedNotaryTests and every demo). Here each node
is a `python -m corda_tpu.node.node <config.toml>` subprocess over real
sockets and its own sqlite; the driver writes configs, waits for the "up at"
banner, and exposes RPC handles and kill/restart for disruption tests.

Node PLACEMENT goes through the Host seam (reference: the loadtest drives
nodes on remote machines over SSH, tools/loadtest/.../ConnectionManager.kt):
every file write, log read and process spawn is a Host method, so the
harness never assumes localhost — LocalHost is the in-tree placement; an
SSH host implements the same four methods to run the identical workload
against a remote cluster.

Usage:
    with driver(tmp_path) as d:
        notary = d.start_node("Notary", notary="simple")
        party = d.start_node("Alice", cordapps=[...], rpc=True)
        client = party.rpc("demo", "s3cret")
        handle = client.start_flow("IssueAndNotariseFlow", 7)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

DEFAULT_RPC_USER = {"username": "demo", "password": "s3cret",
                    "permissions": ["ALL"]}


class Host:
    """Node-placement seam (reference: tools/loadtest/src/main/kotlin/net/
    corda/loadtest/ConnectionManager.kt — the loadtest drives nodes on
    REMOTE hosts over SSH; LoadTest.kt:39-144 runs against them). A Host
    provides file IO + process spawning on the machine that runs a node;
    every Driver operation goes through it, so the harness itself never
    assumes localhost. LocalHost is the in-tree implementation; an SSH twin
    implements the same four methods over a remote connection (sftp for
    files, remote exec returning a signal-capable handle) without touching
    the Driver.

    The handle returned by spawn() must provide the Popen subset the
    driver's disruption primitives use: poll(), wait(timeout),
    send_signal(sig), kill(), terminate(), returncode.
    """

    name = "abstract"

    def mkdir(self, path) -> None:
        raise NotImplementedError

    def write_file(self, path, text: str) -> None:
        raise NotImplementedError

    def read_text(self, path) -> str:
        """Contents of a (log) file; missing file raises OSError."""
        raise NotImplementedError

    def spawn(self, argv: list, log_path, cwd: str, env: dict):
        raise NotImplementedError


class LocalHost(Host):
    """Runs node processes on this machine (the default placement)."""

    name = "localhost"

    def mkdir(self, path) -> None:
        Path(path).mkdir(parents=True, exist_ok=True)

    def write_file(self, path, text: str) -> None:
        Path(path).write_text(text)

    def read_text(self, path) -> str:
        return Path(path).read_text(errors="replace")

    def spawn(self, argv: list, log_path, cwd: str, env: dict):
        # Output goes to a file, NOT a pipe: an undrained pipe would
        # eventually block the node on a full buffer, and the log survives
        # for post-mortem.
        log = open(log_path, "ab")
        try:
            return subprocess.Popen(argv, stdout=log,
                                    stderr=subprocess.STDOUT,
                                    cwd=cwd, env=env)
        finally:
            log.close()  # the child owns the fd now


def _toml_escape(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    raise TypeError(f"cannot TOML-encode {v!r}")


@dataclass
class NodeProcess:
    name: str
    base_dir: Path
    config_path: Path
    process: object  # Host.spawn handle (Popen subset; see Host doc)
    address: tuple[str, int] | None = None
    rpc_users: list = field(default_factory=list)
    device: str = "cpu"  # "cpu" | "accelerator" — survives restart_node
    host: Host = field(default_factory=LocalHost)

    @property
    def log_path(self) -> Path:
        return self.base_dir / "node.log"

    def wait_up(self, timeout: float = 60.0) -> "NodeProcess":
        """Block until the node logs its startup banner; parse the port."""
        deadline = time.monotonic() + timeout
        prefix = f"node {self.name} up at "
        while time.monotonic() < deadline:
            if self.process.poll() is not None:
                tail = ""
                try:
                    tail = self.host.read_text(self.log_path)[-2000:]
                except OSError:
                    pass
                raise RuntimeError(
                    f"node {self.name} exited with {self.process.returncode}:"
                    f"\n{tail}")
            try:
                text = self.host.read_text(self.log_path)
            except OSError:
                text = ""
            for line in text.splitlines():
                if line.startswith(prefix):
                    host, port = line.rsplit(" ", 1)[-1].rsplit(":", 1)
                    self.address = (host, int(port))
                    return self
            time.sleep(0.02)
        raise TimeoutError(f"node {self.name} did not come up in {timeout}s")

    def rpc(self, user: str, password: str, timeout: float = 20.0):
        from ..node.messaging.tcp import TcpAddress
        from ..node.rpc import RpcClient

        assert self.address is not None, "wait_up first"
        return RpcClient(TcpAddress(*self.address), user, password,
                         timeout=timeout)

    def kill(self) -> None:
        """SIGKILL — the Disruption.kt:18-60 'kill the process' primitive."""
        self.process.kill()
        self.process.wait(timeout=10)

    def sigstop(self) -> None:
        """SIGSTOP — the 'hang' primitive (Disruption.kt strainer): the
        process is frozen, not dead; peers see an unresponsive node whose
        sockets stay open — a different failure mode than a clean kill."""
        import signal

        self.process.send_signal(signal.SIGSTOP)

    def sigcont(self) -> None:
        import signal

        self.process.send_signal(signal.SIGCONT)

    def strain(self, seconds: float = 5.0, duty: float = 0.8,
               period: float = 0.1) -> "threading.Thread":
        """CPU-strain disruption (reference: Disruption.kt strainCpu): the
        node is made SLOW-BUT-ALIVE — frozen for `duty` of every `period`
        via SIGSTOP/SIGCONT duty-cycling on a background thread, the
        portable equivalent of the reference's openssl busy-loop siblings.
        Sockets stay open; peers see a node that responds, late — the
        failure mode that exposes timeout tuning, distinct from both a
        clean kill and a full hang. Returns the (daemon) thread; join it to
        wait the strain out."""
        import threading

        def cycle():
            end = time.monotonic() + seconds
            while time.monotonic() < end and self.process.poll() is None:
                try:
                    self.sigstop()
                    time.sleep(duty * period)
                    self.sigcont()
                    time.sleep((1.0 - duty) * period)
                except (OSError, ValueError):
                    return  # process gone mid-cycle
            try:  # never leave the node frozen
                self.sigcont()
            except (OSError, ValueError):
                pass

        t = threading.Thread(target=cycle, daemon=True,
                             name=f"strain-{self.name}")
        t.start()
        return t

    def terminate(self) -> None:
        self.process.terminate()
        try:
            self.process.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=5)


@dataclass
class SidecarProcess:
    """Handle on a spawned verification sidecar (crypto/sidecar.py) — the
    one device-owning verify server every node process on the host feeds.
    Implements the Popen-subset methods stop_all uses, so it rides the
    driver's node list for lifecycle."""

    name: str
    base_dir: Path
    address: str  # unix socket path or host:port
    process: object  # Host.spawn handle (Popen subset)
    host: Host = field(default_factory=LocalHost)

    @property
    def log_path(self) -> Path:
        return self.base_dir / "sidecar.log"

    def wait_up(self, timeout: float = 60.0) -> "SidecarProcess":
        deadline = time.monotonic() + timeout
        prefix = "sidecar up at "
        while time.monotonic() < deadline:
            if self.process.poll() is not None:
                tail = ""
                try:
                    tail = self.host.read_text(self.log_path)[-2000:]
                except OSError:
                    pass
                raise RuntimeError(
                    f"sidecar {self.name} exited with "
                    f"{self.process.returncode}:\n{tail}")
            try:
                text = self.host.read_text(self.log_path)
            except OSError:
                text = ""
            for line in text.splitlines():
                if line.startswith(prefix):
                    # tcp with port 0 resolves here; unix echoes the path
                    self.address = line[len(prefix):].strip()
                    return self
            time.sleep(0.02)
        raise TimeoutError(
            f"sidecar {self.name} did not come up in {timeout}s")

    def kill(self) -> None:
        """SIGKILL mid-batch — the kill-sidecar chaos primitive: clients
        must degrade to their host tier and flows replay, never mis-commit."""
        self.process.kill()
        self.process.wait(timeout=10)

    def sigcont(self) -> None:
        import signal

        self.process.send_signal(signal.SIGCONT)

    def terminate(self) -> None:
        self.process.terminate()
        try:
            self.process.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=5)


def render_node_config(name: str, node_dir, netmap, notary: str = "none",
                       raft_cluster: tuple[str, ...] = (),
                       cordapps: tuple[str, ...] = (),
                       extra_toml: str = "",
                       rpc_users: list | None = None) -> str:
    """The node.toml the driver writes for a child. Ordering is
    load-bearing: extra_toml goes BEFORE any [[rpc_users]] table — TOML
    keys after a table header belong to that table, so a trailing
    `verifier = ...` would silently become an rpc_users field and the node
    would run the default verifier (observed: every RPC-enabled node
    ignored its configured verifier)."""
    lines = [
        f"name = {_toml_escape(name)}",
        f"base_dir = {_toml_escape(str(node_dir))}",
        f"network_map = {_toml_escape(str(netmap))}",
        f"notary = {_toml_escape(notary)}",
    ]
    if raft_cluster:
        lines.append(
            "raft_cluster = ["
            + ", ".join(_toml_escape(n) for n in raft_cluster) + "]")
    if cordapps:
        lines.append(
            "cordapps = ["
            + ", ".join(_toml_escape(c) for c in cordapps) + "]")
    if extra_toml:
        lines.append(extra_toml)
    for user in rpc_users or []:
        lines.append("[[rpc_users]]")
        lines.append(f"username = {_toml_escape(user['username'])}")
        lines.append(f"password = {_toml_escape(user['password'])}")
        lines.append("permissions = ["
                     + ", ".join(_toml_escape(p)
                                 for p in user["permissions"]) + "]")
    return "\n".join(lines) + "\n"


def shard_groups_toml(groups, reserve_ttl_s: float = 15.0,
                      count: int | None = None) -> str:
    """The `[notary_shards]` fragment for a sharded-notary topology
    (services/sharding.py): identical text for every member — each node
    derives its own group from its own name. `groups` is a sequence of
    member-name sequences, index = shard id. `count` below len(groups)
    marks the trailing groups as PENDING split targets (booted and
    electable but owning no keyspace until a reshard epoch activates
    them). NOTE: this opens a TOML table, so when composing extra_toml put
    this fragment LAST among bare keys (the same ordering rule
    render_node_config applies to [[rpc_users]])."""
    groups = list(groups)
    rows = ",\n  ".join(
        "[" + ", ".join(_toml_escape(str(m)) for m in g) + "]"
        for g in groups)
    return ("[notary_shards]\n"
            f"count = {len(groups) if count is None else int(count)}\n"
            f"reserve_ttl_s = {_toml_escape(float(reserve_ttl_s))}\n"
            "groups = [\n  " + rows + ",\n]")


def _node_env(device: str) -> dict:
    """Per-node device policy (the production topology: only the notary
    process owns the accelerator; every other child stays on the host
    path — one tunnel chip cannot be shared by five processes).

    * "cpu": pin the child to the host platform.
    * "accelerator": strip any inherited platform pin / virtual-mesh flags
      so the child initialises the real backend lazily, on its first
      verify batch (node startup never blocks on a wedged tunnel).
    """
    env = dict(os.environ)
    if device == "accelerator":
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        # Persistent compile cache: without it the device-owning notary
        # pays the FULL Pallas/XLA compile on its first >=device_min_sigs
        # batch — measured as a multi-minute in-measurement stall (r5: the
        # raft-validating p99 hit 133 s while transactions queued behind
        # the compile). bench.py warms the same cache dir (both resolve
        # through ops.default_jax_cache_dir), so a child that inherits it
        # compiles once per machine, not once per process. The dir is
        # keyed by host CPU signature: XLA stores AOT host code, and a
        # cache shared across machine types risks SIGILL (MULTICHIP r05
        # cpu_aot_loader machine-feature-mismatch warnings).
        from ..ops import default_jax_cache_dir

        env.setdefault("JAX_COMPILATION_CACHE_DIR", default_jax_cache_dir())
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    else:
        env.setdefault("JAX_PLATFORMS", "cpu")
    return env


class Driver:
    def __init__(self, base_dir: Path, host: Host | None = None):
        self.base_dir = Path(base_dir)
        self.nodes: list[NodeProcess] = []
        self._deferred: list = []  # cleanup callbacks (run first in stop_all)
        self.netmap = self.base_dir / "netmap.json"
        # Default placement for every node; start_node(host=...) overrides
        # per node (the reference's loadtest places nodes on the remote
        # hosts its config lists, ConnectionManager.kt).
        self.host = host or LocalHost()

    _NODE_ARGV = [sys.executable, "-m", "corda_tpu.node.node"]
    _NODE_CWD = "/root/repo"

    def start_node(self, name: str, notary: str = "none",
                   cordapps: tuple[str, ...] = (), rpc: bool = False,
                   raft_cluster: tuple[str, ...] = (),
                   wait: bool = True, extra_toml: str = "",
                   device: str = "cpu",
                   env_extra: dict | None = None,
                   config_overlay: dict | None = None,
                   host: Host | None = None) -> NodeProcess:
        """env_extra: extra environment for the child (e.g.
        CORDA_TPU_FAULT_PLAN=<plan.toml> to arm a chaos plan in that
        process without touching node.toml). config_overlay: per-knob
        config overrides for THIS child, shipped as one
        CORDA_TPU_CONFIG_OVERLAY env (JSON) that NodeConfig.load
        deep-merges over node.toml — the autotune sweep road; precedence
        is TOML < overlay < explicit CORDA_TPU_* env vars."""
        host = host or self.host
        node_dir = self.base_dir / name
        host.mkdir(node_dir)
        rpc_users = [DEFAULT_RPC_USER] if rpc else []
        config_path = node_dir / "node.toml"
        host.write_file(config_path, render_node_config(
            name=name, node_dir=node_dir, netmap=self.netmap, notary=notary,
            raft_cluster=raft_cluster, cordapps=cordapps,
            extra_toml=extra_toml, rpc_users=rpc_users))

        env = _node_env(device)
        if config_overlay:
            env["CORDA_TPU_CONFIG_OVERLAY"] = json.dumps(
                config_overlay, sort_keys=True)
        if env_extra:
            env.update({k: str(v) for k, v in env_extra.items()})
        process = host.spawn(
            self._NODE_ARGV + [str(config_path)],
            node_dir / "node.log", self._NODE_CWD, env)
        handle = NodeProcess(name, node_dir, config_path, process,
                             rpc_users=rpc_users, device=device, host=host)
        self.nodes.append(handle)
        if wait:
            handle.wait_up()
        return handle

    def start_shard_cluster(self, groups: int = 2, members: int = 3,
                            notary: str = "raft-simple",
                            reserve_ttl_s: float = 15.0,
                            extra_toml: str = "",
                            cordapps: tuple[str, ...] = (),
                            rpc: bool = False,
                            device_member: tuple[int, int] | None = None,
                            env_extra: dict | None = None,
                            wait: bool = True,
                            prefix: str = "Shard",
                            count: int | None = None) -> list:
        """Boot a sharded notary: `groups` independent Raft groups of
        `members` nodes each (names Shard0A, Shard0B, ... Shard1A, ...),
        every member carrying the same [notary_shards] map so each derives
        its group from its own name. Returns handles indexed
        [group][member]. `device_member` names the single (group, member)
        that owns the accelerator (production placement: one chip, one
        process); everyone else stays on the host path. `count` below
        `groups` boots the trailing groups as pending split targets for a
        live reshard (publish_reshard_plan activates them)."""
        names = [[f"{prefix}{g}{chr(ord('A') + m)}" for m in range(members)]
                 for g in range(groups)]
        shard_toml = shard_groups_toml(names, reserve_ttl_s, count=count)
        merged = (extra_toml + "\n" + shard_toml) if extra_toml else shard_toml
        handles = []
        for g, group_names in enumerate(names):
            row = []
            for m, name in enumerate(group_names):
                device = ("accelerator" if device_member == (g, m) else "cpu")
                row.append(self.start_node(
                    name, notary=notary, raft_cluster=tuple(group_names),
                    cordapps=cordapps, rpc=rpc,
                    wait=False, extra_toml=merged, device=device,
                    env_extra=env_extra))
            handles.append(row)
        if wait:
            for row in handles:
                for h in row:
                    h.wait_up()
        return handles

    _SIDECAR_ARGV = [sys.executable, "-m", "corda_tpu.crypto.sidecar"]

    def start_sidecar(self, name: str = "sidecar", verifier: str = "jax",
                      device: str = "accelerator", coalesce_us: int = 2000,
                      max_sigs: int = 4096, depth: int = 2,
                      address: str | None = None,
                      env_extra: dict | None = None,
                      wait: bool = True,
                      devices: int | None = None,
                      adaptive_coalesce: bool = False,
                      host: Host | None = None) -> SidecarProcess:
        """Spawn ONE verification sidecar for the host (crypto/sidecar.py).
        Point node processes at it via `[batch] sidecar = "<address>"` (or
        CORDA_TPU_SIDECAR in env_extra) so their verify batches coalesce
        across processes. Default address: a unix socket under the
        sidecar's base dir (falls back to a short /tmp dir when the path
        would blow the ~108-byte AF_UNIX limit).

        devices=N makes the sidecar own an N-device mesh (data-parallel
        sharded verify); on device="cpu" the child gets a VIRTUAL mesh via
        --xla_force_host_platform_device_count so the mesh code path runs
        on hosts without accelerators (tests, the host-only bench)."""
        host = host or self.host
        side_dir = self.base_dir / name
        host.mkdir(side_dir)
        if address is None:
            address = str(side_dir / "sc.sock")
            if len(address) > 90:
                import tempfile

                address = str(Path(tempfile.mkdtemp(
                    prefix="corda-tpu-sc-")) / "sc.sock")
        env = _node_env(device)
        if devices and devices > 1 and device != "accelerator":
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{devices}").strip()
        if env_extra:
            env.update({k: str(v) for k, v in env_extra.items()})
        argv = self._SIDECAR_ARGV + [
            "--socket", address, "--verifier", verifier,
            "--coalesce-us", str(coalesce_us),
            "--max-sigs", str(max_sigs), "--depth", str(depth)]
        if devices:
            argv += ["--devices", str(devices)]
        if adaptive_coalesce:
            argv += ["--adaptive-coalesce"]
        process = host.spawn(argv, side_dir / "sidecar.log",
                             self._NODE_CWD, env)
        handle = SidecarProcess(name, side_dir, address, process, host=host)
        # Rides the node list so stop_all terminates it with the cluster.
        self.nodes.append(handle)
        if wait:
            handle.wait_up()
        return handle

    def start_federation(self, count: int = 2,
                         name_prefix: str = "fedhost",
                         verifier: str = "cpu", device: str = "cpu",
                         coalesce_us: int = 2000, max_sigs: int = 4096,
                         depth: int = 2, devices: int | None = None,
                         env_extra: dict | None = None,
                         wait: bool = True) -> list[SidecarProcess]:
        """Spawn `count` sidecar servers as SIMULATED HOSTS for the
        federated verify plane (crypto/federation.py) — each its own
        process with its own socket, scheduler and (virtual) device mesh,
        so cross-host routing/hedging/degrade runs on one box. Point
        nodes at the tier by joining the returned handles' addresses with
        "," into `[batch] federation_hosts` (or CORDA_TPU_FEDERATION in
        env_extra). Kill any one handle to exercise the per-host
        quarantine → re-probe → re-admit path."""
        handles = [
            self.start_sidecar(
                name=f"{name_prefix}{i}", verifier=verifier, device=device,
                coalesce_us=coalesce_us, max_sigs=max_sigs, depth=depth,
                devices=devices, env_extra=env_extra, wait=False)
            for i in range(count)]
        if wait:
            for h in handles:
                h.wait_up()
        return handles

    def restart_node(self, handle: NodeProcess,
                     wait: bool = True) -> NodeProcess:
        """Re-spawn a (killed) node over its existing base_dir + config —
        rebirth purely from disk (the kill/restart Disruption primitive)."""
        process = handle.host.spawn(
            self._NODE_ARGV + [str(handle.config_path)],
            handle.base_dir / "node.log", self._NODE_CWD,
            _node_env(handle.device))
        reborn = NodeProcess(handle.name, handle.base_dir, handle.config_path,
                             process, rpc_users=handle.rpc_users,
                             device=handle.device, host=handle.host)
        self.nodes.append(reborn)
        if wait:
            reborn.wait_up()
        return reborn

    def defer(self, cleanup) -> None:
        """Register a cleanup (e.g. an RpcClient.close) to run at driver
        exit, BEFORE nodes are stopped — success or exception alike."""
        self._deferred.append(cleanup)

    def stop_all(self) -> None:
        for cleanup in self._deferred:
            try:
                cleanup()
            # lint: allow(no-silent-except) harness teardown: stop_all() must run every deferred cleanup even when earlier ones fail; never on a node path
            except Exception:
                pass
        self._deferred.clear()
        for node in self.nodes:
            if node.process.poll() is None:
                try:
                    node.sigcont()  # un-freeze SIGSTOP'd nodes so they exit
                except (OSError, ValueError):
                    pass
                node.terminate()


@contextmanager
def driver(base_dir: str | Path, host: Host | None = None):
    d = Driver(Path(base_dir), host=host)
    try:
        yield d
    finally:
        d.stop_all()
