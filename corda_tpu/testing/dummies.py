"""The dummy contract: accepts everything, exists for tests and demos.

Capability match for the reference's DummyContract (reference:
core/src/main/kotlin/net/corda/core/contracts/DummyContract.kt) — also the
workload contract of the raft-notary-demo benchmark
(samples/raft-notary-demo/.../NotaryDemoApi).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..contracts.structures import (
    Command,
    ContractState,
    Contract,
    OwnableState,
    StateAndRef,
    TypeOnlyCommandData,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party, PartyAndReference
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder


@register
@dataclass(frozen=True)
class DummyCreate(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class DummyMove(TypeOnlyCommandData):
    pass


class DummyContract(Contract):
    def verify(self, tx) -> None:
        pass  # Always accepts.

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"")

    @staticmethod
    def generate_initial(
        owner: PartyAndReference, magic_number: int, notary: Party
    ) -> TransactionBuilder:
        state = DummySingleOwnerState(magic_number, owner.party.owning_key)
        tx = TransactionBuilder(notary=notary)
        tx.add_output_state(state)
        tx.add_command(Command(DummyCreate(), (owner.party.owning_key,)))
        return tx

    @staticmethod
    def move(priors: list[StateAndRef] | StateAndRef, new_owner: CompositeKey) -> TransactionBuilder:
        if isinstance(priors, StateAndRef):
            priors = [priors]
        if not priors:
            raise ValueError("need at least one prior state")
        prior = priors[0].state.data
        cmd, new_state = prior.with_new_owner(new_owner)
        tx = TransactionBuilder(notary=priors[0].state.notary)
        for p in priors:
            tx.add_input_state(p)
        tx.add_command(Command(cmd, (prior.owner,)))
        tx.add_output_state(new_state)
        return tx

    @staticmethod
    def generate_initial_multi(
        owners: tuple[CompositeKey, ...], magic_number: int, notary: Party
    ) -> TransactionBuilder:
        """Issue a multi-owner state (DummyContract.kt MultiOwnerState): a
        move of it needs a signature from EVERY owner — the fan-out-verify
        workload shape (BASELINE config 4; NotaryDemo firehose widened)."""
        state = DummyMultiOwnerState(magic_number, tuple(owners))
        tx = TransactionBuilder(notary=notary)
        tx.add_output_state(state)
        tx.add_command(Command(DummyCreate(), tuple(owners)))
        return tx

    @staticmethod
    def move_multi(prior: StateAndRef,
                   new_owners: tuple[CompositeKey, ...]) -> TransactionBuilder:
        """Move a multi-owner state; signers = every current owner, so the
        transaction carries len(owners) signatures through the verify pump."""
        prior_state = prior.state.data
        if not isinstance(prior_state, DummyMultiOwnerState):
            raise ValueError("move_multi needs a DummyMultiOwnerState input")
        tx = TransactionBuilder(notary=prior.state.notary)
        tx.add_input_state(prior)
        tx.add_command(Command(DummyMove(), tuple(prior_state.owners)))
        tx.add_output_state(DummyMultiOwnerState(
            prior_state.magic_number, tuple(new_owners)))
        return tx


DUMMY_PROGRAM_ID = DummyContract()


@register
@dataclass(frozen=True)
class DummySingleOwnerState(OwnableState):
    magic_number: int = 0
    owner: CompositeKey = None  # type: ignore[assignment]

    @property
    def contract(self) -> Contract:
        return DUMMY_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return [self.owner]

    def with_new_owner(self, new_owner: CompositeKey):
        return DummyMove(), replace(self, owner=new_owner)


@register
@dataclass(frozen=True)
class DummyMultiOwnerState(ContractState):
    magic_number: int = 0
    owners: tuple[CompositeKey, ...] = ()

    @property
    def contract(self) -> Contract:
        return DUMMY_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return list(self.owners)
