"""Event-sequence assertions: the Expect DSL.

Capability match for the reference's Expect DSL (reference:
test-utils/src/main/kotlin/net/corda/testing/Expect.kt): declare the shape of
an event stream — single expectations, strict sequences, unordered parallel
groups — and check a recorded feed against it.

    expect_events(feed,
        sequence(
            expect(VaultUpdate, lambda e: len(e.produced) == 1),
            parallel(expect(TxRecorded), expect(ProgressChange)),
        ))
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence


class ExpectationFailed(AssertionError):
    pass


@dataclass
class _Expect:
    event_type: type
    predicate: Callable[[Any], bool] | None = None

    def matches(self, event) -> bool:
        if not isinstance(event, self.event_type):
            return False
        return self.predicate is None or bool(self.predicate(event))

    def describe(self) -> str:
        return self.event_type.__name__


@dataclass
class _Sequence:
    parts: tuple

    def describe(self) -> str:
        return "sequence(" + ", ".join(p.describe() for p in self.parts) + ")"


@dataclass
class _Parallel:
    parts: tuple

    def describe(self) -> str:
        return "parallel(" + ", ".join(p.describe() for p in self.parts) + ")"


def expect(event_type: type, predicate=None) -> _Expect:
    return _Expect(event_type, predicate)


def sequence(*parts) -> _Sequence:
    return _Sequence(tuple(parts))


def parallel(*parts) -> _Parallel:
    return _Parallel(tuple(parts))


def expect_events(feed: Sequence, spec) -> None:
    """Consume `feed` against `spec`; raises ExpectationFailed with the first
    unsatisfied expectation. Events not matched by the spec are skipped
    (the reference likewise ignores unexpected events between matches)."""
    remaining = list(feed)
    _consume(remaining, spec)


def _consume(feed: list, spec) -> None:
    if isinstance(spec, _Expect):
        while feed:
            event = feed.pop(0)
            if spec.matches(event):
                return
        raise ExpectationFailed(f"no event matched {spec.describe()}")
    if isinstance(spec, _Sequence):
        for part in spec.parts:
            _consume(feed, part)
        return
    if isinstance(spec, _Parallel):
        outstanding = list(spec.parts)
        while outstanding:
            if not feed:
                raise ExpectationFailed(
                    "feed exhausted with outstanding parallel expectations: "
                    + ", ".join(p.describe() for p in outstanding))
            event = feed.pop(0)
            for part in outstanding:
                if isinstance(part, _Expect) and part.matches(event):
                    outstanding.remove(part)
                    break
        return
    raise TypeError(f"unknown spec {spec!r}")
