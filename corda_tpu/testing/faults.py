"""Deterministic fault injection for the notary pipeline.

A process arms at most one :class:`FaultPlan`.  Hooks compiled into the
transport, Raft, verifier, and checkpoint layers consult the module-level
``ACTIVE`` plan; when no plan is armed the hook is a single attribute
check (``faults.ACTIVE is not None``), so the hot path pays nothing
measurable.

Injection points
----------------

==================  =============================================  =======================================
point               fired from                                     actions
==================  =============================================  =======================================
``transport.send``  inmem ``_transmit`` / tcp ``send``/``send_many``  drop, delay, duplicate, reorder, crash
``transport.recv``  inmem ``pump`` / tcp ``_dispatch``             drop, delay, crash
``raft.append``     RaftMember ``_send`` (append traffic)          drop, delay, duplicate, crash
``raft.fsync``      RaftMember log append (sqlite insert+commit)   fail, stall, crash
``verify.device``   AsyncVerifyService feeder thread               fail, slow, crash
``checkpoint.write`` SMM ``_write_checkpoint``                     fail, stall, crash
``shard.handoff``   reshard coordinator, per streamed state frame  drop, stall, crash
``netmap.refresh``  Node ``refresh_netmap`` (directory reload)     drop, stall, crash
``disk.corrupt``    raft log read path, checkpoint restore read    flip (seeded bit-flip on read)
``disk.full``       raft append / uniqueness-provider commit       full, stall, crash
==================  =============================================  =======================================

``shard.handoff`` crash is the coordinator-death-mid-handoff case (the
next leader of the source group re-runs the idempotent sequence);
``netmap.refresh`` drop keeps a node routing on a stale shard directory —
its requests bounce ``WrongShardEpoch`` until a later refresh lands.

Determinism: every rule owns a ``random.Random`` seeded from
``(plan seed, point, rule index)``, and probability draws consume that
stream one draw per *event at that point*.  Two plans built from the same
seed and rule list therefore produce the same fault schedule regardless
of how events at different points interleave.

TOML plan format (see ``plan_from_toml``)::

    seed = 7

    [[rule]]
    point = "transport.send"
    action = "drop"
    p = 0.05           # fire probability per event (default 1.0)
    delay_s = 0.0      # delay/stall/slow duration (inmem: ticks)
    after = 0          # skip the first N events at this point
    max_fires = 100    # stop firing after N fires (0 = unlimited)
    node = "Raft1"     # only armed on this node (default: all)

Arming across OS processes: export ``CORDA_TPU_FAULT_PLAN=/path/plan.toml``
before starting a node; ``corda_tpu.node.node.main`` calls
:func:`arm_from_env` with the node's name so per-node rules filter
correctly.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field

__all__ = [
    "POINTS",
    "FaultRule",
    "FaultPlan",
    "ACTIVE",
    "arm",
    "disarm",
    "injected",
    "fire",
    "fire_fsync",
    "fire_disk_corrupt",
    "fire_disk_full",
    "plan_from_toml",
    "arm_from_env",
    "builtin_plan",
    "PLAN_ENV",
]

POINTS = (
    "transport.send",
    "transport.recv",
    "raft.append",
    "raft.fsync",
    "verify.device",
    "checkpoint.write",
    "shard.handoff",
    "netmap.refresh",
    "disk.corrupt",
    "disk.full",
)

# Exit code used by the "crash" action so harnesses can tell an injected
# crash from a genuine one.
CRASH_EXIT_CODE = 70

PLAN_ENV = "CORDA_TPU_FAULT_PLAN"


@dataclass
class FaultRule:
    """One named fault at one injection point."""

    point: str
    action: str           # drop | delay | duplicate | reorder | fail | stall | slow | crash
    p: float = 1.0        # fire probability per event
    delay_s: float = 0.0  # delay/stall/slow duration (ticks for inmem)
    after: int = 0        # skip the first N events at this point
    max_fires: int = 0    # 0 = unlimited
    node: str | None = None  # restrict to one node name

    # runtime state (not part of the plan identity)
    fires: int = field(default=0, compare=False)
    _rng: random.Random = field(default=None, compare=False, repr=False)

    def exhausted(self) -> bool:
        return self.max_fires > 0 and self.fires >= self.max_fires


class FaultPlan:
    """A seeded set of fault rules, armed process-wide via :func:`arm`.

    ``node_name`` filters rules with a ``node=`` restriction at
    construction time; filtering never perturbs the per-rule RNG streams
    because each rule is seeded from its index in the *original* rule
    list.
    """

    def __init__(self, seed: int, rules: list[FaultRule],
                 node_name: str | None = None):
        self.seed = int(seed)
        self.node_name = node_name
        self._lock = threading.Lock()
        # event counter per point (all events, fired or not)
        self.events: dict[str, int] = {}
        # fired counter per "point:action"
        self.counters: dict[str, int] = {}
        armed = []
        for idx, rule in enumerate(rules):
            if rule.point not in POINTS:
                raise ValueError(f"unknown injection point {rule.point!r}")
            rule._rng = random.Random(f"{self.seed}:{rule.point}:{idx}")
            rule.fires = 0
            if rule.node is not None and node_name is not None \
                    and rule.node != node_name:
                continue
            armed.append(rule)
        self.rules = armed
        self._by_point: dict[str, list[FaultRule]] = {}
        for rule in self.rules:
            self._by_point.setdefault(rule.point, []).append(rule)

    def fire(self, point: str) -> tuple[str, float] | None:
        """Record one event at *point*; return ``(action, delay_s)`` when a
        rule fires, else ``None``.  The ``crash`` action never returns."""
        rules = self._by_point.get(point)
        with self._lock:
            self.events[point] = self.events.get(point, 0) + 1
            seen = self.events[point]
            if not rules:
                return None
            for rule in rules:
                if rule.exhausted() or seen <= rule.after:
                    continue
                # one draw per event keeps the schedule independent of
                # which earlier rules fired
                if rule.p < 1.0 and rule._rng.random() >= rule.p:
                    continue
                rule.fires += 1
                key = f"{point}:{rule.action}"
                self.counters[key] = self.counters.get(key, 0) + 1
                if rule.action == "crash":
                    os._exit(CRASH_EXIT_CODE)
                return rule.action, rule.delay_s
        return None

    def injected(self) -> dict[str, int]:
        """Copy of the fired counters (``point:action`` -> count)."""
        with self._lock:
            return dict(self.counters)

    def event_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.events)


# The armed plan.  Hooks read this exactly once per event:
#   if faults.ACTIVE is not None: ...
ACTIVE: FaultPlan | None = None


def arm(plan: FaultPlan) -> FaultPlan:
    global ACTIVE
    ACTIVE = plan
    return plan


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def injected() -> dict[str, int]:
    """Fired counters of the armed plan (empty dict when disarmed)."""
    plan = ACTIVE
    return plan.injected() if plan is not None else {}


def fire(point: str) -> tuple[str, float] | None:
    """Convenience: fire *point* against the armed plan, if any."""
    plan = ACTIVE
    return plan.fire(point) if plan is not None else None


def fire_fsync(point: str) -> None:
    """Shared hook body for durability points (``raft.fsync``,
    ``checkpoint.write``): ``stall`` sleeps, ``fail`` raises OSError."""
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire(point)
    if act is None:
        return
    action, delay_s = act
    if action == "stall" and delay_s > 0:
        time.sleep(delay_s)
    elif action in ("fail", "raise"):
        raise OSError(f"fault injected: {point} failure")


def fire_disk_corrupt(blob: bytes) -> bytes:
    """Hook body for ``disk.corrupt``: when a rule fires, return *blob*
    with ONE deterministically-chosen bit flipped (models media bitrot on
    a read path — the stored bytes are untouched, so detection + truncate
    + re-replication genuinely recovers).  The flipped position derives
    from the plan seed and the point's event count, so two runs of the
    same plan corrupt the same reads identically."""
    plan = ACTIVE
    if plan is None or not blob:
        return blob
    act = plan.fire("disk.corrupt")
    if act is None:
        return blob
    action, _delay_s = act
    if action not in ("flip", "corrupt"):
        return blob
    with plan._lock:
        event = plan.events.get("disk.corrupt", 0)
    pos = random.Random(f"{plan.seed}:disk.corrupt:bit:{event}").randrange(
        len(blob) * 8)
    flipped = bytearray(blob)
    flipped[pos // 8] ^= 1 << (pos % 8)
    return bytes(flipped)


def fire_disk_full() -> None:
    """Hook body for ``disk.full``: ``full``/``fail`` raises the exact
    OperationalError sqlite produces on disk exhaustion (so catch sites
    exercise the same string-match they use in production), ``stall``
    sleeps."""
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("disk.full")
    if act is None:
        return
    action, delay_s = act
    if action == "stall" and delay_s > 0:
        time.sleep(delay_s)
    elif action in ("full", "fail"):
        import sqlite3
        raise sqlite3.OperationalError("database or disk is full")


def plan_from_toml(text: str, node_name: str | None = None) -> FaultPlan:
    """Parse a TOML plan (see module docstring for the format)."""
    try:
        import tomllib
    except ModuleNotFoundError:  # Python < 3.11
        import tomli as tomllib

    data = tomllib.loads(text)
    seed = int(data.get("seed", 0))
    rules = []
    for raw in data.get("rule", []):
        rules.append(FaultRule(
            point=raw["point"],
            action=raw["action"],
            p=float(raw.get("p", 1.0)),
            delay_s=float(raw.get("delay_s", 0.0)),
            after=int(raw.get("after", 0)),
            max_fires=int(raw.get("max_fires", 0)),
            node=raw.get("node"),
        ))
    return FaultPlan(seed, rules, node_name=node_name)


def arm_from_env(node_name: str | None = None) -> FaultPlan | None:
    """Arm from ``$CORDA_TPU_FAULT_PLAN`` (a TOML path) if set.

    Called by ``corda_tpu.node.node.main`` so child processes spawned by
    the driver/loadtest pick up the plan without config changes."""
    path = os.environ.get(PLAN_ENV)
    if not path:
        return None
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    return arm(plan_from_toml(text, node_name=node_name))


def builtin_plan(name: str, node_name: str | None = None) -> FaultPlan:
    """Named plans for the chaos loadtest / bench (``lossy``, ``slow-disk``,
    ``flaky-device``, ``reshard``, ``bitrot``)."""
    if name == "lossy":
        # ~5% send-side loss; durable outbox re-poll recovers each loss
        # within ~1s, so the run completes with elevated tail latency.
        return FaultPlan(7, [
            FaultRule("transport.send", "drop", p=0.05, max_fires=500),
        ], node_name=node_name)
    if name == "reshard":
        # The reshard-under-fire plan: lossy transport THROUGH the
        # transition plus handoff-frame loss and one stale-directory
        # window, so the exactly-once audit exercises resubmitted install
        # frames and WrongShardEpoch bounces, not just the happy path.
        return FaultPlan(17, [
            FaultRule("transport.send", "drop", p=0.05, max_fires=500),
            FaultRule("shard.handoff", "drop", p=0.25, max_fires=8),
            FaultRule("netmap.refresh", "drop", p=0.10, max_fires=20),
        ], node_name=node_name)
    if name == "bitrot":
        # Storage-corruption soak (durability plane, round 14): seeded
        # bit-flips on the raft-log read path plus two bounded disk-full
        # write failures. Detection (crc mismatch) turns each flip into a
        # truncate-and-lag repair; the exactly-once audit must still hold.
        return FaultPlan(23, [
            FaultRule("disk.corrupt", "flip", p=0.02, max_fires=6),
            FaultRule("disk.full", "full", p=0.05, after=40, max_fires=2),
        ], node_name=node_name)
    if name == "slow-disk":
        return FaultPlan(11, [
            FaultRule("raft.fsync", "stall", p=0.10, delay_s=0.05,
                      max_fires=200),
        ], node_name=node_name)
    if name == "flaky-device":
        return FaultPlan(13, [
            FaultRule("verify.device", "fail", p=1.0, max_fires=1),
        ], node_name=node_name)
    raise ValueError(f"unknown builtin fault plan {name!r}")
