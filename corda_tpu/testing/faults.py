"""Deterministic fault injection for the notary pipeline.

A process arms at most one :class:`FaultPlan`.  Hooks compiled into the
transport, Raft, verifier, and checkpoint layers consult the module-level
``ACTIVE`` plan; when no plan is armed the hook is a single attribute
check (``faults.ACTIVE is not None``), so the hot path pays nothing
measurable.

Injection points
----------------

==================  =============================================  =======================================
point               fired from                                     actions
==================  =============================================  =======================================
``transport.send``  inmem ``_transmit`` / tcp ``send``/``send_many``  drop, delay, duplicate, reorder, crash
``transport.recv``  inmem ``pump`` / tcp ``_dispatch``             drop, delay, crash
``raft.append``     RaftMember ``_send`` (append traffic)          drop, delay, duplicate, crash
``raft.fsync``      RaftMember log append (sqlite insert+commit)   fail, stall, crash
``verify.device``   AsyncVerifyService feeder thread               fail, slow, crash
``checkpoint.write`` SMM ``_write_checkpoint``                     fail, stall, crash
``shard.handoff``   reshard coordinator, per streamed state frame  drop, stall, crash
``netmap.refresh``  Node ``refresh_netmap`` (directory reload)     drop, stall, crash
``disk.corrupt``    raft log read path, checkpoint restore read    flip (seeded bit-flip on read)
``disk.full``       raft append / uniqueness-provider commit       full, stall, crash
``transport.partition`` inmem ``_transmit``/``pump``, tcp ``send``/``_dispatch``  schedule-driven cut (see below)
==================  =============================================  =======================================

``transport.partition`` is NOT rule-driven: a plan carries a list of
:class:`PartitionSpec` entries (symmetric ``split``, one-way ``asym``,
toggling ``flap``) whose activity is a pure function of the point's
event counter — both transports offer every frame to
:func:`fire_partition` and drop it while a cut covering the
(sender, recipient) pair is live.  ``bind_partition_nodes`` resolves
auto-sided specs over the cluster identities; ``heal_partitions`` lifts
every cut.  TOML plans declare them as ``[[partition]]`` tables
(``kind`` / ``a`` / ``b`` / ``after`` / ``duration`` / ``period``).

``shard.handoff`` crash is the coordinator-death-mid-handoff case (the
next leader of the source group re-runs the idempotent sequence);
``netmap.refresh`` drop keeps a node routing on a stale shard directory —
its requests bounce ``WrongShardEpoch`` until a later refresh lands.

Determinism: every rule owns a ``random.Random`` seeded from
``(plan seed, point, rule index)``, and probability draws consume that
stream one draw per *event at that point*.  Two plans built from the same
seed and rule list therefore produce the same fault schedule regardless
of how events at different points interleave.

TOML plan format (see ``plan_from_toml``)::

    seed = 7

    [[rule]]
    point = "transport.send"
    action = "drop"
    p = 0.05           # fire probability per event (default 1.0)
    delay_s = 0.0      # delay/stall/slow duration (inmem: ticks)
    after = 0          # skip the first N events at this point
    max_fires = 100    # stop firing after N fires (0 = unlimited)
    node = "Raft1"     # only armed on this node (default: all)

Arming across OS processes: export ``CORDA_TPU_FAULT_PLAN=/path/plan.toml``
before starting a node; ``corda_tpu.node.node.main`` calls
:func:`arm_from_env` with the node's name so per-node rules filter
correctly.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field

__all__ = [
    "POINTS",
    "FaultRule",
    "FaultPlan",
    "PartitionSpec",
    "ACTIVE",
    "arm",
    "disarm",
    "injected",
    "fire",
    "fire_fsync",
    "fire_disk_corrupt",
    "fire_disk_full",
    "fire_partition",
    "partitioned",
    "bind_partition_nodes",
    "heal_partitions",
    "plan_from_toml",
    "arm_from_env",
    "builtin_plan",
    "PLAN_ENV",
]

POINTS = (
    "transport.send",
    "transport.recv",
    "transport.partition",
    "raft.append",
    "raft.fsync",
    "verify.device",
    "checkpoint.write",
    "shard.handoff",
    "netmap.refresh",
    "disk.corrupt",
    "disk.full",
)

# Exit code used by the "crash" action so harnesses can tell an injected
# crash from a genuine one.
CRASH_EXIT_CODE = 70

PLAN_ENV = "CORDA_TPU_FAULT_PLAN"


@dataclass
class FaultRule:
    """One named fault at one injection point."""

    point: str
    action: str           # drop | delay | duplicate | reorder | fail | stall | slow | crash
    p: float = 1.0        # fire probability per event
    delay_s: float = 0.0  # delay/stall/slow duration (ticks for inmem)
    after: int = 0        # skip the first N events at this point
    max_fires: int = 0    # 0 = unlimited
    node: str | None = None  # restrict to one node name

    # runtime state (not part of the plan identity)
    fires: int = field(default=0, compare=False)
    _rng: random.Random = field(default=None, compare=False, repr=False)

    def exhausted(self) -> bool:
        return self.max_fires > 0 and self.fires >= self.max_fires


@dataclass
class PartitionSpec:
    """One scheduled network partition (the ``transport.partition`` point).

    Scheduling is EVENT-counted, not wall-clocked: every frame offered to
    ``fire_partition`` advances the point's event counter, and a spec is
    active as a pure function of that counter — two runs of the same plan
    over the same traffic cut identically, with no timing dependence.

    ``kind``:
      * ``split`` — symmetric split-brain: frames between side ``a`` and
        side ``b`` drop in BOTH directions while the cut holds.
      * ``asym`` — one-way cut: frames from ``a`` to ``b`` drop; ``b`` to
        ``a`` still delivers (the half-open link Raft's paper warns about).
      * ``flap`` — a ``split`` that toggles every ``period`` events; a
        ``period`` of 0 derives one deterministically from the plan seed.

    Sides hold node identities (``str(transport address)`` — both
    transports offer their address objects and the engine normalizes
    with ``str()``, so TcpAddress and InMemoryAddress mix-ins match
    however a hook spells the endpoint). Empty sides resolve at
    ``bind_partition_nodes`` time: ``split``/``flap`` put the FIRST
    ``n//2`` bound ids on side ``a`` (the minority when n is odd, so a
    harness that binds the leader first proves the minority-leader case);
    ``asym`` isolates the first id's egress.
    """

    kind: str                     # split | asym | flap
    a: tuple = ()                 # side-a identities (empty = auto)
    b: tuple = ()                 # side-b identities (empty = auto)
    after: int = 0                # events before the cut arms
    duration: int = 0             # events the cut (or flap phase) spans;
    #                               0 = held until heal_partitions()
    period: int = 0               # flap half-cycle in events (0 = seeded)

    def active(self, seen: int) -> bool:
        """Pure schedule query: is this cut live after *seen* events?"""
        since = seen - self.after
        if since <= 0:
            return False
        if self.duration > 0 and since > self.duration:
            return False
        if self.kind == "flap":
            return ((since - 1) // max(1, self.period)) % 2 == 0
        return True

    def cuts(self, src: str, dst: str) -> bool:
        """Does this spec drop a *src* -> *dst* frame while active?"""
        if src in self.a and dst in self.b:
            return True
        return self.kind != "asym" and src in self.b and dst in self.a


class FaultPlan:
    """A seeded set of fault rules, armed process-wide via :func:`arm`.

    ``node_name`` filters rules with a ``node=`` restriction at
    construction time; filtering never perturbs the per-rule RNG streams
    because each rule is seeded from its index in the *original* rule
    list.
    """

    def __init__(self, seed: int, rules: list[FaultRule],
                 node_name: str | None = None,
                 partitions: list[PartitionSpec] | None = None):
        self.seed = int(seed)
        self.node_name = node_name
        self._lock = threading.Lock()
        # event counter per point (all events, fired or not)
        self.events: dict[str, int] = {}
        # fired counter per "point:action"
        self.counters: dict[str, int] = {}
        armed = []
        for idx, rule in enumerate(rules):
            if rule.point not in POINTS:
                raise ValueError(f"unknown injection point {rule.point!r}")
            rule._rng = random.Random(f"{self.seed}:{rule.point}:{idx}")
            rule.fires = 0
            if rule.node is not None and node_name is not None \
                    and rule.node != node_name:
                continue
            armed.append(rule)
        self.rules = armed
        self._by_point: dict[str, list[FaultRule]] = {}
        for rule in self.rules:
            self._by_point.setdefault(rule.point, []).append(rule)
        self.partitions: list[PartitionSpec] = list(partitions or [])
        for idx, spec in enumerate(self.partitions):
            if spec.kind not in ("split", "asym", "flap"):
                raise ValueError(f"unknown partition kind {spec.kind!r}")
            spec.a, spec.b = tuple(spec.a), tuple(spec.b)
            if spec.kind == "flap" and spec.period <= 0:
                # The seeded flap period the docstring promises.
                spec.period = random.Random(
                    f"{self.seed}:transport.partition:flap:{idx}"
                ).randrange(40, 160)
        self._partitions_healed = False
        # Edge-detection state per spec: a cut transition (inactive ->
        # active) counts once as "transport.partition:cut".
        self._partition_was_active = [False] * len(self.partitions)

    # -- the transport.partition point -------------------------------------

    def bind_partition_nodes(self, node_ids) -> None:
        """Resolve auto (empty-sided) partition specs over the cluster's
        identities, in the caller's order — the harness decides which
        side the leader lands on by binding it first."""
        ids = tuple(str(n) for n in node_ids)
        with self._lock:
            for spec in self.partitions:
                if spec.a and spec.b:
                    continue
                if spec.kind == "asym":
                    spec.a, spec.b = ids[:1], ids[1:]
                else:
                    spec.a, spec.b = ids[:len(ids) // 2], ids[len(ids) // 2:]

    def heal_partitions(self) -> None:
        """Permanently lift every cut (the harness's timed heal)."""
        with self._lock:
            self._partitions_healed = True

    def fire_partition(self, src, dst) -> bool:
        """Record one frame event at ``transport.partition``; return True
        when an active cut drops the *src* -> *dst* frame.  Unlike
        :meth:`partitioned` this ADVANCES the schedule — call it exactly
        once per offered frame."""
        src, dst = str(src), str(dst)
        with self._lock:
            self.events["transport.partition"] = seen = \
                self.events.get("transport.partition", 0) + 1
            if self._partitions_healed or not self.partitions:
                return False
            drop = False
            for idx, spec in enumerate(self.partitions):
                live = spec.active(seen)
                if live and not self._partition_was_active[idx]:
                    self.counters["transport.partition:cut"] = \
                        self.counters.get("transport.partition:cut", 0) + 1
                    try:  # telemetry is best-effort from the fault engine
                        from ..obs import telemetry as _tm

                        _tm.inc("partition_cuts_total")
                    # lint: allow(no-silent-except) the fault engine sits inside every transport send — a broken/partially-imported telemetry module must cost the counter, never the frame
                    except Exception:  # noqa: BLE001 - never fail a frame
                        pass
                self._partition_was_active[idx] = live
                if live and spec.cuts(src, dst):
                    drop = True
            if drop:
                self.counters["transport.partition:drop"] = \
                    self.counters.get("transport.partition:drop", 0) + 1
            return drop

    def partitioned(self, src, dst) -> bool:
        """Pure query: would a *src* -> *dst* frame drop RIGHT NOW?  Never
        advances the event counter — safe for polling (the TCP bridge
        parks on this instead of spin-resending across a held cut)."""
        src, dst = str(src), str(dst)
        with self._lock:
            if self._partitions_healed:
                return False
            seen = self.events.get("transport.partition", 0)
            return any(spec.active(seen) and spec.cuts(src, dst)
                       for spec in self.partitions)

    def fire(self, point: str) -> tuple[str, float] | None:
        """Record one event at *point*; return ``(action, delay_s)`` when a
        rule fires, else ``None``.  The ``crash`` action never returns."""
        rules = self._by_point.get(point)
        with self._lock:
            self.events[point] = self.events.get(point, 0) + 1
            seen = self.events[point]
            if not rules:
                return None
            for rule in rules:
                if rule.exhausted() or seen <= rule.after:
                    continue
                # one draw per event keeps the schedule independent of
                # which earlier rules fired
                if rule.p < 1.0 and rule._rng.random() >= rule.p:
                    continue
                rule.fires += 1
                key = f"{point}:{rule.action}"
                self.counters[key] = self.counters.get(key, 0) + 1
                if rule.action == "crash":
                    os._exit(CRASH_EXIT_CODE)
                return rule.action, rule.delay_s
        return None

    def injected(self) -> dict[str, int]:
        """Copy of the fired counters (``point:action`` -> count)."""
        with self._lock:
            return dict(self.counters)

    def event_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.events)


# The armed plan.  Hooks read this exactly once per event:
#   if faults.ACTIVE is not None: ...
ACTIVE: FaultPlan | None = None


def arm(plan: FaultPlan) -> FaultPlan:
    global ACTIVE
    ACTIVE = plan
    return plan


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def injected() -> dict[str, int]:
    """Fired counters of the armed plan (empty dict when disarmed)."""
    plan = ACTIVE
    return plan.injected() if plan is not None else {}


def fire(point: str) -> tuple[str, float] | None:
    """Convenience: fire *point* against the armed plan, if any."""
    plan = ACTIVE
    return plan.fire(point) if plan is not None else None


def fire_partition(src, dst) -> bool:
    """Hook body for ``transport.partition``: True = drop the frame.
    Counts one schedule event; call once per offered frame."""
    plan = ACTIVE
    return plan.fire_partition(src, dst) if plan is not None else False


def partitioned(src, dst) -> bool:
    """Pure cut query against the armed plan (no schedule advance)."""
    plan = ACTIVE
    return plan.partitioned(src, dst) if plan is not None else False


def bind_partition_nodes(node_ids) -> None:
    """Resolve auto partition sides on the armed plan, if any."""
    plan = ACTIVE
    if plan is not None:
        plan.bind_partition_nodes(node_ids)


def heal_partitions() -> None:
    """Lift every cut on the armed plan, if any."""
    plan = ACTIVE
    if plan is not None:
        plan.heal_partitions()


def fire_fsync(point: str) -> None:
    """Shared hook body for durability points (``raft.fsync``,
    ``checkpoint.write``): ``stall`` sleeps, ``fail`` raises OSError."""
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire(point)
    if act is None:
        return
    action, delay_s = act
    if action == "stall" and delay_s > 0:
        time.sleep(delay_s)
    elif action in ("fail", "raise"):
        raise OSError(f"fault injected: {point} failure")


def fire_disk_corrupt(blob: bytes) -> bytes:
    """Hook body for ``disk.corrupt``: when a rule fires, return *blob*
    with ONE deterministically-chosen bit flipped (models media bitrot on
    a read path — the stored bytes are untouched, so detection + truncate
    + re-replication genuinely recovers).  The flipped position derives
    from the plan seed and the point's event count, so two runs of the
    same plan corrupt the same reads identically."""
    plan = ACTIVE
    if plan is None or not blob:
        return blob
    act = plan.fire("disk.corrupt")
    if act is None:
        return blob
    action, _delay_s = act
    if action not in ("flip", "corrupt"):
        return blob
    with plan._lock:
        event = plan.events.get("disk.corrupt", 0)
    pos = random.Random(f"{plan.seed}:disk.corrupt:bit:{event}").randrange(
        len(blob) * 8)
    flipped = bytearray(blob)
    flipped[pos // 8] ^= 1 << (pos % 8)
    return bytes(flipped)


def fire_disk_full() -> None:
    """Hook body for ``disk.full``: ``full``/``fail`` raises the exact
    OperationalError sqlite produces on disk exhaustion (so catch sites
    exercise the same string-match they use in production), ``stall``
    sleeps."""
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("disk.full")
    if act is None:
        return
    action, delay_s = act
    if action == "stall" and delay_s > 0:
        time.sleep(delay_s)
    elif action in ("full", "fail"):
        import sqlite3
        raise sqlite3.OperationalError("database or disk is full")


def plan_from_toml(text: str, node_name: str | None = None) -> FaultPlan:
    """Parse a TOML plan (see module docstring for the format)."""
    try:
        import tomllib
    except ModuleNotFoundError:  # Python < 3.11
        import tomli as tomllib

    data = tomllib.loads(text)
    seed = int(data.get("seed", 0))
    rules = []
    for raw in data.get("rule", []):
        rules.append(FaultRule(
            point=raw["point"],
            action=raw["action"],
            p=float(raw.get("p", 1.0)),
            delay_s=float(raw.get("delay_s", 0.0)),
            after=int(raw.get("after", 0)),
            max_fires=int(raw.get("max_fires", 0)),
            node=raw.get("node"),
        ))
    partitions = []
    for raw in data.get("partition", []):
        partitions.append(PartitionSpec(
            kind=raw["kind"],
            a=tuple(raw.get("a", ())),
            b=tuple(raw.get("b", ())),
            after=int(raw.get("after", 0)),
            duration=int(raw.get("duration", 0)),
            period=int(raw.get("period", 0)),
        ))
    return FaultPlan(seed, rules, node_name=node_name, partitions=partitions)


def arm_from_env(node_name: str | None = None) -> FaultPlan | None:
    """Arm from ``$CORDA_TPU_FAULT_PLAN`` (a TOML path) if set.

    Called by ``corda_tpu.node.node.main`` so child processes spawned by
    the driver/loadtest pick up the plan without config changes."""
    path = os.environ.get(PLAN_ENV)
    if not path:
        return None
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    return arm(plan_from_toml(text, node_name=node_name))


def builtin_plan(name: str, node_name: str | None = None) -> FaultPlan:
    """Named plans for the chaos loadtest / bench (``lossy``, ``slow-disk``,
    ``flaky-device``, ``reshard``, ``bitrot``, and the partition family
    ``split-brain`` / ``asym`` / ``flap`` — also reachable as
    ``partition.<name>`` for CLI pass-through)."""
    if name.startswith("partition."):
        name = name[len("partition."):]
    if name == "split-brain":
        # Symmetric split-brain with the familiar lossy rule riding along
        # (partitions and probabilistic rules compose in one plan): the
        # cut arms after 200 offered frames, holds for 2500, then heals —
        # the majority side must keep committing, the minority none.
        return FaultPlan(29, [
            FaultRule("transport.send", "drop", p=0.02, max_fires=200),
        ], node_name=node_name, partitions=[
            PartitionSpec("split", after=200, duration=2500),
        ])
    if name == "asym":
        # One-way cut: the first bound node can still HEAR the cluster
        # but nothing it sends gets out — the half-open link that makes
        # naive elections churn.
        return FaultPlan(31, [], node_name=node_name, partitions=[
            PartitionSpec("asym", after=200, duration=2000),
        ])
    if name == "flap":
        # Flapping split with a seeded half-cycle: the cut toggles every
        # `period` frames for 4000 frames — the rejoin-storm shape that
        # pre-vote exists to keep from inflating terms.
        return FaultPlan(37, [], node_name=node_name, partitions=[
            PartitionSpec("flap", after=200, duration=4000),
        ])
    if name == "lossy":
        # ~5% send-side loss; durable outbox re-poll recovers each loss
        # within ~1s, so the run completes with elevated tail latency.
        return FaultPlan(7, [
            FaultRule("transport.send", "drop", p=0.05, max_fires=500),
        ], node_name=node_name)
    if name == "reshard":
        # The reshard-under-fire plan: lossy transport THROUGH the
        # transition plus handoff-frame loss and one stale-directory
        # window, so the exactly-once audit exercises resubmitted install
        # frames and WrongShardEpoch bounces, not just the happy path.
        return FaultPlan(17, [
            FaultRule("transport.send", "drop", p=0.05, max_fires=500),
            FaultRule("shard.handoff", "drop", p=0.25, max_fires=8),
            FaultRule("netmap.refresh", "drop", p=0.10, max_fires=20),
        ], node_name=node_name)
    if name == "bitrot":
        # Storage-corruption soak (durability plane, round 14): seeded
        # bit-flips on the raft-log read path plus two bounded disk-full
        # write failures. Detection (crc mismatch) turns each flip into a
        # truncate-and-lag repair; the exactly-once audit must still hold.
        return FaultPlan(23, [
            FaultRule("disk.corrupt", "flip", p=0.02, max_fires=6),
            FaultRule("disk.full", "full", p=0.05, after=40, max_fires=2),
        ], node_name=node_name)
    if name == "slow-disk":
        return FaultPlan(11, [
            FaultRule("raft.fsync", "stall", p=0.10, delay_s=0.05,
                      max_fires=200),
        ], node_name=node_name)
    if name == "flaky-device":
        return FaultPlan(13, [
            FaultRule("verify.device", "fail", p=1.0, max_fires=1),
        ], node_name=node_name)
    raise ValueError(f"unknown builtin fault plan {name!r}")
