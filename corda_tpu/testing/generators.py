"""Composable random generators for property-style tests.

Capability match for the reference's generator infrastructure (reference:
core/src/main/kotlin/net/corda/core/testing/Generators.kt and
client/src/main/kotlin/net/corda/client/mock/Generator.kt, EventGenerator.kt):
a tiny generator monad plus domain generators (keys, parties, amounts,
issued tokens, state refs) and the cash EventGenerator the loadtest uses to
produce random-but-valid command streams.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class Generator(Generic[T]):
    """Wraps rng -> T; composes with map/flat_map/choice (Generator.kt)."""

    def __init__(self, fn: Callable[[random.Random], T]):
        self._fn = fn

    def generate(self, rng: random.Random) -> T:
        return self._fn(rng)

    def map(self, f: Callable[[T], U]) -> "Generator[U]":
        return Generator(lambda rng: f(self._fn(rng)))

    def flat_map(self, f: Callable[[T], "Generator[U]"]) -> "Generator[U]":
        return Generator(lambda rng: f(self._fn(rng)).generate(rng))

    @staticmethod
    def pure(value: T) -> "Generator[T]":
        return Generator(lambda _rng: value)

    @staticmethod
    def choice(options: list["Generator[T]"]) -> "Generator[T]":
        return Generator(lambda rng: rng.choice(options).generate(rng))

    @staticmethod
    def int_range(lo: int, hi: int) -> "Generator[int]":
        return Generator(lambda rng: rng.randint(lo, hi))

    @staticmethod
    def pick(values: list[T]) -> "Generator[T]":
        return Generator(lambda rng: rng.choice(values))

    def list_of(self, n: int) -> "Generator[list[T]]":
        return Generator(lambda rng: [self._fn(rng) for _ in range(n)])


# -- domain generators (core Generators.kt capability) ----------------------


def key_pair_gen() -> Generator:
    from ..crypto.keys import KeyPair

    return Generator(lambda rng: KeyPair.generate(rng.randbytes(32)))


def party_gen(names=("Alice Corp", "Bob Plc", "Charlie GmbH")) -> Generator:
    from ..crypto.party import Party

    return key_pair_gen().flat_map(
        lambda kp: Generator.pick(list(names)).map(
            lambda name: Party.of(name, kp.public)))


def secure_hash_gen() -> Generator:
    from ..crypto.hashes import SecureHash

    return Generator(lambda rng: SecureHash(rng.randbytes(32)))


def state_ref_gen() -> Generator:
    from ..contracts.structures import StateRef

    return secure_hash_gen().flat_map(
        lambda h: Generator.int_range(0, 9).map(lambda i: StateRef(h, i)))


def amount_gen(token="USD", lo=1, hi=1_000_000) -> Generator:
    from ..finance.amount import Amount

    return Generator.int_range(lo, hi).map(lambda q: Amount(q, token))


def issued_amount_gen(issuer, token="USD") -> Generator:
    from ..contracts.structures import Issued
    from ..finance.amount import Amount

    return Generator.int_range(1, 1_000_000).map(
        lambda q: Amount(q, Issued(issuer, token)))


# -- the cash event stream (client mock EventGenerator.kt capability) -------


class CashEvent:
    pass


class IssueEvent(CashEvent):
    def __init__(self, amount, owner):
        self.amount, self.owner = amount, owner


class MoveEvent(CashEvent):
    def __init__(self, amount, new_owner):
        self.amount, self.new_owner = amount, new_owner


class ExitEvent(CashEvent):
    def __init__(self, amount):
        self.amount = amount


def cash_event_generator(owners: list, issued_so_far: Callable[[], int],
                         currency: str = "USD") -> Generator:
    """Random-but-valid cash commands: issues always valid; moves/exits
    bounded by what has been issued (EventGenerator.kt shape)."""

    def gen(rng: random.Random) -> CashEvent:
        from ..finance.amount import Amount

        balance = issued_so_far()
        if balance <= 0 or rng.random() < 0.5:
            return IssueEvent(Amount(rng.randint(1, 10_000), currency),
                              rng.choice(owners))
        if rng.random() < 0.8:
            return MoveEvent(Amount(rng.randint(1, balance), currency),
                             rng.choice(owners))
        return ExitEvent(Amount(rng.randint(1, balance), currency))

    return Generator(gen)
