"""History-based consistency auditor (partition plane, round 20).

A chaos/partition harness records what every CLIENT observed — one
``invoke`` event per submitted transaction plus exactly one outcome
event (``ok`` acked, ``fail`` final rejection, ``timeout`` gave up
undecided) — into a bounded :class:`History`.  After the run, the
harness reads the LEDGER side (the union of every member's
``committed_states`` rows: which tx consumed which state ref) and
:func:`check_history` replays the client history against it, proving
the first-committer-wins contract held through the faults:

  * **no lost ack** — every tx a client was told committed IS in the
    committed set (an ok ack followed by an absent tx means a leader
    acknowledged before quorum and the cut ate the commit);
  * **no double-spend** — no state ref is consumed by two different
    txs anywhere in the union (members on opposite sides of a
    split-brain committing different spenders shows up HERE);
  * **no lying rejection** — a tx a client was told *conflicted* must
    not itself appear committed (the reject and the commit cannot both
    be true);
  * **every timeout resolves** — a timed-out op is allowed either
    outcome, but exactly one: its tx is either in the committed set or
    absent, and its refs were not meanwhile split between spenders
    (covered by the double-spend scan over the same union);
  * **no minority commit** — the harness samples the minority side's
    committed rows while the cut holds and feeds the delta in; any
    advance means a leader without quorum applied state.

The checker is pure data-in/verdict-out (no node imports), so auditor
fixtures in the test suite construct histories and committed sets by
hand to prove each failure mode is actually caught.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

__all__ = ["HistoryEvent", "History", "check_history"]

#: Outcome kinds a client may record for an invocation.
OUTCOMES = ("ok", "fail", "timeout")


@dataclass(frozen=True)
class HistoryEvent:
    """One client-side observation.

    ``kind`` is ``invoke`` or one of :data:`OUTCOMES`; ``request_id``
    ties the outcome back to its invoke; ``tx_id`` / ``refs`` describe
    the transaction (hex/str keys — the checker never decodes them,
    it only compares); ``t`` is seconds on the harness clock;
    ``during_cut`` marks invocations submitted while a partition held.
    """

    kind: str
    client: str
    request_id: str
    tx_id: str = ""
    refs: tuple = ()
    t: float = 0.0
    during_cut: bool = False


class History:
    """Bounded append-only event log, one per harness run.

    The bound protects long soaks (a dropped oldest event can only make
    the checker MISS a violation, never invent one — and the default
    cap comfortably holds every bench/test workload)."""

    def __init__(self, cap: int = 100_000):
        self._events: deque[HistoryEvent] = deque(maxlen=cap)

    def record_invoke(self, client: str, request_id: str, tx_id: str,
                      refs=(), t: float = 0.0,
                      during_cut: bool = False) -> None:
        self._events.append(HistoryEvent(
            "invoke", client, request_id, tx_id, tuple(refs), t,
            during_cut))

    def record_outcome(self, client: str, request_id: str, kind: str,
                       t: float = 0.0) -> None:
        if kind not in OUTCOMES:
            raise ValueError(f"unknown outcome kind {kind!r}")
        self._events.append(HistoryEvent(kind, client, request_id, t=t))

    def events(self) -> list[HistoryEvent]:
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)


def check_history(history, committed_tx_ids, consumed=(),
                  minority_commits: int = 0) -> dict:
    """Replay *history* against the ledger; return the audit verdict.

    ``history`` is a :class:`History` or a plain iterable of
    :class:`HistoryEvent`; ``committed_tx_ids`` is the union of tx ids
    the ledger committed (any member); ``consumed`` is an iterable of
    ``(ref, tx_id)`` pairs drawn from EVERY member's committed rows —
    duplicates across members are expected (replication), two
    *different* tx ids for one ref are the split-brain smoking gun.

    The verdict dict is JSON-ready; ``history_linearizable`` is the
    single gate bit (True = every check passed)."""
    events = history.events() if isinstance(history, History) else \
        list(history)
    committed = set(committed_tx_ids)

    invokes: dict[str, HistoryEvent] = {}
    outcomes: dict[str, str] = {}
    duplicate_outcomes: list[str] = []
    for ev in events:
        if ev.kind == "invoke":
            invokes[ev.request_id] = ev
        elif ev.kind in OUTCOMES:
            if ev.request_id in outcomes:
                duplicate_outcomes.append(ev.request_id)
            outcomes[ev.request_id] = ev.kind

    # Ledger-side scan: one consumer per ref, ever.
    consumers: dict = {}
    double_spends: list = []
    for ref, tx_id in consumed:
        prior = consumers.setdefault(ref, tx_id)
        if prior != tx_id:
            double_spends.append(
                {"ref": str(ref), "txs": sorted((str(prior), str(tx_id)))})

    lost_acks: list[str] = []
    fail_conflicts: list[str] = []
    unresolved: list[str] = []
    timeouts_committed = timeouts_aborted = 0
    for rid, inv in invokes.items():
        outcome = outcomes.get(rid)
        if outcome is None:
            # The harness records a timeout for every op it abandons;
            # a hole here means the history itself is broken — fail
            # loudly rather than under-checking.
            unresolved.append(rid)
        elif outcome == "ok" and inv.tx_id not in committed:
            lost_acks.append(rid)
        elif outcome == "fail" and inv.tx_id in committed:
            fail_conflicts.append(rid)
        elif outcome == "timeout":
            if inv.tx_id in committed:
                timeouts_committed += 1
            else:
                timeouts_aborted += 1

    ok = not (lost_acks or double_spends or fail_conflicts or unresolved
              or duplicate_outcomes) and minority_commits == 0
    return {
        "history_linearizable": ok,
        "events": len(events),
        "invoked": len(invokes),
        "acked_ok": sum(1 for k in outcomes.values() if k == "ok"),
        "acked_fail": sum(1 for k in outcomes.values() if k == "fail"),
        "timeouts": sum(1 for k in outcomes.values() if k == "timeout"),
        "timeouts_resolved_committed": timeouts_committed,
        "timeouts_resolved_aborted": timeouts_aborted,
        "lost_acks": lost_acks,
        "double_spends": double_spends,
        "fail_conflicts": fail_conflicts,
        "unresolved": unresolved,
        "duplicate_outcomes": duplicate_outcomes,
        "minority_commits": int(minority_commits),
    }
