"""Canned deterministic test identities.

Capability match for the reference's CoreTestUtils (reference:
test-utils/src/main/kotlin/net/corda/testing/CoreTestUtils.kt:40-80 — MEGA_CORP,
MINI_CORP, ALICE/BOB/CHARLIE, DUMMY_NOTARY with fixed entropy keys).
"""

from __future__ import annotations

from ..crypto.keys import KeyPair
from ..crypto.party import Party


def entropy_keypair(entropy: int) -> KeyPair:
    """Deterministic key pair from an integer seed (entropyToKeyPair)."""
    return KeyPair.generate(entropy.to_bytes(32, "little"))


ALICE_KEY = entropy_keypair(70)
ALICE = Party.of("Alice", ALICE_KEY.public)

BOB_KEY = entropy_keypair(80)
BOB = Party.of("Bob", BOB_KEY.public)

CHARLIE_KEY = entropy_keypair(90)
CHARLIE = Party.of("Charlie", CHARLIE_KEY.public)

MEGA_CORP_KEY = entropy_keypair(110)
MEGA_CORP = Party.of("MegaCorp", MEGA_CORP_KEY.public)

MINI_CORP_KEY = entropy_keypair(120)
MINI_CORP = Party.of("MiniCorp", MINI_CORP_KEY.public)

DUMMY_NOTARY_KEY = entropy_keypair(20)
DUMMY_NOTARY = Party.of("Notary Service", DUMMY_NOTARY_KEY.public)
