"""The ledger/transaction test DSL — contract unit testing without a node.

Capability match for the reference's test DSL (reference:
test-utils/src/main/kotlin/net/corda/testing/TestDSL.kt:19-50,
LedgerDSLInterpreter.kt, TransactionDSLInterpreter.kt — the `ledger {
transaction { input/output/command; verifies() / "fails with" } tweak {...}
}` pattern every contract test in the reference is written in).

Python form:

    l = ledger(notary=NOTARY)
    with l.transaction() as tx:
        tx.output("alice's cash", CashState(...))
        tx.command(CashIssue(1), issuer_key)
        tx.verifies()
    with l.transaction() as tx:
        tx.input("alice's cash")
        tx.output("bob's cash", CashState(...))
        tx.command(CashMove(), alice_key)
        with tx.tweak() as tw:          # scoped what-if, parent unchanged
            tw.output("extra", CashState(...))
            tw.fails_with("amounts balance")
        tx.verifies()

verifies() runs every referenced contract's verify() against a
TransactionForContract exactly as platform verification does; labeled outputs
become resolvable inputs for later transactions in the same ledger.
"""

from __future__ import annotations

from contextlib import contextmanager

from ..contracts.structures import (
    AuthenticatedObject,
    Command,
    ContractState,
    StateRef,
    Timestamp,
)
from ..contracts.verification import TransactionForContract
from ..crypto.hashes import SecureHash
from ..crypto.party import Party


class DslError(AssertionError):
    pass


class TransactionDsl:
    def __init__(self, ledger: "Ledger", base: "TransactionDsl | None" = None):
        self._ledger = ledger
        if base is not None:  # tweak: start from a snapshot of the parent
            self.inputs = list(base.inputs)
            self.outputs = list(base.outputs)
            self.commands = list(base.commands)
            self._timestamp = base._timestamp
        else:
            self.inputs: list[tuple[StateRef, ContractState]] = []
            self.outputs: list[tuple[str | None, ContractState]] = []
            self.commands: list[Command] = []
            self._timestamp: Timestamp | None = None
        self._verified = False

    # -- building ----------------------------------------------------------

    def input(self, label_or_state) -> None:
        if isinstance(label_or_state, str):
            ref, state = self._ledger.resolve(label_or_state)
        else:
            ref = StateRef(SecureHash.random(), 0)  # unlabeled ad-hoc input
            state = label_or_state
        self.inputs.append((ref, state))

    def output(self, label: str | None, state: ContractState = None) -> None:
        if state is None:
            label, state = None, label  # output(state) shorthand
        self.outputs.append((label, state))

    def command(self, value, *signers) -> None:
        self.commands.append(Command(value, tuple(signers)))

    def timestamp(self, ts: Timestamp) -> None:
        self._timestamp = ts

    # -- verification ------------------------------------------------------

    def _tx_for_contract(self) -> TransactionForContract:
        return TransactionForContract(
            inputs=tuple(s for _, s in self.inputs),
            outputs=tuple(s for _, s in self.outputs),
            attachments=(),
            commands=tuple(
                AuthenticatedObject(c.signers, (), c.value)
                for c in self.commands),
            id=SecureHash.random(),
            notary=self._ledger.notary,
            timestamp=self._timestamp,
        )

    def _run_contracts(self) -> None:
        tx = self._tx_for_contract()
        contracts = {s.contract for s in tx.inputs} | {
            s.contract for s in tx.outputs}
        for contract in contracts:
            contract.verify(tx)

    def verifies(self) -> None:
        """Every referenced contract accepts (TestDSL verifies())."""
        self._run_contracts()
        self._verified = True

    def fails_with(self, fragment: str) -> None:
        """Verification fails AND the message mentions `fragment`
        (TestDSL `fails with`)."""
        try:
            self._run_contracts()
        except Exception as e:
            if fragment.lower() not in str(e).lower():
                raise DslError(
                    f"failed, but with {e!r}; expected {fragment!r}") from e
            self._verified = True
            return
        raise DslError(f"expected failure mentioning {fragment!r}, "
                       "but the transaction verified")

    @contextmanager
    def tweak(self):
        """A scoped copy: changes inside don't affect this transaction
        (TestDSL tweak)."""
        yield TransactionDsl(self._ledger, base=self)


class Ledger:
    def __init__(self, notary: Party):
        self.notary = notary
        self._labeled: dict[str, tuple[StateRef, ContractState]] = {}
        self._tx_count = 0

    def resolve(self, label: str):
        if label not in self._labeled:
            raise DslError(f"no output labeled {label!r}")
        return self._labeled[label]

    @contextmanager
    def transaction(self):
        tx = TransactionDsl(self)
        yield tx
        if not tx._verified:
            raise DslError(
                "transaction block ended without verifies()/fails_with()")
        # Register labeled outputs for later transactions.
        self._tx_count += 1
        fake_id = SecureHash.sha256(b"ledger-dsl-tx-%d" % self._tx_count)
        for index, (label, state) in enumerate(tx.outputs):
            if label is not None:
                self._labeled[label] = (StateRef(fake_id, index), state)


def ledger(notary: Party) -> Ledger:
    return Ledger(notary)
