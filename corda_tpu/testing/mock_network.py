"""MockNetwork: whole-network multi-node tests in one process.

Capability match for the reference's MockNetwork/MockNode (reference:
test-utils/src/main/kotlin/net/corda/testing/node/MockNode.kt:47-160) — the
survey's load-bearing testing idea (SURVEY.md §4): real node wiring (services,
state machine manager, notary) with fakes swapped in — the deterministic
manually-pumped InMemoryMessagingNetwork, in-memory storage/uniqueness, and a
shared network-map view. Multi-party protocols, crash/restart recovery and
double-spend rejection all run deterministically with no real network.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..crypto.keys import KeyPair
from ..crypto.party import Party
from ..crypto.provider import BatchVerifier
from ..flows.api import FlowLogic
from ..node.messaging.inmem import InMemoryMessagingNetwork
from ..node.services.api import (
    NodeInfo,
    ServiceHub,
    ServiceInfo,
    StorageService,
    UniquenessProvider,
    SIMPLE_NOTARY,
    VALIDATING_NOTARY,
)
from ..node.services.inmemory import (
    InMemoryAttachmentStorage,
    InMemoryNetworkMapCache,
    InMemoryTransactionMappingStorage,
    InMemoryTransactionStorage,
    InMemoryUniquenessProvider,
    InMemoryIdentityService,
    NodeVaultService,
    SimpleKeyManagementService,
)
from ..node.statemachine import (
    CheckpointStorage,
    FlowHandle,
    InMemoryCheckpointStorage,
    StateMachineManager,
)


class MockNode:
    """One in-process node: real services + SMM over the fake network."""

    def __init__(
        self,
        network: "MockNetwork",
        name: str,
        key: KeyPair,
        advertised_services: tuple[ServiceInfo, ...] = (),
        verifier: BatchVerifier | None = None,
        checkpoint_storage: CheckpointStorage | None = None,
        reattach_address=None,
    ):
        self.network = network
        self.name = name
        self.key = key
        self.identity = Party.of(name, key.public)
        if reattach_address is not None:
            # Crash recovery: rebind to the same durable address so queued
            # store-and-forward messages reach the reborn node.
            self.messaging = network.messaging_network.reattach(reattach_address)
        else:
            self.messaging = network.messaging_network.create_node_messaging(name)
        self.info = NodeInfo(
            address=self.messaging.my_address,
            legal_identity=self.identity,
            advertised_services=advertised_services,
        )
        self.checkpoint_storage = (
            checkpoint_storage if checkpoint_storage is not None
            else InMemoryCheckpointStorage()
        )

        key_service = SimpleKeyManagementService([key])
        self.services = ServiceHub(
            identity_service=network.identity_service,
            key_management_service=key_service,
            storage_service=StorageService(
                validated_transactions=InMemoryTransactionStorage(),
                attachments=InMemoryAttachmentStorage(),
                state_machine_recorded_transaction_mapping=(
                    InMemoryTransactionMappingStorage()),
            ),
            vault_service=NodeVaultService(
                lambda: set(key_service.keys.keys())
            ),
            network_map_cache=network.network_map_cache,
            my_info=self.info,
        )
        self.smm = StateMachineManager(
            service_hub=self.services,
            messaging=self.messaging,
            checkpoint_storage=self.checkpoint_storage,
            verifier=verifier or network.verifier,
            our_identity=self.identity,
            defer_verify=True,  # batch across the whole scheduling round
        )
        self.uniqueness_provider: UniquenessProvider | None = None
        self.notary_service = None

    def start(self) -> "MockNode":
        from ..flows.data_vending import install_data_vending

        install_data_vending(self.smm)
        self.smm.start()
        return self

    def start_flow(self, logic: FlowLogic) -> FlowHandle:
        return self.smm.add(logic)

    def register_initiated_flow(
        self, initiator_name: str, factory: Callable[[Party], FlowLogic]
    ) -> None:
        self.smm.register_flow_initiator(initiator_name, factory)

    def record_transaction(self, stx) -> None:
        self.services.record_transactions([stx])

    def stop(self) -> None:
        self.messaging.stop()

    def restart(self) -> "MockNode":
        """Crash/recover: a fresh node with the same durable state — identity
        key, checkpoint storage, storage — then checkpoint-restore resumes
        mid-protocol flows (reference: TwoPartyTradeProtocolTests mid-flow
        restart)."""
        self.stop()
        replacement = MockNode(
            self.network,
            self.name,
            self.key,
            self.info.advertised_services,
            checkpoint_storage=self.checkpoint_storage,
            reattach_address=self.messaging.my_address,
        )
        # Durable storage survives the crash.
        replacement.services.storage_service = self.services.storage_service
        replacement.services.vault_service = self.services.vault_service
        replacement.uniqueness_provider = self.uniqueness_provider
        self.network._replace_node(self, replacement)
        if self.notary_service is not None:
            from ..node.services.notary import rebuild_notary_service

            replacement.notary_service = rebuild_notary_service(
                self.notary_service, replacement
            )
        replacement.start()
        return replacement


class MockNetwork:
    """Factory + shared fabric for MockNodes."""

    def __init__(self, verifier: BatchVerifier | None = None):
        self.messaging_network = InMemoryMessagingNetwork()
        self.identity_service = InMemoryIdentityService()
        self.network_map_cache = InMemoryNetworkMapCache()
        self.verifier = verifier
        self.nodes: list[MockNode] = []
        self._key_counter = 1000

    def _next_key(self) -> KeyPair:
        self._key_counter += 1
        return KeyPair.generate(self._key_counter.to_bytes(32, "little"))

    def create_node(
        self,
        name: str,
        key: KeyPair | None = None,
        advertised_services: tuple[ServiceInfo, ...] = (),
        start: bool = True,
    ) -> MockNode:
        node = MockNode(
            self, name, key or self._next_key(), tuple(advertised_services)
        )
        self.nodes.append(node)
        self.identity_service.register_identity(node.identity)
        self.network_map_cache.add_node(node.info)
        if start:
            node.start()
        return node

    def create_notary_node(
        self, name: str = "Notary Service", validating: bool = True
    ) -> MockNode:
        from ..node.services.notary import SimpleNotaryService, ValidatingNotaryService

        service_type = VALIDATING_NOTARY if validating else SIMPLE_NOTARY
        node = self.create_node(
            name, advertised_services=(ServiceInfo(service_type),), start=False
        )
        node.uniqueness_provider = InMemoryUniquenessProvider()
        cls = ValidatingNotaryService if validating else SimpleNotaryService
        node.notary_service = cls(node.smm, node.services, node.identity, node.key, node.uniqueness_provider)
        node.start()
        return node

    def _replace_node(self, old: MockNode, new: MockNode) -> None:
        self.nodes[self.nodes.index(old)] = new
        self.identity_service.register_identity(new.identity)
        self.network_map_cache.add_node(new.info)

    def run_network(self, max_messages: int = 100_000) -> int:
        """Pump until quiescent: drain all in-flight messages, then flush
        every node's accumulated verify micro-batch, poll parked
        ServiceRequests (async providers, retry-backoff timers), repeat.
        Message drains between flushes are what make the batches wide."""
        import time as _time

        delivered = 0
        while True:
            delivered += self.messaging_network.run(max_messages)
            flushed = sum(node.smm.flush_pending_verifies() for node in self.nodes)
            polled = sum(node.smm.poll_services() for node in self.nodes)
            parked = sum(len(node.smm._service_queue) for node in self.nodes)
            if (flushed == 0 and polled == 0 and parked == 0
                    and self.messaging_network.in_flight_count == 0):
                return delivered
            if (parked and not flushed and not polled
                    and self.messaging_network.in_flight_count == 0):
                # Everything quiescent except a pending service poll (e.g.
                # a retry-backoff timer): wait it out without spinning hot.
                _time.sleep(0.005)

    def stop_nodes(self) -> None:
        for node in self.nodes:
            node.stop()
        self.messaging_network.stop()
