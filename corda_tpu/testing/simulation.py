"""Whole-network simulations with latency injection.

Capability match for the reference's simulation harness (reference:
samples/irs-demo/src/main/kotlin/net/corda/simulation/Simulation.kt:37-45 —
MockNetwork-based scenarios with banks placed in cities and an injected
latency calculator — and TradeSimulation.kt — a cash-for-asset trade run
through the simulated network). The sent-message feed these simulations
produce is what the reference's network-visualiser replays
(samples/network-visualiser/.../NetworkMapVisualiser.kt); here it's
`Simulation.network.messaging_network.sent_messages`.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..crypto.provider import BatchVerifier
from .mock_network import MockNetwork, MockNode


@dataclass(frozen=True)
class City:
    name: str
    latitude: float
    longitude: float


LONDON = City("London", 51.5, -0.12)
NEW_YORK = City("New York", 40.7, -74.0)
TOKYO = City("Tokyo", 35.7, 139.7)
SINGAPORE = City("Singapore", 1.35, 103.8)
ZURICH = City("Zurich", 47.4, 8.5)

_CITIES = (LONDON, NEW_YORK, TOKYO, SINGAPORE, ZURICH)


def _great_circle_km(a: City, b: City) -> float:
    phi1, phi2 = math.radians(a.latitude), math.radians(b.latitude)
    dphi = phi2 - phi1
    dlam = math.radians(b.longitude - a.longitude)
    h = math.sin(dphi / 2) ** 2 \
        + math.cos(phi1) * math.cos(phi2) * math.sin(dlam / 2) ** 2
    return 2 * 6371 * math.asin(math.sqrt(h))


class Simulation:
    """Banks in cities over a latency-injected MockNetwork
    (Simulation.kt:37-45). Latency ticks are proportional to great-circle
    distance, so message interleavings resemble a real WAN's."""

    def __init__(self, bank_count: int = 2, notary_city: City = ZURICH,
                 verifier: BatchVerifier | None = None):
        self._locations: dict[int, City] = {}
        self.network = MockNetwork(verifier=verifier)
        self.network.messaging_network.latency_calculator = self._latency
        self.notary = self.network.create_notary_node("Notary")
        self._place(self.notary, notary_city)
        self.banks: list[MockNode] = []
        for i in range(bank_count):
            city = _CITIES[i % len(_CITIES)]
            bank = self.network.create_node(f"Bank of {city.name} {i}")
            self._place(bank, city)
            self.banks.append(bank)

    def _place(self, node: MockNode, city: City) -> None:
        self._locations[node.messaging.my_address.id] = city

    def _latency(self, sender, recipient) -> int:
        a = self._locations.get(sender.id)
        b = self._locations.get(recipient.id)
        if a is None or b is None or a == b:
            return 1
        return 1 + int(_great_circle_km(a, b) / 1000)  # ~1 tick per 1000 km

    @property
    def sent_messages(self):
        """The visualiser feed (InMemoryMessagingNetwork.sentMessages)."""
        return self.network.messaging_network.sent_messages

    def run(self) -> int:
        return self.network.run_network()

    def stop(self) -> None:
        self.network.stop_nodes()


class TradeSimulation(Simulation):
    """One bank sells an asset to another for cash (TradeSimulation.kt):
    exercises issuance, DvP trade, notarisation and broadcast across the
    simulated WAN."""

    def __init__(self, verifier: BatchVerifier | None = None):
        super().__init__(bank_count=2, verifier=verifier)

    def run_trade(self, price_quantity: int = 750):
        from ..contracts.structures import Issued, now_micros
        from ..finance import Amount, Cash
        from ..finance.trade import BuyerFlow, SellerFlow
        from .dummies import DummyContract

        seller, buyer = self.banks
        asset_issue = DummyContract.generate_initial(
            seller.identity.ref(b"\x01"), 99, self.notary.identity)
        asset_issue.sign_with(seller.key)
        asset_stx = asset_issue.to_signed_transaction()
        seller.record_transaction(asset_stx)

        cash_issue = Cash.generate_issue(
            Amount(1_000, "USD"), buyer.identity.ref(b"\x02"),
            buyer.identity.owning_key, self.notary.identity)
        cash_issue.sign_with(buyer.key)
        buyer.record_transaction(cash_issue.to_signed_transaction())

        buyer.register_initiated_flow(
            "SellerFlow",
            lambda party: BuyerFlow(party, Amount(1_000, "USD"),
                                    self.notary.identity))
        handle = seller.start_flow(SellerFlow(
            buyer.identity, asset_stx.tx.out_ref(0),
            Amount(price_quantity, "USD")))
        self.run()
        return handle.result.result()
