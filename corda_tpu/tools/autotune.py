"""autotune — the closed-loop tuner's CLI.

Consume a perf-doctor verdict, sweep the knobs it implicates, gate every
candidate against the hand-tuned incumbent, commit the winner::

    python -m corda_tpu.tools.autotune artifacts/INGEST_r15_local.json \\
        --budget 4 --seed 7 --out artifacts/AUTOTUNE_r21_local.json

The positional argument is any artifact ``perfdoctor`` can diagnose (a
bench report, ingest sweep, flagship capture) OR an already-rendered
verdict (a JSON object carrying ``bottlenecks``). The controller maps
the top bottleneck's structured experiment spec (obs/doctor.RULE_SPECS)
to a sweep, runs each candidate through the real multiprocess ingest
harness (or a deterministic mock surface with ``--mock``), and prints
the full provenance record as one JSON line. Unless ``--no-append``,
the run's ``autotune`` record is appended to the trajectory store, so
``perfdoctor --gate`` bands the loop's own output from then on.

Replay: the search is deterministic — same seed, same runner responses,
identical ``decision_sequence``. ``--mock monotone|noisy|regressing|
cliff`` swaps in the pure response surfaces (no cluster) for demos and
replay checks.

``--validate`` runs the knob-registry drift check (every registry entry
must resolve to a live config key / harness kwarg / env read) and exits
non-zero on any violation — the analyzer-style CI hook.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..autotune import controller, space
from ..obs import doctor

DEFAULT_TRAJECTORY = os.path.join("artifacts", "TRAJECTORY.jsonl")


def _load_json(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        loaded = json.load(f)
    if not isinstance(loaded, dict):
        raise ValueError(f"{path}: expected a JSON object at top level")
    return loaded


def _verdict_of(artifact: dict) -> dict:
    """The artifact as a verdict: pass through an already-rendered one
    (it carries ``bottlenecks``), diagnose anything else."""
    if "bottlenecks" in artifact:
        return artifact
    return doctor.diagnose(doctor.extract_signals(artifact))


def cmd_validate() -> int:
    errors = space.validate_registry()
    print(json.dumps({"ok": not errors, "knobs": len(space.KNOBS),
                      "errors": errors}, sort_keys=True))
    return 0 if not errors else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m corda_tpu.tools.autotune",
        description="Closed-loop autotuner: doctor verdict -> gated "
                    "parameter sweep -> committed config overlay.")
    parser.add_argument("verdict", nargs="?",
                        help="artifact or verdict JSON to consume")
    parser.add_argument("--validate", action="store_true",
                        help="check the knob registry against the live "
                             "config/harness/env surface and exit")
    parser.add_argument("--mock", metavar="CURVE",
                        choices=("monotone", "noisy", "regressing",
                                 "cliff"),
                        help="deterministic mock response surface "
                             "instead of the real harness")
    parser.add_argument("--budget", type=int, default=4,
                        help="candidates to evaluate beyond the "
                             "incumbent (default 4)")
    parser.add_argument("--seed", type=int, default=0,
                        help="search seed: same seed replays the same "
                             "decision sequence (default 0)")
    parser.add_argument("--metric", help="swept metric override")
    parser.add_argument("--explore", action="store_true",
                        help="fall back to the default exploratory sweep "
                             "when the verdict abstains or implicates "
                             "no sweepable knob")
    parser.add_argument("--rate", type=float, default=2400.0,
                        help="offered tx/s for real candidates")
    parser.add_argument("--n-tx", type=int, default=400,
                        help="corpus size per real candidate")
    parser.add_argument("--workers", type=int, default=2,
                        help="replay workers per real candidate")
    parser.add_argument("--out", metavar="PATH",
                        help="also write the provenance record to PATH")
    parser.add_argument("--overlay-out", metavar="PATH",
                        help="write the committed TOML overlay to PATH "
                             "(only when the loop improved)")
    parser.add_argument("--trajectory", metavar="PATH",
                        help=f"trajectory store to append the autotune "
                             f"record to (default {DEFAULT_TRAJECTORY})")
    parser.add_argument("--no-append", action="store_true",
                        help="do not append to the trajectory store")
    args = parser.parse_args(argv)

    if args.validate:
        return cmd_validate()
    if not args.verdict:
        print("autotune: no verdict artifact given (or use --validate)",
              file=sys.stderr)
        return 2
    try:
        artifact = _load_json(args.verdict)
    except (OSError, ValueError) as exc:
        print(f"autotune: {args.verdict}: {exc}", file=sys.stderr)
        return 2
    verdict = _verdict_of(artifact)
    try:
        spec = controller.spec_from_verdict(verdict, metric=args.metric)
    except ValueError as exc:
        if not args.explore:
            print(f"autotune: {exc} (pass --explore to sweep the "
                  f"default knobs anyway)", file=sys.stderr)
            return 2
        spec = controller.exploratory_spec(metric=args.metric)

    if args.mock:
        runner = controller.make_mock_runner(spec, args.mock)
    else:
        runner = controller.make_ingest_runner(
            rates=(args.rate,), n_tx=args.n_tx, workers=args.workers)

    result = controller.run_autotune(
        spec, runner, budget=args.budget, seed=args.seed,
        verdict_consumed={
            "source": os.path.basename(args.verdict),
            "first_bottleneck": verdict.get("first_bottleneck"),
            "experiment_id": spec.experiment_id,
        })
    if args.mock:
        result["runner"] = {"mock": args.mock}
    else:
        result["runner"] = {"harness": "run_ingest_sweep",
                            "rates": [args.rate], "n_tx": args.n_tx,
                            "workers": args.workers}

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.overlay_out and result["overlay"]:
        with open(args.overlay_out, "w", encoding="utf-8") as f:
            f.write(result["overlay"]["toml"])
    if not args.no_append:
        store = args.trajectory or os.environ.get(
            "CORDA_TPU_TRAJECTORY", DEFAULT_TRAJECTORY)
        source = args.out or "autotune-run.json"
        doctor.append_trajectory(
            store, doctor.normalize_record(result, source=source))
        result["trajectory"] = store
    print(json.dumps(result, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
