"""CrossCash: random cash traffic + a predicted state model + convergence.

Capability match for the reference's CrossCashTest (reference:
tools/loadtest/src/main/kotlin/net/corda/loadtest/tests/CrossCashTest.kt:1-80
and LoadTest.kt:121-129): random issues / payments between real node
processes, a coordinator-side PREDICTED model of every node's cash balance,
and a gather step that polls remote vaults until they CONVERGE to the
prediction — the check that catches double-spends, lost updates and
vault/notary divergence that commit/reject counting cannot.

Model-shape differences from the reference, by design:

* The reference gathers mid-traffic and therefore needs an interleaving
  search over per-node diff queues (CrossCashTest.kt:50-66). Here commands
  execute in seeded WAVES and every wave ends with a poll-until-converged
  gather, where each notarised transaction has a deterministic eventual
  state — broadcast laggards are absorbed by the polling loop rather than a
  queue search. Same detection power at the states we check.
* The model predicts per-node TOTALS, not per-issuer buckets: which coins
  Cash.generate_spend consumes depends on vault iteration order, which a
  remote model cannot know — predicting issuer flows would need to mirror
  it. Totals are order-independent and still expose every consistency bug
  the check exists for (a double-spend inflates a balance; a lost update
  deflates one). Per-issuer detail is still gathered for diagnostics.

Disruptions (reference: Disruption.kt:18-60): kill-follower (SIGKILL +
restart from disk), sigstop-follower (hang), and strain-follower — the
CPU-strain equivalent implemented as SIGSTOP duty-cycling, producing the
slow-but-alive node that exposes timeout tuning.
"""

from __future__ import annotations

import random
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path

from ..finance import Amount, Cash, CashState
from ..flows.api import FlowException, FlowLogic, register_flow
from ..flows.finality import FinalityFlow
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder

CURRENCY = "USD"


@register
@dataclass(frozen=True)
class CashCommandResult:
    committed: bool
    error: str | None = None


def _party_by_name(hub, name: str):
    for info in hub.network_map_cache.party_nodes:
        if info.legal_identity.name == name:
            return info.legal_identity
    raise FlowException(f"no party named {name!r} in the network map")


def _notary_of(hub):
    # The cache's notary_nodes predicate (service type is_sub_type_of
    # NOTARY_TYPE) — NOT "any advertised service", which would happily
    # pick an oracle as the notary.
    notary = hub.network_map_cache.get_any_notary()
    if notary is None:
        raise FlowException("no notary advertised in the network map")
    return notary


@register_flow(name="crosscash.CashCommandFlow")
class CashCommandFlow(FlowLogic):
    """RPC-startable: one CrossCash command on this node.

    kind "issue": self-issued cash paid straight to `recipient` (a node
    name). kind "pay": coin-select own vault cash, pay `recipient`.
    Both finalise through the notary and broadcast to participants, so
    recipient vaults converge via the data-vending resolve path.
    """

    def __init__(self, kind: str, quantity: int, recipient: str = "",
                 nonce: int = 0):
        self.kind = kind
        self.quantity = quantity
        self.recipient = recipient
        self.nonce = nonce

    def call(self):
        hub = self.service_hub
        me = hub.my_identity
        notary = _notary_of(hub)
        try:
            recipient = _party_by_name(hub, self.recipient)
            if self.kind == "issue":
                tx = Cash.generate_issue(
                    Amount(self.quantity, CURRENCY),
                    me.ref(self.nonce.to_bytes(4, "big")),
                    recipient.owning_key, notary, nonce=self.nonce)
            elif self.kind == "pay":
                tx = TransactionBuilder(notary=notary)
                # Soft-locked selection: concurrent pay commands on one
                # node reserve disjoint coins (the chaos harness runs
                # several at once against a shared vault).
                states = hub.vault_service.select_coins(
                    str(CURRENCY), self.quantity,
                    holder=self.run_id or b"crosscash")
                Cash.generate_spend(
                    tx, Amount(self.quantity, CURRENCY),
                    recipient.owning_key, states,
                    change_owner=me.owning_key)
            else:
                raise FlowException(f"unknown command kind {self.kind!r}")
        except Exception as e:
            return CashCommandResult(False, f"{type(e).__name__}: {e}")
        tx.sign_with(hub.legal_identity_key)
        stx = tx.to_signed_transaction(check_sufficient_signatures=False)
        try:
            yield from self.sub_flow(FinalityFlow(stx, (recipient,)))
        except Exception as e:
            return CashCommandResult(False, f"{type(e).__name__}: {e}")
        return CashCommandResult(True)


def install(node) -> None:
    """Cordapp hook — importing registers the flow + codec types."""


# ---------------------------------------------------------------------------
# Coordinator-side model + harness
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CrossCashCommand:
    kind: str  # issue | pay
    node: str  # executing node name
    quantity: int
    recipient: str
    nonce: int = 0

    def rpc_args(self) -> tuple:
        return (self.kind, self.quantity, self.recipient, self.nonce)


@dataclass
class CrossCashModel:
    """Predicted per-node cash totals (the simplified CrossCashState)."""

    balances: dict = field(default_factory=dict)

    def apply(self, cmd: CrossCashCommand) -> None:
        if cmd.kind == "issue":
            self.balances[cmd.recipient] = (
                self.balances.get(cmd.recipient, 0) + cmd.quantity)
        elif cmd.kind == "pay":
            if self.balances.get(cmd.node, 0) < cmd.quantity:
                raise ValueError(f"model generated unpayable command {cmd}")
            self.balances[cmd.node] -= cmd.quantity
            self.balances[cmd.recipient] = (
                self.balances.get(cmd.recipient, 0) + cmd.quantity)
        else:
            raise ValueError(cmd.kind)


def generate_wave(model: CrossCashModel, node_names: list[str],
                  rng: random.Random, size: int) -> list[CrossCashCommand]:
    """Seeded command generation against the model (CrossCashTest.kt's
    generate): issues always possible; pays only up to the predicted
    balance. One command per spender node per wave."""
    cmds: list[CrossCashCommand] = []
    nonce = rng.randrange(1 << 30)
    spenders = rng.sample(node_names, min(size, len(node_names)))
    for i, node in enumerate(spenders):
        balance = model.balances.get(node, 0)
        kind = rng.choice(["issue", "pay", "pay"]) if balance else "issue"
        recipient = rng.choice([n for n in node_names if n != node])
        if kind == "issue":
            cmds.append(CrossCashCommand(
                "issue", node, rng.randrange(100, 10_000), recipient,
                nonce + i))
        else:
            cmds.append(CrossCashCommand(
                "pay", node, rng.randrange(1, balance + 1), recipient))
    return cmds


def gather_balances(rpc) -> dict:
    """One node's vault over RPC -> {issuer_name: quantity} (diagnostic
    detail; convergence compares totals)."""
    out: dict = {}
    for sar in rpc.call("vault_snapshot"):
        state = sar.state.data
        if isinstance(state, CashState):
            issuer = state.amount.token.issuer.party.name
            out[issuer] = out.get(issuer, 0) + state.amount.quantity
    return out


def vaults_match(expected_totals: dict, gathered_by_issuer: dict) -> bool:
    """Per-node total equality (absent == zero)."""
    nodes = set(expected_totals) | set(gathered_by_issuer)
    for node in nodes:
        if expected_totals.get(node, 0) \
                != sum(gathered_by_issuer.get(node, {}).values()):
            return False
    return True


@dataclass
class CrossCashResult:
    waves: int
    commands_run: int
    commands_committed: int
    commands_rejected: int
    converged: bool
    disruptions: list
    expected: dict
    gathered: dict


def run_crosscash(
    n_waves: int = 4,
    wave_size: int = 3,
    clients: int = 3,
    notary: str = "raft",
    cluster_size: int = 3,
    seed: int = 7,
    disrupt: str | tuple | None = None,  # kill-follower | sigstop-follower
    # | strain-follower, or a tuple of them — one per successive wave
    disrupt_wave: int = 1,  # inject the first before this wave (0-based)
    base_dir: str | None = None,
    converge_timeout: float = 90.0,
    max_seconds: float = 600.0,
    _drop_model_update: bool = False,  # fault-injection hook for tests: lose
    # one committed update from the model; convergence MUST then fail, which
    # proves the checker detects a lost-update/double-spend class divergence.
) -> CrossCashResult:
    """The generate → execute → gather-and-converge loop over real OS-process
    nodes (LoadTest.kt:39-144 + CrossCashTest), with fault injection."""
    from ..testing.driver import driver

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-xc-"))
    rng = random.Random(seed)
    model = CrossCashModel()
    disruptions: list[str] = []
    n_run = n_ok = n_rej = 0
    dropped = False
    deadline = time.monotonic() + max_seconds
    with driver(base) as d:
        members = []
        if notary.startswith("raft"):
            cluster = tuple(f"Raft{i}" for i in range(cluster_size))
            for name in cluster:
                members.append(d.start_node(
                    name, notary="raft-simple", raft_cluster=cluster,
                    cordapps=("corda_tpu.tools.crosscash",)))
        else:
            members.append(d.start_node(
                "Notary", notary=notary,
                cordapps=("corda_tpu.tools.crosscash",)))
        names = [f"Bank{i}" for i in range(clients)]
        rpcs = {}
        for name in names:
            handle = d.start_node(
                name, rpc=True, cordapps=("corda_tpu.tools.crosscash",))
            rpcs[name] = handle.rpc("demo", "s3cret", timeout=60.0)
            d.defer(rpcs[name].close)

        kinds = ((disrupt,) if isinstance(disrupt, str)
                 else tuple(disrupt or ()))
        schedule = {disrupt_wave + k: kind for k, kind in enumerate(kinds)}
        converged = True
        sigstopped_wave = None
        gathered: dict = {}
        for wave in range(n_waves):
            kind = schedule.get(wave)
            if kind and len(members) > 1:
                victim = members[1]
                if kind == "kill-follower":
                    victim.kill()
                    disruptions.append(f"SIGKILL {victim.name}")
                    members[1] = d.restart_node(victim)
                    disruptions.append(f"restarted {victim.name}")
                elif kind == "sigstop-follower":
                    victim.sigstop()
                    disruptions.append(f"SIGSTOP {victim.name}")
                    sigstopped_wave = wave
                elif kind == "strain-follower":
                    victim.strain(seconds=6.0, duty=0.8)
                    disruptions.append(
                        f"strain {victim.name} (80% duty-cycle hang)")
            cmds = generate_wave(model, names, rng, wave_size)
            flows = [(cmd, rpcs[cmd.node].call(
                "start_flow_dynamic", "crosscash.CashCommandFlow",
                cmd.rpc_args())) for cmd in cmds]
            for cmd, fh in flows:
                while time.monotonic() < deadline:
                    done, value = rpcs[cmd.node].call("flow_result", fh.run_id)
                    if done:
                        break
                    time.sleep(0.1)
                else:
                    raise TimeoutError(f"wave {wave} did not finish")
                n_run += 1
                if value.committed:
                    n_ok += 1
                    if _drop_model_update and not dropped \
                            and cmd.kind == "pay":
                        dropped = True  # injected lost-update
                    else:
                        model.apply(cmd)
                else:
                    n_rej += 1
            if sigstopped_wave == wave and len(members) > 1:
                members[1].sigcont()
                disruptions.append(f"SIGCONT {members[1].name}")
                sigstopped_wave = None
            # Converge BEFORE the next wave: the next wave's pays rely on
            # broadcast cash having landed in recipient vaults.
            converged = False
            poll_deadline = min(time.monotonic() + converge_timeout, deadline)
            while time.monotonic() < poll_deadline:
                gathered = {n: gather_balances(rpcs[n]) for n in names}
                if vaults_match(model.balances, gathered):
                    converged = True
                    break
                time.sleep(0.4)
            if not converged:
                break  # report the divergence; do not compound it
    return CrossCashResult(
        waves=n_waves, commands_run=n_run, commands_committed=n_ok,
        commands_rejected=n_rej, converged=converged,
        disruptions=disruptions,
        expected=dict(model.balances), gathered=gathered)


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="CrossCash: random cash traffic + convergence checking")
    ap.add_argument("--waves", type=int, default=4)
    ap.add_argument("--wave-size", type=int, default=3)
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--notary", choices=("simple", "validating", "raft"),
                    default="raft")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--disrupt", action="append", default=None,
                    choices=("kill-follower", "sigstop-follower",
                             "strain-follower"),
                    help="repeatable; one disruption per successive wave")
    args = ap.parse_args(argv)
    result = run_crosscash(
        n_waves=args.waves, wave_size=args.wave_size, clients=args.clients,
        notary=args.notary, seed=args.seed,
        disrupt=tuple(args.disrupt) if args.disrupt else None)
    print(json.dumps(result.__dict__))
    return 0 if result.converged else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
