"""The notary-demo CorDapp: issue + move + notarise in one flow.

Capability match for the reference's raft-notary-demo app (reference:
samples/raft-notary-demo/src/main/kotlin/net/corda/notarydemo/api/
NotaryDemoApi.kt driven by NotaryDemo.kt:14-29, installed through
plugin/NotaryDemoPlugin.kt:8-16): a client asks the node to mint a dummy
state, spend it, and obtain the notary's uniqueness signature. Load it into
a node with `cordapps = ["corda_tpu.tools.demo_cordapp"]` and drive it over
RPC with `start_flow("IssueAndNotariseFlow", magic)`.
"""

from __future__ import annotations

from ..flows.api import FlowException, FlowLogic, register_flow
from ..flows.notary import NotaryClientFlow
from ..node.services.api import NOTARY_TYPE
from ..testing.dummies import DummyContract


@register_flow
class IssueAndNotariseFlow(FlowLogic):
    """Mint a DummyContract state, move it to ourselves, notarise the move.
    Returns the notarised transaction id (hex)."""

    def __init__(self, magic: int):
        self.magic = magic

    def call(self):
        notary = self._pick_notary()
        me = self.service_hub.my_identity
        builder = DummyContract.generate_initial(
            me.ref(self.magic.to_bytes(4, "big")), self.magic, notary)
        builder.sign_with(self.service_hub.legal_identity_key)
        issue_stx = builder.to_signed_transaction()
        self.record_transactions([issue_stx])

        move = DummyContract.move(issue_stx.tx.out_ref(0), me.owning_key)
        move.sign_with(self.service_hub.legal_identity_key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        sig = yield from self.sub_flow(NotaryClientFlow(stx))
        self.record_transactions([stx.with_additional_signature(sig)])
        return stx.id.hex()

    def _pick_notary(self):
        for info in self.service_hub.network_map_cache.party_nodes:
            if any(s.type.is_sub_type_of(NOTARY_TYPE)
                   for s in info.advertised_services):
                return info.legal_identity
        raise FlowException("no notary advertised in the network map")


def install(node) -> None:  # plugin hook; nothing extra to wire
    pass
