"""Explorer: the operations dashboard, served as a web page over RPC.

Capability match for the reference's explorer tool (reference:
tools/explorer/src/main/kotlin/net/corda/explorer/Main.kt — a TornadoFX/
JavaFX GUI whose views are CashViewer, transaction viewer and network
identity lists, all fed by the client RPC observables via NodeMonitorModel,
client/src/main/kotlin/net/corda/client/model/NodeMonitorModel.kt).

TPU-framework form: the node side is identical (everything rides the RPC
surface: vault/network/state-machine snapshots plus the ``state_machine_
changes`` cursor stream), but the presentation tier is a dependency-free web
dashboard instead of a desktop JavaFX shell — an http.server endpoint that
renders one self-refreshing HTML page and exposes the same data as JSON
(``/api/dashboard``) for headless consumers. The JFX observable models
(ContractStateModel's cash rollup, GatheredTransactionDataModel's tx list,
NodeMonitorModel's flow progress feed) map to the ``gather()`` aggregation
below: cash balances grouped by currency, recent transactions, in-flight
flows with progress, node metrics.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from ..node.rpc import RpcClient


def render_value(obj, depth: int = 0):
    """Recursively turn ledger objects into plain JSON-able structures.
    The explorer displays *everything* the RPC surface returns, so this is
    deliberately generic: dataclasses become tagged dicts, keys/hashes render
    as short strings, and depth is capped against adversarial nesting."""
    if depth > 12:
        return "…"
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return obj.hex() if len(obj) <= 64 else f"{len(obj)} bytes"
    if isinstance(obj, SecureHash):
        return obj.prefix_chars(12)
    if isinstance(obj, Party):
        return str(obj.name)
    from ..transactions.signed import SignedTransaction

    if isinstance(obj, SignedTransaction):
        # Render the deserialized wire transaction, not the opaque tx_bits
        # (the GUI explorer's transaction viewer shows components).
        return {"_type": "SignedTransaction",
                "id": render_value(obj.id, depth + 1),
                "tx": render_value(obj.tx, depth + 1),
                "sigs": render_value(obj.sigs, depth + 1)}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {"_type": type(obj).__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = render_value(getattr(obj, f.name), depth + 1)
        return out
    if isinstance(obj, (list, tuple, set, frozenset)):
        items = [render_value(x, depth + 1) for x in obj]
        if isinstance(obj, (set, frozenset)):
            items.sort(key=json.dumps)
        return items
    if isinstance(obj, dict):
        return {str(k): render_value(v, depth + 1) for k, v in obj.items()}
    to_render = getattr(obj, "__dict__", None)
    if to_render is not None:
        return {"_type": type(obj).__name__,
                **{k: render_value(v, depth + 1)
                   for k, v in to_render.items() if not k.startswith("_")}}
    return repr(obj)


def cash_balances(vault_states) -> dict[str, int]:
    """ContractStateModel.kt's cash rollup: sum CashState quantities per
    currency code across the unconsumed set."""
    from ..finance import CashState

    balances: dict[str, int] = {}
    for sref in vault_states:
        data = getattr(getattr(sref, "state", sref), "data", None)
        if isinstance(data, CashState):
            currency = data.amount.token.product
            balances[str(currency)] = (
                balances.get(str(currency), 0) + data.amount.quantity)
    return balances


class ExplorerModel:
    """The data-gathering half (NodeMonitorModel.kt capability): aggregates
    every RPC feed into one dashboard snapshot, tracking the state-machine
    change cursor across polls so flow history accumulates client-side."""

    MAX_TX, MAX_EVENTS = 50, 200

    # Renew the push subscription well inside the server's 120 s TTL.
    RESUBSCRIBE_S = 30.0

    def __init__(self, rpc: RpcClient):
        self.rpc = rpc
        self._cursor = 0
        self._events: list = []
        # Flow events arrive as SERVER-PUSHED frames (RpcClient.
        # subscribe_changes): the node streams its change feed to us and
        # _on_pushed accumulates it; gather() only drains the transport.
        # The subscription id is sticky, so a reconnect resumes from the
        # last pushed cursor without loss.
        self._subscription_id: bytes | None = None
        self._subscribed_at = 0.0
        # Transactions are immutable and content-addressed: fetch each hash
        # over RPC once, ever, instead of ~MAX_TX round trips per poll.
        self._tx_cache: dict = {}
        # tx id (short hex) -> producing flow run ids; seeded once from the
        # RPC snapshot, then maintained from pushed tx_recorded events.
        self._provenance: dict = {}
        self._provenance_seeded = False
        self._provenance_gaps = 0

    def _on_pushed(self, events: tuple, cursor: int) -> None:
        self._events.extend(events)
        self._cursor = cursor
        del self._events[:-self.MAX_EVENTS]
        # Provenance is maintained INCREMENTALLY from the pushed
        # ("tx_recorded", run_id, tx_id) events: the tx_mappings log is
        # append-only and unbounded, so re-polling the full snapshot every
        # refresh would grow without limit (one snapshot seeds the view;
        # push keeps it current; a detected push gap triggers re-seed).
        for ev in events:
            if ev and ev[0] == "tx_recorded":
                self._add_provenance(bytes(ev[1]), bytes(ev[2]))

    def _add_provenance(self, run_id: bytes, tx_id: bytes) -> None:
        runs = self._provenance.setdefault(tx_id.hex()[:16], [])
        short = run_id.hex()[:8]
        if short not in runs:
            runs.append(short)
        while len(self._provenance) > 4 * self.MAX_TX:  # bound the view
            self._provenance.pop(next(iter(self._provenance)))

    def _ensure_subscribed(self) -> None:
        import time as _time

        now = _time.monotonic()
        if now - self._subscribed_at < self.RESUBSCRIBE_S:
            return
        self._subscription_id = self.rpc.subscribe_changes(
            self._on_pushed, subscription_id=self._subscription_id,
            cursor=self._cursor)
        self._subscribed_at = now

    MAX_VAULT_PAGES = 64  # dashboard view bound: 64 pages × 256 states

    def _gather_vault(self, rpc) -> tuple:
        """The unconsumed set via keyset-paginated vault_page calls —
        bounded frames instead of one vault_snapshot that grows with the
        ledger (and a page cap: a dashboard never needs a million rows)."""
        states: list = []
        cursor = (None, 0)
        for _ in range(self.MAX_VAULT_PAGES):
            page, cursor = rpc.call(
                "vault_page", cursor[0], cursor[1], 256)
            states.extend(page)
            if cursor is None:
                break
        return tuple(states)

    def gather(self) -> dict:
        rpc = self.rpc
        self._ensure_subscribed()
        identity = rpc.call("node_identity")
        network = rpc.call("network_map_snapshot")
        vault = self._gather_vault(rpc)
        balances = rpc.call("vault_balances")
        in_flight = rpc.call("state_machines_snapshot")
        metrics = rpc.call("node_metrics")
        rpc.poll_push()  # drain any pushed frames not seen during calls

        # Flow→tx provenance join (reference: the explorer's transaction
        # view joins flows to transactions through StateMachineRecorded
        # TransactionMappingStorage): one full RPC snapshot seeds the
        # view, then the pushed ("tx_recorded", ...) events keep it
        # current (_on_pushed); a detected push gap re-seeds so evicted
        # events cannot leave the join silently stale.
        gaps = sum(rpc.push_gaps.values())
        if not self._provenance_seeded or gaps != self._provenance_gaps:
            for m in rpc.call("state_machine_recorded_transaction_mapping"):
                self._add_provenance(m.run_id, m.tx_id.bytes)
            self._provenance_seeded = True
            self._provenance_gaps = gaps

        transactions = []
        seen = set()
        for sref in vault:
            ref = getattr(sref, "ref", None)
            txhash = getattr(ref, "txhash", None)
            if txhash is None or txhash in seen:
                continue
            seen.add(txhash)
            stx = self._tx_cache.get(txhash)
            if stx is None:  # never cache a miss: the tx may land later
                stx = rpc.call("verified_transaction", txhash)
                if stx is not None:
                    self._tx_cache[txhash] = stx
            if stx is not None:
                transactions.append(stx)
            if len(transactions) >= self.MAX_TX:
                break
        # Bound the cache to hashes still referenced by the vault (the full
        # snapshot, not just the prefix visited before the MAX_TX break).
        if len(self._tx_cache) > 4 * self.MAX_TX:
            live = {getattr(getattr(s, "ref", None), "txhash", None)
                    for s in vault}
            self._tx_cache = {h: s for h, s in self._tx_cache.items()
                              if h in live}

        return {
            "identity": render_value(identity),
            "network": render_value(network),
            "balances": {str(c): int(q) for c, q in balances.items()},
            "vault": render_value(vault),
            "transactions": render_value(transactions),
            "tx_provenance": dict(self._provenance),
            "flows_in_flight": render_value(in_flight),
            "flow_events": render_value(self._events),
            "metrics": render_value(metrics),
        }


_PAGE = """<!DOCTYPE html>
<html><head><title>corda_tpu explorer</title><style>
 body { font-family: system-ui, sans-serif; margin: 2em; color: #222; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; margin-top: 1.4em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #ccc; padding: 4px 10px; font-size: 0.85em;
          text-align: left; vertical-align: top; }
 pre { background: #f6f6f6; padding: 8px; font-size: 0.8em;
       max-height: 22em; overflow: auto; }
 .muted { color: #777; }
</style></head><body>
<h1>corda_tpu explorer <span class="muted" id="who"></span></h1>
<h2>Cash balances</h2><table id="balances"></table>
<h2>Network</h2><table id="network"></table>
<h2>Flows in flight</h2><pre id="flows"></pre>
<h2>Recent flow events</h2><pre id="events"></pre>
<h2>Vault (unconsumed states)</h2><pre id="vault"></pre>
<h2>Recent transactions</h2><pre id="txs"></pre>
<h2>Transaction provenance <span class="muted">(tx id &rarr; producing flow
run ids)</span></h2><table id="provenance"></table>
<h2>Node metrics</h2><table id="metrics"></table>
<script>
function rows(el, pairs) {
  // Ledger data (party names, currency codes) is attacker-influenced:
  // build DOM nodes so it can never execute as HTML.
  el.replaceChildren(...pairs.map(p => {
    const tr = document.createElement("tr");
    const th = document.createElement("th");
    const td = document.createElement("td");
    th.textContent = String(p[0]);
    td.textContent = String(p[1]);
    tr.append(th, td);
    return tr;
  }));
}
async function refresh() {
  const r = await fetch("/api/dashboard");
  if (!r.ok) return;
  const d = await r.json();
  document.getElementById("who").textContent = "— " + d.identity;
  rows(document.getElementById("balances"), Object.entries(d.balances));
  rows(document.getElementById("network"),
       d.network.map(n => [n.legal_identity ?? JSON.stringify(n),
                           JSON.stringify(n.address)]));
  rows(document.getElementById("metrics"), Object.entries(d.metrics));
  document.getElementById("flows").textContent =
      JSON.stringify(d.flows_in_flight, null, 1);
  document.getElementById("events").textContent =
      JSON.stringify(d.flow_events.slice(-40), null, 1);
  document.getElementById("vault").textContent =
      JSON.stringify(d.vault, null, 1);
  document.getElementById("txs").textContent =
      JSON.stringify(d.transactions, null, 1);
  rows(document.getElementById("provenance"),
       Object.entries(d.tx_provenance).map(p => [p[0], p[1].join(", ")]));
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class ExplorerServer:
    """HTTP shell around ExplorerModel (the Main.kt/TornadoFX equivalent)."""

    def __init__(self, rpc: RpcClient, host: str = "127.0.0.1",
                 port: int = 0):
        self.model = ExplorerModel(rpc)
        model = self.model
        lock = threading.Lock()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                try:
                    if self.path == "/":
                        body, ctype = _PAGE.encode(), "text/html"
                    elif self.path == "/api/dashboard":
                        with lock:  # one RPC conversation at a time
                            body = json.dumps(model.gather()).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # pragma: no cover - network races
                    try:
                        self.send_error(500, str(e)[:200])
                    # lint: allow(no-silent-except) demo HTTP tooling: the client already vanished mid-error-reply; nothing to count or degrade
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class DemoTraffic:
    """Random-but-valid cash activity against an in-process node — the
    reference explorer's simulated-node mode (explorer Main.kt `-S` flag +
    client/mock EventGenerator): issues and moves drawn from the generator
    monad keep the dashboard alive without a real network."""

    def __init__(self, node, period: float = 0.7, seed: int = 42):
        import random

        from ..finance.cash import Cash
        from ..testing.generators import (
            ExitEvent, IssueEvent, MoveEvent, cash_event_generator)

        self.node = node
        self.period = period
        self._stop = threading.Event()
        self._rng = random.Random(seed)
        keys = node.services.key_management_service
        owners = [node.identity.owning_key] + [
            keys.fresh_key().public.composite for _ in range(3)]

        def issued() -> int:
            # O(#currencies) aggregate instead of a per-tick vault scan.
            return sum(node.services.vault_service.balances().values())

        self._gen = cash_event_generator(owners, issued)
        self._cash = Cash
        self._issue_cls = IssueEvent
        self._move_cls = MoveEvent
        self._exit_cls = ExitEvent
        self._nonce = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self._tick()
            # lint: allow(no-silent-except) demo traffic generator: a failed tick is retried next period; this never runs on a production path
            except Exception:
                pass  # demo traffic is best-effort

    def _tick(self) -> None:
        from ..finance import CashState
        from ..transactions.builder import TransactionBuilder

        node = self.node
        event = self._gen.generate(self._rng)
        if isinstance(event, self._issue_cls):
            self._nonce += 1
            builder = self._cash.generate_issue(
                event.amount, node.identity.ref(bytes([self._nonce % 256])),
                event.owner, node.identity, nonce=self._nonce)
            builder.sign_with(node.key)
            node.services.record_transactions([builder.to_signed_transaction()])
        elif isinstance(event, (self._move_cls, self._exit_cls)):
            builder = TransactionBuilder(notary=node.identity)
            if isinstance(event, self._move_cls):
                # Indexed soft-locked selection instead of a vault scan.
                states = node.services.vault_service.select_coins(
                    str(event.amount.token), event.amount.quantity,
                    holder=b"explorer-demo")
                if not states:
                    return
                signers = self._cash.generate_spend(
                    builder, event.amount, event.new_owner, states)
            else:
                states = node.services.vault_service.unconsumed_states(
                    CashState)
                if not states:
                    return
                # Exit burns an exact issued token: pick one and clamp.
                from ..finance import Amount

                token = states[0].state.data.amount.token
                avail = sum(s.state.data.amount.quantity for s in states
                            if s.state.data.amount.token == token)
                qty = min(event.amount.quantity, avail)
                signers = self._cash.generate_exit(
                    builder, Amount(qty, token), states)
            keys = node.services.key_management_service
            for key in signers:
                for pub in key.keys:
                    kp = keys.keys.get(pub)
                    if kp is not None:
                        builder.sign_with(kp)
                        break
            node.services.record_transactions(
                [builder.to_signed_transaction(
                    check_sufficient_signatures=False)])

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)


def _run_demo(listen: int):
    """An in-process node + generated traffic + dashboard (Main.kt -S)."""
    import tempfile
    from pathlib import Path

    from ..node.config import NodeConfig
    from ..node.node import Node

    tmp = Path(tempfile.mkdtemp(prefix="corda-tpu-explorer-demo-"))
    node = Node(NodeConfig(
        name="DemoBank", base_dir=tmp / "DemoBank",
        network_map=tmp / "netmap.json",
        rpc_users=({"username": "demo", "password": "demo",
                    "permissions": ["ALL"]},))).start()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            node.run_once(timeout=0.02)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    traffic = DemoTraffic(node)
    rpc = RpcClient(node.messaging.my_address, "demo", "demo")
    server = ExplorerServer(rpc, port=listen)

    def cleanup():
        import shutil

        traffic.stop()
        server.stop()
        rpc.close()
        stop.set()
        pumper.join(timeout=2)  # never tear the node down under run_once
        node.stop()
        shutil.rmtree(tmp, ignore_errors=True)

    return server, cleanup


def main(argv=None) -> None:
    from ..node.messaging.tcp import TcpAddress

    parser = argparse.ArgumentParser(
        description="Web explorer for a running corda_tpu node")
    parser.add_argument("node", nargs="?",
                        help="node RPC address, host:port")
    parser.add_argument("user", nargs="?")
    parser.add_argument("password", nargs="?")
    parser.add_argument("--listen", type=int, default=8880,
                        help="dashboard port (default 8880)")
    parser.add_argument("--demo", action="store_true",
                        help="spin up an in-process node with generated "
                             "cash traffic (the reference explorer's "
                             "simulation mode)")
    args = parser.parse_args(argv)
    if args.demo:
        server, cleanup = _run_demo(args.listen)
    elif args.node and args.user and args.password:
        host, _, port = args.node.partition(":")
        rpc = RpcClient(TcpAddress(host, int(port)), args.user, args.password)
        server = ExplorerServer(rpc, port=args.listen)

        def cleanup():
            server.stop()
            rpc.close()
    else:
        parser.error("either --demo or node/user/password are required")
    print(f"explorer on http://{server.address[0]}:{server.address[1]}/")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cleanup()


if __name__ == "__main__":
    main()
