"""Offline store verifier + repairer for a node directory (boot fsck).

Walks every sqlite store under a node dir (``*.db``) and verifies the
CRC32C integrity frames written by ``node/services/integrity.py`` over the
raft log, flow checkpoints, and ledger rows. Exit status is the contract:

  0  every checked row verified (legacy NULL-crc rows count as clean)
  1  at least one corrupt row was found (or remains after --repair)

``--repair`` applies the same row-level actions the online planes use:

  * legacy rows (NULL crc) are backfilled with a freshly computed frame;
  * a corrupt checkpoint moves to the ``quarantine`` table (the flow is
    declared failed at next boot, replay is never poisoned);
  * a corrupt raft-log row truncates the log suffix from that index when
    it is beyond the applied prefix (the member rejoins as a lagging
    follower and re-replicates), or compacts the applied prefix behind
    the snapshot marker when the effects are already durable in
    committed_states — the exact decision tree of
    ``RaftMember._heal_corrupt_entry``, applied cold;
  * corrupt committed/reserved ledger rows are REPORTED only — a spent
    input must never be un-spent by a repair tool; re-replication or the
    shard audit is the recovery path.

Usage:
  python -m corda_tpu.tools.fsck <node-dir> [--json] [--repair]

``fsck_paths()`` is the importable form the loadtest harnesses call as a
post-run gate (every surviving node's store must verify clean after a
chaos soak).
"""

from __future__ import annotations

import argparse
import json
import sqlite3
import sys
import time
from pathlib import Path

from ..node.services import integrity as _integrity
from ..obs import trace as _obs

__all__ = ["fsck_db", "fsck_paths", "main"]


def _heal_raft_log(conn, corrupt_keys: list) -> dict:
    """Cold-store version of RaftMember._heal_corrupt_entry: truncate an
    unapplied corrupt suffix, compact an applied corrupt prefix."""
    raw = conn.execute(
        "SELECT value FROM settings WHERE key = 'raft_last_applied'"
    ).fetchone()
    last_applied = int(raw[0]) if raw else 0
    actions = {"truncated_from": None, "compacted_upto": None}
    # corrupt_keys are raft_log idx values (see integrity._SCAN_SQL).
    bad = sorted(int(k) for k in corrupt_keys)
    applied_bad = [i for i in bad if i <= last_applied]
    suffix_bad = [i for i in bad if i > last_applied]
    if applied_bad:
        # Effects are durable in committed_states: drop the applied prefix
        # behind the snapshot marker, ONE transaction (raft.maybe_compact
        # invariant — a crash between DELETE and marker rebases indices).
        upto = last_applied
        row = conn.execute(
            "SELECT term FROM raft_log WHERE idx = ?", (upto,)).fetchone()
        term = int(row[0]) if row else 0
        if term == 0:
            raw = conn.execute(
                "SELECT value FROM settings "
                "WHERE key = 'raft_snapshot_term'").fetchone()
            term = int(raw[0]) if raw else 0
        conn.execute("DELETE FROM raft_log WHERE idx <= ?", (upto,))
        for key, value in (("raft_snapshot_index", str(upto)),
                           ("raft_snapshot_term", str(term))):
            conn.execute(
                "INSERT OR REPLACE INTO settings (key, value) VALUES (?, ?)",
                (key, value))
        actions["compacted_upto"] = upto
    if suffix_bad:
        frm = suffix_bad[0]
        conn.execute("DELETE FROM raft_log WHERE idx >= ?", (frm,))
        actions["truncated_from"] = frm
    conn.commit()
    return actions


def fsck_db(path: str | Path, *, repair: bool = False) -> dict:
    """Verify (and optionally repair) ONE sqlite store. Returns a report
    dict; report["clean"] is the gate verdict."""
    t0 = time.monotonic()
    conn = sqlite3.connect(str(path), timeout=5.0)
    try:
        # A pre-durability store has no crc columns yet: apply the same
        # idempotent in-place upgrade the node does at open (rows become
        # legacy NULL-crc rows, which verify clean and backfill under
        # --repair). No-op on an already-upgraded store.
        _integrity.ensure_integrity_schema(conn)
        conn.commit()
        tables = {}
        total_corrupt = 0
        healed = {}
        for table in _integrity.INTEGRITY_TABLES:
            res = _integrity.scan_table(conn, table, repair=repair)
            tables[table] = res
            total_corrupt += res["corrupt"]
            if repair and table == "raft_log" and res["corrupt_keys"]:
                healed["raft_log"] = _heal_raft_log(
                    conn, res["corrupt_keys"])
        # Checkpoint quarantines count as repaired, not still-corrupt: the
        # damage is contained and boot proceeds. Raft heals likewise. A
        # corrupt LEDGER row is never auto-repaired and keeps the store
        # dirty — that demands re-replication, not a local rewrite.
        unrepaired = total_corrupt
        if repair:
            unrepaired = (tables["committed_states"]["corrupt"]
                          + tables["reserved_states"]["corrupt"])
        return {
            "path": str(path),
            "clean": (unrepaired == 0 if repair else total_corrupt == 0),
            "corrupt": total_corrupt,
            "scanned": sum(t["scanned"] for t in tables.values()),
            "legacy": sum(t["legacy"] for t in tables.values()),
            "backfilled": sum(t["backfilled"] for t in tables.values()),
            "repaired": healed if repair else None,
            "tables": tables,
            "elapsed_s": round(time.monotonic() - t0, 6),
        }
    finally:
        conn.close()


def fsck_paths(path: str | Path, *, repair: bool = False) -> dict:
    """Verify every ``*.db`` store under a node dir (or one file). The
    harness gate: report["clean"] over all stores."""
    path = Path(path)
    dbs = [path] if path.is_file() else sorted(path.glob("**/*.db"))
    t0 = _obs.now()
    reports = [fsck_db(db, repair=repair) for db in dbs]
    if _obs.ACTIVE is not None:
        _obs.record("scrub", t0, _obs.now(),
                    attrs={"stores": len(reports), "tool": "fsck"})
    return {
        "path": str(path),
        "stores": len(reports),
        "clean": all(r["clean"] for r in reports),
        "corrupt": sum(r["corrupt"] for r in reports),
        "scanned": sum(r["scanned"] for r in reports),
        "backfilled": sum(r["backfilled"] for r in reports),
        "reports": reports,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m corda_tpu.tools.fsck",
        description="verify (and repair) a node dir's integrity frames")
    ap.add_argument("path", help="node base dir or a single .db file")
    ap.add_argument("--json", action="store_true",
                    help="one-line JSON report on stdout")
    ap.add_argument("--repair", action="store_true",
                    help="backfill legacy frames, quarantine corrupt "
                         "checkpoints, truncate/compact corrupt raft rows")
    args = ap.parse_args(argv)
    report = fsck_paths(args.path, repair=args.repair)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        for r in report["reports"]:
            verdict = "clean" if r["clean"] else "CORRUPT"
            print(f"{r['path']}: {verdict} "
                  f"(scanned={r['scanned']} corrupt={r['corrupt']} "
                  f"legacy={r['legacy']} backfilled={r['backfilled']})")
        print(f"{report['stores']} store(s): "
              + ("clean" if report["clean"] else "CORRUPT"))
    return 0 if report["clean"] else 1


if __name__ == "__main__":  # pragma: no cover - CLI shim
    sys.exit(main())
