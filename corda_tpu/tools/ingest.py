"""Vectorized ingest plane: columnar tx build/sign and multi-tx frames.

Round-15 mirror of the verify plane's columnar design, pointed the other
way: instead of one Python loop iteration per transaction (build a
TransactionBuilder, sign each key with a per-call `fast_ed25519.sign`,
serialize, send one frame), the ingest path batches each per-item cost
into one columnar pass over the whole chunk:

  * **build** — construct every issue/move builder for the chunk first
    (plain object graph work, no crypto);
  * **sign** — collect every (seed, wire-id) job across the chunk into
    two contiguous n*32-byte buffers and sign them in ONE GIL-released
    native call (crypto/batch_sign.py over `_cverify.c` sign_many),
    byte-identical to the per-tx `TransactionBuilder.sign_with` loop;
  * **serialize** — one codec pass per chunk packing N SignedTransactions
    into a single length-prefixed multi-tx frame (`pack_frame`) for
    shared-corpus handoff to replay workers, so worker processes never
    rebuild or re-sign anything.

The multi-tx frame is all-or-nothing: `unpack_frame` re-validates magic,
counts and exact length consumption and raises DeserializationError on
any junk or truncation — a damaged corpus blob loudly rejects, it never
partially applies.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass

from ..serialization.codec import DeserializationError, deserialize, serialize

# -- multi-tx frame ---------------------------------------------------------

FRAME_MAGIC = b"CTI1"  # corda_tpu ingest frame, version 1
_U32 = struct.Struct("<I")
MAX_FRAME_ENTRIES = 1 << 22  # oversize-frame guard: reject before allocating


def pack_frame(payloads) -> bytes:
    """N serialized payloads -> one multi-tx frame: magic, u32 count, then
    u32-length-prefixed entries. One buffer, one write, one read."""
    parts = [FRAME_MAGIC, _U32.pack(len(payloads))]
    for p in payloads:
        b = bytes(p)
        parts.append(_U32.pack(len(b)))
        parts.append(b)
    return b"".join(parts)


def unpack_frame(blob: bytes) -> list[bytes]:
    """Inverse of pack_frame, loud on damage: bad magic, an oversize
    count, a truncated entry or trailing junk all raise
    DeserializationError. Returns every payload or none — a partially
    valid frame never partially applies."""
    blob = bytes(blob)
    if len(blob) < 8 or blob[:4] != FRAME_MAGIC:
        raise DeserializationError(
            "not an ingest multi-tx frame (bad magic)")
    (count,) = _U32.unpack_from(blob, 4)
    if count > MAX_FRAME_ENTRIES:
        raise DeserializationError(
            f"ingest frame claims {count} entries "
            f"(max {MAX_FRAME_ENTRIES}) — oversize frame rejected")
    out: list[bytes] = []
    off = 8
    for i in range(count):
        if off + 4 > len(blob):
            raise DeserializationError(
                f"ingest frame truncated in entry {i} length "
                f"(offset {off} of {len(blob)})")
        (ln,) = _U32.unpack_from(blob, off)
        off += 4
        if off + ln > len(blob):
            raise DeserializationError(
                f"ingest frame truncated in entry {i} body "
                f"(need {ln} bytes at offset {off} of {len(blob)})")
        out.append(blob[off:off + ln])
        off += ln
    if off != len(blob):
        raise DeserializationError(
            f"ingest frame carries {len(blob) - off} trailing junk bytes")
    return out


# -- columnar corpus build --------------------------------------------------


@dataclass
class IngestStats:
    """Client-plane throughput attribution for one prepared corpus."""

    n_tx: int = 0
    sigs_signed: int = 0
    build_s: float = 0.0  # builder/object-graph construction (incl. wire)
    sign_s: float = 0.0  # columnar batch sign + attach
    serialize_s: float = 0.0  # codec pass packing the multi-tx frame(s)
    prepare_s: float = 0.0  # whole prepare wall (build + sign + record)
    cpu_s: float = 0.0  # process CPU consumed by prepare

    @property
    def tx_built_per_s(self) -> float:
        return round(self.n_tx / self.prepare_s, 1) if self.prepare_s else 0.0

    @property
    def sigs_signed_per_s(self) -> float:
        return round(self.sigs_signed / self.sign_s, 1) if self.sign_s \
            else 0.0

    @property
    def serialize_ms(self) -> float:
        return round(1e3 * self.serialize_s, 3)

    def stamp(self) -> dict:
        return {"n_tx": self.n_tx, "sigs_signed": self.sigs_signed,
                "build_s": round(self.build_s, 4),
                "sign_s": round(self.sign_s, 4),
                "serialize_ms": self.serialize_ms,
                "prepare_s": round(self.prepare_s, 4),
                "cpu_s": round(self.cpu_s, 4),
                "tx_built_per_s": self.tx_built_per_s,
                "sigs_signed_per_s": self.sigs_signed_per_s}


def build_chunk_columnar(firehose, start: int, count: int,
                         stats: IngestStats) -> list:
    """Columnar replacement for the firehose's per-tx prepare loop: build
    `count` corpus entries (each an issue-or-two + a width-signed move)
    in three batch phases — build every builder, ONE columnar sign over
    every (key, wire-id) job in the chunk, then one record_transactions
    call for every issuance. Output entries `(stx, route, cross)` are
    byte-identical to the retired `_build_one` loop (parity-tested):
    deterministic RFC 8032 signing over identical wire bytes.

    `firehose` is the loadgen._Firehose engine (duck-typed: uses its
    flow/keys/issuer/owners/notary/directory and cross bookkeeping).
    """
    from ..contracts.structures import Command
    from ..crypto.batch_sign import sign_builders
    from ..testing.dummies import (
        DummyCreate,
        DummyMove,
        DummyMultiOwnerState,
    )
    from ..transactions.builder import TransactionBuilder

    t0 = time.perf_counter()
    cpu0 = time.process_time()
    fh = firehose
    issuer_cmd = (fh.issuer.public.composite,)

    def issue_builder(marker: int):
        b = TransactionBuilder(notary=fh.notary)
        b.add_output_state(DummyMultiOwnerState(marker, fh.owners))
        b.add_command(Command(DummyCreate(), issuer_cmd))
        return b

    # Phase 1: BUILD. Object-graph construction only — the cross-shard
    # retry needs each issue's wire id (shard_of hashes the out-ref), which
    # the unsigned wire already carries; nothing here signs.
    issues: list = []  # builders, one record_transactions batch later
    entries: list = []  # (move_builder, route_ref, cross)
    for i in range(start, start + count):
        cross = bool(fh._cross_every) and i % fh._cross_every == 0
        first = issue_builder(i * 1_000_003)
        issues.append(first)
        refs = [first._wire_cached().out_ref(0)]
        if cross:
            fh.cross_requested += 1
            for attempt in range(1, 17):
                second = issue_builder(i * 1_000_003 + attempt)
                ref2 = second._wire_cached().out_ref(0)
                if fh.directory is None:
                    break
                from ..node.services.sharding import shard_of

                cnt = fh.directory[0]
                if shard_of(ref2.ref, cnt) != shard_of(refs[0].ref, cnt):
                    break  # spans two groups (expected ~n/(n-1) tries)
            issues.append(second)
            refs.append(ref2)
        move = TransactionBuilder(notary=fh.notary)
        for ref in refs:
            move.add_input_state(ref)
        move.add_command(Command(DummyMove(), fh.owners))
        move.add_output_state(DummyMultiOwnerState(i, fh.owners))
        entries.append((move, refs[0], cross))
    stats.build_s += time.perf_counter() - t0

    # Phase 2: SIGN. One columnar batch over issue jobs (1 sig each) and
    # move jobs (width sigs each) — the GIL-released native hot loop.
    t1 = time.perf_counter()
    builders = issues + [mv for mv, _, _ in entries]
    keysets = [(fh.issuer,)] * len(issues) + [fh.keys] * len(entries)
    signed = sign_builders(builders, keysets)
    fh.sigs_signed += signed
    stats.sigs_signed += signed
    stats.sign_s += time.perf_counter() - t1

    # Phase 3: RECORD + ASSEMBLE. Issue provenance lands in one
    # record_transactions call (one storage batch instead of `count`).
    issue_stxs = [b.to_signed_transaction() for b in issues]
    fh.flow.record_transactions(issue_stxs)
    out = []
    for move, route_ref, cross in entries:
        stx = move.to_signed_transaction(check_sufficient_signatures=False)
        out.append((stx, fh._route(route_ref), cross))
    stats.n_tx += count
    stats.prepare_s += time.perf_counter() - t0
    stats.cpu_s += time.process_time() - cpu0
    return out


def serialize_corpus(stxs, stats: "IngestStats | None" = None) -> bytes:
    """One codec pass: N SignedTransactions -> one multi-tx frame. Used
    for the pre-serialized corpus handoff to replay worker processes."""
    t0 = time.perf_counter()
    frame = pack_frame([serialize(stx).bytes for stx in stxs])
    if stats is not None:
        stats.serialize_s += time.perf_counter() - t0
    return frame


def deserialize_corpus(blob: bytes) -> list:
    """Inverse of serialize_corpus: the whole corpus or a loud reject."""
    return [deserialize(p) for p in unpack_frame(blob)]
