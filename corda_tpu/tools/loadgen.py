"""In-node load generation: the firehose lives WHERE the flows live.

Capability match for the reference's loadtest generate/execute loop
(reference: tools/loadtest/src/main/kotlin/net/corda/loadtest/LoadTest.kt:
39-144 — generation happens against remote nodes, execution runs ON them)
re-shaped for the multi-process driver: instead of the coordinating process
round-robin-pumping every node under one GIL (the round-2 harness artifact),
each client NODE PROCESS runs a FirehoseFlow that generates, signs and
notarises its own transaction stream in-process. The coordinator only makes
two RPC calls per client: start the firehose, fetch the result summary.

Workload shape (NotaryDemo firehose widened to the fan-out-verify case,
BASELINE config 4): every move transaction is owned by `width` distinct keys
and carries `width` signatures, so one notarisation round-trip pushes `width`
signature checks through the client's verify pump (and the validating
notary's, if configured) — tens of signatures per flow, the VERDICT round-2
prescription for feeding the TPU through the framework instead of beside it.

Admission control is the open-loop/closed-loop seam (VERDICT round-2 item 2):

  * closed-loop (`inflight=K`): keep K notarisations in flight — measures
    capacity;
  * open-loop (`rate_tx_s=λ`): start flows on a fixed-rate schedule
    regardless of completions — measures latency at an offered load, giving
    p50 ≠ p99 tail behaviour that the start-all-then-pump shape cannot.

The flow itself suspends exactly ONCE (on a ServiceRequest): the per-tx
machinery runs in the poll callable the node's run loop drives each round,
so the firehose's own checkpoint stays O(1) while its children (ordinary
NotaryClientFlow instances) checkpoint normally.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..crypto.keys import KeyPair
from ..flows.api import FlowLogic, register_flow
from ..flows.notary import NotaryClientFlow
from ..serialization.codec import register
from .ingest import IngestStats, build_chunk_columnar


@register
@dataclass(frozen=True)
class FirehoseResult:
    """Summary returned to the RPC caller."""

    requested: int
    committed: int
    rejected: int
    duration_s: float
    tx_per_sec: float
    p50_ms: float
    p90_ms: float
    p99_ms: float
    width: int
    sigs_signed: int
    # Sharded-notary mix accounting: how many of the requested transactions
    # were generated with inputs spanning two shards, and how many of those
    # committed (the exactly-once audit needs both sides of the ratio).
    cross_requested: int = 0
    cross_committed: int = 0
    # QoS lane this firehose ran on ("" = unlabelled) and how many of its
    # rejections were admission-control sheds (OverloadedError) — the SLO
    # sweep separates shed load from genuine conflicts with these.
    lane: str = ""
    shed: int = 0
    # Ingest (round-15) client-plane attribution: columnar prepare
    # throughput and the process CPU this firehose consumed. Defaulted so
    # older drivers deserialize newer results (ClientReply precedent).
    tx_built_per_s: float = 0.0
    sigs_signed_per_s: float = 0.0
    serialize_ms: float = 0.0
    prepare_s: float = 0.0
    cpu_s: float = 0.0


class _Firehose:
    """The per-round engine driven by poll(); lives only in memory (the
    owning flow re-creates it from scratch if restored — idempotent for a
    load TOOL: a restart restarts the measurement, it does not double-spend
    because every generated state is fresh)."""

    BURST_CAP = 512  # max flow starts admitted per scheduling round
    # Transactions built+signed per prepare round. Columnar prepare
    # (ingest.build_chunk_columnar) amortizes signing into ONE native
    # batch per chunk, so bigger chunks are cheaper per tx — 256 keeps a
    # round under ~100 ms while the node still services its run loop.
    PREPARE_CHUNK = 256

    def __init__(self, flow: "FirehoseFlow"):
        self.flow = flow
        hub = flow.service_hub
        self.smm = flow.state_machine.manager
        self.notary = self._find_notary(hub)
        # Throwaway signer set: `width` owner keys sign every move; one
        # issuer key signs issues (the contract does not require issue
        # signatures from owners, and this keeps signing cost ~width+1/tx).
        self.keys = [KeyPair.generate() for _ in range(flow.width)]
        self.owners = tuple(k.public.composite for k in self.keys)
        self.issuer = KeyPair.generate()
        # PREPARE phase: the corpus is built and signed BEFORE the timer
        # starts (NotaryDemo semantics — issuance/signing is workload setup;
        # the measured quantity is the notarisation pipeline). Chunked so
        # the node keeps servicing its run loop while preparing.
        self.corpus: list = []  # (stx, via_party_or_None, is_cross)
        self.started = 0
        self.done = 0
        self.committed = 0
        self.rejected = 0
        self.shed = 0
        self.cross_requested = 0
        self.cross_committed = 0
        self.sigs_signed = 0
        self.latencies: list[float] = []
        self.ingest = IngestStats()  # columnar prepare attribution
        self.t0: float | None = None  # set when the measured phase begins
        self._cpu0 = 0.0  # process CPU mark at measured-phase start
        # Sharded topology (if any) from the netmap: routes each move to
        # its owning group's first member so single-shard traffic takes the
        # fast path (without this every request lands on one arbitrary
        # member and most commits cross groups — shard scaling would
        # measure the coordinator, not the shards).
        from ..flows.notary import _shard_directory

        self.directory = _shard_directory(flow)
        # Every Nth corpus transaction spans two shards (0 = none). With no
        # shard directory the "cross" txs still carry two inputs — the
        # same tx shape through an unsharded notary.
        frac = getattr(flow, "cross_frac", 0.0)
        self._cross_every = round(1.0 / frac) if frac > 0.0 else 0

    @staticmethod
    def _find_notary(hub):
        notary = hub.network_map_cache.get_any_notary()
        if notary is None:
            raise RuntimeError("no notary advertised in the network map")
        return notary

    def _route(self, state_and_ref):
        """Member Party of the shard group owning a StateAndRef's ref
        (None when the notary is unsharded)."""
        return self._route_ref(state_and_ref.ref)

    def _route_ref(self, ref):
        """Same routing from a bare StateRef (replay workers only carry
        the deserialized wire, not StateAndRefs)."""
        if self.directory is None:
            return None
        from ..node.services.sharding import shard_of

        count, groups = self.directory
        members = groups.get(shard_of(ref, count))
        return members[0] if members else None

    def _prepare_round(self) -> bool:
        """One prepare round; True once the corpus is complete. The base
        engine builds columnar (ingest.build_chunk_columnar replaced the
        retired per-tx `_build_one` loop: byte-identical output, one
        batched sign + one record_transactions per chunk)."""
        if len(self.corpus) < self.flow.n_tx:
            k = min(self.PREPARE_CHUNK, self.flow.n_tx - len(self.corpus))
            self.corpus.extend(
                build_chunk_columnar(self, len(self.corpus), k, self.ingest))
            return False  # the clock starts on a LATER round
        return True

    def _admit_quota(self) -> int:
        """How many new flows this round may start."""
        remaining = self.flow.n_tx - self.started
        if remaining <= 0:
            return 0
        if self.flow.rate_tx_s > 0.0:
            # Open loop: the schedule says `rate*elapsed` flows should have
            # started by now — start the shortfall, completions be damned.
            elapsed = time.perf_counter() - self.t0
            due = int(self.flow.rate_tx_s * elapsed) - self.started
            return max(0, min(remaining, due, self.BURST_CAP))
        in_flight = self.started - self.done
        return max(0, min(remaining, self.flow.inflight - in_flight,
                          self.BURST_CAP))

    def poll(self):
        if not self._prepare_round():
            return None  # still preparing; the clock has not started
        if self.t0 is None:
            self.t0 = time.perf_counter()
            self._cpu0 = time.process_time()
        from ..qos import context as _qos

        lane = getattr(self.flow, "lane", "")
        plane = _qos.ACTIVE
        for _ in range(self._admit_quota()):
            stx, via, cross = self.corpus[self.started]
            self.started += 1
            submitted = time.perf_counter()
            # Lane-labelled load: each tx gets a fresh QosContext stamped
            # admitted-now (interactive derives its deadline from slo_ms),
            # so the whole QoS plane sees this firehose's class. Unlabelled
            # (lane="" or plane disarmed) starts exactly as before.
            qctx = (plane.new_context(
                        lane, getattr(self.flow, "slo_ms", 0.0) or None)
                    if plane is not None and lane else None)
            handle = self.smm.add(NotaryClientFlow(stx, via=via), qos=qctx)

            def on_done(future, t=submitted, cross=cross):
                self.done += 1
                self.latencies.append(time.perf_counter() - t)
                exc = future.exception()
                if exc is None:
                    self.committed += 1
                    if cross:
                        self.cross_committed += 1
                else:
                    self.rejected += 1
                    from ..flows.notary import OverloadedError

                    if isinstance(getattr(exc, "error", None),
                                  OverloadedError):
                        self.shed += 1

            handle.result.add_done_callback(on_done)
        if self.done < self.flow.n_tx:
            return None
        duration = time.perf_counter() - self.t0
        lat = sorted(self.latencies) or [0.0]

        def pct(p: float) -> float:
            return round(1e3 * lat[min(len(lat) - 1, int(len(lat) * p))], 2)

        return FirehoseResult(
            requested=self.flow.n_tx,
            committed=self.committed,
            rejected=self.rejected,
            duration_s=round(duration, 3),
            tx_per_sec=round(self.flow.n_tx / duration, 1),
            p50_ms=pct(0.50),
            p90_ms=pct(0.90),
            p99_ms=pct(0.99),
            width=self.flow.width,
            sigs_signed=self.sigs_signed,
            cross_requested=self.cross_requested,
            cross_committed=self.cross_committed,
            lane=getattr(self.flow, "lane", ""),
            shed=self.shed,
            tx_built_per_s=self.ingest.tx_built_per_s,
            sigs_signed_per_s=self.ingest.sigs_signed_per_s,
            serialize_ms=self.ingest.serialize_ms,
            prepare_s=round(self.ingest.prepare_s, 4),
            # Total process CPU attributable to this firehose: columnar
            # prepare plus the measured drive phase.
            cpu_s=round(self.ingest.cpu_s
                        + (time.process_time() - self._cpu0), 4),
        )


@register_flow(name="loadgen.FirehoseFlow")
class FirehoseFlow(FlowLogic):
    """RPC-startable firehose: start_flow("loadgen.FirehoseFlow", n_tx,
    width, inflight, rate_tx_s, cross_frac) → FirehoseResult.

    cross_frac > 0 makes every round(1/cross_frac)-th move consume inputs
    owned by two different notary shards (the 2PC path); single-shard moves
    route to their owning group via the netmap shard directory."""

    def __init__(self, n_tx: int, width: int = 1, inflight: int = 64,
                 rate_tx_s: float = 0.0, cross_frac: float = 0.0,
                 lane: str = "", slo_ms: float = 0.0):
        self.n_tx = n_tx
        self.width = width
        self.inflight = inflight
        self.rate_tx_s = rate_tx_s
        self.cross_frac = cross_frac
        # QoS lane for every generated tx ("interactive"/"bulk"; "" starts
        # them unlabelled) and the interactive SLO override in ms (0 uses
        # the armed plane's default).
        self.lane = lane
        self.slo_ms = slo_ms

    def call(self):
        result = yield self.service_request(lambda: _Firehose(self).poll)
        return result


@register
@dataclass(frozen=True)
class IngestBuildResult:
    """Summary of a pre-built, pre-serialized corpus (IngestBuildFlow)."""

    path: str
    n_tx: int
    sigs_signed: int
    bytes_written: int
    tx_built_per_s: float
    sigs_signed_per_s: float
    serialize_ms: float
    prepare_s: float
    cpu_s: float
    cross_requested: int = 0


class _IngestBuild(_Firehose):
    """Build + sign + serialize a corpus to a multi-tx frame on disk,
    WITHOUT driving any load: the multiprocess firehose's prepare stage.
    Replay workers map disjoint slices of the written frame, so they
    never rebuild or re-sign anything."""

    def poll(self):
        if not self._prepare_round():
            return None
        from .ingest import serialize_corpus

        frame = serialize_corpus(
            [stx for stx, _, _ in self.corpus], self.ingest)
        tmp = self.flow.out_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
        os.replace(tmp, self.flow.out_path)  # atomic: never a torn corpus
        st = self.ingest
        return IngestBuildResult(
            path=self.flow.out_path,
            n_tx=st.n_tx,
            sigs_signed=st.sigs_signed,
            bytes_written=len(frame),
            tx_built_per_s=st.tx_built_per_s,
            sigs_signed_per_s=st.sigs_signed_per_s,
            serialize_ms=st.serialize_ms,
            prepare_s=round(st.prepare_s, 4),
            cpu_s=round(st.cpu_s, 4),
            cross_requested=self.cross_requested,
        )


@register_flow(name="loadgen.IngestBuildFlow")
class IngestBuildFlow(FlowLogic):
    """RPC-startable corpus builder: columnar build+sign n_tx moves, pack
    them into ONE multi-tx frame at out_path, return IngestBuildResult.
    Issue provenance is recorded on THIS node only — replay slices must
    be driven at a non-validating notary (uniqueness does not need the
    back chain; validation would)."""

    def __init__(self, out_path: str, n_tx: int, width: int = 1,
                 cross_frac: float = 0.0):
        self.out_path = out_path
        self.n_tx = n_tx
        self.width = width
        self.cross_frac = cross_frac
        self.inflight = 0  # unused: this flow never starts children
        self.rate_tx_s = 0.0
        self.lane = ""
        self.slo_ms = 0.0

    def call(self):
        result = yield self.service_request(lambda: _IngestBuild(self).poll)
        return result


class _Replay(_Firehose):
    """Firehose engine whose prepare phase LOADS a pre-serialized corpus
    slice instead of building one — the worker half of the multiprocess
    firehose. Deserialization is chunked so the node's run loop keeps
    servicing transport while the slice loads; route and cross flags are
    re-derived from each wire's inputs (first-input shard owner; >1 input
    = cross), so the frame needs no sidecar metadata."""

    LOAD_CHUNK = 512  # wires deserialized per prepare round

    def __init__(self, flow):
        super().__init__(flow)
        self._payloads: list | None = None

    def _prepare_round(self) -> bool:
        from ..serialization.codec import deserialize
        from .ingest import unpack_frame

        t0 = time.perf_counter()
        cpu0 = time.process_time()
        if self._payloads is None:
            with open(self.flow.corpus_path, "rb") as f:
                blob = f.read()
            payloads = unpack_frame(blob)  # loud on any damage
            lo = self.flow.offset
            hi = lo + self.flow.n_tx
            if hi > len(payloads):
                raise RuntimeError(
                    f"corpus slice [{lo}:{hi}) exceeds frame of "
                    f"{len(payloads)} entries")
            self._payloads = payloads[lo:hi]
        done = len(self.corpus)
        if done < self.flow.n_tx:
            for p in self._payloads[done:done + self.LOAD_CHUNK]:
                stx = deserialize(p)
                inputs = stx.tx.inputs
                cross = len(inputs) > 1
                if cross:
                    self.cross_requested += 1
                if not self.flow.width:
                    self.flow.width = len(stx.sigs)
                self.corpus.append(
                    (stx, self._route_ref(inputs[0]), cross))
            self.ingest.n_tx = len(self.corpus)
            self.ingest.prepare_s += time.perf_counter() - t0
            self.ingest.cpu_s += time.process_time() - cpu0
            return False
        return True


@register_flow(name="loadgen.FirehoseReplayFlow")
class FirehoseReplayFlow(FlowLogic):
    """RPC-startable replay firehose: drive a disjoint [offset, offset+
    n_tx) slice of a pre-built multi-tx corpus frame through the notary.
    Same admission control and result shape as FirehoseFlow; the corpus
    was signed once by IngestBuildFlow, so the worker's own CPU is almost
    entirely submission — the shape that lets W processes offer W× the
    single-process rate."""

    def __init__(self, corpus_path: str, offset: int, n_tx: int,
                 inflight: int = 64, rate_tx_s: float = 0.0,
                 lane: str = "", slo_ms: float = 0.0):
        self.corpus_path = corpus_path
        self.offset = offset
        self.n_tx = n_tx
        self.inflight = inflight
        self.rate_tx_s = rate_tx_s
        self.lane = lane
        self.slo_ms = slo_ms
        self.width = 0  # observed from the first deserialized wire
        self.cross_frac = 0.0  # cross mix is baked into the corpus

    def call(self):
        result = yield self.service_request(lambda: _Replay(self).poll)
        return result


def install(node) -> None:
    """Cordapp hook — importing the module registers the flows; nothing
    else to wire (the firehose starts children directly on the node's
    SMM)."""
