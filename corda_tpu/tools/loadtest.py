"""Loadtest + notary-demo driver: firehose a notary (cluster) and disrupt it.

Capability match for the reference's load/chaos tooling and demo driver
(reference: tools/loadtest/src/main/kotlin/net/corda/loadtest/LoadTest.kt:
39-144 — generate/execute/gather loop with convergence checking;
Disruption.kt:18-60 — node kill/restart fault injection; and
samples/raft-notary-demo/src/main/kotlin/net/corda/notarydemo/NotaryDemo.kt:
14-29 — the issue+move firehose through NotaryFlow.Client).

Everything runs in one process over real TCP sockets + sqlite nodes (the
reference drives remote JVMs over SSH; the in-process form keeps the same
measurement semantics — real transport, real persistence, real consensus —
without a cluster). Disruptions kill a node mid-run and rebuild it purely
from its base_dir.

CLI:
  python -m corda_tpu.tools.loadtest --tx 200 --notary simple
  python -m corda_tpu.tools.loadtest --tx 200 --notary raft --disrupt kill-follower
"""

from __future__ import annotations

import argparse
import json
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path

from ..flows.notary import NotaryClientFlow
from ..node.config import BatchConfig, NodeConfig
from ..node.node import Node
from ..testing.dummies import DummyContract


@dataclass
class LoadTestResult:
    tx_requested: int
    tx_committed: int
    tx_rejected: int
    duration_s: float
    tx_per_sec: float
    p50_ms: float
    p99_ms: float
    sigs_verified: int
    verify_batches: int
    disruptions: list = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


def _make_node(base: Path, name: str, **kw) -> Node:
    return Node(NodeConfig(
        name=name, base_dir=base / name, network_map=base / "netmap.json",
        **kw)).start()


def _rebuild(config: NodeConfig) -> Node:
    return Node(NodeConfig(
        name=config.name, base_dir=config.base_dir, notary=config.notary,
        raft_cluster=config.raft_cluster, network_map=config.network_map,
        batch=config.batch, verifier=config.verifier)).start()


def run_loadtest(
    n_tx: int = 100,
    notary: str = "simple",  # simple | validating | raft
    cluster_size: int = 3,
    disrupt: str | None = None,  # kill-notary | kill-follower | None
    verifier: str = "cpu",
    batch: BatchConfig | None = None,
    base_dir: str | None = None,
    max_seconds: float = 120.0,
) -> LoadTestResult:
    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-load-"))
    batch = batch or BatchConfig()
    notaries: list[Node] = []
    disruptions: list[str] = []

    if notary == "raft":
        cluster = tuple(f"Raft{i}" for i in range(cluster_size))
        for name in cluster:
            notaries.append(_make_node(
                base, name, notary="raft-simple", raft_cluster=cluster,
                verifier=verifier, batch=batch))
    else:
        notaries.append(_make_node(base, "Notary", notary=notary,
                                   verifier=verifier, batch=batch))
    client = _make_node(base, "LoadClient", verifier=verifier, batch=batch)
    nodes = notaries + [client]
    for n in nodes:
        n.refresh_netmap()

    if notary == "raft":  # wait for a leader before the firehose
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            for n in nodes:
                n.run_once(timeout=0.005)
            if any(n.raft_member.role == "leader" for n in notaries):
                break
        else:
            raise RuntimeError("raft cluster failed to elect")

    target = notaries[0].identity
    # The firehose workload: issue (local) + move (notarised) per tx —
    # the raft-notary-demo shape (NotaryDemoApi issue+move).
    stxs = []
    for i in range(n_tx):
        builder = DummyContract.generate_initial(
            client.identity.ref(i.to_bytes(4, "big")), i, target)
        builder.sign_with(client.key)
        issue_stx = builder.to_signed_transaction()
        client.services.record_transactions([issue_stx])
        move = DummyContract.move(issue_stx.tx.out_ref(0),
                                  client.identity.owning_key)
        move.sign_with(client.key)
        stxs.append(move.to_signed_transaction(
            check_sufficient_signatures=False))

    t0 = time.perf_counter()
    done_at: list[float] = []
    handles = []
    for stx in stxs:
        h = client.start_flow(NotaryClientFlow(stx))
        h.result.add_done_callback(
            lambda _f: done_at.append(time.perf_counter() - t0))
        handles.append(h)

    disrupted = False
    deadline = time.monotonic() + max_seconds
    while time.monotonic() < deadline:
        for n in nodes:
            n.run_once(timeout=0.002)
        completed = sum(1 for h in handles if h.result.done)
        if not disrupted and disrupt and completed >= n_tx // 3:
            disrupted = True
            if disrupt == "kill-notary" or notary != "raft":
                victim = notaries[0]
            else:  # kill-follower: keep quorum; don't kill the leader
                victim = next(
                    (n for n in notaries if n.raft_member.role != "leader"),
                    notaries[-1])
            cfg = victim.config
            victim.stop()
            nodes.remove(victim)
            notaries.remove(victim)
            disruptions.append(f"killed {cfg.name} after {completed} tx")
            reborn = _rebuild(cfg)
            notaries.append(reborn)
            nodes.append(reborn)
            for n in nodes:
                n.refresh_netmap()
            disruptions.append(f"rebuilt {cfg.name} from disk")
        if completed == n_tx:
            break
    duration = time.perf_counter() - t0

    committed = rejected = 0
    for h in handles:
        if not h.result.done:
            continue
        if h.result.exception() is None:
            committed += 1
        else:
            rejected += 1
    lat = sorted(done_at) or [0.0]
    metrics = client.smm.metrics
    notary_metrics = [n.smm.metrics for n in notaries]
    result = LoadTestResult(
        tx_requested=n_tx,
        tx_committed=committed,
        tx_rejected=rejected,
        duration_s=round(duration, 3),
        tx_per_sec=round(len(done_at) / duration, 1) if done_at else 0.0,
        p50_ms=round(1e3 * lat[len(lat) // 2], 2),
        p99_ms=round(1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2),
        sigs_verified=metrics["verify_sigs"]
        + sum(m["verify_sigs"] for m in notary_metrics),
        verify_batches=metrics["verify_batches"]
        + sum(m["verify_batches"] for m in notary_metrics),
        disruptions=disruptions,
    )
    for n in nodes:
        n.stop()
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tx", type=int, default=100)
    ap.add_argument("--notary", choices=("simple", "validating", "raft"),
                    default="simple")
    ap.add_argument("--cluster-size", type=int, default=3)
    ap.add_argument("--disrupt", choices=("kill-notary", "kill-follower"),
                    default=None)
    ap.add_argument("--verifier", choices=("cpu", "jax", "jax-shadow"),
                    default="cpu")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-sigs", type=int, default=4096)
    args = ap.parse_args(argv)
    result = run_loadtest(
        n_tx=args.tx, notary=args.notary, cluster_size=args.cluster_size,
        disrupt=args.disrupt, verifier=args.verifier,
        batch=BatchConfig(max_sigs=args.max_sigs,
                          max_wait_ms=args.max_wait_ms))
    print(result.to_json())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
