"""Loadtest + notary-demo driver: firehose a notary (cluster) and disrupt it.

Capability match for the reference's load/chaos tooling and demo driver
(reference: tools/loadtest/src/main/kotlin/net/corda/loadtest/LoadTest.kt:
39-144 — generate/execute/gather loop with convergence checking;
Disruption.kt:18-60 — node kill/restart fault injection; and
samples/raft-notary-demo/src/main/kotlin/net/corda/notarydemo/NotaryDemo.kt:
14-29 — the issue+move firehose through NotaryFlow.Client).

Everything runs in one process over real TCP sockets + sqlite nodes (the
reference drives remote JVMs over SSH; the in-process form keeps the same
measurement semantics — real transport, real persistence, real consensus —
without a cluster). Disruptions kill a node mid-run and rebuild it purely
from its base_dir.

CLI:
  python -m corda_tpu.tools.loadtest --tx 200 --notary simple
  python -m corda_tpu.tools.loadtest --tx 200 --notary raft --disrupt kill-follower
  python -m corda_tpu.tools.loadtest --tx 200 --notary raft --processes \
      --trace /tmp/notary.trace.json   # open in ui.perfetto.dev
"""

from __future__ import annotations

import argparse
import json
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path

from ..flows.api import FlowLogic, register_flow
from ..flows.notary import NotaryClientFlow
from ..node.config import BatchConfig, NodeConfig
from ..node.node import Node
from ..obs import doctor as _doctor
from ..obs import telemetry as _tm
from ..testing.dummies import DummyContract
# Codec registration for the coordinator process: FirehoseResult rides the
# flow_result RPC reply and must be decodable HERE, not just in the client
# node processes that run the flow.
from . import loadgen as _loadgen  # noqa: F401


@dataclass
class LoadTestResult:
    tx_requested: int
    tx_committed: int
    tx_rejected: int
    duration_s: float
    tx_per_sec: float
    p50_ms: float
    p99_ms: float
    sigs_verified: int
    verify_batches: int
    disruptions: list = field(default_factory=list)
    trace_file: str | None = None  # merged Chrome/Perfetto JSON (--trace)

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


def _make_node(base: Path, name: str, **kw) -> Node:
    return Node(NodeConfig(
        name=name, base_dir=base / name, network_map=base / "netmap.json",
        **kw)).start()


def _rebuild(config: NodeConfig) -> Node:
    return Node(NodeConfig(
        name=config.name, base_dir=config.base_dir, notary=config.notary,
        raft_cluster=config.raft_cluster, network_map=config.network_map,
        batch=config.batch, verifier=config.verifier,
        notary_shards=config.notary_shards,
        # A rebuilt member must rejoin with the SAME commit-plane policy
        # (pipeline/apply_queue_depth/...) — silently reverting to defaults
        # would let a chaos run flip a serial A/B leg pipelined mid-kill.
        raft=config.raft)).start()


def _collect_trace_snapshots(rpcs) -> list[dict]:
    """Gather every node process's span buffer over RPC (trace_snapshot is
    the RPC twin of GET /api/trace). A dead node costs its spans, not the
    run — the merged trace is honestly partial."""
    snapshots: list[dict] = []
    for rpc in rpcs:
        try:
            snap = rpc.call("trace_snapshot")
        except Exception:
            continue
        if snap and snap.get("spans"):
            snapshots.append(snap)
    return snapshots


def _write_trace(path: str, snapshots: list[dict]) -> str | None:
    if not snapshots:
        return None
    from ..obs.collect import write_chrome_trace

    write_chrome_trace(path, snapshots)
    return path


def _inproc_trace_snapshot() -> list[dict]:
    """Snapshot the process-global recorder for in-process harnesses, where
    every node shares one ring (spans self-attribute via their node field)."""
    from ..obs import trace as _obs

    rec = _obs.ACTIVE
    if rec is None:
        return []
    return [{"node": rec.node_name or "inproc", "armed": True,
             "spans": rec.snapshot(), "stats": rec.stats()}]


def run_loadtest(
    n_tx: int = 100,
    notary: str = "simple",  # simple | validating | raft
    cluster_size: int = 3,
    disrupt: str | None = None,  # kill-notary | kill-follower | None
    verifier: str = "cpu",
    batch: BatchConfig | None = None,
    base_dir: str | None = None,
    max_seconds: float = 120.0,
    trace: str | None = None,  # write a merged Chrome/Perfetto trace here
) -> LoadTestResult:
    from ..obs import trace as _obs

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-load-"))
    batch = batch or BatchConfig()
    notaries: list[Node] = []
    disruptions: list[str] = []
    armed_here = None
    if trace and _obs.ACTIVE is None:
        # In-process run: every node shares the process-global recorder.
        armed_here = _obs.arm("inproc")

    if notary == "raft":
        cluster = tuple(f"Raft{i}" for i in range(cluster_size))
        for name in cluster:
            notaries.append(_make_node(
                base, name, notary="raft-simple", raft_cluster=cluster,
                verifier=verifier, batch=batch))
    else:
        notaries.append(_make_node(base, "Notary", notary=notary,
                                   verifier=verifier, batch=batch))
    client = _make_node(base, "LoadClient", verifier=verifier, batch=batch)
    nodes = notaries + [client]
    for n in nodes:
        n.refresh_netmap()

    if notary == "raft":  # wait for a leader before the firehose
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            for n in nodes:
                n.run_once(timeout=0.005)
            if any(n.raft_member.role == "leader" for n in notaries):
                break
        else:
            raise RuntimeError("raft cluster failed to elect")

    target = notaries[0].identity
    # The firehose workload: issue (local) + move (notarised) per tx —
    # the raft-notary-demo shape (NotaryDemoApi issue+move).
    stxs = []
    for i in range(n_tx):
        builder = DummyContract.generate_initial(
            client.identity.ref(i.to_bytes(4, "big")), i, target)
        builder.sign_with(client.key)
        issue_stx = builder.to_signed_transaction()
        client.services.record_transactions([issue_stx])
        move = DummyContract.move(issue_stx.tx.out_ref(0),
                                  client.identity.owning_key)
        move.sign_with(client.key)
        stxs.append(move.to_signed_transaction(
            check_sufficient_signatures=False))

    t0 = time.perf_counter()
    done_at: list[float] = []
    handles = []
    for stx in stxs:
        h = client.start_flow(NotaryClientFlow(stx))
        h.result.add_done_callback(
            lambda _f: done_at.append(time.perf_counter() - t0))
        handles.append(h)

    disrupted = False
    deadline = time.monotonic() + max_seconds
    while time.monotonic() < deadline:
        for n in nodes:
            n.run_once(timeout=0.002)
        completed = sum(1 for h in handles if h.result.done)
        if not disrupted and disrupt and completed >= n_tx // 3:
            disrupted = True
            if disrupt == "kill-notary" or notary != "raft":
                victim = notaries[0]
            else:  # kill-follower: keep quorum; don't kill the leader
                victim = next(
                    (n for n in notaries if n.raft_member.role != "leader"),
                    notaries[-1])
            cfg = victim.config
            victim.stop()
            nodes.remove(victim)
            notaries.remove(victim)
            disruptions.append(f"killed {cfg.name} after {completed} tx")
            reborn = _rebuild(cfg)
            notaries.append(reborn)
            nodes.append(reborn)
            for n in nodes:
                n.refresh_netmap()
            disruptions.append(f"rebuilt {cfg.name} from disk")
        if completed == n_tx:
            break
    duration = time.perf_counter() - t0

    committed = rejected = 0
    for h in handles:
        if not h.result.done:
            continue
        if h.result.exception() is None:
            committed += 1
        else:
            rejected += 1
    lat = sorted(done_at) or [0.0]
    metrics = client.smm.metrics
    notary_metrics = [n.smm.metrics for n in notaries]
    result = LoadTestResult(
        tx_requested=n_tx,
        tx_committed=committed,
        tx_rejected=rejected,
        duration_s=round(duration, 3),
        tx_per_sec=round(len(done_at) / duration, 1) if done_at else 0.0,
        p50_ms=round(1e3 * lat[len(lat) // 2], 2),
        p99_ms=round(1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2),
        sigs_verified=metrics["verify_sigs"]
        + sum(m["verify_sigs"] for m in notary_metrics),
        verify_batches=metrics["verify_batches"]
        + sum(m["verify_batches"] for m in notary_metrics),
        disruptions=disruptions,
    )
    if trace:
        result.trace_file = _write_trace(trace, _inproc_trace_snapshot())
        if armed_here is not None:
            _obs.disarm()
    for n in nodes:
        n.stop()
    return result


@register_flow
class RetryingNotariseFlow(FlowLogic):
    """Chaos-harness client flow: notarise with the PRODUCT retry policy
    (deadline-bounded, exponential backoff, leader-hint redirects) so an
    availability window — a killed leader, an election — is ridden out
    instead of reported as a failure. The plain loadtest keeps calling
    NotaryClientFlow raw; this flow exists to measure recovery, not to
    mask unavailability."""

    def __init__(self, stx, deadline_s: float = 60.0):
        self.stx = stx
        self.deadline_s = deadline_s

    def call(self):
        from ..flows.notary import notarise_with_retry

        sig = yield from notarise_with_retry(
            self, self.stx, deadline_s=self.deadline_s)
        return sig


@dataclass
class ChaosResult:
    """One chaos loadtest run: outcome audit + measured recovery."""

    plan: str | None
    tx_requested: int
    tx_committed: int
    tx_rejected: int
    tx_unresolved: int  # flows that never completed (MUST be 0)
    exactly_once: bool  # committed==requested, none rejected/lost/doubled
    cluster_committed: int  # committed_states rows on the leader
    duration_s: float
    tx_per_sec: float
    p50_ms: float
    p99_ms: float
    faults_injected: dict = field(default_factory=dict)
    leader_kill_recovery_s: float | None = None
    disruptions: list = field(default_factory=list)
    trace_file: str | None = None  # merged Chrome/Perfetto JSON (--trace)
    # Sharded-notary runs: shard count, how many of the requested txs
    # consumed inputs on two shards, per-group committed rows, and live
    # reservation rows left after the drain (MUST be 0 — a leak means a
    # 2PC wedged inputs past its TTL backstop).
    shards: int = 0
    cross_requested: int = 0
    per_group_committed: list = field(default_factory=list)
    reserved_leaked: int | None = None
    # Durability plane: corruption detections summed over every member's
    # raft stamp (> 0 proves a disk.corrupt plan actually fired AND was
    # caught), and the post-run fsck gate verdict over every surviving
    # node's store (None = gate skipped, e.g. a node died un-stopped).
    integrity_errors: int = 0
    fsck_clean: bool | None = None

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


def run_chaos_loadtest(
    plan=None,  # FaultPlan | builtin name | path to a plan TOML | None
    n_tx: int = 60,
    cluster_size: int = 3,
    kill_leader: bool = False,
    verifier: str = "cpu",
    batch: BatchConfig | None = None,
    base_dir: str | None = None,
    max_seconds: float = 180.0,
    rate_tx_s: float = 0.0,  # >0: open-loop pacing, latency from schedule
    retry_deadline_s: float = 60.0,
    trace: str | None = None,  # write a merged Chrome/Perfetto trace here
    shards: int = 0,  # >0: that many Raft GROUPS of cluster_size members
    # each (sharded notary, services/sharding.py); kill_leader then kills
    # group 0's leader mid-burst
    cross_frac: float = 0.0,  # fraction of txs spending inputs on TWO
    # shards (the 2PC path); only meaningful with shards >= 2
    reserve_ttl_s: float = 15.0,
) -> ChaosResult:
    """Chaos mode: an in-process raft cluster + client over REAL TCP and
    sqlite, with a deterministic FaultPlan armed process-wide and/or the
    LEADER killed mid-burst and rebuilt from disk. Clients notarise through
    RetryingNotariseFlow (the product retry policy), so the run audits the
    end-to-end exactly-once contract: every tx committed exactly once, none
    lost, none rejected, no input double-spent — and measures recovery
    (first completion after the kill) plus tail latency under faults.

    In-process runs share ONE plan across client and members; `crash`
    actions would kill the whole harness — use process-level kill_leader
    (or the driver's env_extra arming) for crash faults."""
    from ..testing import faults

    plan_obj = None
    if plan is not None:
        if isinstance(plan, faults.FaultPlan):
            plan_obj = plan
        elif isinstance(plan, (str, Path)):
            text = None
            p = Path(plan)
            if p.suffix == ".toml" or p.exists():
                text = p.read_text(encoding="utf-8")
            if text is not None:
                plan_obj = faults.plan_from_toml(text)
            else:
                plan_obj = faults.builtin_plan(str(plan))
        else:
            raise TypeError(f"plan: expected FaultPlan/str/Path, got {plan!r}")

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-chaos-"))
    batch = batch or BatchConfig()
    disruptions: list[str] = []
    notaries: list[Node] = []
    group_nodes: list[list[Node]] = []
    shard_cfg = None
    if shards > 0:
        from ..node.config import ShardConfig

        groups = tuple(
            tuple(f"Shard{g}{chr(ord('A') + m)}" for m in range(cluster_size))
            for g in range(shards))
        shard_cfg = ShardConfig(count=shards, groups=groups,
                                reserve_ttl_s=reserve_ttl_s)
    cluster = tuple(f"Raft{i}" for i in range(cluster_size))
    from ..obs import trace as _obs

    armed_here = None
    if trace and _obs.ACTIVE is None:
        armed_here = _obs.arm("inproc")
    if plan_obj is not None:
        faults.arm(plan_obj)
    try:
        if shard_cfg is not None:
            for names in shard_cfg.groups:
                row = [_make_node(
                    base, name, notary="raft-simple", raft_cluster=names,
                    notary_shards=shard_cfg, verifier=verifier, batch=batch)
                    for name in names]
                group_nodes.append(row)
                notaries.extend(row)
        else:
            for name in cluster:
                notaries.append(_make_node(
                    base, name, notary="raft-simple", raft_cluster=cluster,
                    verifier=verifier, batch=batch))
            group_nodes = [list(notaries)]
        client = _make_node(base, "ChaosClient", verifier=verifier,
                            batch=batch)
        nodes = notaries + [client]
        for n in nodes:
            n.refresh_netmap()
        deadline = time.monotonic() + 20.0 + 10.0 * len(group_nodes)
        while time.monotonic() < deadline:
            for n in nodes:
                n.run_once(timeout=0.005)
            if all(any(n.raft_member.role == "leader" for n in row)
                   for row in group_nodes):
                break
        else:
            raise RuntimeError("raft cluster(s) failed to elect")

        if plan_obj is not None and plan_obj.partitions:
            # Auto-sided partition specs bind over the live cluster,
            # LEADER first: the builtins put the first n//2 identities on
            # side a, so the acting leader of group 0 lands in the
            # minority and the cut proves leader deposition, not just
            # follower lag. The client stays outside every cut.
            ordered = sorted(
                group_nodes[0],
                key=lambda n: n.raft_member.role != "leader")
            ordered += [n for row in group_nodes[1:] for n in row]
            plan_obj.bind_partition_nodes(
                [n.messaging.my_address for n in ordered])
            disruptions.append("partition sides bound (leader first)")

        target = notaries[0].identity
        # Mixed workload: every round(1/cross_frac)-th move consumes TWO
        # issued states owned by DIFFERENT shards (the 2PC path); the rest
        # are the plain single-input moves.
        from ..node.services.sharding import shard_of

        cross_every = round(1.0 / cross_frac) if cross_frac > 0.0 else 0
        cross_requested = 0
        stxs = []

        def _issue(i: int) -> object:
            builder = DummyContract.generate_initial(
                client.identity.ref((i % (1 << 30)).to_bytes(4, "big")),
                i, target)
            builder.sign_with(client.key)
            issue_stx = builder.to_signed_transaction()
            client.services.record_transactions([issue_stx])
            return issue_stx.tx.out_ref(0)

        for i in range(n_tx):
            priors = [_issue(i)]
            if cross_every and shards > 1 and i % cross_every == 0:
                cross_requested += 1
                for attempt in range(1, 17):
                    p2 = _issue(i + n_tx * attempt)
                    if (shard_of(p2.ref, shards)
                            != shard_of(priors[0].ref, shards)):
                        break
                priors.append(p2)
            move = DummyContract.move(priors, client.identity.owning_key)
            move.sign_with(client.key)
            stxs.append(move.to_signed_transaction(
                check_sufficient_signatures=False))

        t0 = time.perf_counter()
        completions: list[float] = []  # completion times since t0
        lat: list[float] = []  # per-tx latency (from schedule when paced)
        handles = []
        submitted = 0
        killed_at: float | None = None
        run_deadline = time.monotonic() + max_seconds
        while time.monotonic() < run_deadline:
            now = time.perf_counter() - t0
            while submitted < n_tx and (
                    rate_tx_s <= 0 or now >= submitted / rate_tx_s):
                sched = submitted / rate_tx_s if rate_tx_s > 0 else 0.0
                h = client.start_flow(RetryingNotariseFlow(
                    stxs[submitted], retry_deadline_s))

                def _done(_f, sched=sched):
                    t = time.perf_counter() - t0
                    completions.append(t)
                    lat.append(t - sched)

                h.result.add_done_callback(_done)
                handles.append(h)
                submitted += 1
                if rate_tx_s > 0:
                    now = time.perf_counter() - t0
            for n in nodes:
                n.run_once(timeout=0.002)
            completed = sum(1 for h in handles if h.result.done)
            if (kill_leader and killed_at is None
                    and completed >= max(1, n_tx // 3)):
                # Sharded: kill GROUP 0's leader (one shard degraded, the
                # others keep committing — the blast-radius story).
                victim = next(
                    (n for n in group_nodes[0]
                     if n.raft_member.role == "leader"), None)
                if victim is not None:
                    cfg = victim.config
                    victim.stop()
                    nodes.remove(victim)
                    notaries.remove(victim)
                    group_nodes[0].remove(victim)
                    killed_at = time.perf_counter() - t0
                    disruptions.append(
                        f"killed leader {cfg.name} after {completed} tx")
                    reborn = _rebuild(cfg)
                    notaries.append(reborn)
                    nodes.append(reborn)
                    group_nodes[0].append(reborn)
                    for n in nodes:
                        n.refresh_netmap()
                    disruptions.append(f"rebuilt {cfg.name} from disk")
            if submitted == n_tx and completed == n_tx:
                break
        duration = time.perf_counter() - t0

        committed = rejected = unresolved = 0
        for h in handles:
            if not h.result.done:
                unresolved += 1
            elif h.result.exception() is None:
                committed += 1
            else:
                rejected += 1
        unresolved += n_tx - submitted
        # Cluster-side audit, per Raft group: committed_states rows count
        # consumed REFS — single-input moves contribute 1, cross-shard
        # moves 2 (one on each owning group) — so across groups the rows
        # must total exactly n_tx + cross_requested. Fewer means lost
        # commits, more means a double-spend got through. Per group the
        # most-caught-up member is authoritative (followers may trail).
        per_group_committed = [
            max((n.uniqueness_provider.committed_count for n in row
                 if getattr(n, "uniqueness_provider", None) is not None),
                default=0)
            for row in group_nodes]
        cluster_committed = sum(per_group_committed)
        expected_rows = n_tx + cross_requested
        reserved_leaked = None
        if shards > 0:
            # Live holds after the drain: every member of every group must
            # show zero (a leaked reservation = a wedged input the TTL
            # failed to release).
            reserved_leaked = sum(
                min((n.raft_member.stamp()["reserved_states"]
                     for n in row), default=0)
                for row in group_nodes)
        recovery = None
        if killed_at is not None:
            after = [t for t in completions if t > killed_at]
            recovery = round(min(after) - killed_at, 3) if after else None
        # Durability audit: detections counted by the replicas themselves
        # (read BEFORE stop() — stamps need live members).
        integrity_errors = sum(
            n.raft_member.stamp()["integrity_errors"]
            for row in group_nodes for n in row
            if getattr(n, "raft_member", None) is not None)
        srt = sorted(lat) or [0.0]
        result = ChaosResult(
            plan=(getattr(plan, "name", None) or str(plan)
                  if not isinstance(plan, faults.FaultPlan) else "custom")
                 if plan is not None else None,
            tx_requested=n_tx,
            tx_committed=committed,
            tx_rejected=rejected,
            tx_unresolved=unresolved,
            exactly_once=(committed == n_tx and rejected == 0
                          and unresolved == 0
                          and cluster_committed == expected_rows
                          and not reserved_leaked),
            cluster_committed=cluster_committed,
            duration_s=round(duration, 3),
            tx_per_sec=round(committed / duration, 1) if duration else 0.0,
            p50_ms=round(1e3 * srt[len(srt) // 2], 2),
            p99_ms=round(1e3 * srt[min(len(srt) - 1,
                                       int(len(srt) * 0.99))], 2),
            faults_injected=(plan_obj.injected() if plan_obj is not None
                             else faults.injected()),
            leader_kill_recovery_s=recovery,
            disruptions=disruptions,
            shards=shards,
            cross_requested=cross_requested,
            per_group_committed=per_group_committed,
            reserved_leaked=reserved_leaked,
            integrity_errors=integrity_errors,
        )
        if trace:
            result.trace_file = _write_trace(trace, _inproc_trace_snapshot())
        for n in nodes:
            n.stop()
        # Post-run fsck gate: every surviving node's STORED bytes must
        # verify clean after the soak. Runs with faults disarmed (below the
        # finally would be too late for the report), so an injected
        # read-path bit-flip — which never touches disk — does not fail the
        # gate, while real on-disk damage (or a torn write) does.
        was_armed, faults.ACTIVE = faults.ACTIVE, None
        try:
            from .fsck import fsck_paths

            result.fsck_clean = fsck_paths(base)["clean"]
        finally:
            faults.ACTIVE = was_armed
        return result
    finally:
        if plan_obj is not None:
            faults.disarm()
        if armed_here is not None:
            _obs.disarm()


@dataclass
class PartitionResult:
    """One partition soak: cut -> hold -> heal, with the client history
    audited against the ledger (testing/history.py)."""

    plan: str
    prevote: bool
    isolate: str            # leader | follower (who the cut puts alone)
    cluster_size: int
    tx_requested: int
    tx_committed: int
    tx_rejected: int
    tx_unresolved: int
    duration_s: float
    cut_at_s: float
    healed_at_s: float | None
    # Heal -> first post-heal commit completion (the recovery observable
    # the bench gates on; None = nothing completed after the heal).
    recovery_s: float | None
    # Max member term delta across the soak: bounded with prevote on,
    # grows with every futile minority timeout with it off.
    term_before: int = 0
    term_after: int = 0
    max_term_inflation: int = 0
    # Ledger advance observed on the minority side WHILE the cut held
    # (MUST be 0 — a lone leader applying state is the split-brain bug).
    minority_commits_during_cut: int = 0
    # Summed member stamps (raft.py round-20 counters).
    elections_won: int = 0
    prevotes: int = 0
    prevote_rejections: int = 0
    checkquorum_stepdowns: int = 0
    leader_stepdowns: int = 0
    # Fault-engine counters: cut transitions + frames eaten by cuts.
    partition_cuts: int = 0
    partition_drops: int = 0
    # Auditor verdict (check_history) — the flat gate bit plus evidence.
    history_linearizable: bool = False
    history_events: int = 0
    lost_acks: int = 0
    double_spends: int = 0
    fail_conflicts: int = 0
    unresolved_ops: int = 0
    history: dict = field(default_factory=dict)
    disruptions: list = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


def run_partition_loadtest(
    plan=None,  # FaultPlan | builtin name | plan TOML path | None = held split
    n_tx: int = 60,
    cluster_size: int = 3,
    prevote: bool = True,
    isolate: str = "leader",  # who the auto-bound minority side holds
    precut_frac: float = 0.25,  # txs committed BEFORE the cut arms
    cut_hold_s: float = 6.0,  # wall-clock hold before the timed heal
    verifier: str = "cpu",
    batch: BatchConfig | None = None,
    base_dir: str | None = None,
    max_seconds: float = 150.0,
    retry_deadline_s: float = 45.0,
) -> PartitionResult:
    """Partition soak: an in-process raft cluster over real TCP commits a
    pre-cut tranche, then a deterministic network partition isolates the
    leader (or a follower), holds for ``cut_hold_s``, and heals. Every
    client invocation and outcome lands in a :class:`testing.history`
    History; after the drain the checker replays it against the union of
    every member's committed rows — acked-then-lost commits, cross-side
    double spends, lying rejections and ledger advance on the minority
    side all fail the run's ``history_linearizable`` bit.

    ``isolate="leader"`` proves the check-quorum story (a quorumless
    leader must stop answering); ``isolate="follower"`` proves the
    pre-vote story (a cut-off follower must not inflate the term and
    depose the healthy leader at heal) — run it with ``prevote`` on and
    off for the A/B the bench reports."""
    from ..node.config import RaftConfig
    from ..serialization.codec import deserialize
    from ..testing import faults
    from ..testing.history import History, check_history
    from ..flows.notary import (NotaryException, NotaryUnavailable,
                                OverloadedError, WrongShardEpoch)

    if isolate not in ("leader", "follower"):
        raise ValueError(f"isolate: expected leader|follower, got {isolate!r}")
    if plan is None:
        # Held symmetric split: active from the first post-arm frame,
        # lifted only by the timed heal below — the cut window is the
        # harness's wall clock, the cut itself stays event-deterministic.
        plan_obj = faults.FaultPlan(29, [], partitions=[
            faults.PartitionSpec("split")])
        plan_name = "split-hold"
    elif isinstance(plan, faults.FaultPlan):
        plan_obj, plan_name = plan, "custom"
    else:
        p = Path(str(plan))
        if p.suffix == ".toml" or p.exists():
            plan_obj = faults.plan_from_toml(p.read_text(encoding="utf-8"))
        else:
            plan_obj = faults.builtin_plan(str(plan))
        plan_name = str(plan)
    if not plan_obj.partitions:
        raise ValueError("partition soak needs a plan with [[partition]] "
                         "specs (see faults.builtin_plan('split-brain'))")

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-part-"))
    batch = batch or BatchConfig()
    raft_cfg = RaftConfig(prevote=prevote)
    disruptions: list[str] = []
    history = History()
    cluster = tuple(f"Raft{i}" for i in range(cluster_size))
    notaries = [_make_node(base, name, notary="raft-simple",
                           raft_cluster=cluster, verifier=verifier,
                           batch=batch, raft=raft_cfg)
                for name in cluster]
    client = _make_node(base, "PartitionClient", verifier=verifier,
                        batch=batch)
    nodes = notaries + [client]
    try:
        for n in nodes:
            n.refresh_netmap()
        deadline = time.monotonic() + 30.0
        leader = None
        while time.monotonic() < deadline:
            for n in nodes:
                n.run_once(timeout=0.005)
            leader = next((n for n in notaries
                           if n.raft_member.role == "leader"), None)
            if leader is not None:
                break
        if leader is None:
            raise RuntimeError("raft cluster failed to elect")

        target = notaries[0].identity
        stxs = []
        for i in range(n_tx):
            builder = DummyContract.generate_initial(
                client.identity.ref((i % (1 << 30)).to_bytes(4, "big")),
                i, target)
            builder.sign_with(client.key)
            issue_stx = builder.to_signed_transaction()
            client.services.record_transactions([issue_stx])
            prior = issue_stx.tx.out_ref(0)
            move = DummyContract.move(prior, client.identity.owning_key)
            move.sign_with(client.key)
            stxs.append((move.to_signed_transaction(
                check_sufficient_signatures=False), prior))

        t0 = time.perf_counter()
        completions: list[float] = []
        handles: list = []
        cut_at: float | None = None
        healed_at: float | None = None

        def _submit(i: int) -> None:
            stx, prior = stxs[i]
            history.record_invoke(
                "PartitionClient", f"tx{i}", str(stx.id),
                refs=(str(prior.ref),), t=time.perf_counter() - t0,
                during_cut=cut_at is not None and healed_at is None)
            h = client.start_flow(RetryingNotariseFlow(
                stx, retry_deadline_s))
            h.result.add_done_callback(
                lambda _f: completions.append(time.perf_counter() - t0))
            handles.append(h)

        # Phase A: the pre-cut tranche commits against the healthy
        # cluster (proves the baseline, seeds the ledger).
        precut = max(1, min(n_tx, int(round(n_tx * precut_frac))))
        for i in range(precut):
            _submit(i)
        phase_deadline = time.monotonic() + max_seconds / 3
        while time.monotonic() < phase_deadline:
            for n in nodes:
                n.run_once(timeout=0.002)
            if all(h.result.done for h in handles):
                break

        # Arm the cut with the ISOLATED node bound first (auto-sided
        # specs put the first n//2 identities on side a — the minority).
        isolated = leader if isolate == "leader" else next(
            n for n in notaries if n.raft_member.role != "leader")
        ordered = [isolated] + [n for n in notaries if n is not isolated]
        minority = ordered[:max(1, len(ordered) // 2)]
        plan_obj.bind_partition_nodes(
            [n.messaging.my_address for n in ordered])
        faults.arm(plan_obj)
        cut_at = time.perf_counter() - t0
        term_before = max(n.raft_member.term for n in notaries)
        minority_base = sum(
            n.uniqueness_provider.committed_count for n in minority)
        minority_commits = 0
        disruptions.append(
            f"cut armed at {cut_at:.2f}s isolating "
            f"{[n.config.name for n in minority]} ({isolate})")

        # Phase B: the rest of the workload rides through cut + heal.
        for i in range(precut, n_tx):
            _submit(i)
        run_deadline = time.monotonic() + max_seconds
        while time.monotonic() < run_deadline:
            for n in nodes:
                n.run_once(timeout=0.002)
            now = time.perf_counter() - t0
            if healed_at is None:
                # While the cut holds: the minority's ledger must not
                # advance (sampled every pump pass — one COUNT(*) per
                # minority member against a page-cached sqlite).
                minority_commits = max(minority_commits, sum(
                    n.uniqueness_provider.committed_count
                    for n in minority) - minority_base)
                if now >= cut_at + cut_hold_s:
                    faults.heal_partitions()
                    healed_at = now
                    disruptions.append(f"healed at {healed_at:.2f}s")
            elif all(h.result.done for h in handles):
                break
        duration = time.perf_counter() - t0

        committed = rejected = unresolved = 0
        for i, h in enumerate(handles):
            if not h.result.done:
                unresolved += 1
                kind = "timeout"
            elif h.result.exception() is None:
                committed += 1
                kind = "ok"
            else:
                exc = h.result.exception()
                # A retry-deadline exhaustion re-raises the last RETRYABLE
                # error (unavailable/shed/fence) — that decided NOTHING
                # about the tx, so the history records an ambiguous
                # timeout the checker resolves against the ledger. Only a
                # FINAL notary error (conflict, invalid) is a "fail".
                final = (isinstance(exc, NotaryException)
                         and not isinstance(exc.error, (
                             NotaryUnavailable, OverloadedError,
                             WrongShardEpoch)))
                rejected += 1
                kind = "fail" if final else "timeout"
            history.record_outcome("PartitionClient", f"tx{i}", kind,
                                   t=duration)

        recovery = None
        if healed_at is not None:
            after = [t for t in completions if t > healed_at]
            recovery = round(min(after) - healed_at, 3) if after else None

        # Ledger side of the audit: the union of every member's
        # committed rows (ref -> consuming tx), read while members live.
        consumed = []
        committed_tx_ids = set()
        for n in notaries:
            with n.db.lock:
                rows = n.db.conn.execute(
                    "SELECT state_ref, consuming FROM committed_states"
                ).fetchall()
            for ref_blob, consuming in rows:
                tx = deserialize(consuming)
                consumed.append((bytes(ref_blob).hex(), str(tx.id)))
                committed_tx_ids.add(str(tx.id))
        # History refs are str(StateRef) while ledger refs are serialized
        # blobs — the double-spend scan only needs ref keys CONSISTENT
        # across members, which the blob hex is.
        verdict = check_history(history, committed_tx_ids, consumed,
                                minority_commits=minority_commits)

        term_after = max(n.raft_member.term for n in notaries)
        stamps = [n.raft_member.stamp() for n in notaries]
        injected = plan_obj.injected()
        result = PartitionResult(
            plan=plan_name,
            prevote=prevote,
            isolate=isolate,
            cluster_size=cluster_size,
            tx_requested=n_tx,
            tx_committed=committed,
            tx_rejected=rejected,
            tx_unresolved=unresolved,
            duration_s=round(duration, 3),
            cut_at_s=round(cut_at, 3),
            healed_at_s=round(healed_at, 3) if healed_at is not None
            else None,
            recovery_s=recovery,
            term_before=term_before,
            term_after=term_after,
            max_term_inflation=term_after - term_before,
            minority_commits_during_cut=minority_commits,
            elections_won=sum(s["elections_won"] for s in stamps),
            prevotes=sum(s["prevotes"] for s in stamps),
            prevote_rejections=sum(s["prevote_rejections"]
                                   for s in stamps),
            checkquorum_stepdowns=sum(s["checkquorum_stepdowns"]
                                      for s in stamps),
            leader_stepdowns=sum(s["leader_stepdowns"] for s in stamps),
            partition_cuts=injected.get("transport.partition:cut", 0),
            partition_drops=injected.get("transport.partition:drop", 0),
            history_linearizable=verdict["history_linearizable"],
            history_events=verdict["events"],
            lost_acks=len(verdict["lost_acks"]),
            double_spends=len(verdict["double_spends"]),
            fail_conflicts=len(verdict["fail_conflicts"]),
            unresolved_ops=len(verdict["unresolved"]),
            history=verdict,
            disruptions=disruptions,
        )
        return result
    finally:
        faults.disarm()
        for n in nodes:
            try:
                n.stop()
            # lint: allow(no-silent-except) harness teardown: a node that dies mid-stop already produced its result; not a production verify/notarise path
            except Exception:
                pass


@dataclass
class ReshardResult:
    """One live-reshard run: the group count changes MID-LOAD and the
    audit proves nobody noticed except the tail. Windows split the per-tx
    latencies at the plan-publish and handoff-complete marks, so the p99
    blip is measured, not asserted."""

    plan: str | None
    epoch: int
    from_shards: int
    to_shards: int
    direction: str  # "split" | "merge"
    tx_requested: int
    tx_committed: int
    tx_rejected: int
    tx_unresolved: int  # flows that never completed (MUST be 0)
    exactly_once: bool  # committed==requested, ledger rows == expected
    cluster_committed: int
    per_group_committed: list
    reserved_leaked: int | None
    cross_requested: int
    wrong_epoch_bounces: int  # fence bounces served (client retry driver)
    handoff_frames: int       # InstallShardState frames acked
    reshard_started_s: float | None   # plan publish, since t0
    reshard_completed_s: float | None  # every member at the new epoch
    duration_s: float
    tx_per_sec: float
    p50_ms: float
    p99_ms: float
    p99_before_ms: float  # completions before the plan published
    p99_during_ms: float  # completions inside the transition window
    p99_after_ms: float   # completions after every member cut over
    faults_injected: dict = field(default_factory=dict)
    # Post-run fsck gate over every node's store (durability plane);
    # None = gate skipped.
    fsck_clean: bool | None = None

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


def run_reshard_loadtest(
    plan="reshard",  # FaultPlan | builtin name | plan TOML path | None
    n_tx: int = 240,
    shards: int = 2,
    to_shards: int = 4,
    cluster_size: int = 1,
    verifier: str = "cpu",
    batch: BatchConfig | None = None,
    base_dir: str | None = None,
    max_seconds: float = 240.0,
    rate_tx_s: float = 40.0,
    retry_deadline_s: float = 60.0,
    reserve_ttl_s: float = 15.0,
    cross_frac: float = 0.0,
    reshard_after_frac: float = 0.3,
    epoch: int = 1,
) -> ReshardResult:
    """Live shard split/merge under load (and, by default, under the
    lossy `reshard` chaos plan): boot max(shards, to_shards) Raft groups
    with count=shards (the extra groups are pending split targets), pace
    an open loop of moves through RetryingNotariseFlow, publish the
    reshard plan through the netmap once `reshard_after_frac` of the load
    is submitted, and keep driving while the source leaders seal, stream,
    and cut over. The run audits the same exactly-once contract as the
    chaos harness — every tx committed exactly once, ledger rows across
    groups total exactly the consumed refs, zero leaked reservations —
    plus the reshard-specific story: bounded WrongShardEpoch retries and
    a p99 blip confined to the transition window."""
    from ..testing import faults

    if to_shards != 2 * shards and shards != 2 * to_shards:
        raise ValueError(
            f"reshard must double or halve: {shards} -> {to_shards}")
    direction = "split" if to_shards > shards else "merge"
    plan_obj = None
    if plan is not None:
        if isinstance(plan, faults.FaultPlan):
            plan_obj = plan
        elif isinstance(plan, (str, Path)):
            p = Path(plan)
            if p.suffix == ".toml" or p.exists():
                plan_obj = faults.plan_from_toml(
                    p.read_text(encoding="utf-8"))
            else:
                plan_obj = faults.builtin_plan(str(plan))
        else:
            raise TypeError(f"plan: expected FaultPlan/str/Path, got {plan!r}")

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-reshard-"))
    batch = batch or BatchConfig()
    from ..node.config import ShardConfig
    from ..node.services.sharding import publish_reshard_plan, shard_of

    n_groups = max(shards, to_shards)
    groups = tuple(
        tuple(f"Shard{g}{chr(ord('A') + m)}" for m in range(cluster_size))
        for g in range(n_groups))
    shard_cfg = ShardConfig(count=shards, groups=groups,
                            reserve_ttl_s=reserve_ttl_s)
    notaries: list[Node] = []
    group_nodes: list[list[Node]] = []
    if plan_obj is not None:
        faults.arm(plan_obj)
    try:
        for names in shard_cfg.groups:
            row = [_make_node(
                base, name, notary="raft-simple", raft_cluster=names,
                notary_shards=shard_cfg, verifier=verifier, batch=batch)
                for name in names]
            group_nodes.append(row)
            notaries.extend(row)
        client = _make_node(base, "ReshardClient", verifier=verifier,
                            batch=batch)
        nodes = notaries + [client]
        for n in nodes:
            n.refresh_netmap()
        deadline = time.monotonic() + 20.0 + 10.0 * len(group_nodes)
        while time.monotonic() < deadline:
            for n in nodes:
                n.run_once(timeout=0.005)
            if all(any(n.raft_member.role == "leader" for n in row)
                   for row in group_nodes):
                break
        else:
            raise RuntimeError("raft group(s) failed to elect")

        target = notaries[0].identity
        cross_every = round(1.0 / cross_frac) if cross_frac > 0.0 else 0
        cross_requested = 0
        stxs = []

        def _issue(i: int) -> object:
            builder = DummyContract.generate_initial(
                client.identity.ref((i % (1 << 30)).to_bytes(4, "big")),
                i, target)
            builder.sign_with(client.key)
            issue_stx = builder.to_signed_transaction()
            client.services.record_transactions([issue_stx])
            return issue_stx.tx.out_ref(0)

        for i in range(n_tx):
            priors = [_issue(i)]
            if cross_every and shards > 1 and i % cross_every == 0:
                cross_requested += 1
                for attempt in range(1, 17):
                    p2 = _issue(i + n_tx * attempt)
                    if (shard_of(p2.ref, shards)
                            != shard_of(priors[0].ref, shards)):
                        break
                priors.append(p2)
            move = DummyContract.move(priors, client.identity.owning_key)
            move.sign_with(client.key)
            stxs.append(move.to_signed_transaction(
                check_sufficient_signatures=False))

        t0 = time.perf_counter()
        samples: list[tuple[float, float]] = []  # (completed_at, latency)
        handles = []
        submitted = 0
        started_at: float | None = None
        completed_at: float | None = None
        run_deadline = time.monotonic() + max_seconds
        while time.monotonic() < run_deadline:
            now = time.perf_counter() - t0
            while submitted < n_tx and (
                    rate_tx_s <= 0 or now >= submitted / rate_tx_s):
                sched = submitted / rate_tx_s if rate_tx_s > 0 else 0.0
                h = client.start_flow(RetryingNotariseFlow(
                    stxs[submitted], retry_deadline_s))

                def _done(_f, sched=sched):
                    t = time.perf_counter() - t0
                    samples.append((t, t - sched))

                h.result.add_done_callback(_done)
                handles.append(h)
                submitted += 1
                if rate_tx_s > 0:
                    now = time.perf_counter() - t0
            if started_at is None and submitted >= max(
                    1, int(n_tx * reshard_after_frac)):
                # Doubling (or halving) the group count MID-LOAD: the plan
                # rides the shared netmap; source-group leaders pick it up
                # on their next refresh cadence and start the handoff.
                publish_reshard_plan(base / "netmap.json", epoch,
                                     shards, to_shards,
                                     client.identity.owning_key)
                started_at = time.perf_counter() - t0
            for n in nodes:
                n.run_once(timeout=0.002)
                n.refresh_netmap_maybe(0.25)
            if (started_at is not None and completed_at is None
                    and all(getattr(n.uniqueness_provider, "epoch", 0)
                            >= epoch for n in notaries)):
                completed_at = time.perf_counter() - t0
            if (submitted == n_tx
                    and sum(1 for h in handles if h.result.done) == n_tx
                    and completed_at is not None):
                break
        duration = time.perf_counter() - t0

        committed = rejected = unresolved = 0
        for h in handles:
            if not h.result.done:
                unresolved += 1
            elif h.result.exception() is None:
                committed += 1
            else:
                rejected += 1
        unresolved += n_tx - submitted
        # Ledger-side audit at the NEW topology: activation purged every
        # moved row from its source group, so across groups the rows must
        # total exactly the consumed refs — fewer is a lost commit, more
        # is a double-count that survived the handoff.
        per_group_committed = [
            max((n.uniqueness_provider.committed_count for n in row
                 if getattr(n, "uniqueness_provider", None) is not None),
                default=0)
            for row in group_nodes]
        cluster_committed = sum(per_group_committed)
        expected_rows = n_tx + cross_requested
        reserved_leaked = sum(
            min((n.raft_member.stamp()["reserved_states"]
                 for n in row), default=0)
            for row in group_nodes)
        wrong_epoch = sum(
            n.uniqueness_provider.metrics.get("wrong_epoch", 0)
            for n in notaries
            if hasattr(n.uniqueness_provider, "metrics"))
        frames = sum(
            n.uniqueness_provider.metrics.get("handoff_frames", 0)
            for n in notaries
            if hasattr(n.uniqueness_provider, "metrics"))

        def _p99(window) -> float:
            srt = sorted(window)
            if not srt:
                return 0.0
            return round(1e3 * srt[min(len(srt) - 1,
                                       int(len(srt) * 0.99))], 2)

        lat = [l for _, l in samples] or [0.0]
        srt = sorted(lat)
        before = [l for t, l in samples
                  if started_at is not None and t < started_at]
        during = [l for t, l in samples
                  if started_at is not None and t >= started_at
                  and (completed_at is None or t < completed_at)]
        after = [l for t, l in samples
                 if completed_at is not None and t >= completed_at]
        result = ReshardResult(
            plan=(getattr(plan, "name", None) or str(plan)
                  if not isinstance(plan, faults.FaultPlan) else "custom")
                 if plan is not None else None,
            epoch=epoch,
            from_shards=shards,
            to_shards=to_shards,
            direction=direction,
            tx_requested=n_tx,
            tx_committed=committed,
            tx_rejected=rejected,
            tx_unresolved=unresolved,
            exactly_once=(committed == n_tx and rejected == 0
                          and unresolved == 0
                          and cluster_committed == expected_rows
                          and not reserved_leaked),
            cluster_committed=cluster_committed,
            per_group_committed=per_group_committed,
            reserved_leaked=reserved_leaked,
            cross_requested=cross_requested,
            wrong_epoch_bounces=wrong_epoch,
            handoff_frames=frames,
            reshard_started_s=(round(started_at, 3)
                               if started_at is not None else None),
            reshard_completed_s=(round(completed_at, 3)
                                 if completed_at is not None else None),
            duration_s=round(duration, 3),
            tx_per_sec=round(committed / duration, 1) if duration else 0.0,
            p50_ms=round(1e3 * srt[len(srt) // 2], 2),
            p99_ms=_p99(lat),
            p99_before_ms=_p99(before),
            p99_during_ms=_p99(during),
            p99_after_ms=_p99(after),
            faults_injected=(plan_obj.injected() if plan_obj is not None
                             else faults.injected()),
        )
        for n in nodes:
            n.stop()
        # Post-run fsck gate (durability plane): a reshard soak rewrites
        # whole ledgers across groups — every store must still verify.
        was_armed, faults.ACTIVE = faults.ACTIVE, None
        try:
            from .fsck import fsck_paths

            result.fsck_clean = fsck_paths(base)["clean"]
        finally:
            faults.ACTIVE = was_armed
        return result
    finally:
        if plan_obj is not None:
            faults.disarm()


@dataclass
class MultiProcessResult:
    """Aggregate over C client processes firehosing one notary (cluster)."""

    tx_requested: int
    tx_committed: int
    tx_rejected: int
    width: int
    clients: int
    duration_s: float  # max measured-phase duration across clients
    wall_s: float  # coordinator wall incl. prepare (the conservative bound)
    tx_per_sec: float
    sigs_verified: int  # across every node process, RPC metric deltas
    sigs_per_sec: float  # sigs_verified / duration_s — the north-star rate
    p50_ms: float
    p99_ms: float
    per_client: list = field(default_factory=list)
    disruptions: list = field(default_factory=list)
    # Self-describing stamps: which verifier/backend/device each notary
    # member actually ran (round-4 verdict weak #4 — un-stamped numbers
    # made cross-round comparison a trap). Homogeneous: every value is a
    # per-member dict (ADVICE r5 — scalars mixed into the mapping broke
    # consumers iterating members).
    node_stamps: dict = field(default_factory=dict)
    # How long the coordinator waited for the device-owning member's warm
    # gate before starting traffic (0.0 when no accelerator is assigned).
    device_warm_wait_s: float = 0.0
    trace_file: str | None = None  # merged Chrome/Perfetto JSON (--trace)
    # Server-side stats of the host's verification sidecar
    # (crypto/sidecar.py stats(): batch-size histogram, cross-request
    # coalescing counts, device/host batches); None when the run did not
    # use a sidecar.
    sidecar: dict | None = None
    # Sharded-notary runs (shards > 0): group count, cross-shard tx mix,
    # the per-group ledger audit (committed_states rows count consumed
    # REFS: 1 per single move, 2 per cross move), live reservation rows
    # left after the drain, and the exactly-once verdict over all of it.
    # None/0 when the run is unsharded.
    shards: int = 0
    cross_requested: int = 0
    cross_committed: int = 0
    per_group_committed: list | None = None
    ledger_committed: int | None = None
    ledger_expected: int | None = None
    reserved_leaked: int | None = None
    exactly_once: bool | None = None

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


# A member that ran fewer rounds than this has a stage breakdown made of
# noise (a 2-sample stage winning "busiest" steered a whole sweep's
# first_bottleneck verdict) — below it, attribution abstains. The doctor
# owns the constant (its round_breakdown merge honours the same floor);
# this alias keeps the historical loadtest name importable.
BUSIEST_STAGE_MIN_ROUNDS = _doctor.MIN_ATTRIBUTION_ROUNDS


def _busiest_stage(stage: dict | None) -> str | None:
    """The round stage this member spent the most wall time in, guarded:

    * abstains (None) below BUSIEST_STAGE_MIN_ROUNDS rounds — too few
      samples to mean anything;
    * excludes the "rounds" key, which is an integer COUNT riding in the
      same dict as the float seconds (the unguarded ``max(stage,
      key=stage.get)`` happily crowned it after ~200 rounds);
    * breaks ties deterministically (alphabetically first of the maxima)
      so two equal stages can't flap the sweep verdict between runs.
    * abstains when every timed value is zero — a freshly-deltaed window
      that did no measured work has no busiest stage, and crowning the
      alphabetical first would be a fabricated verdict."""
    stage = stage or {}
    if stage.get("rounds", 0) < BUSIEST_STAGE_MIN_ROUNDS:
        return None
    timed = {k: v for k, v in stage.items() if k != "rounds"}
    if not timed or all((v or 0) <= 0 for v in timed.values()):
        return None
    return max(sorted(timed), key=timed.get)


def _delta_counters(current: dict | None, baseline: dict | None) -> dict:
    """Per-key numeric delta of a cumulative counter dict against a
    baseline snapshot (missing baseline keys count 0; negatives clamp —
    a member restart resets its counters)."""
    current = current or {}
    baseline = baseline or {}
    out = {}
    for k, v in current.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[k] = max(type(v)(0), v - (baseline.get(k) or 0))
    return out


def _member_stamp(metrics: dict, device: str,
                  baseline: dict | None = None) -> dict:
    """One notary member's self-describing stamp from its node_metrics
    snapshot: verifier/backend/device identity, device-vs-host routing,
    and the async-pipeline numbers (depth + overlap ratio: the fraction
    of verify wall time served on the feeder thread instead of inside
    the round — 0.0/None when the pipeline is off or never engaged).

    ``baseline`` (an earlier node_metrics snapshot, e.g. taken after
    warmup) switches the round attribution fields — busiest_stage and
    round_breakdown — to DELTAS over the measured window. Cumulative
    stamps were the stale-carryover trap: a short measured leg inherited
    warmup + earlier legs' round counters, so attribution named whatever
    the PREVIOUS workload was bound by."""
    av = metrics.get("async_verify") or {}
    stage = metrics.get("round_stage_s") or {}
    if baseline is not None:
        stage = _delta_counters(stage, baseline.get("round_stage_s"))
        breakdown = _tm.format_breakdown(_delta_counters(
            metrics.get("round_phase_s"), baseline.get("round_phase_s")))
    else:
        breakdown = metrics.get("round_breakdown")
    wall = av.get("verify_wall_s", 0.0) or 0.0
    in_loop = stage.get("verify", 0.0) or 0.0
    overlap = (round(wall / (wall + in_loop), 3)
               if (wall + in_loop) > 0 else None)
    raft = metrics.get("raft") or {}
    transport = metrics.get("transport") or {}
    dev_b = metrics.get("verify_device_batches") or 0
    host_b = metrics.get("verify_host_batches") or 0
    return {"verifier": metrics.get("verifier"),
            "kernel_backend": metrics.get("kernel_backend"),
            "device": device,
            "device_batches": metrics.get("verify_device_batches"),
            "host_batches": metrics.get("verify_host_batches"),
            # Fraction of this member's verify batches the device tier
            # actually served (0.0 = everything host-routed — the r05
            # regression shape; None when no batch ran at all).
            "device_occupancy": (round(dev_b / (dev_b + host_b), 3)
                                 if (dev_b + host_b) else None),
            "device_ready": metrics.get("verify_device_ready"),
            "device_min_sigs": metrics.get("verify_device_min_sigs"),
            # The EFFECTIVE size crossover in force at stamp time —
            # AdaptiveCrossover moves it at runtime, and without this the
            # artifact can't explain why traffic routed where it did.
            "effective_min_sigs": metrics.get(
                "verify_effective_min_sigs",
                av.get("effective_min_sigs",
                       metrics.get("verify_device_min_sigs"))),
            "static_min_sigs": metrics.get(
                "verify_static_min_sigs", av.get("static_min_sigs")),
            "adaptive_adjustments": av.get("adaptive_adjustments"),
            # Sidecar CLIENT stamps (node/verify_client.py): batches/sigs
            # shipped to the shared server, fallbacks, gate state; None
            # when this member runs without a sidecar. The client stamp
            # embeds a cached SERVER snapshot ("server") whose mesh fields
            # are hoisted flat here so artifacts grep them per member.
            "sidecar": metrics.get("sidecar"),
            "sidecar_devices": ((metrics.get("sidecar") or {}).get("server")
                                or {}).get("mesh_devices"),
            "sidecar_per_device_occupancy": (
                ((metrics.get("sidecar") or {}).get("server")
                 or {}).get("per_device_occupancy")),
            # Federation ROUTER stamps (crypto/federation.py): per-host
            # routing shares / hedges / degrade counters, hoisted flat so
            # doctor.stamp_attribution's host_imbalance rule (and artifact
            # greps) reach them without digging through the sidecar stamp.
            # None when this member feeds a single sidecar or none.
            "federation": ((metrics.get("sidecar") or {}).get("federation")),
            "async_verify": av or None,
            "pipeline_depth": av.get("depth"),
            "overlap_ratio": overlap,
            # Commit-pipeline stamps (ARCHITECTURE.md "Commit pipeline"):
            # group-commit density, wire RTT, and coalescing ratios, so a
            # latency number can't travel without the replication shape
            # that produced it.
            "raft": raft or None,
            "raft_role": raft.get("role"),
            "entries_per_batch": raft.get("entries_per_batch"),
            "replication_rtt_ms_avg": raft.get("replication_rtt_ms_avg"),
            "reply_coalesce_ratio": raft.get("reply_coalesce_ratio"),
            "transport": transport or None,
            "outbox_burst_avg": transport.get("outbox_burst_avg"),
            "bridge_flush_avg": transport.get("bridge_flush_avg"),
            # Ingest-plane observables: total frames this node enqueued for
            # the wire (frames / firehose tx = frames-per-tx) and the
            # session-send coalescer's burst counters (statemachine._pump).
            "frames_sent_total": transport.get("frames_sent_total"),
            "session_bursts": metrics.get("session_bursts"),
            "session_burst_frames": metrics.get("session_burst_frames"),
            # The round stage this member spent the most wall time in — the
            # first SERVER-side bottleneck a saturating firehose exposes
            # (min-sample guarded + tie-broken, see _busiest_stage).
            "busiest_stage": _busiest_stage(stage),
            # The round profiler's phase attribution (obs/telemetry.py):
            # the block that decomposes a busiest_stage of "rounds"/"pump"
            # into poll/verify_wait/seal/replicate/apply/reply shares —
            # delta-windowed when the caller supplied a baseline.
            "round_breakdown": breakdown,
            # Admission-controller counters (rpc node_metrics "admission")
            # so the doctor's shed-dominated rule has evidence in every
            # stamp, not just slo_sweep's separate qos gather.
            "admission": metrics.get("admission")}


def run_loadtest_multiprocess(
    n_tx: int = 1000,
    width: int = 32,
    clients: int = 2,
    notary: str = "raft",  # simple | validating | raft | raft-validating
    cluster_size: int = 3,
    verifier: str = "cpu",  # notary-side provider
    client_verifier: str | None = None,  # defaults to `verifier`
    notary_device: str = "cpu",  # "accelerator": first notary owns the TPU
    inflight: int = 64,
    rate_tx_s: float = 0.0,  # per client; 0 = closed loop
    max_sigs: int = 4096,
    max_wait_ms: float = 2.0,
    coalesce_ms: float = 10.0,  # round accumulation window (all nodes);
    # measured on the 1-core driver host: raft 60->115 tx/s with p99
    # IMPROVING (fewer fsyncs/ACK frames/AppendEntries per tx)
    disrupt: str | None = None,  # kill-follower | sigstop-follower | None
    disrupt_after_s: float = 2.0,  # wall time (incl. prepare) before firing
    base_dir: str | None = None,
    max_seconds: float = 600.0,
    async_verify: bool = True,  # pipelined verification (all nodes)
    async_depth: int = 2,
    trace: str | None = None,  # write a merged Chrome/Perfetto trace here
    sidecar: bool = False,  # spawn ONE verification sidecar for the host;
    # every raft member feeds it, so micro-batches coalesce ACROSS
    # processes (crypto/sidecar.py) instead of host-routing per process
    sidecar_coalesce_us: int = 2000,
    sidecar_devices: int = 0,  # > 1: the sidecar owns an N-device mesh and
    # shards each coalesced bucket data-parallel across it (ops/sharded.py;
    # a virtual CPU mesh when notary_device == "cpu")
    adaptive_coalesce: bool = False,  # sidecar picks its own coalesce
    # window from observed arrival gaps (crypto/sidecar.py controller;
    # PR 7, off by default — flip per run to A/B against the static window)
    federation_hosts: int = 0,  # > 0: spawn N host-local sidecars as
    # simulated hosts and point every member's FederatedVerifier at the
    # set (crypto/federation.py routes by queue depth + QoS lane, hedges
    # slow hosts, quarantines dead ones). Mutually exclusive with
    # `sidecar` — federation IS the multi-sidecar generalization.
    shards: int = 0,  # > 0: boot `shards` independent raft groups of
    # `cluster_size` members each, partitioned by StateRef hash
    # (node/services/sharding.py); requires a raft-flavoured `notary`
    cross_frac: float = 0.0,  # fraction of txs built to span two shards
    # (the 2PC path); 0 = single-shard-only mix
    reserve_ttl_s: float = 15.0,  # cross-shard reservation TTL
    lane: str = "",  # QoS lane label for every firehose tx ("interactive"
    # or "bulk"); non-empty arms the QoS plane on every node. "" keeps the
    # run bit-identical to the pre-QoS harness.
    slo_ms: float = 50.0,  # interactive SLO (deadline per tx) when a lane
    # is set; ignored otherwise
) -> MultiProcessResult:
    """The reference-shaped harness: every node is a REAL OS process (its own
    GIL, transport sockets, sqlite), the coordinator only starts firehoses
    and gathers results over RPC (LoadTest.kt:39-144's remote-nodes shape;
    round-2 VERDICT: 'client/loadgen, raft members, and the TPU-feeding
    notary must not share one GIL')."""
    from ..testing.driver import driver

    if federation_hosts and sidecar:
        raise ValueError("federation_hosts and sidecar are mutually "
                         "exclusive (federation IS the multi-sidecar "
                         "generalization)")
    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-mp-"))
    def _extra(v: str, sidecar_addr: str = "",
               federation_addrs: str = "") -> str:
        out = (f'verifier = "{v}"\n'
               f"[batch]\nmax_sigs = {max_sigs}\n"
               f"max_wait_ms = {max_wait_ms}\n"
               f"coalesce_ms = {coalesce_ms}\n"
               f"async_verify = {str(async_verify).lower()}\n"
               f"async_depth = {async_depth}\n")
        if federation_addrs:
            out += f"federation_hosts = {json.dumps(federation_addrs)}\n"
        elif sidecar_addr:
            out += f"sidecar = {json.dumps(sidecar_addr)}\n"
            if sidecar_devices:
                out += f"sidecar_devices = {int(sidecar_devices)}\n"
        if lane:
            out += f"[qos]\nenabled = true\nslo_ms = {float(slo_ms)}\n"
        return out

    disruptions: list[str] = []
    # --trace: arm the span recorder in EVERY node process via the driver's
    # env vector (node.main() calls obs.trace.arm_from_env beside faults).
    trace_env = {"CORDA_TPU_TRACE": "1"} if trace else None
    trace_file = None
    side_stats = None
    with driver(base) as d:
        side = None
        fed_handles = []
        if sidecar:
            # The sidecar — not any member — owns the device: all members
            # ship micro-batches to it and it coalesces across processes.
            side = d.start_sidecar(
                verifier=verifier, device=notary_device,
                coalesce_us=sidecar_coalesce_us, max_sigs=max_sigs,
                devices=sidecar_devices or None,
                adaptive_coalesce=adaptive_coalesce, env_extra=trace_env)
        elif federation_hosts:
            # Federation tier: N host-local sidecars as simulated hosts;
            # every member routes verify buckets across the set.
            fed_handles = d.start_federation(
                count=federation_hosts, verifier=verifier,
                device=notary_device, coalesce_us=sidecar_coalesce_us,
                max_sigs=max_sigs, devices=sidecar_devices or None,
                env_extra=trace_env)
        side_addr = side.address if side is not None else ""
        fed_addrs = ",".join(h.address for h in fed_handles)
        toml_extra = _extra(verifier, side_addr, fed_addrs)
        # Followers stay on the host crypto path even when the leader runs
        # a device verifier: an election flip must degrade to host crypto,
        # not stall a cpu-pinned process behind an in-round XLA compile.
        # (With a sidecar, followers feed the same server instead.)
        follower_extra = _extra("cpu", side_addr, fed_addrs)
        client_extra = _extra(client_verifier or verifier)
        if shards > 0:
            if not notary.startswith("raft"):
                raise ValueError("shards > 0 requires a raft-* notary")
            kind = ("raft-validating" if notary.endswith("validating")
                    else "raft-simple")
            # One raft group per shard; every member carries the verifier
            # config (shard runs are symmetric — there is no single
            # "leader owns the device" member across groups, so only an
            # explicit accelerator assignment pins group 0's first member).
            rows = d.start_shard_cluster(
                groups=shards, members=cluster_size, notary=kind,
                reserve_ttl_s=reserve_ttl_s, extra_toml=toml_extra,
                cordapps=("corda_tpu.testing.dummies",), rpc=True,
                device_member=((0, 0) if notary_device == "accelerator"
                               else None),
                env_extra=trace_env)
            members = [m for row in rows for m in row]
        else:
            members = _start_notary_processes(
                d, notary, cluster_size, toml_extra,
                follower_extra=follower_extra, device=notary_device,
                rpc=True, env_extra=trace_env)
        handles = []
        rpcs = []
        for i in range(clients):
            handles.append(d.start_node(
                f"Client{i}", rpc=True,
                cordapps=("corda_tpu.tools.loadgen",),
                extra_toml=client_extra, env_extra=trace_env))
        for h in handles:
            rpcs.append(h.rpc("demo", "s3cret", timeout=60.0))
            d.defer(rpcs[-1].close)
        # Notary-side metrics matter now that the notary process can OWN the
        # accelerator (device policy): its pump verifications are exactly
        # the device-backed work, so sigs_verified sums RPC metric deltas
        # across EVERY node process — clients and notary members alike.
        member_rpcs = []
        for m in members:
            member_rpcs.append(m.rpc("demo", "s3cret", timeout=60.0))
            d.defer(member_rpcs[-1].close)
        device_warm_s = 0.0
        if side is not None and notary_device == "accelerator":
            # Sidecar topology: the warm gate lives in the SIDECAR process
            # (members run the sidecar client, which has no local gate), so
            # readiness polls the server's stats endpoint. Same 420 s
            # budget and same honesty fallback: a dead tunnel measures the
            # (stamped) host path.
            from ..node.verify_client import SidecarError, fetch_sidecar_stats

            t_warm = time.perf_counter()
            deadline = time.monotonic() + 420.0
            while time.monotonic() < deadline:
                try:
                    ready = fetch_sidecar_stats(
                        side.address).get("device_ready")
                except SidecarError:
                    ready = False
                if ready or ready is None:
                    break
                time.sleep(1.0)
            device_warm_s = round(time.perf_counter() - t_warm, 1)
        elif notary_device == "accelerator":
            # Production shape: a device-owning notary warms its kernel at
            # boot (node.py _warm_verifier_maybe) and takes traffic only
            # once warm — otherwise every batch host-routes behind the
            # gate and the "device" run measures the host path. The budget
            # covers BOTH pump buckets' first-use compiles: the axon
            # platform loads nothing from the persistent cache (measured:
            # ~107 s/bucket per process, cache hit or not), so warm-up is
            # a genuine per-process compile. Bounded: a dead tunnel must
            # not hang the harness, it just measures (and stamps) the
            # gated host path honestly.
            t_warm = time.perf_counter()
            deadline = time.monotonic() + 420.0
            while time.monotonic() < deadline:
                ready = member_rpcs[0].call(
                    "node_metrics").get("verify_device_ready")
                if ready or ready is None:
                    # None: no warm gate exists in that process (e.g. a
                    # cpu verifier on an accelerator-assigned node) — it
                    # will never flip, so waiting buys nothing.
                    break
                time.sleep(1.0)
            device_warm_s = round(time.perf_counter() - t_warm, 1)
        before = [r.call("node_metrics") for r in rpcs + member_rpcs]
        t_start = time.perf_counter()
        per_client_n = n_tx // clients
        flow_args = (per_client_n, width, inflight, float(rate_tx_s),
                     float(cross_frac))
        if lane:  # unlabelled runs keep the pre-QoS start_flow arg shape
            flow_args += (lane, float(slo_ms))
        flow_handles = [
            r.call("start_flow_dynamic", "loadgen.FirehoseFlow", flow_args)
            for r in rpcs]
        results: list = [None] * clients
        deadline = time.monotonic() + max_seconds
        disrupted = False
        while time.monotonic() < deadline:
            all_done = True
            for i, (r, fh) in enumerate(zip(rpcs, flow_handles)):
                if results[i] is not None:
                    continue
                done, value = r.call("flow_result", fh.run_id)
                if done:
                    results[i] = value
                else:
                    all_done = False
            if all_done:
                break
            if (disrupt and not disrupted
                    and time.perf_counter() - t_start > disrupt_after_s
                    and len(members) > 1):
                disrupted = True
                victim = members[1]  # a follower (leader is usually Raft0,
                # and kill-follower must preserve quorum either way: 2/3 up)
                if disrupt == "kill-follower":
                    victim.kill()
                    disruptions.append(f"SIGKILL {victim.name}")
                    members[1] = d.restart_node(victim)
                    disruptions.append(f"restarted {victim.name} from disk")
                elif disrupt == "sigstop-follower":
                    victim.sigstop()
                    disruptions.append(f"SIGSTOP {victim.name} (hung)")
                    time.sleep(2.0)
                    victim.sigcont()
                    disruptions.append(f"SIGCONT {victim.name}")
            time.sleep(0.05)
        else:
            raise TimeoutError(
                f"loadtest did not finish in {max_seconds}s: {results}")
        wall = time.perf_counter() - t_start
        after = []
        for r, b in zip(rpcs + member_rpcs, before):
            try:
                after.append(r.call("node_metrics"))
            except Exception:
                # A killed/restarted member's old RPC connection is gone
                # (and a reborn node's counters reset anyway): count zero
                # delta for it — an honest undercount.
                after.append(b)
        stamps = {}
        for m, a in zip(members, after[len(rpcs):]):
            stamps[m.name] = _member_stamp(a, m.device)
        if side is not None:
            from ..node.verify_client import SidecarError, fetch_sidecar_stats

            try:
                side_stats = fetch_sidecar_stats(side.address)
            except SidecarError:
                side_stats = {"error": "sidecar unreachable at gather"}
        elif fed_handles:
            # Per-host server view beside the members' client-side
            # federation stamps (node_stamps[...]["federation"]).
            from ..node.verify_client import SidecarError, fetch_sidecar_stats

            servers: dict = {}
            for h in fed_handles:
                try:
                    servers[h.address] = fetch_sidecar_stats(h.address)
                except SidecarError:
                    servers[h.address] = {
                        "error": "host unreachable at gather"}
            side_stats = {"federation_servers": servers}
        if trace:
            trace_file = _write_trace(
                trace, _collect_trace_snapshots(rpcs + member_rpcs))

    sigs = sum(max(0, a["verify_sigs"] - b["verify_sigs"])
               for a, b in zip(after, before))
    duration = max(r.duration_s for r in results)
    committed = sum(r.committed for r in results)
    rejected = sum(r.rejected for r in results)
    total = per_client_n * clients
    cross_req = sum(getattr(r, "cross_requested", 0) for r in results)
    cross_com = sum(getattr(r, "cross_committed", 0) for r in results)
    per_group = ledger_committed = ledger_expected = None
    leaked = once = None
    if shards > 0:
        # Ledger-side exactly-once audit: committed_states rows count
        # consumed input REFS, so N committed moves with cross_com of them
        # two-input must leave exactly N + cross_com rows across all
        # groups — one missing row is a lost spend, one extra is a double
        # commit. A clean drain also leaves zero live reservation rows on
        # every member (min per group: a lagging follower may not have
        # applied the abort yet, the leader's floor is the truth).
        member_after = after[len(rpcs):]
        rows_after = [member_after[g * cluster_size:(g + 1) * cluster_size]
                      for g in range(shards)]
        per_group = [max(((a.get("raft") or {}).get("committed_states")
                          or 0) for a in row) for row in rows_after]
        ledger_committed = sum(per_group)
        ledger_expected = committed + cross_com
        leaked = sum(min(((a.get("raft") or {}).get("reserved_states")
                          or 0) for a in row) for row in rows_after)
        once = (rejected == 0 and committed == total
                and ledger_committed == ledger_expected and not leaked)
    return MultiProcessResult(
        tx_requested=total,
        tx_committed=committed,
        tx_rejected=rejected,
        width=width,
        clients=clients,
        duration_s=round(duration, 3),
        wall_s=round(wall, 3),
        tx_per_sec=round(total / duration, 1) if duration else 0.0,
        sigs_verified=sigs,
        sigs_per_sec=round(sigs / duration, 1) if duration else 0.0,
        p50_ms=max(r.p50_ms for r in results),
        p99_ms=max(r.p99_ms for r in results),
        per_client=[r.__dict__ for r in results],
        disruptions=disruptions,
        node_stamps=stamps,
        device_warm_wait_s=device_warm_s,
        trace_file=trace_file,
        sidecar=side_stats,
        shards=shards,
        cross_requested=cross_req,
        cross_committed=cross_com,
        per_group_committed=per_group,
        ledger_committed=ledger_committed,
        ledger_expected=ledger_expected,
        reserved_leaked=leaked,
        exactly_once=once,
    )


def _start_notary_processes(d, notary: str, cluster_size: int,
                            extra_toml: str, follower_extra: str | None = None,
                            device: str = "cpu", rpc: bool = False,
                            env_extra: dict | None = None) -> list:
    """Spawn the notary process(es) for a driver run; returns the members.
    For a raft cluster, member 0 gets extra_toml + device (the leader-owns-
    the-device topology: deterministic timeouts make the first member win
    the initial election) and the rest get follower_extra (defaults to
    extra_toml) on the cpu; an election flip degrades to host crypto
    rather than fighting over one chip."""
    if notary.startswith("raft"):
        kind = ("raft-validating" if notary.endswith("validating")
                else "raft-simple")
        cluster = tuple(f"Raft{i}" for i in range(cluster_size))
        return [d.start_node(
            name, notary=kind, raft_cluster=cluster,
            cordapps=("corda_tpu.testing.dummies",), rpc=rpc,
            extra_toml=extra_toml if i == 0 else (follower_extra
                                                  or extra_toml),
            device=device if i == 0 else "cpu", env_extra=env_extra)
            for i, name in enumerate(cluster)]
    return [d.start_node(
        "Notary", notary=notary, cordapps=("corda_tpu.testing.dummies",),
        rpc=rpc, extra_toml=extra_toml, device=device, env_extra=env_extra)]


@dataclass
class SweepResult:
    """{rate: FirehoseResult} plus per-member node stamps. Mapping-style
    access (sweep[rate], .items(), iteration) delegates to the rate
    results so existing sweep consumers keep working unchanged."""

    results: dict
    node_stamps: dict = field(default_factory=dict)
    # Per-node span snapshots (trace_snapshot RPC shape) when the sweep ran
    # with tracing armed — bench.py feeds these to obs.collect.
    trace_snapshots: list = field(default_factory=list)
    # Server-side verification-sidecar stats for the whole sweep
    # (crypto/sidecar.py stats()); None when the sweep ran without one.
    sidecar: dict | None = None
    # Per-member QoS plane + admission-controller stats (rpc node_metrics
    # "qos"/"admission") when the sweep ran with the plane armed.
    qos: dict | None = None
    # Cluster telemetry fold (obs/export.collect_cluster over per-member
    # telemetry_snapshot RPCs): per-node registries + the merged view.
    telemetry: dict | None = None
    # Flight-recorder artifact paths the sweep produced (slo_sweep with
    # flight_dir set: the latched slo_breach dump); None when unarmed.
    flight: list | None = None
    # The performance doctor's evidence-ranked attribution over the
    # member stamps (obs/doctor.stamp_attribution): ranked bottlenecks
    # with per-entry evidence + next experiment. This — not the legacy
    # Counter-majority over busiest_stage — is where first_bottleneck
    # comes from; None when the sweep gathered no stamps.
    doctor: dict | None = None

    @property
    def first_bottleneck(self):
        """Top of the doctor's ranked bottleneck list; honest None when
        no member produced enough evidence (the <MIN_ATTRIBUTION_ROUNDS
        abstention contract survives end-to-end)."""
        return (self.doctor or {}).get("first_bottleneck")

    def __getitem__(self, rate):
        return self.results[rate]

    def __iter__(self):
        return iter(self.results)

    def __len__(self):
        return len(self.results)

    def __contains__(self, rate):
        return rate in self.results

    def items(self):
        return self.results.items()

    def keys(self):
        return self.results.keys()

    def values(self):
        return self.results.values()


def _merge_firehose(values: list):
    """Fold per-client FirehoseResults for ONE offered rate into a single
    summary: counts/signatures/throughput sum, the measured phase is the
    slowest client's, and each percentile takes the worst client (an upper
    bound — exact merged percentiles would need the raw latency lists,
    which stay in the client processes by design)."""
    from .loadgen import FirehoseResult

    return FirehoseResult(
        requested=sum(v.requested for v in values),
        committed=sum(v.committed for v in values),
        rejected=sum(v.rejected for v in values),
        duration_s=max(v.duration_s for v in values),
        tx_per_sec=round(sum(v.tx_per_sec for v in values), 1),
        p50_ms=max(v.p50_ms for v in values),
        p90_ms=max(v.p90_ms for v in values),
        p99_ms=max(v.p99_ms for v in values),
        width=values[0].width,
        sigs_signed=sum(v.sigs_signed for v in values),
        cross_requested=sum(getattr(v, "cross_requested", 0)
                            for v in values),
        cross_committed=sum(getattr(v, "cross_committed", 0)
                            for v in values),
        lane=getattr(values[0], "lane", ""),
        shed=sum(getattr(v, "shed", 0) for v in values),
        # Ingest attribution: throughput rates sum across clients (they
        # prepared concurrently in separate processes); prepare wall is the
        # slowest client's; CPU is the honest total burned.
        tx_built_per_s=round(sum(getattr(v, "tx_built_per_s", 0.0)
                                 for v in values), 1),
        sigs_signed_per_s=round(sum(getattr(v, "sigs_signed_per_s", 0.0)
                                    for v in values), 1),
        serialize_ms=round(sum(getattr(v, "serialize_ms", 0.0)
                               for v in values), 3),
        prepare_s=round(max(getattr(v, "prepare_s", 0.0)
                            for v in values), 4),
        cpu_s=round(sum(getattr(v, "cpu_s", 0.0) for v in values), 4),
    )


def run_latency_sweep(
    # Raised for round 15: columnar prepare (one native batch sign per
    # chunk) moved the per-client ceiling off build/sign, so the stale
    # (30, 90, 150) ladder never left the comfortable region — the top
    # rung must sit ABOVE single-process capacity for the sweep to show
    # a knee.
    rates: tuple[float, ...] = (60.0, 240.0, 720.0),
    n_tx: int = 250,
    width: int = 4,
    clients: int = 1,  # client processes splitting each offered rate;
    # one client process's measured phase saturates near a few hundred
    # tx/s of submissions, so rates above that need the load SPREAD (each
    # paces at rate/clients) or the sweep measures the generator, not the
    # notary — or use run_ingest_sweep, whose replay workers skip
    # build/sign entirely
    notary: str = "simple",  # simple | validating | raft | raft-validating
    cluster_size: int = 3,
    verifier: str = "cpu",  # notary member 0's provider (followers: cpu)
    notary_device: str = "cpu",  # "accelerator": first notary owns the TPU
    max_sigs: int = 4096,
    max_wait_ms: float = 2.0,
    # 0 preserves the pre-r5 sweep behaviour so the simple-notary trend
    # line keeps its meaning; the raft sweep passes the production 10 ms.
    coalesce_ms: float = 0.0,
    base_dir: str | None = None,
    max_seconds: float = 300.0,
    async_verify: bool = True,
    async_depth: int = 2,
    trace: "str | bool | None" = None,  # True: collect span snapshots onto
    # the SweepResult; a path additionally writes the merged Chrome trace
    sidecar: bool = False,  # one host-wide verification sidecar; members
    # feed it so batches coalesce across processes (crypto/sidecar.py)
    sidecar_coalesce_us: int = 2000,
    sidecar_devices: int = 0,  # > 1: the sidecar owns an N-device mesh
) -> SweepResult:
    """Open-loop tail-latency measurement: a notary (or raft cluster) +
    `clients` client processes, the firehose driven at each offered load in
    `rates` sequentially (rate_tx_s pacing: flows start on schedule
    regardless of completions; with clients > 1 the rate is split evenly so
    offered loads beyond one generator's GIL ceiling stay honest). Per-tx latency is measured from scheduled submission, so
    queueing at offered loads near capacity shows up as a p99 ≫ p50 tail —
    the number the closed-loop start-all-then-pump shape structurally cannot
    produce (round-3 VERDICT item 3). notary="raft" sweeps the flagship
    BASELINE config-1 cluster through real OS processes (round-4 VERDICT
    item 4: the flagship config's p99 was only ever measured closed-loop).
    Returns a SweepResult: {rate: FirehoseResult} plus node_stamps
    attributing each member's routing (device_batches, pipeline depth,
    overlap ratio) for the whole sweep."""
    from ..testing.driver import driver

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-lat-"))
    def _extra(v: str, sidecar_addr: str = "") -> str:
        out = (f'verifier = "{v}"\n'
               f"[batch]\nmax_sigs = {max_sigs}\n"
               f"max_wait_ms = {max_wait_ms}\n"
               f"coalesce_ms = {coalesce_ms}\n"
               f"async_verify = {str(async_verify).lower()}\n"
               f"async_depth = {async_depth}\n")
        if sidecar_addr:
            out += f"sidecar = {json.dumps(sidecar_addr)}\n"
            if sidecar_devices:
                out += f"sidecar_devices = {int(sidecar_devices)}\n"
        return out

    results: dict = {}
    stamps: dict = {}
    snapshots: list = []
    side_stats = None
    trace_env = {"CORDA_TPU_TRACE": "1"} if trace else None
    with driver(base) as d:
        side = None
        if sidecar:
            side = d.start_sidecar(
                verifier=verifier, device=notary_device,
                coalesce_us=sidecar_coalesce_us, max_sigs=max_sigs,
                devices=sidecar_devices or None, env_extra=trace_env)
        side_addr = side.address if side is not None else ""
        toml_extra = _extra(verifier, side_addr)
        members = _start_notary_processes(
            d, notary, cluster_size, toml_extra,
            follower_extra=_extra("cpu", side_addr), device=notary_device,
            rpc=True, env_extra=trace_env)
        member_rpcs = []
        for m in members:
            member_rpcs.append(m.rpc("demo", "s3cret", timeout=60.0))
            d.defer(member_rpcs[-1].close)
        if side is not None and notary_device == "accelerator":
            # The warm gate lives in the sidecar process (see the
            # multiprocess harness): poll the server's stats endpoint.
            from ..node.verify_client import SidecarError, fetch_sidecar_stats

            deadline = time.monotonic() + 420.0
            while time.monotonic() < deadline:
                try:
                    ready = fetch_sidecar_stats(
                        side.address).get("device_ready")
                except SidecarError:
                    ready = False
                if ready or ready is None:
                    break
                time.sleep(1.0)
        elif notary_device == "accelerator":
            # Same policy as the multiprocess harness: take traffic only
            # once the device-owning member's warm gate opens, else the
            # whole sweep measures the gated host path. Bounded — a dead
            # tunnel degrades to an (honestly stamped) host-path sweep.
            deadline = time.monotonic() + 420.0
            while time.monotonic() < deadline:
                ready = member_rpcs[0].call(
                    "node_metrics").get("verify_device_ready")
                if ready or ready is None:
                    break
                time.sleep(1.0)
        clients = max(1, clients)
        client_rpcs = []
        for i in range(clients):
            handle = d.start_node(f"Client{i}", rpc=True,
                                  cordapps=("corda_tpu.tools.loadgen",),
                                  extra_toml=_extra("cpu"),
                                  env_extra=trace_env)
            client_rpcs.append(handle.rpc("demo", "s3cret", timeout=60.0))
            d.defer(client_rpcs[-1].close)
        rpc = client_rpcs[0]
        # Warm-up: a tiny closed-loop burst per client drives session
        # establishment, netmap propagation and first-contact code paths
        # OUTSIDE the measured rates — a cold-start redelivery backoff
        # would otherwise show up as a multi-second p99 artifact in the
        # first rate.
        warms = [r.call("start_flow_dynamic", "loadgen.FirehoseFlow",
                        (5, width, 5, 0.0)) for r in client_rpcs]
        deadline = time.monotonic() + max_seconds
        pending = list(zip(client_rpcs, warms))
        while pending and time.monotonic() < deadline:
            pending = [(r, w) for r, w in pending
                       if not r.call("flow_result", w.run_id)[0]]
            time.sleep(0.1)
        if pending:
            raise TimeoutError("latency-sweep warmup did not finish")
        for rate in rates:
            # Each client paces at rate/clients with its share of n_tx:
            # the notary sees the full offered load, no single generator
            # process has to sustain more than its GIL can schedule.
            per_n = max(1, n_tx // clients)
            fhs = [r.call("start_flow_dynamic", "loadgen.FirehoseFlow",
                          (per_n, width, 1 << 30, float(rate) / clients))
                   for r in client_rpcs]
            values: list = [None] * clients
            deadline = time.monotonic() + max_seconds
            while time.monotonic() < deadline:
                for i, (r, fh) in enumerate(zip(client_rpcs, fhs)):
                    if values[i] is None:
                        done, value = r.call("flow_result", fh.run_id)
                        if done:
                            values[i] = value
                if all(v is not None for v in values):
                    break
                time.sleep(0.25)
            else:
                raise TimeoutError(
                    f"open-loop sweep at {rate} tx/s did not finish "
                    f"in {max_seconds}s")
            results[rate] = (values[0] if clients == 1
                             else _merge_firehose(values))
        for m, r in zip(members, member_rpcs):
            try:
                stamps[m.name] = _member_stamp(
                    r.call("node_metrics"), m.device)
            # lint: allow(no-silent-except) sweep tooling: a dead member costs its stamp, not the whole sweep; not a production verify/notarise path
            except Exception:
                pass  # a dead member costs its stamp, not the sweep
        if side is not None:
            from ..node.verify_client import SidecarError, fetch_sidecar_stats

            try:
                side_stats = fetch_sidecar_stats(side.address)
            except SidecarError:
                side_stats = {"error": "sidecar unreachable at gather"}
        if trace:
            snapshots = _collect_trace_snapshots(member_rpcs + client_rpcs)
            if isinstance(trace, str):
                _write_trace(trace, snapshots)
    return SweepResult(results=results, node_stamps=stamps,
                       trace_snapshots=snapshots, sidecar=side_stats,
                       doctor=_doctor.stamp_attribution(stamps))


def run_slo_sweep(
    # Raised for round 15 (vectorized ingest): with columnar prepare the
    # generators pace well past the old 240 top rung, so the default
    # ladder now reaches into overload — calibrate_admission re-derives
    # its knobs (and provenance) from whatever ladder actually ran.
    rates: tuple[float, ...] = (120.0, 240.0, 480.0),
    n_tx: int = 240,
    width: int = 4,
    clients: int = 2,
    interactive_frac: float = 0.25,  # share of each offered load (and of
    # n_tx) labelled interactive; the rest runs on the bulk lane
    slo_ms: float = 50.0,  # the explicit SLO: interactive deadline per tx
    bulk_rate: float = 0.0,  # bulk admission bucket (tx/s; 0 = unlimited,
    # the watermark alone does the shedding)
    queue_watermark: int = 48,  # runnable-backlog depth above which the
    # notary sheds BULK (interactive is never watermark-shed)
    notary: str = "simple",  # simple | validating | raft | raft-validating
    cluster_size: int = 3,
    verifier: str = "cpu",
    notary_device: str = "cpu",
    max_sigs: int = 4096,
    max_wait_ms: float = 2.0,
    coalesce_ms: float = 0.0,
    base_dir: str | None = None,
    max_seconds: float = 300.0,
    async_verify: bool = True,
    async_depth: int = 2,
    sidecar: bool = False,
    sidecar_coalesce_us: int = 2000,
    sidecar_devices: int = 0,
    qos: bool = True,  # False: the SAME mixed-lane offered load through an
    # unarmed plane — the no-QoS baseline the SLO verdict compares against
    flight_dir: str | None = None,  # arm the driver-side flight recorder:
    # the first rate whose merged interactive p99 breaches slo_ms dumps
    # ONE artifact (breaching window's per-rate metric deltas + member
    # spans) into this directory
) -> SweepResult:
    """Mixed-lane open-loop sweep for the explicit p99 SLO verdict: at each
    offered load, every client process drives TWO concurrent firehoses —
    one interactive (lane-labelled, deadline = slo_ms) at
    ``rate * interactive_frac`` and one bulk at the remainder — so the
    notary sees a contended mix, not a single-class stream. Per-lane
    FirehoseResults (p50/p99, committed, shed) are merged across clients;
    results[rate] is ``{"interactive": FirehoseResult, "bulk": ...}``.

    With ``qos=True`` every node arms the plane ([qos] in its TOML): lanes
    reorder the runnable queue, deadlines early-flush the three batching
    points, and the notary's admission controller watermark-sheds bulk —
    the claim under test is that interactive p99 stays inside slo_ms while
    bulk absorbs the overload as sheds. With ``qos=False`` the same load
    runs bit-identical to the pre-QoS tree and both lanes collapse
    together — the baseline."""
    from ..obs import telemetry as _tm
    from ..testing.driver import driver

    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-slo-"))
    recorder = None
    member_env = None
    if flight_dir:
        # Driver-side recorder: the sweep loop ticks it with per-rate lane
        # summaries, so the breach artifact's window reads as "how the
        # ladder climbed into the breach". Members get tracing armed so
        # the artifact carries their spans; they do NOT get their own
        # flight dir (exactly-one-artifact is the sweep's contract, and a
        # member overload dump would race it).
        recorder = _tm.FlightRecorder(str(flight_dir), node="slo-driver")
        member_env = {"CORDA_TPU_TRACE": "1"}

    def _extra(v: str, sidecar_addr: str = "") -> str:
        out = (f'verifier = "{v}"\n'
               f"[batch]\nmax_sigs = {max_sigs}\n"
               f"max_wait_ms = {max_wait_ms}\n"
               f"coalesce_ms = {coalesce_ms}\n"
               f"async_verify = {str(async_verify).lower()}\n"
               f"async_depth = {async_depth}\n")
        if sidecar_addr:
            out += f"sidecar = {json.dumps(sidecar_addr)}\n"
            if sidecar_devices:
                out += f"sidecar_devices = {int(sidecar_devices)}\n"
        if qos:
            # Arms the plane in EVERY node process: clients stamp lane
            # contexts onto generated txs, members schedule/shed by them.
            out += (f"[qos]\nenabled = true\n"
                    f"slo_ms = {float(slo_ms)}\n"
                    f"bulk_rate = {float(bulk_rate)}\n"
                    f"queue_watermark = {int(queue_watermark)}\n")
        return out

    results: dict = {}
    stamps: dict = {}
    qstats: dict = {}
    tsnaps: dict = {}
    side_stats = None
    lanes = (("interactive", float(interactive_frac), float(slo_ms)),
             ("bulk", 1.0 - float(interactive_frac), 0.0))
    with driver(base) as d:
        side = None
        if sidecar:
            side = d.start_sidecar(
                verifier=verifier, device=notary_device,
                coalesce_us=sidecar_coalesce_us, max_sigs=max_sigs,
                devices=sidecar_devices or None)
        side_addr = side.address if side is not None else ""
        members = _start_notary_processes(
            d, notary, cluster_size, _extra(verifier, side_addr),
            follower_extra=_extra("cpu", side_addr), device=notary_device,
            rpc=True, env_extra=member_env)
        member_rpcs = []
        for m in members:
            member_rpcs.append(m.rpc("demo", "s3cret", timeout=60.0))
            d.defer(member_rpcs[-1].close)
        clients = max(1, clients)
        client_rpcs = []
        for i in range(clients):
            handle = d.start_node(f"Client{i}", rpc=True,
                                  cordapps=("corda_tpu.tools.loadgen",),
                                  extra_toml=_extra("cpu"))
            client_rpcs.append(handle.rpc("demo", "s3cret", timeout=60.0))
            d.defer(client_rpcs[-1].close)
        # Same warm-up as the latency sweep: session establishment and
        # first-contact paths run OUTSIDE the measured rates.
        warms = [r.call("start_flow_dynamic", "loadgen.FirehoseFlow",
                        (5, width, 5, 0.0)) for r in client_rpcs]
        deadline = time.monotonic() + max_seconds
        pending = list(zip(client_rpcs, warms))
        while pending and time.monotonic() < deadline:
            pending = [(r, w) for r, w in pending
                       if not r.call("flow_result", w.run_id)[0]]
            time.sleep(0.1)
        if pending:
            raise TimeoutError("SLO-sweep warmup did not finish")
        for rate in rates:
            # Two firehoses per client — the lanes CONTEND inside each
            # client process and at the notary, which is the point.
            fhs = []
            for lane, frac, lane_slo in lanes:
                ln = max(1, int(round(n_tx * frac)) // clients)
                lane_rate = float(rate) * frac / clients
                for r in client_rpcs:
                    fhs.append((r, r.call(
                        "start_flow_dynamic", "loadgen.FirehoseFlow",
                        (ln, width, 1 << 30, lane_rate, 0.0,
                         lane, lane_slo)), lane))
            values: list = [None] * len(fhs)
            deadline = time.monotonic() + max_seconds
            while time.monotonic() < deadline:
                for i, (r, fh, _) in enumerate(fhs):
                    if values[i] is None:
                        done, value = r.call("flow_result", fh.run_id)
                        if done:
                            values[i] = value
                if all(v is not None for v in values):
                    break
                time.sleep(0.25)
            else:
                raise TimeoutError(
                    f"SLO sweep at {rate} tx/s did not finish "
                    f"in {max_seconds}s")
            by_lane: dict = {}
            for (_, _, lane), v in zip(fhs, values):
                by_lane.setdefault(lane, []).append(v)
            results[rate] = {lane: _merge_firehose(vs)
                             for lane, vs in by_lane.items()}
            if recorder is not None:
                sample: dict = {"rate_tx_s": float(rate)}
                for lane, fr in results[rate].items():
                    sample[f"{lane}_p99_ms"] = fr.p99_ms
                    sample[f"{lane}_tx_per_sec"] = fr.tx_per_sec
                    sample[f"{lane}_committed"] = fr.committed
                    sample[f"{lane}_shed"] = fr.shed
                recorder.tick(sample)
                inter = results[rate].get("interactive")
                if inter is not None and inter.p99_ms > slo_ms:
                    # SLO breach: dump once (the recorder latches on the
                    # reason, so later breaching rungs add nothing) with
                    # the breaching window's deltas, the members' span
                    # buffers, and their telemetry counters AT the breach.
                    spans: list = []
                    counters: dict = {}
                    routing: dict = {}
                    for m, r in zip(members, member_rpcs):
                        try:
                            spans.extend(
                                r.call("trace_snapshot").get("spans") or [])
                            counters[m.name] = (
                                (r.call("telemetry_snapshot").get("snapshot")
                                 or {}).get("counters"))
                            # Federation routing state AT the breach:
                            # per-host shares + the recent-decisions ring
                            # (which host each batch went to and why), so
                            # a breach on the federated plane is
                            # attributable to a routing choice, not just
                            # a latency number. Absent when the member
                            # feeds a single sidecar or none.
                            fed = ((r.call("node_metrics").get("sidecar")
                                    or {}).get("federation"))
                            if fed:
                                routing[m.name] = fed
                        # lint: allow(no-silent-except) sweep tooling: a dead member costs its breach evidence, not the sweep; not a production verify/notarise path
                        except Exception:
                            pass
                    recorder.trigger("slo_breach", extra={
                        "rate_tx_s": float(rate), "slo_ms": float(slo_ms),
                        "interactive_p99_ms": inter.p99_ms,
                        "member_counters": counters,
                        "federation_routing": routing or None}, spans=spans)
        for m, r in zip(members, member_rpcs):
            try:
                metrics = r.call("node_metrics")
                stamps[m.name] = _member_stamp(metrics, m.device)
                qstats[m.name] = {"qos": metrics.get("qos"),
                                  "admission": metrics.get("admission")}
                tsnaps[m.name] = r.call(
                    "telemetry_snapshot").get("snapshot")
            # lint: allow(no-silent-except) sweep tooling: a dead member costs its stamp, not the whole sweep; not a production verify/notarise path
            except Exception:
                pass  # a dead member costs its stamp, not the sweep
        if side is not None:
            from ..node.verify_client import SidecarError, fetch_sidecar_stats

            try:
                side_stats = fetch_sidecar_stats(side.address)
            except SidecarError:
                side_stats = {"error": "sidecar unreachable at gather"}
    from ..obs.export import collect_cluster

    return SweepResult(results=results, node_stamps=stamps,
                       sidecar=side_stats, qos=qstats or None,
                       telemetry=collect_cluster(tsnaps) if tsnaps else None,
                       flight=(sorted(recorder.dumped.values())
                               if recorder is not None else None),
                       doctor=_doctor.stamp_attribution(stamps))


_LOSSY_PLAN_TOML = """\
seed = 7
[[rule]]
point = "transport.send"
action = "drop"
p = 0.05
max_fires = 500
"""


def run_ingest_sweep(
    rates: tuple[float, ...] = (1200.0, 3600.0, 10000.0),
    n_tx: int = 2000,
    width: int = 1,
    workers: int = 3,  # replay worker processes splitting each offered rate
    notary: str = "simple",  # simple | raft (validating kinds rejected:
    # replay workers hold no issue provenance — uniqueness does not need
    # the back chain, validation would)
    cluster_size: int = 3,
    cross_frac: float = 0.0,
    verifier: str = "cpu",
    max_sigs: int = 4096,
    max_wait_ms: float = 2.0,
    coalesce_ms: float = 10.0,
    chaos: str | None = None,  # "lossy" or a fault-plan TOML path: armed
    # (via CORDA_TPU_FAULT_PLAN) in member + worker processes, NOT the
    # builder — the corpus build stays deterministic, delivery does not
    base_dir: str | None = None,
    max_seconds: float = 600.0,
    async_verify: bool = True,
    async_depth: int = 2,
    pipeline: bool = True,  # commit-plane round pipelining ([raft]
    # pipeline): False runs the serial reference path for before/after
    # committed-tx/s deltas (bench.bench_ingest_sweep stamps both)
) -> SweepResult:
    """The multiprocess ingest firehose: ONE builder process constructs,
    batch-signs and serializes the whole corpus (loadgen.IngestBuildFlow →
    a CTI1 multi-tx frame on disk), then `workers` replay processes each
    drive a DISJOINT slice of that frame open-loop at rate/workers — no
    worker ever rebuilds or re-signs a transaction, so the offered rate
    scales with worker count instead of one process's build+sign ceiling.

    Each rate gets a FRESH corpus (reusing one would double-spend its
    inputs) and is isolated: a failed rate records {"error": ...} in
    results[rate] and the sweep continues. results[rate] is otherwise a
    flat dict: offered/achieved tx/s, commit counts, latency percentiles,
    frames-per-tx (worker transport deltas), the builder's ingest
    attribution block, and the exactly-once audit verdict."""
    from ..testing.driver import driver

    if "validating" in notary:
        raise ValueError(
            "ingest sweep requires a non-validating notary: replay "
            "workers carry no issue provenance")
    base = Path(base_dir or tempfile.mkdtemp(prefix="corda-tpu-ingest-"))

    def _extra(v: str) -> str:
        return (f'verifier = "{v}"\n'
                f"[batch]\nmax_sigs = {max_sigs}\n"
                f"max_wait_ms = {max_wait_ms}\n"
                f"coalesce_ms = {coalesce_ms}\n"
                f"async_verify = {str(async_verify).lower()}\n"
                f"async_depth = {async_depth}\n"
                f"[raft]\npipeline = {str(pipeline).lower()}\n")

    chaos_env = None
    if chaos:
        plan = Path(chaos)
        if plan.suffix == ".toml" or plan.exists():
            plan_path = str(plan)
        elif chaos == "lossy":
            plan_path = str(base / "fault-plan.toml")
            base.mkdir(parents=True, exist_ok=True)
            Path(plan_path).write_text(_LOSSY_PLAN_TOML, encoding="utf-8")
        else:
            raise ValueError(f"chaos: expected 'lossy' or a TOML path, "
                             f"got {chaos!r}")
        chaos_env = {"CORDA_TPU_FAULT_PLAN": plan_path}

    results: dict = {}
    stamps: dict = {}
    with driver(base) as d:
        members = _start_notary_processes(
            d, notary, cluster_size, _extra(verifier),
            follower_extra=_extra("cpu"), rpc=True, env_extra=chaos_env)
        member_rpcs = []
        for m in members:
            member_rpcs.append(m.rpc("demo", "s3cret", timeout=60.0))
            d.defer(member_rpcs[-1].close)
        builder = d.start_node("Ingest0", rpc=True,
                               cordapps=("corda_tpu.tools.loadgen",),
                               extra_toml=_extra("cpu"))
        builder_rpc = builder.rpc("demo", "s3cret", timeout=60.0)
        d.defer(builder_rpc.close)
        workers = max(1, workers)
        worker_rpcs = []
        for i in range(workers):
            h = d.start_node(f"Worker{i}", rpc=True,
                             cordapps=("corda_tpu.tools.loadgen",),
                             extra_toml=_extra("cpu"), env_extra=chaos_env)
            worker_rpcs.append(h.rpc("demo", "s3cret", timeout=60.0))
            d.defer(worker_rpcs[-1].close)

        def _await(jobs, what):
            """jobs: [(rpc, flow_handle)] -> values, bounded wait."""
            values: list = [None] * len(jobs)
            deadline = time.monotonic() + max_seconds
            while time.monotonic() < deadline:
                for i, (r, fh) in enumerate(jobs):
                    if values[i] is None:
                        done, value = r.call("flow_result", fh.run_id)
                        if done:
                            values[i] = value
                if all(v is not None for v in values):
                    return values
                time.sleep(0.1)
            raise TimeoutError(f"{what} did not finish in {max_seconds}s")

        # Warm-up: session establishment / netmap / first-contact paths
        # run OUTSIDE the measured rates (same policy as the sweeps).
        _await([(r, r.call("start_flow_dynamic", "loadgen.FirehoseFlow",
                           (3, 1, 3, 0.0))) for r in worker_rpcs],
               "ingest-sweep warmup")
        # Post-warmup baseline snapshots: the end-of-sweep member stamps
        # delta against these, so busiest_stage / round_breakdown describe
        # the MEASURED legs — cumulative stamps carried warmup and earlier
        # rate legs into the verdict (the stale-"rounds" trap: a short
        # pipelined run inherited the previous workload's attribution).
        baselines: dict = {}
        for m, r in zip(members, member_rpcs):
            try:
                baselines[m.name] = r.call("node_metrics")
            # lint: allow(no-silent-except) sweep tooling: losing a baseline degrades one stamp to cumulative, not the sweep
            except Exception:
                pass
        for rate in rates:
            try:
                corpus_path = str(base / f"corpus-{rate:g}.bin")
                bh = builder_rpc.call(
                    "start_flow_dynamic", "loadgen.IngestBuildFlow",
                    (corpus_path, n_tx, width, float(cross_frac)))
                build = _await([(builder_rpc, bh)], f"corpus build@{rate}")[0]
                t_before = [r.call("node_metrics").get("transport") or {}
                            for r in worker_rpcs]
                per_n = max(1, n_tx // workers)
                jobs = [(r, r.call(
                    "start_flow_dynamic", "loadgen.FirehoseReplayFlow",
                    (corpus_path, i * per_n, per_n, 1 << 30,
                     float(rate) / workers)))
                    for i, r in enumerate(worker_rpcs)]
                values = _await(jobs, f"ingest replay@{rate}")
                t_after = [r.call("node_metrics").get("transport") or {}
                           for r in worker_rpcs]
                merged = _merge_firehose(values)
                frames = sum(
                    (a.get("frames_sent_total") or 0)
                    - (b.get("frames_sent_total") or 0)
                    for a, b in zip(t_after, t_before))
                results[rate] = {
                    "offered_tx_s": float(rate),
                    "achieved_tx_s": merged.tx_per_sec,
                    "requested": merged.requested,
                    "committed": merged.committed,
                    "rejected": merged.rejected,
                    "duration_s": merged.duration_s,
                    "p50_ms": merged.p50_ms,
                    "p99_ms": merged.p99_ms,
                    "workers": workers,
                    "frames_per_tx": (round(frames / merged.requested, 3)
                                      if merged.requested else None),
                    # No tx lost, none double-counted: every requested tx
                    # resolved exactly once as commit or loud reject.
                    "exactly_once": (merged.committed + merged.rejected
                                     == merged.requested),
                    "ingest": {
                        "tx_built_per_s": build.tx_built_per_s,
                        "sigs_signed_per_s": build.sigs_signed_per_s,
                        "serialize_ms": build.serialize_ms,
                        "prepare_s": build.prepare_s,
                        "bytes_written": build.bytes_written,
                        "sigs_signed": build.sigs_signed,
                        # Client-plane CPU attribution: builder prepare +
                        # worker load/drive CPU, all processes.
                        "cpu_s": round(build.cpu_s + merged.cpu_s, 4),
                        "load_prepare_s": merged.prepare_s,
                    },
                }
            except Exception as e:
                # Per-sub-run isolation: one rate failing (timeout, dead
                # worker) records an error row; later rates still run.
                results[rate] = {"error": f"{type(e).__name__}: {e}",
                                 "offered_tx_s": float(rate)}
        for m, r in zip(members, member_rpcs):
            try:
                stamps[m.name] = _member_stamp(
                    r.call("node_metrics"), m.device,
                    baseline=baselines.get(m.name))
            # lint: allow(no-silent-except) sweep tooling: a dead member costs its stamp, not the whole sweep; not a production verify/notarise path
            except Exception:
                pass  # a dead member costs its stamp, not the sweep
    return SweepResult(results=results, node_stamps=stamps,
                       doctor=_doctor.stamp_attribution(stamps))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tx", type=int, default=100)
    ap.add_argument("--notary", choices=("simple", "validating", "raft",
                                         "raft-validating"),
                    default="simple")
    ap.add_argument("--cluster-size", type=int, default=3)
    ap.add_argument("--disrupt",
                    choices=("kill-notary", "kill-follower",
                             "sigstop-follower"),
                    default=None)
    ap.add_argument("--verifier", choices=("cpu", "jax", "jax-shadow"),
                    default="cpu")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-sigs", type=int, default=4096)
    ap.add_argument("--processes", action="store_true",
                    help="real OS-process nodes via the driver (+ loadgen "
                         "cordapp firehose) instead of in-process nodes")
    ap.add_argument("--width", type=int, default=32,
                    help="signatures per transaction (multi-owner states)")
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--inflight", type=int, default=64)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop offered load per client (tx/s); 0 = "
                         "closed loop")
    ap.add_argument("--chaos", default=None, metavar="PLAN",
                    help="chaos mode: arm a fault plan (lossy | slow-disk | "
                         "flaky-device | bitrot | partition.split-brain | "
                         "partition.asym | partition.flap | path to a plan "
                         "TOML) and notarise through the retrying client "
                         "flow; partition.* plans auto-bind their cut sides "
                         "leader-first over the live cluster")
    ap.add_argument("--kill-leader", action="store_true",
                    help="chaos mode: kill the raft LEADER mid-burst and "
                         "measure recovery (implies chaos mode)")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="record per-stage spans on every node and write "
                         "one merged Chrome trace-event JSON here (open in "
                         "chrome://tracing or ui.perfetto.dev)")
    ap.add_argument("--notary-device", choices=("cpu", "accelerator"),
                    default="cpu",
                    help="device the first notary member (or the sidecar, "
                         "with --sidecar) owns; --processes mode only")
    ap.add_argument("--sidecar", action="store_true",
                    help="spawn ONE verification sidecar for the host and "
                         "point every notary member at it, coalescing "
                         "verify batches ACROSS processes "
                         "(crypto/sidecar.py; --processes mode only). "
                         "If the sidecar dies, members degrade to their "
                         "local host tier and re-probe on a cooldown — "
                         "at-least-once replay, never a wrong answer")
    ap.add_argument("--sidecar-devices", type=int, default=0,
                    help="mesh width the sidecar owns (--sidecar only): the "
                         "driver passes --devices to the sidecar process "
                         "and, on cpu hosts, forces a virtual device mesh "
                         "of that size so the data-parallel verify plane "
                         "is exercised end to end")
    ap.add_argument("--federation-hosts", type=int, default=0,
                    help="spawn N host-local verification sidecars as "
                         "simulated hosts and point every notary member's "
                         "FederatedVerifier at the set "
                         "(crypto/federation.py: depth + QoS-lane routing, "
                         "hedged re-dispatch, quarantine/re-admit; "
                         "--processes mode, excludes --sidecar). A lost "
                         "host degrades its in-flight batch to the local "
                         "host tier — never a wrong answer")
    ap.add_argument("--shards", type=int, default=0,
                    help="boot N independent raft notary groups partitioned "
                         "by StateRef hash (--processes + raft notary); "
                         "see node/services/sharding.py")
    ap.add_argument("--cross-frac", type=float, default=0.0,
                    help="fraction of transactions spanning two shards "
                         "(the two-phase commit path)")
    ap.add_argument("--lane", choices=("interactive", "bulk"), default="",
                    help="QoS lane label for every firehose transaction "
                         "(--processes mode); arms the QoS plane on every "
                         "node (qos/context.py). Omit for the unlabelled, "
                         "bit-identical pre-QoS run")
    ap.add_argument("--slo-ms", type=float, default=50.0,
                    help="interactive SLO in ms: each interactive tx "
                         "carries deadline = admit + slo_ms, which the "
                         "plane's three batching points flush against "
                         "(with --lane or --offered-load)")
    ap.add_argument("--offered-load", default=None, metavar="R1,R2,..",
                    help="run the mixed-lane SLO sweep instead of a single "
                         "burst: at each offered load (tx/s, comma list) "
                         "every client drives an interactive AND a bulk "
                         "firehose concurrently; prints per-lane p50/p99, "
                         "committed and shed counts plus member QoS stats")
    ap.add_argument("--ingest-sweep", default=None, metavar="R1,R2,..",
                    help="run the multiprocess ingest firehose: one builder "
                         "process batch-signs and serializes the corpus to "
                         "a multi-tx frame, --clients replay workers drive "
                         "disjoint slices of it open-loop at each offered "
                         "rate (tx/s, comma list); prints per-rate "
                         "achieved tx/s, ingest attribution and the "
                         "exactly-once verdict (optionally under --chaos)")
    args = ap.parse_args(argv)
    if args.shards and not args.processes:
        ap.error("--shards requires --processes (each shard group is a "
                 "real raft cluster of OS-process nodes)")
    if args.sidecar and not args.processes:
        ap.error("--sidecar requires --processes (one sidecar per HOST "
                 "only makes sense with real OS-process nodes)")
    if args.sidecar_devices and not args.sidecar:
        ap.error("--sidecar-devices requires --sidecar (the mesh lives "
                 "inside the sidecar server)")
    if args.federation_hosts:
        if not args.processes:
            ap.error("--federation-hosts requires --processes (each "
                     "simulated host is a real sidecar OS process)")
        if args.sidecar:
            ap.error("--federation-hosts excludes --sidecar (federation "
                     "IS the multi-sidecar generalization)")
    if args.lane and not args.processes:
        ap.error("--lane requires --processes (the QoS plane spans real "
                 "node processes; in-process mode has no lane plumbing)")
    if args.ingest_sweep:
        sweep = run_ingest_sweep(
            rates=tuple(float(x) for x in args.ingest_sweep.split(",")),
            n_tx=args.tx, width=args.width, workers=args.clients,
            notary=args.notary, cluster_size=args.cluster_size,
            cross_frac=args.cross_frac, verifier=args.verifier,
            max_sigs=args.max_sigs, max_wait_ms=args.max_wait_ms,
            chaos=args.chaos)
        print(json.dumps({
            "rates": {f"{rate:g}": row for rate, row in sweep.items()},
            "node_stamps": sweep.node_stamps,
            "first_bottleneck": sweep.first_bottleneck,
            "doctor": sweep.doctor,
        }))
        return 0
    if args.offered_load:
        sweep = run_slo_sweep(
            rates=tuple(float(x) for x in args.offered_load.split(",")),
            n_tx=args.tx, width=args.width, clients=args.clients,
            slo_ms=args.slo_ms, notary=args.notary,
            cluster_size=args.cluster_size, verifier=args.verifier,
            notary_device=args.notary_device, max_sigs=args.max_sigs,
            max_wait_ms=args.max_wait_ms, sidecar=args.sidecar,
            sidecar_devices=args.sidecar_devices)
        print(json.dumps({
            "slo_ms": args.slo_ms,
            "rates": {f"{rate:g}": {lane: dict(vars(fr))
                                    for lane, fr in by_lane.items()}
                      for rate, by_lane in sweep.items()},
            "node_stamps": sweep.node_stamps,
            "qos": sweep.qos,
            "first_bottleneck": sweep.first_bottleneck,
            "doctor": sweep.doctor,
        }))
        return 0
    if args.chaos is not None or args.kill_leader:
        result = run_chaos_loadtest(
            plan=args.chaos, n_tx=args.tx, cluster_size=args.cluster_size,
            kill_leader=args.kill_leader, verifier=args.verifier,
            batch=BatchConfig(max_sigs=args.max_sigs,
                              max_wait_ms=args.max_wait_ms),
            rate_tx_s=args.rate, trace=args.trace)
    elif args.processes:
        result = run_loadtest_multiprocess(
            n_tx=args.tx, width=args.width, clients=args.clients,
            notary=args.notary, cluster_size=args.cluster_size,
            verifier=args.verifier, inflight=args.inflight,
            rate_tx_s=args.rate, max_sigs=args.max_sigs,
            max_wait_ms=args.max_wait_ms, disrupt=args.disrupt,
            notary_device=args.notary_device,
            trace=args.trace, sidecar=args.sidecar,
            sidecar_devices=args.sidecar_devices,
            federation_hosts=args.federation_hosts,
            shards=args.shards, cross_frac=args.cross_frac,
            lane=args.lane, slo_ms=args.slo_ms)
    else:
        result = run_loadtest(
            n_tx=args.tx, notary=args.notary,
            cluster_size=args.cluster_size,
            disrupt=args.disrupt, verifier=args.verifier,
            batch=BatchConfig(max_sigs=args.max_sigs,
                              max_wait_ms=args.max_wait_ms),
            trace=args.trace)
    print(result.to_json())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
