"""perfdoctor — the performance doctor's CLI.

Three modes, all stdlib-only (obs/doctor.py does the work):

  diagnose (default)::

      python -m corda_tpu.tools.perfdoctor artifacts/BENCH_r05_local_e.json

  One ``PerfVerdict`` JSON per artifact on stdout: the roofline
  (measured ceiling vs committed/e2e rates, gap factored per layer) and
  the evidence-ranked ``bottlenecks`` list with a suggested next
  experiment per entry.

  backfill::

      python -m corda_tpu.tools.perfdoctor --backfill artifacts/

  Ingest every checked-in bench artifact (``*.json``, minus flight
  recordings and the trajectory itself) into
  ``artifacts/TRAJECTORY.jsonl`` in deterministic chronological order —
  (round, filename) — rewriting the store so re-runs are idempotent.

  gate::

      python -m corda_tpu.tools.perfdoctor --gate \\
          [--trajectory artifacts/TRAJECTORY.jsonl] [--policy policy.json]

  Compare each kind's newest trajectory record against its predecessor
  under the tolerance policy (per-metric direction + percent band;
  ``doctor.DEFAULT_POLICY`` unless ``--policy`` overrides specific
  metrics). Exit 1 on any regression — the CI hook perf PRs are judged
  with.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..obs import doctor

DEFAULT_TRAJECTORY = os.path.join("artifacts", "TRAJECTORY.jsonl")

# Never ingested by --backfill: the store itself, and flight recordings
# (breach captures are diagnostics, not bench runs).
_SKIP_PREFIXES = ("flight-",)
_SKIP_NAMES = ("TRAJECTORY.jsonl",)


def _load_json(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        loaded = json.load(f)
    if not isinstance(loaded, dict):
        raise ValueError(f"{path}: expected a JSON object at top level")
    return loaded


def _backfill_paths(directory: str) -> list[str]:
    names = [n for n in os.listdir(directory)
             if n.endswith(".json")
             and n not in _SKIP_NAMES
             and not n.startswith(_SKIP_PREFIXES)]

    def order(name: str):
        artifact_round = doctor._round_of({}, name)
        return (artifact_round if artifact_round is not None else 1 << 30,
                name)

    return [os.path.join(directory, n) for n in sorted(names, key=order)]


def cmd_diagnose(paths: list[str]) -> int:
    if not paths:
        print("perfdoctor: no artifacts given (pass paths, or --backfill/"
              "--gate)", file=sys.stderr)
        return 2
    exit_code = 0
    for path in paths:
        try:
            artifact = _load_json(path)
        except (OSError, ValueError) as exc:
            print(f"perfdoctor: {path}: {exc}", file=sys.stderr)
            exit_code = 2
            continue
        verdict = doctor.diagnose(doctor.extract_signals(artifact))
        verdict["source"] = os.path.basename(path)
        print(json.dumps(verdict, sort_keys=True))
    return exit_code


def cmd_backfill(directory: str, trajectory: str | None) -> int:
    if not os.path.isdir(directory):
        print(f"perfdoctor: --backfill: not a directory: {directory}",
              file=sys.stderr)
        return 2
    store = trajectory or os.path.join(directory, "TRAJECTORY.jsonl")
    records = []
    skipped = []
    for path in _backfill_paths(directory):
        try:
            artifact = _load_json(path)
        except (OSError, ValueError) as exc:
            skipped.append({"source": os.path.basename(path),
                            "error": str(exc)})
            continue
        record = doctor.normalize_record(artifact, source=path)
        if record["kind"] == "unknown":
            skipped.append({"source": os.path.basename(path),
                            "error": "unrecognized artifact shape"})
            continue
        records.append(record)
    # Rewrite, don't append: backfill is a full rebuild of history and
    # must be idempotent across re-runs.
    parent = os.path.dirname(os.path.abspath(store))
    os.makedirs(parent, exist_ok=True)
    tmp = store + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for record in records:
            f.write(json.dumps(record, sort_keys=True) + "\n")
    os.replace(tmp, store)
    print(json.dumps({
        "trajectory": store,
        "records": len(records),
        "kinds": sorted({r["kind"] for r in records}),
        "verdicts": [{"source": r["source"],
                      "first_bottleneck": r["verdict"]["first_bottleneck"]}
                     for r in records],
        "skipped": skipped,
    }, sort_keys=True))
    return 0


def cmd_gate(trajectory: str, policy_path: str | None) -> int:
    policy = dict(doctor.DEFAULT_POLICY)
    if policy_path:
        try:
            override = _load_json(policy_path)
        except (OSError, ValueError) as exc:
            print(f"perfdoctor: --policy: {exc}", file=sys.stderr)
            return 2
        policy.update(override)
    try:
        records = doctor.load_trajectory(trajectory)
    except ValueError as exc:
        print(f"perfdoctor: {exc}", file=sys.stderr)
        return 2
    if not records:
        print(f"perfdoctor: --gate: no trajectory at {trajectory} "
              "(run --backfill first, or point --trajectory at the store)",
              file=sys.stderr)
        return 2
    verdict = doctor.gate(records, policy)
    verdict["trajectory"] = trajectory
    print(json.dumps(verdict, sort_keys=True))
    return 0 if verdict["ok"] else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m corda_tpu.tools.perfdoctor",
        description="Bottleneck attribution, bench trajectory store, and "
                    "regression gating over corda_tpu perf artifacts.")
    parser.add_argument("artifacts", nargs="*",
                        help="artifact JSON files to diagnose")
    parser.add_argument("--backfill", metavar="DIR",
                        help="rebuild the trajectory store from every "
                             "bench artifact in DIR")
    parser.add_argument("--gate", action="store_true",
                        help="compare newest trajectory records against "
                             "their predecessors; exit 1 on regression")
    parser.add_argument("--trajectory", metavar="PATH",
                        help=f"trajectory store (default: "
                             f"{DEFAULT_TRAJECTORY}, or DIR/TRAJECTORY."
                             f"jsonl under --backfill)")
    parser.add_argument("--policy", metavar="JSON",
                        help="JSON file of per-metric overrides merged "
                             "over the default gate policy")
    args = parser.parse_args(argv)

    if args.backfill and args.gate:
        # Backfill-then-gate in one invocation is a supported CI shape.
        code = cmd_backfill(args.backfill, args.trajectory)
        if code:
            return code
        store = args.trajectory or os.path.join(
            args.backfill, "TRAJECTORY.jsonl")
        return cmd_gate(store, args.policy)
    if args.backfill:
        return cmd_backfill(args.backfill, args.trajectory)
    if args.gate:
        return cmd_gate(args.trajectory or DEFAULT_TRAJECTORY, args.policy)
    return cmd_diagnose(args.artifacts)


if __name__ == "__main__":
    sys.exit(main())
