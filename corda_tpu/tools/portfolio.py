"""Portfolio valuation agreement — the simm-valuation-demo shape.

Capability match for the reference's simm-valuation-demo flows (reference:
samples/simm-valuation-demo/src/main/kotlin/net/corda/vega/flows/SimmFlow.kt
— two parties deterministically value their shared portfolio and agree the
result on-ledger; PortfolioState/PortfolioValuation in .../contracts). The
margin number comes from the sensitivities-based fixed-point SIMM model in
corda_tpu/tools/simm.py (per-trade tenor-bucket sensitivities + risk-weight
and correlation aggregation — the reference's AnalyticsEngine.kt pipeline in
integer arithmetic): both sides compute independently from the shared trades
and oracle fix, compare, and only an AGREED valuation reaches the ledger.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..contracts.dsl import require_that, select_command
from ..contracts.structures import (
    Command,
    Contract,
    DealState,
    TypeOnlyCommandData,
    UniqueIdentifier,
)
from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from ..flows.api import FlowException, FlowLogic, register_flow
from ..flows.finality import FinalityFlow
from ..flows.oracle import FixOf, RatesFixQueryFlow
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
# Codec registration: PortfolioState.trades holds simm.IRSTrade values, so
# any process loading the portfolio cordapp must be able to (de)serialize
# them — importing at module level registers the type (the lazy import
# inside compute_valuation runs too late for an inbound transaction).
from . import simm as _simm  # noqa: F401


@register
@dataclass(frozen=True)
class ValueCommand(TypeOnlyCommandData):
    pass


class PortfolioContract(Contract):
    def verify(self, tx) -> None:
        ins = [s for s in tx.inputs if isinstance(s, PortfolioState)]
        outs = [s for s in tx.outputs if isinstance(s, PortfolioState)]
        all_signers = {k for c in tx.commands for k in c.signers}
        if not ins:  # creation: unvalued portfolio appears
            with require_that() as req:
                req("a new portfolio starts unvalued",
                    all(o.valuation is None for o in outs))
                req("every participant signs the portfolio creation",
                    all(k in all_signers for o in outs
                        for k in o.participants))
            return
        value_cmd = select_command(tx.commands, ValueCommand)
        with require_that() as req:
            req("a valuation updates exactly one portfolio",
                len(ins) == 1 and len(outs) == 1)
            req("the valuation is set", outs[0].valuation is not None)
            req("the portfolio's trades are unchanged",
                replace(outs[0], valuation=None)
                == replace(ins[0], valuation=None))
            # The agreement is only an agreement if BOTH parties must sign —
            # the builder picks the signer list, so the contract enforces it.
            req("both parties sign the valuation",
                all(k in value_cmd.signers for k in ins[0].participants))

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.tools.Portfolio")


PORTFOLIO_PROGRAM_ID = PortfolioContract()


@register
@dataclass(frozen=True)
class PortfolioState(DealState):
    """The shared portfolio: trade notionals between two parties, plus the
    latest agreed valuation (PortfolioState capability)."""

    party_a: Party = None  # type: ignore[assignment]
    party_b: Party = None  # type: ignore[assignment]
    oracle: Party = None  # type: ignore[assignment]
    rate_ref: FixOf = None  # type: ignore[assignment]
    trades: tuple = ()  # tuple[simm.IRSTrade, ...]
    valuation: int | None = None
    uid: UniqueIdentifier = field(default_factory=UniqueIdentifier)

    @property
    def linear_id(self) -> UniqueIdentifier:
        return self.uid

    @property
    def contract(self) -> Contract:
        return PORTFOLIO_PROGRAM_ID

    @property
    def participants(self):
        return [self.party_a.owning_key, self.party_b.owning_key]

    @property
    def parties(self):
        return [self.party_a, self.party_b]


def compute_valuation(trades, rate: int) -> int:
    """The deterministic margin model both sides run independently: the
    sensitivities-based fixed-point SIMM pipeline (tools/simm.py) on the
    shared trades and the oracle's rate fix."""
    from .simm import initial_margin

    return initial_margin(trades, rate)


@register_flow
class SimmValuationFlow(FlowLogic):
    """party_a: fetch the rate, value the portfolio, and agree the valuation
    with party_b (who recomputes independently) — then notarise+broadcast."""

    def __init__(self, portfolio_ref):
        self.portfolio_ref = portfolio_ref

    def call(self):
        from ..contracts.structures import StateAndRef

        state = self.service_hub.load_state(self.portfolio_ref)
        if state is None:
            raise FlowException("unknown portfolio")
        sar = StateAndRef(state, self.portfolio_ref)
        portfolio = state.data
        me = self.service_hub.my_identity
        other = (portfolio.party_b if me == portfolio.party_a
                 else portfolio.party_a)

        fix = yield from self.sub_flow(
            RatesFixQueryFlow(portfolio.oracle, portfolio.rate_ref))
        my_valuation = compute_valuation(portfolio.trades, fix.value)

        # Consensus on the number BEFORE anything is signed (SimmFlow's
        # agree step): the counterparty recomputes and must match.
        response = yield self.send_and_receive(
            other, (self.portfolio_ref, my_valuation), object)
        reply = response.unwrap(lambda r: r)
        if reply != my_valuation:
            raise FlowException(
                f"valuations diverge: ours {my_valuation}, theirs {reply}")

        tx = TransactionBuilder(notary=sar.state.notary)
        tx.add_input_state(sar)
        tx.add_output_state(replace(portfolio, valuation=my_valuation))
        tx.add_command(Command(ValueCommand(),
                               (me.owning_key, other.owning_key)))
        tx.sign_with(self.service_hub.legal_identity_key)
        ptx = tx.to_signed_transaction(check_sufficient_signatures=False)
        response = yield self.send_and_receive(other, ptx, object)
        sig = response.unwrap(
            lambda s: self.check_counterparty_signature(
                s, ptx.id.bytes, other))
        stx = ptx.with_additional_signature(sig)
        final = yield from self.sub_flow(FinalityFlow(stx, (me, other)))
        return final


@register_flow
class SimmValuationResponder(FlowLogic):
    """party_b: recompute the valuation from the SAME oracle and only agree
    (and later sign) if the numbers match."""

    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        from ..transactions.signed import SignedTransaction

        proposal = yield self.receive(self.other_party, object)
        ref, their_valuation = proposal.unwrap(self._shape)
        state = self.service_hub.load_state(ref)
        if state is None:
            raise FlowException("we do not hold this portfolio")
        portfolio = state.data
        fix = yield from self.sub_flow(
            RatesFixQueryFlow(portfolio.oracle, portfolio.rate_ref))
        my_valuation = compute_valuation(portfolio.trades, fix.value)
        yield self.send(self.other_party, my_valuation)
        if my_valuation != their_valuation:
            return None  # disagreement: nothing further to sign

        response = yield self.receive(self.other_party, SignedTransaction)
        ptx = response.unwrap(lambda p: self._validate(p, my_valuation))
        sig = self.service_hub.legal_identity_key.sign(ptx.id.bytes)
        yield self.send(self.other_party, sig)
        return None

    @staticmethod
    def _shape(payload):
        if (not isinstance(payload, tuple) or len(payload) != 2
                or not isinstance(payload[1], int)):
            raise FlowException("expected (portfolio_ref, valuation)")
        return payload

    def _validate(self, ptx, agreed_valuation):
        outs = [o.data for o in ptx.tx.outputs
                if isinstance(o.data, PortfolioState)]
        if len(outs) != 1 or outs[0].valuation != agreed_valuation:
            raise FlowException("transaction does not carry the agreed value")
        return ptx


def install_simm_responder(smm) -> None:
    smm.register_flow_initiator(
        "SimmValuationFlow", lambda party: SimmValuationResponder(party))
