"""Deterministic fixed-point SIMM-style initial-margin model.

Capability match for the simm-valuation-demo's analytics tier (reference:
samples/simm-valuation-demo/src/main/kotlin/net/corda/vega/analytics/
AnalyticsEngine.kt — OpenGamma Strata computes per-trade curve
sensitivities, then an ISDA-SIMM margin aggregates them;
flows/SimmFlow.kt drives both sides to compute independently and agree).
The reference's engine is double-precision OpenGamma; a consensus protocol
built on doubles only works because both sides run the SAME jar. Here the
model is **integer fixed-point end to end** — every node computes the
bit-identical margin from the shared portfolio and oracle fix, which is the
property the on-ledger agreement actually needs.

Model shape (simplified but structurally the ISDA SIMM delta-margin
pipeline):

1. **Curve**: a 12-tenor zero curve built deterministically from the
   oracle's rate fix (flat + a fixed slope), rates in basis points.
2. **Pricing**: each IRS trade (notional, fixed rate, maturity) PVs as
   annual-fixed-leg-vs-float-leg with simple-compounding integer discount
   factors at SCALE=1e8 fixed point.
3. **Sensitivities**: first-order bump-and-revalue — PV delta per +1bp bump
   of each tenor bucket (CurveCalibrator/parameterSensitivity capability,
   AnalyticsEngine.kt:77-93).
4. **Aggregation**: ISDA-SIMM delta margin shape — per-tenor risk weights,
   then margin = isqrt(sum_kl rho_kl * WS_k * WS_l) with a PSD
   exponential-decay correlation matrix (rho^|k-l|, the Kac-Murdock-Szego
   form; decays 1.00 -> 0.31 across the tenor span, matching the published
   ISDA IR correlation decay).
"""

from __future__ import annotations

from dataclasses import dataclass
from math import isqrt

from ..serialization.codec import register

SCALE = 10**8  # discount-factor / PV fixed point

# ISDA SIMM IR delta tenors (2w ... 30y), in days.
TENOR_DAYS = (14, 30, 91, 182, 365, 730, 1095, 1825, 3650, 5475, 7300, 10950)

# Per-tenor risk weights (ISDA SIMM v1 regular-volatility shape), integer.
RISK_WEIGHTS = (113, 113, 98, 69, 56, 52, 51, 51, 51, 53, 56, 64)

# Correlation in percent: rho_kl = round(100 * 0.9^|k-l|) — precomputed so
# no float touches the consensus path. KMS form => positive semi-definite.
_DECAY = (100, 90, 81, 73, 66, 59, 53, 48, 43, 39, 35, 31)
RHO_PCT = tuple(tuple(_DECAY[abs(k - l)] for l in range(len(TENOR_DAYS)))
                for k in range(len(TENOR_DAYS)))

# Deterministic curve slope added to the oracle's flat fix, per tenor (bp).
CURVE_SLOPE_BP = (0, 1, 2, 4, 7, 12, 16, 22, 30, 34, 37, 40)


@register
@dataclass(frozen=True)
class IRSTrade:
    """One interest-rate swap leg pair: positive notional receives fixed."""

    notional: int        # signed; units of portfolio currency
    fixed_rate_bp: int   # fixed leg rate, basis points
    maturity_days: int   # days from the valuation date; > 0


def curve_from_fix(fix_value: int) -> tuple[int, ...]:
    """Oracle fix (1 unit = 0.01 bp, e.g. 2_5000 = 2.5%) -> per-tenor zero
    rates in basis points."""
    base_bp = fix_value // 100
    return tuple(base_bp + slope for slope in CURVE_SLOPE_BP)


def _df(rate_bp: int, days: int) -> int:
    """Simple-compounded discount factor at SCALE: 1 / (1 + r*t)."""
    denominator = 10_000 * 365 + rate_bp * days
    return (SCALE * 10_000 * 365) // denominator


def _rate_at(curve_bp: tuple[int, ...], days: int) -> int:
    """Step interpolation: the first tenor >= days (flat extrapolation)."""
    for tenor, rate in zip(TENOR_DAYS, curve_bp):
        if days <= tenor:
            return rate
    return curve_bp[-1]


def trade_pv(trade: IRSTrade, curve_bp: tuple[int, ...]) -> int:
    """Integer PV at SCALE fixed point, from the fixed-receiver's side.

    Fixed leg: annual payments of notional * rate * 1y, discounted; the
    stub period at maturity pays pro-rata. Float leg: the textbook
    identity N * (1 - df(maturity))."""
    n = trade.notional
    fixed_pv = 0
    day = 365
    while day <= trade.maturity_days:
        df = _df(_rate_at(curve_bp, day), day)
        fixed_pv += n * trade.fixed_rate_bp * df // 10_000
        day += 365
    stub_days = trade.maturity_days - (day - 365)
    if stub_days > 0:
        df = _df(_rate_at(curve_bp, trade.maturity_days),
                 trade.maturity_days)
        fixed_pv += n * trade.fixed_rate_bp * stub_days * df \
            // (10_000 * 365)
    df_end = _df(_rate_at(curve_bp, trade.maturity_days),
                 trade.maturity_days)
    float_pv = n * (SCALE - df_end)
    return fixed_pv - float_pv


def trade_sensitivities(trade: IRSTrade,
                        curve_bp: tuple[int, ...]) -> tuple[int, ...]:
    """First-order bucket sensitivities: PV(+1bp bump of bucket k) - PV."""
    base = trade_pv(trade, curve_bp)
    out = []
    for k in range(len(TENOR_DAYS)):
        bumped = tuple(r + (1 if i == k else 0)
                       for i, r in enumerate(curve_bp))
        out.append(trade_pv(trade, bumped) - base)
    return tuple(out)


def portfolio_sensitivities(trades, curve_bp) -> tuple[int, ...]:
    total = [0] * len(TENOR_DAYS)
    for trade in trades:
        for k, s in enumerate(trade_sensitivities(trade, curve_bp)):
            total[k] += s
    return tuple(total)


def initial_margin(trades, fix_value: int) -> int:
    """The agreed number: ISDA-SIMM-shaped delta margin, integer end to end.

    margin = isqrt( sum_kl rho_kl * (RW_k s_k) * (RW_l s_l) ) de-scaled
    back to portfolio-currency units."""
    curve = curve_from_fix(fix_value)
    sens = portfolio_sensitivities(trades, curve)
    weighted = [RISK_WEIGHTS[k] * sens[k] for k in range(len(sens))]
    acc = 0
    for k, wk in enumerate(weighted):
        for l, wl in enumerate(weighted):
            acc += RHO_PCT[k][l] * wk * wl
    # acc is at (SCALE * 100-pct) fixed point squared; PSD correlation
    # keeps it non-negative, max(0) guards integer-rounding dust.
    return isqrt(max(0, acc) // 100) // SCALE
