"""Accelerator tunnel watcher: capture a device-backed bench the moment
the tunnel answers.

The axon relay that fronts the TPU is known to flap and to WEDGE
uninterruptibly (observed 2026-07-30: ``jax.devices()`` blocked >7h; a
short-lived subprocess probe can even succeed seconds before a real
device init hangs). ``bench.py`` already degrades honestly when the
device is unreachable, but a degraded report cannot prove the Pallas
recovery path on hardware. This watcher closes that gap:

* probe the device in DISPOSABLE subprocesses (a wedged probe is killed
  by its timeout and leaks nothing into the watcher process);
* require ``consecutive`` successful probes before trusting the tunnel
  (a single success proves nothing across a flap);
* then run ``python bench.py`` — which warms the persistent compile
  cache at ``/tmp/corda_tpu_jax_cache`` as a side effect, so even a
  capture that dies mid-run makes the NEXT attempt faster;
* keep the report only if the device was genuinely in the loop
  (``device`` present and not ``"unavailable"``), writing it to
  ``--out`` and exiting 0.

Run it in the background for as long as the round lasts::

    python -m corda_tpu.tools.tunnel_watch --out BENCH_TPU_CAPTURE.json

The reference has no tunnel to babysit; this tool exists because the
TPU here sits behind a remote relay, while the reference's benchmark
loop assumes a local device (reference: tools/loadtest/src/main/kotlin/
net/corda/loadtest/LoadTest.kt:39-144 drives remote NODES, not a remote
accelerator).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

PROBE_SNIPPET = (
    "import jax; d = jax.devices(); "
    "print('TUNNEL_OK', d[0].platform, len(d))"
)


def probe_once(timeout_s: float) -> bool:
    """One disposable-subprocess device probe. The child must NOT inherit a
    CPU platform pin — the whole point is to touch the real backend."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SNIPPET],
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except (subprocess.TimeoutExpired, OSError):
        return False
    return out.returncode == 0 and "TUNNEL_OK" in out.stdout


def run_bench(bench_path: str, timeout_s: float) -> dict | None:
    """Run bench.py in a child (its own watchdog set a notch below ours),
    parse the single JSON line, return it — or None on any failure."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["CORDA_TPU_BENCH_TIMEOUT"] = str(int(timeout_s - 120))
    try:
        out = subprocess.run(
            [sys.executable, bench_path],
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except (subprocess.TimeoutExpired, OSError):
        return None
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def device_backed(report: dict | None) -> bool:
    return bool(report) and bool(report.get("device")) \
        and report.get("device") != "unavailable"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_TPU_CAPTURE.json",
                    help="where to write the first device-backed report")
    ap.add_argument("--bench", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "bench.py"))
    ap.add_argument("--interval", type=float, default=120.0,
                    help="seconds between probes while the tunnel is down")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--consecutive", type=int, default=2,
                    help="successful probes required before running bench")
    ap.add_argument("--bench-timeout", type=float, default=2700.0)
    ap.add_argument("--max-hours", type=float, default=12.0)
    args = ap.parse_args(argv)

    deadline = time.monotonic() + args.max_hours * 3600
    streak = 0
    attempt = 0
    while time.monotonic() < deadline:
        if probe_once(args.probe_timeout):
            streak += 1
            print(f"[tunnel_watch] probe ok ({streak}/{args.consecutive})",
                  flush=True)
        else:
            if streak:
                print("[tunnel_watch] probe failed; streak reset", flush=True)
            streak = 0
        if streak >= args.consecutive:
            attempt += 1
            print(f"[tunnel_watch] tunnel looks up — bench attempt "
                  f"{attempt} (cache warm-up rides along)", flush=True)
            report = run_bench(args.bench, args.bench_timeout)
            if device_backed(report):
                with open(args.out, "w") as f:
                    json.dump(report, f, indent=1)
                print(f"[tunnel_watch] device-backed capture written to "
                      f"{args.out} (value={report.get('value')})", flush=True)
                return 0
            print("[tunnel_watch] bench ran but device was not in the "
                  "loop; re-probing", flush=True)
            streak = 0
        time.sleep(args.interval)
    print("[tunnel_watch] gave up: max watch window elapsed", flush=True)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
