"""Network visualiser: render a simulation's message feed to SVG.

Capability match for the reference's network-visualiser (reference:
samples/network-visualiser/src/main/kotlin/net/corda/netmap/
NetworkMapVisualiser.kt — replays InMemoryMessagingNetwork.sentMessages as an
animated map). Headless variant: the same feed becomes a static SVG sequence
diagram (one lifeline per node, one arrow per message, topic-coloured), which
drops into any browser or doc. Zero rendering dependencies.

    from corda_tpu.testing.simulation import TradeSimulation
    from corda_tpu.tools.visualiser import render_svg
    sim = TradeSimulation(); sim.run_trade()
    render_svg(sim.sent_messages, "trade.svg")
"""

from __future__ import annotations

from pathlib import Path

_COLORS = ("#4477AA", "#EE6677", "#228833", "#CCBB44", "#66CCEE", "#AA3377")


def _escape(text: str) -> str:
    return (text.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def render_svg(sent_messages, path: str | Path | None = None,
               max_messages: int = 400) -> str:
    """Sequence diagram of SentMessage records; returns the SVG text and
    optionally writes it to `path`."""
    messages = list(sent_messages)[:max_messages]
    nodes: list = []
    for m in messages:
        for endpoint in (m.sender, m.recipient):
            if endpoint not in nodes:
                nodes.append(endpoint)
    if not nodes:
        return "<svg xmlns='http://www.w3.org/2000/svg'/>"

    col_w, row_h, top = 180, 22, 60
    width = col_w * len(nodes) + 40
    height = top + row_h * (len(messages) + 1) + 40
    x_of = {n: 40 + col_w * i + col_w // 2 for i, n in enumerate(nodes)}
    topics = []
    parts = [
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
        f"height='{height}' font-family='monospace' font-size='11'>",
        "<rect width='100%' height='100%' fill='white'/>",
    ]
    for n in nodes:  # lifelines + headers
        x = x_of[n]
        parts.append(f"<line x1='{x}' y1='{top}' x2='{x}' "
                     f"y2='{height - 30}' stroke='#bbb'/>")
        parts.append(f"<text x='{x}' y='{top - 12}' text-anchor='middle' "
                     f"font-weight='bold'>{_escape(str(n))}</text>")
    for i, m in enumerate(messages):
        topic = m.message.topic_session.topic
        if topic not in topics:
            topics.append(topic)
        color = _COLORS[topics.index(topic) % len(_COLORS)]
        y = top + row_h * (i + 1)
        x1, x2 = x_of[m.sender], x_of[m.recipient]
        parts.append(f"<line x1='{x1}' y1='{y}' x2='{x2}' y2='{y}' "
                     f"stroke='{color}' marker-end='url(#arr)'/>")
        label_x = (x1 + x2) // 2
        parts.append(f"<text x='{label_x}' y='{y - 4}' text-anchor='middle' "
                     f"fill='{color}'>{_escape(topic)}</text>")
    parts.insert(1, "<defs><marker id='arr' markerWidth='8' markerHeight='8' "
                    "refX='7' refY='3' orient='auto'>"
                    "<path d='M0,0 L8,3 L0,6 z' fill='context-stroke'/>"
                    "</marker></defs>")
    parts.append("</svg>")
    svg = "\n".join(parts)
    if path is not None:
        Path(path).write_text(svg)
    return svg
