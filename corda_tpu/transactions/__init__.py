"""L1 transactions: wire/signed/ledger transactions, builder, tear-offs."""

from .types import TransactionType, GeneralTransactionType, NotaryChangeTransactionType  # noqa: F401
from .wire import WireTransaction  # noqa: F401
from .signed import SignedTransaction, SignaturesMissingException  # noqa: F401
from .ledger import LedgerTransaction  # noqa: F401
from .builder import TransactionBuilder  # noqa: F401
from .filtered import FilteredLeaves, FilteredTransaction, FilterFuns  # noqa: F401
