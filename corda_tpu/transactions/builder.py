"""TransactionBuilder: the one mutable transaction type.

Capability match for the reference's TransactionBuilder (reference:
core/src/main/kotlin/net/corda/core/transactions/TransactionBuilder.kt):
gather inputs/outputs/commands, then sign and freeze into a
SignedTransaction. The NotaryChange variant auto-collects participants as
signers (TransactionTypes.kt:129-140).
"""

from __future__ import annotations

from typing import Any

from ..contracts.structures import (
    Command,
    CommandData,
    ContractState,
    StateAndRef,
    StateRef,
    Timestamp,
    TransactionState,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.keys import DigitalSignature, KeyPair
from ..crypto.party import Party
from .signed import SignedTransaction
from .types import GeneralTransactionType, NotaryChangeTransactionType, TransactionType
from .wire import WireTransaction


class TransactionBuilder:
    def __init__(
        self,
        type: TransactionType | None = None,
        notary: Party | None = None,
    ):
        self.type = type or GeneralTransactionType()
        self.notary = notary
        self.inputs: list[StateRef] = []
        self.attachments: list[SecureHash] = []
        self.outputs: list[TransactionState] = []
        self.commands: list[Command] = []
        self.signers: list[CompositeKey] = []  # insertion-ordered, deduped
        self.timestamp: Timestamp | None = None
        self.current_sigs: list[DigitalSignature.WithKey] = []
        self._wtx_cache: WireTransaction | None = None

    @staticmethod
    def notary_change(notary: Party) -> "NotaryChangeBuilder":
        return NotaryChangeBuilder(notary)

    def copy(self) -> "TransactionBuilder":
        out = type(self)(self.type, self.notary)
        out.inputs = list(self.inputs)
        out.attachments = list(self.attachments)
        out.outputs = list(self.outputs)
        out.commands = list(self.commands)
        out.signers = list(self.signers)
        out.timestamp = self.timestamp
        return out

    def _add_signer(self, key: CompositeKey) -> None:
        if key not in self.signers:
            self.signers.append(key)

    def _check_not_signed(self) -> None:
        if self.current_sigs:
            raise ValueError("Cannot modify transaction after signing has started")
        # Every mutator calls this FIRST, so reaching here (no signatures
        # yet, mutation about to happen) is the one moment the cached wire
        # form can go stale.
        self._wtx_cache = None

    def _wire_cached(self) -> WireTransaction:
        """The wire form, computed once per content-state: an N-of-M
        multi-sig build calls sign_with N times, and rebuilding the
        WireTransaction each time discards its memoised Merkle tree —
        measured as the dominant cost of width-32 client builds (the id
        was recomputed per signature)."""
        if self._wtx_cache is None:
            self._wtx_cache = self.to_wire_transaction()
        return self._wtx_cache

    # -- mutation ----------------------------------------------------------

    def with_items(self, *items: Any) -> "TransactionBuilder":
        """Type-dispatched add (TransactionBuilder.kt:78-92)."""
        for t in items:
            if isinstance(t, StateAndRef):
                self.add_input_state(t)
            elif isinstance(t, TransactionState):
                self.add_output_state(t)
            elif isinstance(t, ContractState):
                self.add_output_state(t)
            elif isinstance(t, Command):
                self.add_command(t)
            elif isinstance(t, CommandData):
                raise ValueError(
                    "You passed CommandData without signer keys; wrap it in a Command first."
                )
            else:
                raise ValueError(f"Wrong argument type: {type(t)}")
        return self

    def add_input_state(self, state_and_ref: StateAndRef) -> None:
        self._check_not_signed()
        notary = state_and_ref.state.notary
        if notary != self.notary:
            raise ValueError(
                f'Input state requires notary "{notary}" which does not match '
                f'the transaction notary "{self.notary}".'
            )
        self._add_signer(notary.owning_key)
        self.inputs.append(state_and_ref.ref)

    def add_attachment(self, attachment_id: SecureHash) -> None:
        self._check_not_signed()
        self.attachments.append(attachment_id)

    def add_output_state(self, state: TransactionState | ContractState, notary: Party | None = None) -> int:
        self._check_not_signed()
        if isinstance(state, ContractState):
            n = notary or self.notary
            if n is None:
                raise ValueError(
                    "Need to specify a notary for the state, or a default one on the builder"
                )
            state = TransactionState(state, n)
        self.outputs.append(state)
        return len(self.outputs) - 1

    def add_command(self, command: Command | CommandData, *keys: CompositeKey) -> None:
        self._check_not_signed()
        if isinstance(command, CommandData):
            command = Command(command, tuple(keys))
        for k in command.signers:
            self._add_signer(k)
        self.commands.append(command)

    def set_time(self, timestamp: Timestamp) -> None:
        """Timestamps require the notary as timestamp authority
        (TransactionBuilder.kt:66-75)."""
        if self.notary is None:
            raise ValueError("Only notarised transactions can have a timestamp")
        self._check_not_signed()
        self._add_signer(self.notary.owning_key)
        self.timestamp = timestamp

    # -- signing & freezing ------------------------------------------------

    def sign_with(self, key: KeyPair) -> "TransactionBuilder":
        if any(s.by == key.public for s in self.current_sigs):
            raise ValueError("This partial transaction was already signed by that key")
        data = self._wire_cached().id
        self.current_sigs.append(key.sign(data.bytes))
        return self

    def check_signature(self, sig: DigitalSignature.WithKey) -> None:
        """Signature must match a command key and the tx contents
        (TransactionBuilder.kt:113-122)."""
        if not any(sig.by in c.keys for cmd in self.commands for c in cmd.signers):
            raise ValueError("Signature key doesn't match any command")
        sig.verify(self._wire_cached().id.bytes)

    def check_and_add_signature(self, sig: DigitalSignature.WithKey) -> None:
        self.check_signature(sig)
        self.add_signature_unchecked(sig)

    def add_signature_unchecked(self, sig: DigitalSignature.WithKey) -> "TransactionBuilder":
        self.current_sigs.append(sig)
        return self

    def to_wire_transaction(self) -> WireTransaction:
        return WireTransaction(
            inputs=tuple(self.inputs),
            attachments=tuple(self.attachments),
            outputs=tuple(self.outputs),
            commands=tuple(self.commands),
            notary=self.notary,
            signers=tuple(self.signers),
            type=self.type,
            timestamp=self.timestamp,
        )

    def to_signed_transaction(self, check_sufficient_signatures: bool = True) -> SignedTransaction:
        if check_sufficient_signatures:
            got = {s.by for s in self.current_sigs}
            missing = {ck for ck in self.signers if not ck.is_fulfilled_by(got)}
            if missing:
                raise ValueError(
                    f"Missing signatures on the transaction for: {sorted(missing, key=repr)}"
                )
        wtx = self._wire_cached()
        return SignedTransaction(tx_bits=wtx.serialized, sigs=tuple(self.current_sigs), id=wtx.id)


class NotaryChangeBuilder(TransactionBuilder):
    """Auto-adds input participants as signers (TransactionTypes.kt:129-140)."""

    def __init__(self, notary: Party):
        super().__init__(NotaryChangeTransactionType(), notary)

    def add_input_state(self, state_and_ref: StateAndRef) -> None:
        for participant in state_and_ref.state.data.participants:
            self._add_signer(participant)
        super().add_input_state(state_and_ref)
