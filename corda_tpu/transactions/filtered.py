"""Filtered transactions — Merkle tear-offs.

Capability match for the reference's FilteredTransaction machinery (reference:
core/src/main/kotlin/net/corda/core/transactions/MerkleTransaction.kt:104-178):
reveal only a chosen subset of a transaction's components (e.g. just the
commands an oracle must sign over) together with a partial Merkle proof tying
them to the transaction id. Used by oracles (NodeInterestRates) and
non-validating verification.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..contracts.structures import Command, StateRef, TransactionState
from ..crypto.hashes import SecureHash
from ..crypto.merkle import MerkleTreeException, PartialMerkleTree
from ..serialization.codec import register, serialized_hash
from .wire import WireTransaction


@register
@dataclass(frozen=True)
class FilteredLeaves:
    """The revealed components (MerkleTransaction.kt:104-117)."""

    inputs: tuple[StateRef, ...] = ()
    outputs: tuple[TransactionState, ...] = ()
    attachments: tuple[SecureHash, ...] = ()
    commands: tuple[Command, ...] = ()

    def filtered_hashes(self) -> list[SecureHash]:
        return [
            serialized_hash(x)
            for group in (self.inputs, self.outputs, self.attachments, self.commands)
            for x in group
        ]


@dataclass(frozen=True)
class FilterFuns:
    """Per-component-kind predicates (MerkleTransaction.kt:120-137)."""

    filter_inputs: Callable[[StateRef], bool] = field(default=lambda _: False)
    filter_outputs: Callable[[TransactionState], bool] = field(default=lambda _: False)
    filter_attachments: Callable[[SecureHash], bool] = field(default=lambda _: False)
    filter_commands: Callable[[Command], bool] = field(default=lambda _: False)


@register
@dataclass(frozen=True)
class FilteredTransaction:
    """Revealed leaves + the Merkle branch proving them
    (MerkleTransaction.kt:139-178)."""

    filtered_leaves: FilteredLeaves
    partial_merkle_tree: PartialMerkleTree

    @staticmethod
    def build_merkle_transaction(
        wtx: WireTransaction, filter_funs: FilterFuns
    ) -> "FilteredTransaction":
        leaves = FilteredLeaves(
            inputs=tuple(i for i in wtx.inputs if filter_funs.filter_inputs(i)),
            outputs=tuple(o for o in wtx.outputs if filter_funs.filter_outputs(o)),
            attachments=tuple(a for a in wtx.attachments if filter_funs.filter_attachments(a)),
            commands=tuple(c for c in wtx.commands if filter_funs.filter_commands(c)),
        )
        pmt = PartialMerkleTree.build(wtx.merkle_tree, leaves.filtered_hashes())
        return FilteredTransaction(leaves, pmt)

    def verify(self, merkle_root_hash: SecureHash) -> bool:
        """Check the revealed leaves really belong to the transaction whose id
        is merkle_root_hash (MerkleTransaction.kt:170-177)."""
        hashes = self.filtered_leaves.filtered_hashes()
        if not hashes:
            raise MerkleTreeException("Transaction without included leaves.")
        return self.partial_merkle_tree.verify(merkle_root_hash, hashes)
