"""Backwards search over the transaction dependency graph.

Capability match for the reference's TransactionGraphSearch (reference:
core/src/main/kotlin/net/corda/core/contracts/TransactionGraphSearch.kt):
starting from a transaction, walk its input ancestry through local storage
and collect transactions matching a query (e.g. "which issuance introduced
this cash?" — used by the trader demo's provenance display).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..crypto.hashes import SecureHash
from .wire import WireTransaction


@dataclass
class Query:
    """Match criteria (TransactionGraphSearch.Query): command type and/or an
    arbitrary predicate over the WireTransaction."""

    with_command_of_type: type | None = None
    predicate: Callable[[WireTransaction], bool] | None = None

    def matches(self, wtx: WireTransaction) -> bool:
        if self.with_command_of_type is not None and not any(
                isinstance(cmd.value, self.with_command_of_type)
                for cmd in wtx.commands):
            return False
        if self.predicate is not None and not self.predicate(wtx):
            return False
        return True


class TransactionGraphSearch:
    def __init__(self, transaction_storage, start_points: list[WireTransaction]):
        self._storage = transaction_storage
        self._start = list(start_points)

    def run(self, query: Query) -> list[WireTransaction]:
        """BFS over input ancestry; returns matches in discovery order,
        deduplicated (TransactionGraphSearch.call)."""
        next_hashes: list[SecureHash] = [
            ref.txhash for wtx in self._start for ref in wtx.inputs]
        visited: set[SecureHash] = set()
        results: list[WireTransaction] = []
        while next_hashes:
            h = next_hashes.pop(0)
            if h in visited:
                continue
            visited.add(h)
            stx = self._storage.get_transaction(h)
            if stx is None:
                continue
            wtx = stx.tx
            if query.matches(wtx):
                results.append(wtx)
            next_hashes.extend(ref.txhash for ref in wtx.inputs)
        return results
