"""LedgerTransaction: a WireTransaction with its dependencies resolved.

Capability match for the reference's LedgerTransaction (reference:
core/src/main/kotlin/net/corda/core/transactions/LedgerTransaction.kt):
inputs resolved to actual states, commands authenticated against known
parties, attachments opened — ready for contract verification.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..contracts.structures import (
    Attachment,
    AuthenticatedObject,
    StateAndRef,
    StateRef,
    Timestamp,
    TransactionState,
)
from ..contracts.verification import TransactionForContract
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from .types import TransactionType


@dataclass(frozen=True)
class LedgerTransaction:
    """Resolved transaction; verify() runs the platform + contract rules."""

    inputs: tuple[StateAndRef, ...]
    outputs: tuple[TransactionState, ...]
    commands: tuple[AuthenticatedObject, ...]
    attachments: tuple[Attachment, ...]
    id: SecureHash
    notary: Party | None
    must_sign: tuple[CompositeKey, ...]
    timestamp: Timestamp | None
    type: TransactionType

    def __post_init__(self):
        if self.notary is None and self.inputs:
            raise ValueError("The notary must be specified explicitly for any transaction that has inputs.")
        if self.timestamp is not None and self.notary is None:
            raise ValueError("If a timestamp is provided, there must be a notary.")

    def out_ref(self, index: int) -> StateAndRef:
        return StateAndRef(self.outputs[index], StateRef(self.id, index))

    def to_transaction_for_contract(self) -> TransactionForContract:
        notaries = {inp.state.notary for inp in self.inputs}
        return TransactionForContract(
            inputs=tuple(inp.state.data for inp in self.inputs),
            outputs=tuple(out.data for out in self.outputs),
            attachments=self.attachments,
            commands=self.commands,
            id=self.id,
            notary=next(iter(notaries)) if len(notaries) == 1 else None,
            timestamp=self.timestamp,
        )

    def verify(self) -> None:
        """Type-specific + platform verification (LedgerTransaction.kt:57)."""
        self.type.verify(self)
