"""SignedTransaction: the wire payload plus signatures — and the hot path.

Capability match for the reference's SignedTransaction (reference:
core/src/main/kotlin/net/corda/core/transactions/SignedTransaction.kt). The
reference's checkSignaturesAreValid is a sequential per-signature loop
(SignedTransaction.kt:83-87) — THE notary hot loop this framework re-designs:
here every signature check goes through the pluggable BatchVerifier
(corda_tpu/crypto/provider.py), so one transaction's signatures verify as a
batch, and the state machine manager aggregates *across* transactions into
TPU-sized micro-batches (StateMachineManager._flush_verify_batch in
corda_tpu/node/statemachine.py).

The id is the WireTransaction Merkle root, so adding/removing signatures never
changes identity.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Iterable, Sequence

from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.keys import DigitalSignature, SignatureError, by_keys
from ..crypto.provider import VerifyJob, get_verifier
from ..serialization.codec import SerializedBytes, mark_cacheable, register
from .wire import WireTransaction


from ..utils.excheckpoint import register_flow_exception


@register_flow_exception
class SignaturesMissingException(SignatureError):
    """Required signatures absent (SignedTransaction.kt:41-46).

    Survives checkpoint replay with its structure intact so restored flows
    can branch on isinstance / .missing exactly as live ones do.
    """

    def __init__(self, missing: set[CompositeKey], descriptions: list[str], id: SecureHash):
        super().__init__(
            f"Missing signatures for {descriptions} on transaction {id.prefix_chars()} "
            f"for {sorted(missing, key=repr)}"
        )
        self.missing = missing
        self.descriptions = descriptions
        self.id = id

    def __checkpoint_payload__(self):
        return (frozenset(self.missing), tuple(self.descriptions), self.id)

    @classmethod
    def __from_checkpoint__(cls, message, payload):
        missing, descriptions, id = payload
        return cls(set(missing), list(descriptions), id)


@register
@dataclass(frozen=True)
class SignedTransaction:
    """Serialized WireTransaction + signatures over its id."""

    tx_bits: SerializedBytes
    sigs: tuple[DigitalSignature.WithKey, ...]
    id: SecureHash

    def __post_init__(self):
        object.__setattr__(self, "sigs", tuple(self.sigs))
        if not self.sigs:
            raise ValueError("SignedTransaction requires at least one signature")

    @staticmethod
    def of(wtx: WireTransaction, sigs: Sequence[DigitalSignature.WithKey]) -> "SignedTransaction":
        return SignedTransaction(tx_bits=wtx.serialized, sigs=tuple(sigs), id=wtx.id)

    @property
    def tx(self) -> WireTransaction:
        """Deserialized payload; id cross-checked (SignedTransaction.kt:33-37)."""
        cached = getattr(self, "_tx", None)
        if cached is None:
            cached = self.tx_bits.deserialize()
            if cached.id != self.id:
                raise ValueError(
                    "Supplied transaction ID does not match deserialized transaction's ID"
                )
            object.__setattr__(self, "_tx", cached)
        return cached

    @staticmethod
    def prime_ids(stxs: "Sequence[SignedTransaction]",
                  device_min: int | None = None) -> str:
        """Batch the id cross-check of many payloads: every component leaf
        of every transaction hashes in ONE bulk call (the device kernel
        above the crossover batch size, hashlib below — ops/sha256_jax.
        hash_many_auto), and the per-object caches are seeded so later
        .tx / .id accesses are hits. Semantics are identical to touching
        .tx one transaction at a time, including the mismatch ValueError.

        This is the batched form of the reference's per-component hashing
        on the validating-notary resolve path (reference:
        core/.../transactions/MerkleTransaction.kt:26-38 driven by
        ResolveTransactionsFlow.kt:105-111). Returns the hashing backend
        used ("host" | "device") for bench attribution.
        """
        from ..crypto.hashes import SecureHash
        from ..ops import sha256_jax
        from ..serialization.codec import serialize

        todo = [stx for stx in stxs if getattr(stx, "_tx", None) is None]
        wtxs: list[WireTransaction] = []
        flat: list[bytes] = []
        spans: list[tuple[int, int]] = []
        for stx in todo:
            wtx = stx.tx_bits.deserialize()
            comps = [serialize(x).bytes
                     for group in (wtx.inputs, wtx.outputs,
                                   wtx.attachments, wtx.commands)
                     for x in group]
            spans.append((len(flat), len(flat) + len(comps)))
            flat.extend(comps)
            wtxs.append(wtx)
        digests, backend = sha256_jax.hash_many_auto(flat,
                                                     device_min=device_min)
        for stx, wtx, (lo, hi) in zip(todo, wtxs, spans):
            object.__setattr__(
                wtx, "_leaves", [SecureHash(d) for d in digests[lo:hi]])
            if wtx.id != stx.id:  # tree reduce over the seeded leaves
                raise ValueError(
                    "Supplied transaction ID does not match deserialized "
                    "transaction's ID"
                )
            object.__setattr__(stx, "_tx", wtx)
        return backend

    # -- signature verification (the hot path) ----------------------------

    def check_signatures_are_valid(self) -> None:
        """Mathematically validate every attached signature over the tx id.

        The reference loops one signature at a time
        (SignedTransaction.kt:83-87); here the whole set goes to the
        BatchVerifier in one call.
        """
        jobs = [
            VerifyJob(pubkey=sig.by.encoded, message=self.id.bytes, sig=sig.bytes)
            for sig in self.sigs
        ]
        ok = get_verifier().verify_batch(jobs)
        if not all(ok):
            bad = [self.sigs[i].by for i in range(len(jobs)) if not ok[i]]
            raise SignatureError(f"Signature did not match for keys: {bad}")

    def verify_signatures(self, *allowed_to_be_missing: CompositeKey) -> WireTransaction:
        """Check validity AND completeness of signatures
        (SignedTransaction.kt:59-74); returns the verified WireTransaction."""
        self.check_signatures_are_valid()
        missing = self.get_missing_signatures()
        if missing:
            needed = missing - set(allowed_to_be_missing)
            if needed:
                raise SignaturesMissingException(
                    needed, self._missing_key_descriptions(needed), self.id
                )
        if self.tx.id != self.id:
            raise ValueError("id mismatch")
        return self.tx

    def get_missing_signatures(self) -> set[CompositeKey]:
        sig_keys = by_keys(self.sigs)
        return {ck for ck in self.tx.must_sign if not ck.is_fulfilled_by(sig_keys)}

    def _missing_key_descriptions(self, missing: set[CompositeKey]) -> list[str]:
        out = []
        for cmd in self.tx.commands:
            if any(s in missing for s in cmd.signers):
                out.append(str(cmd))
        if self.tx.notary is not None and self.tx.notary.owning_key in missing:
            out.append("notary")
        return out

    # -- composition -------------------------------------------------------

    def with_additional_signature(self, sig: DigitalSignature.WithKey) -> "SignedTransaction":
        return replace(self, sigs=self.sigs + (sig,))

    def with_additional_signatures(
        self, sig_list: Iterable[DigitalSignature.WithKey]
    ) -> "SignedTransaction":
        return replace(self, sigs=self.sigs + tuple(sig_list))

    def __add__(self, sig):
        if isinstance(sig, DigitalSignature.WithKey):
            return self.with_additional_signature(sig)
        return self.with_additional_signatures(sig)

    def to_ledger_transaction(self, services):
        """verify_signatures + resolve dependencies (SignedTransaction.kt:131-137)."""
        return self.verify_signatures().to_ledger_transaction(services)


# The checkpoint/wire hot object: a flow's SignedTransaction argument was
# re-encoded at every suspension; the instance is deeply immutable, so its
# canonical encoding is memoized (serialization/codec.py mark_cacheable).
mark_cacheable(SignedTransaction)
