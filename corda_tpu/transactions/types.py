"""Transaction types: platform-level validity rules per kind of transaction.

Capability match for the reference's TransactionType (reference:
core/src/main/kotlin/net/corda/core/contracts/TransactionTypes.kt:20-160):
General transactions run contract code; NotaryChange transactions move states
between notaries without contract involvement. Both enforce signer
completeness and single-notary rules.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

from ..contracts.verification import (
    ContractRejection,
    InvalidNotaryChange,
    MoreThanOneNotary,
    NotaryChangeInWrongTransactionType,
    SignersMissing,
    TransactionMissingEncumbranceException,
)
from ..serialization.codec import register

if TYPE_CHECKING:
    from .ledger import LedgerTransaction


@dataclass(frozen=True)
class TransactionType:
    """Base: shared platform checks (TransactionTypes.kt:20-45)."""

    def verify(self, tx: "LedgerTransaction") -> None:
        """Platform rules + type rules. Presence of signatures is NOT checked
        here — only that the signer *list* covers what's required.
        (Timestamp-requires-notary is enforced by the transaction
        constructors themselves.)"""
        missing = self.verify_signers(tx)
        if missing:
            raise SignersMissing(tx.id, sorted(missing, key=repr))
        self.verify_transaction(tx)

    def verify_signers(self, tx: "LedgerTransaction") -> set:
        notary_keys = {inp.state.notary.owning_key for inp in tx.inputs}
        if len(notary_keys) > 1:
            raise MoreThanOneNotary(tx.id)
        required = self.get_required_signers(tx) | notary_keys
        return required - set(tx.must_sign)

    def get_required_signers(self, tx: "LedgerTransaction") -> set:
        raise NotImplementedError

    def verify_transaction(self, tx: "LedgerTransaction") -> None:
        raise NotImplementedError


@register
@dataclass(frozen=True)
class GeneralTransactionType(TransactionType):
    """Validity determined by contract code (TransactionTypes.kt:47-121)."""

    def get_required_signers(self, tx):
        return {k for cmd in tx.commands for k in cmd.signers}

    def verify_transaction(self, tx):
        self._verify_no_notary_change(tx)
        self._verify_encumbrances(tx)
        self._verify_contracts(tx)

    @staticmethod
    def _verify_no_notary_change(tx):
        # With inputs present, all outputs must stay on the same notary
        # (TransactionTypes.kt:60-74).
        if tx.notary is not None and tx.inputs:
            for out in tx.outputs:
                if out.notary != tx.notary:
                    raise NotaryChangeInWrongTransactionType(tx.id, out.notary)

    @staticmethod
    def _verify_encumbrances(tx):
        # Encumbered inputs must bring their encumbrance state along; output
        # encumbrance indices must point at a *different*, existing output
        # (TransactionTypes.kt:76-100).
        for inp in tx.inputs:
            enc = inp.state.data.encumbrance
            if enc is None:
                continue
            present = any(
                other.ref.txhash == inp.ref.txhash and other.ref.index == enc
                for other in tx.inputs
            )
            if not present:
                raise TransactionMissingEncumbranceException(
                    tx.id, enc, TransactionMissingEncumbranceException.INPUT
                )
        for i, out in enumerate(tx.outputs):
            enc = out.data.encumbrance
            if enc is None:
                continue
            if enc == i or enc >= len(tx.outputs):
                raise TransactionMissingEncumbranceException(
                    tx.id, enc, TransactionMissingEncumbranceException.OUTPUT
                )

    @staticmethod
    def _verify_contracts(tx):
        # Run every mentioned contract; any failure rejects the whole tx
        # (TransactionTypes.kt:106-117).
        ctx = tx.to_transaction_for_contract()
        contracts = []
        for s in list(ctx.inputs) + list(ctx.outputs):
            if s.contract not in contracts:
                contracts.append(s.contract)
        for contract in contracts:
            try:
                contract.verify(ctx)
            except Exception as e:
                raise ContractRejection(tx.id, contract, e) from e


@register
@dataclass(frozen=True)
class NotaryChangeTransactionType(TransactionType):
    """Reassign states to a new notary; no contract code runs
    (TransactionTypes.kt:123-160)."""

    def get_required_signers(self, tx):
        return {k for inp in tx.inputs for k in inp.state.data.participants}

    def verify_transaction(self, tx):
        ok = (
            len(tx.inputs) == len(tx.outputs)
            and not tx.commands
            and all(
                inp.state.data == out.data and inp.state.notary != out.notary
                for inp, out in zip(tx.inputs, tx.outputs)
            )
        )
        if not ok:
            raise InvalidNotaryChange(tx.id)
