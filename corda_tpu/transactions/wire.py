"""WireTransaction: the signable, serializable transaction payload.

Capability match for the reference's WireTransaction + BaseTransaction
(reference: core/src/main/kotlin/net/corda/core/transactions/WireTransaction.kt,
BaseTransaction.kt). The transaction id is the root of a Merkle tree over the
canonical serialization of each component (inputs, outputs, attachments,
commands — reference: MerkleTransaction.kt:26-38, WireTransaction.kt:45-52),
so signatures live *outside* the id and verify in parallel — the property the
whitepaper singles out (corda-technical-whitepaper.tex:1597-1604) and the TPU
batch kernel exploits.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..contracts.structures import (
    AuthenticatedObject,
    Command,
    StateAndRef,
    StateRef,
    Timestamp,
    TransactionState,
)
from ..contracts.verification import (
    AttachmentResolutionException,
    TransactionResolutionException,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.merkle import MerkleTree, PartialMerkleTree
from ..crypto.party import Party
from ..serialization.codec import register, serialize, serialized_hash
from .types import GeneralTransactionType, TransactionType

if TYPE_CHECKING:
    from .ledger import LedgerTransaction


@register
@dataclass(frozen=True)
class WireTransaction:
    """Immutable transaction payload; id = Merkle root of component hashes."""

    inputs: tuple[StateRef, ...] = ()
    attachments: tuple[SecureHash, ...] = ()
    outputs: tuple[TransactionState, ...] = ()
    commands: tuple[Command, ...] = ()
    notary: Party | None = None
    signers: tuple[CompositeKey, ...] = ()
    type: TransactionType = field(default_factory=GeneralTransactionType)
    timestamp: Timestamp | None = None

    def __post_init__(self):
        for name in ("inputs", "attachments", "outputs", "commands", "signers"):
            object.__setattr__(self, name, tuple(getattr(self, name)))
        # Invariants from BaseTransaction.checkInvariants (BaseTransaction.kt:42-45).
        if self.notary is None and self.inputs:
            raise ValueError("The notary must be specified explicitly for any transaction that has inputs.")
        if self.timestamp is not None and self.notary is None:
            raise ValueError("If a timestamp is provided, there must be a notary.")

    # -- identity ----------------------------------------------------------

    @property
    def all_leaves_hashes(self) -> list[SecureHash]:
        """Per-component canonical-serialization hashes, in the fixed
        component-group order (MerkleTransaction.kt:26-31).

        KNOWN MALLEABILITY (inherited, reference parity): the id covers only
        inputs/outputs/attachments/commands — exactly the reference snapshot's
        calculateLeavesHashes — so notary, signers, type and timestamp can be
        re-encoded by a relayer without changing the id or invalidating
        signatures. Later upstream versions add those fields as extra leaves;
        here we keep bit-parity with the snapshot. The id cross-check in
        SignedTransaction.tx catches component tampering only; altered
        notary/signers/type/timestamp must be caught by the verification
        rules that read them (timestamp window, notary match, must_sign
        fulfilment), which run on the payload the verifier received."""
        cached = getattr(self, "_leaves", None)
        if cached is None:
            cached = [
                serialized_hash(x)
                for group in (self.inputs, self.outputs, self.attachments, self.commands)
                for x in group
            ]
            object.__setattr__(self, "_leaves", cached)
        return cached

    @property
    def merkle_tree(self) -> MerkleTree:
        cached = getattr(self, "_tree", None)
        if cached is None:
            cached = MerkleTree.build(self.all_leaves_hashes)
            object.__setattr__(self, "_tree", cached)
        return cached

    @property
    def id(self) -> SecureHash:
        return self.merkle_tree.hash

    @property
    def serialized(self):
        cached = getattr(self, "_bytes", None)
        if cached is None:
            cached = serialize(self)
            object.__setattr__(self, "_bytes", cached)
        return cached

    @property
    def must_sign(self) -> tuple[CompositeKey, ...]:
        return self.signers

    # -- derived views -----------------------------------------------------

    def out_ref(self, index: int) -> StateAndRef:
        if not 0 <= index < len(self.outputs):
            raise IndexError(index)
        return StateAndRef(self.outputs[index], StateRef(self.id, index))

    def out_ref_of(self, state) -> StateAndRef:
        for i, out in enumerate(self.outputs):
            if out.data == state:
                return self.out_ref(i)
        raise ValueError("state not found among outputs")

    def to_ledger_transaction(self, services) -> "LedgerTransaction":
        """Resolve inputs/attachments/parties from services
        (WireTransaction.kt:79-96). Requires dependencies already resolved
        (ResolveTransactionsFlow)."""
        from .ledger import LedgerTransaction

        authenticated = tuple(
            AuthenticatedObject(
                signers=cmd.signers,
                signing_parties=tuple(
                    p
                    for p in (
                        services.identity_service.party_from_key(k) for k in cmd.signers
                    )
                    if p is not None
                ),
                value=cmd.value,
            )
            for cmd in self.commands
        )
        attachments = []
        for att_id in self.attachments:
            att = services.storage_service.attachments.open_attachment(att_id)
            if att is None:
                raise AttachmentResolutionException(att_id)
            attachments.append(att)
        resolved = []
        for ref in self.inputs:
            state = services.load_state(ref)
            if state is None:
                raise TransactionResolutionException(ref.txhash)
            resolved.append(StateAndRef(state, ref))
        return LedgerTransaction(
            inputs=tuple(resolved),
            outputs=self.outputs,
            commands=authenticated,
            attachments=tuple(attachments),
            id=self.id,
            notary=self.notary,
            must_sign=self.signers,
            timestamp=self.timestamp,
            type=self.type,
        )

    def build_filtered_transaction(self, filter_funs) -> "FilteredTransaction":
        from .filtered import FilteredTransaction

        return FilteredTransaction.build_merkle_transaction(self, filter_funs)

    def partial_merkle_tree(self, include: list[SecureHash]) -> PartialMerkleTree:
        return PartialMerkleTree.build(self.merkle_tree, include)

    def __str__(self) -> str:
        lines = [f"Transaction {self.id}:"]
        lines += [f"  INPUT:   {i}" for i in self.inputs]
        lines += [f"  OUTPUT:  {o}" for o in self.outputs]
        lines += [f"  COMMAND: {c}" for c in self.commands]
        lines += [f"  ATTACH:  {a}" for a in self.attachments]
        return "\n".join(lines)
