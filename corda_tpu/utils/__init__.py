"""Utility primitives: opaque byte wrappers, progress tracking, misc."""

from .bytes import OpaqueBytes  # noqa: F401
