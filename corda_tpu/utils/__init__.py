"""Utility primitives: opaque byte wrappers, progress tracking, misc."""

from .bytes import OpaqueBytes  # noqa: F401
from .clock import Clock  # noqa: F401
from .collections import NonEmptySet  # noqa: F401
from .interpolators import CubicSplineInterpolator, LinearInterpolator  # noqa: F401
from .progress import ProgressTracker, Step  # noqa: F401
from .progress_render import ProgressRenderer  # noqa: F401

# NOTE: service_identity is NOT re-exported here — it imports the crypto
# package, which itself depends on corda_tpu.utils (cycle). Import it as
# `from corda_tpu.utils.service_identity import generate_service_identity`.
