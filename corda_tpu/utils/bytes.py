"""Opaque byte-array wrappers.

Capability match for the reference's OpaqueBytes (reference:
core/src/main/kotlin/net/corda/core/serialization/ByteArrays.kt) — a typed
wrapper that stops raw byte arrays being confused with one another in
signatures, references and payloads.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class OpaqueBytes:
    """An immutable, comparable wrapper around a byte string."""

    bytes: bytes

    def __post_init__(self):
        if not isinstance(self.bytes, bytes):
            object.__setattr__(self, "bytes", bytes(self.bytes))

    @staticmethod
    def of(*values: int) -> "OpaqueBytes":
        return OpaqueBytes(bytes(values))

    @property
    def size(self) -> int:
        return len(self.bytes)

    def __len__(self) -> int:
        return len(self.bytes)

    def __bytes__(self) -> bytes:
        return self.bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.bytes.hex()[:32]})"
