"""Clock abstraction with a test-controllable variant.

Capability match for the reference's TestClock/MutableClock virtual time
(reference: test-utils/src/main/kotlin/net/corda/testing/node/TestClock.kt,
node/.../utilities/ClockUtils.kt). Times are epoch-microseconds.
"""

from __future__ import annotations

import time


class Clock:
    def now_micros(self) -> int:
        return int(time.time() * 1_000_000)


class TestClock(Clock):
    """A clock tests can set and advance deterministically."""

    def __init__(self, start_micros: int = 1_700_000_000_000_000):
        self._now = start_micros

    def now_micros(self) -> int:
        return self._now

    def set_time(self, micros: int) -> None:
        self._now = micros

    def advance(self, micros: int) -> None:
        self._now += micros
