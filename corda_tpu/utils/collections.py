"""Small collection utilities.

Capability match for the reference's NonEmptySet (reference:
core/src/main/kotlin/net/corda/core/utilities/NonEmptySet.kt — a set that
can never become empty, used where "at least one" is a type-level invariant,
e.g. signature sets)."""

from __future__ import annotations

from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")


class NonEmptySet(frozenset):
    """A frozenset that refuses to be empty. Set-algebra results that would
    be empty raise instead of silently violating the invariant."""

    def __new__(cls, items: Iterable[T]):
        self = super().__new__(cls, items)
        if not len(self):
            raise ValueError("NonEmptySet cannot be empty")
        return self

    # Every operation that could shrink the set routes through the
    # constructor so an empty result raises instead of silently escaping as
    # a plain frozenset.

    def __and__(self, other):
        return NonEmptySet(frozenset(self) & frozenset(other))

    __rand__ = __and__

    def __sub__(self, other):
        return NonEmptySet(frozenset(self) - frozenset(other))

    def __xor__(self, other):
        return NonEmptySet(frozenset(self) ^ frozenset(other))

    __rxor__ = __xor__

    def __or__(self, other):
        return NonEmptySet(frozenset(self) | frozenset(other))

    __ror__ = __or__

    def intersection(self, *others):
        return NonEmptySet(frozenset(self).intersection(*others))

    def difference(self, *others):
        return NonEmptySet(frozenset(self).difference(*others))

    def symmetric_difference(self, other):
        return NonEmptySet(frozenset(self).symmetric_difference(other))

    def union(self, *others):
        return NonEmptySet(frozenset(self).union(*others))

    @staticmethod
    def of(first: T, *rest: T) -> "NonEmptySet":
        return NonEmptySet((first,) + rest)
