"""Typed-exception registry for checkpoint replay.

The reference Kryo-serializes live exception objects inside fiber checkpoints,
so a flow that catches a specific exception subtype behaves identically before
and after a crash (reference: node/src/main/kotlin/net/corda/node/services/
statemachine/FlowStateMachineImpl.kt:238-261).  This framework's replay
checkpoints record suspension *results* instead — including raised errors —
so exception types must survive the round trip explicitly: a whitelist of
registered classes, mirroring the serialization codec's class whitelist.

Default round trip is ``cls(message)``.  Classes whose constructors need
structure implement two hooks:

    def __checkpoint_payload__(self):             # -> codec-serializable
    @classmethod
    def __from_checkpoint__(cls, message, payload):  # -> instance
"""

from __future__ import annotations

_registry: dict[str, type] = {}


def register_flow_exception(cls: type) -> type:
    """Decorator: whitelist an exception class for typed checkpoint replay."""
    existing = _registry.get(cls.__name__)
    if existing is not None and existing is not cls:
        raise ValueError(f"exception name {cls.__name__!r} already registered")
    _registry[cls.__name__] = cls
    return cls


def record_exception(err: BaseException) -> tuple:
    """Checkpoint entry for a raised suspension result:
    ('e', type_name, message[, payload])."""
    name = type(err).__name__
    if name in _registry:
        payload_fn = getattr(err, "__checkpoint_payload__", None)
        if payload_fn is not None:
            return ("e", name, str(err), payload_fn())
    return ("e", name, str(err))


def rebuild_exception(entry: tuple) -> BaseException | None:
    """Rebuild the recorded exception, or None if the type is unregistered
    (caller falls back to a generic flow error)."""
    _, name, message, *rest = entry
    cls = _registry.get(name)
    if cls is None:
        return None
    from_cp = getattr(cls, "__from_checkpoint__", None)
    try:
        if from_cp is not None:
            return from_cp(message, rest[0] if rest else None)
        return cls(message)
    except Exception:
        return None
