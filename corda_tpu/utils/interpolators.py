"""Interpolators for rate curves.

Capability match for the reference's math package (reference:
core/src/main/kotlin/net/corda/core/math/Interpolators.kt — Linear and
CubicSpline interpolation over (x, y) knots, used by the IRS demo's rate
oracle to price off a sparse curve). Pure host math — these run per-fixing,
not on the verification hot path.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class LinearInterpolator:
    xs: tuple[float, ...]
    ys: tuple[float, ...]

    def __post_init__(self):
        _check_knots(self.xs, self.ys)

    def interpolate(self, x: float) -> float:
        i = _bracket(self.xs, x)
        x0, x1 = self.xs[i], self.xs[i + 1]
        y0, y1 = self.ys[i], self.ys[i + 1]
        return y0 + (y1 - y0) * (x - x0) / (x1 - x0)


@dataclass(frozen=True)
class CubicSplineInterpolator:
    """Natural cubic spline (second derivative zero at the ends), matching
    the reference's CubicSplineInterpolator semantics."""

    xs: tuple[float, ...]
    ys: tuple[float, ...]

    def __post_init__(self):
        _check_knots(self.xs, self.ys)
        n = len(self.xs) - 1
        h = [self.xs[i + 1] - self.xs[i] for i in range(n)]
        # Solve the tridiagonal system for second derivatives (natural BCs).
        alpha = [0.0] * (n + 1)
        for i in range(1, n):
            alpha[i] = (3 / h[i]) * (self.ys[i + 1] - self.ys[i]) \
                - (3 / h[i - 1]) * (self.ys[i] - self.ys[i - 1])
        l = [1.0] + [0.0] * n
        mu = [0.0] * (n + 1)
        z = [0.0] * (n + 1)
        for i in range(1, n):
            l[i] = 2 * (self.xs[i + 1] - self.xs[i - 1]) - h[i - 1] * mu[i - 1]
            mu[i] = h[i] / l[i]
            z[i] = (alpha[i] - h[i - 1] * z[i - 1]) / l[i]
        c = [0.0] * (n + 1)
        b = [0.0] * n
        d = [0.0] * n
        for j in range(n - 1, -1, -1):
            c[j] = z[j] - mu[j] * c[j + 1]
            b[j] = (self.ys[j + 1] - self.ys[j]) / h[j] \
                - h[j] * (c[j + 1] + 2 * c[j]) / 3
            d[j] = (c[j + 1] - c[j]) / (3 * h[j])
        object.__setattr__(self, "_coeffs", (tuple(b), tuple(c), tuple(d)))

    def interpolate(self, x: float) -> float:
        i = _bracket(self.xs, x)
        b, c, d = self._coeffs
        dx = x - self.xs[i]
        return self.ys[i] + b[i] * dx + c[i] * dx * dx + d[i] * dx ** 3


def _check_knots(xs, ys):
    if len(xs) != len(ys) or len(xs) < 2:
        raise ValueError("need >= 2 knots with matching lengths")
    if any(xs[i] >= xs[i + 1] for i in range(len(xs) - 1)):
        raise ValueError("x knots must be strictly increasing")


def _bracket(xs, x) -> int:
    if x < xs[0] or x > xs[-1]:
        raise ValueError(f"{x} outside the curve [{xs[0]}, {xs[-1]}]")
    for i in range(len(xs) - 1):
        if x <= xs[i + 1]:
            return i
    return len(xs) - 2
