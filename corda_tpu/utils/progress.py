"""Hierarchical progress tracking for flows.

Capability match for the reference's ProgressTracker (reference:
core/src/main/kotlin/net/corda/core/utilities/ProgressTracker.kt:35): a flow
declares its steps up front, moves a cursor through them, and can splice a
child tracker under a step (sub-flow progress). Observers receive a flat
change stream (the reference exposes an rx Observable; here a subscription
list — the client RPC layer forwards it the same way,
reference: node/.../messaging/CordaRPCOps.kt:66-67).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class Step:
    label: str


DONE = Step("Done")
UNSTARTED = Step("Unstarted")


@dataclass(frozen=True)
class Change:
    """One progress event: the tracker's path to the current step."""

    path: tuple[str, ...]


class ProgressTracker:
    def __init__(self, *steps: Step):
        self.steps: tuple[Step, ...] = tuple(steps)
        self._index = -1  # UNSTARTED
        self._children: dict[Step, "ProgressTracker"] = {}
        self._observers: list[Callable[[Change], None]] = []
        self._parent: "ProgressTracker | None" = None

    # -- structure ---------------------------------------------------------

    def set_child_tracker(self, step: Step, child: "ProgressTracker") -> None:
        """Attach a sub-flow's tracker beneath one of our steps
        (ProgressTracker.kt childrenFor)."""
        self._children[step] = child
        child._parent = self

    def get_child_tracker(self, step: Step) -> "ProgressTracker | None":
        return self._children.get(step)

    # -- state -------------------------------------------------------------

    @property
    def current_step(self) -> Step:
        if self._index < 0:
            return UNSTARTED
        if self._index >= len(self.steps):
            return DONE
        return self.steps[self._index]

    @current_step.setter
    def current_step(self, step: Step) -> None:
        if step == DONE:
            self._index = len(self.steps)
        else:
            self._index = self.steps.index(step)
        self._emit()

    def next_step(self) -> Step:
        self._index += 1
        self._emit()
        return self.current_step

    # -- change stream -----------------------------------------------------

    def subscribe(self, observer: Callable[[Change], None]) -> None:
        self._observers.append(observer)

    def _path(self) -> tuple[str, ...]:
        parts: list[str] = [self.current_step.label]
        node = self
        while node._parent is not None:
            parent = node._parent
            for step, child in parent._children.items():
                if child is node:
                    parts.insert(0, step.label)
                    break
            node = parent
        return tuple(parts)

    def _emit(self) -> None:
        change = Change(self._path())
        node: ProgressTracker | None = self
        while node is not None:  # bubble to the root's observers too
            for obs in list(node._observers):
                obs(change)
            node = node._parent
